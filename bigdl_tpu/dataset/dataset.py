"""DataSet abstractions (reference dataset/DataSet.scala).

The reference distinguishes LocalDataSet (in-memory array + atomic cursor)
from DistributedDataSet (cached RDDs, partition==executor). Here a DataSet is
a host-side batch source; the distributed analog shards *by host process*
(each host reads its slice and forms its local part of the global batch —
the `jax.make_array_from_process_local_data` model that replaces
ZippedPartitionsWithLocalityRDD, SURVEY.md §2.6).
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.transformer import Transformer

__all__ = ["DataSet", "LocalArrayDataSet", "BatchDataSet", "MiniBatch"]


class MiniBatch:
    """(input, target) batch pair (reference dataset/Types.scala:74)."""

    __slots__ = ("input", "target")

    def __init__(self, input: Any, target: Any):
        self.input = input
        self.target = target

    def __iter__(self):  # tuple-unpacking convenience
        yield self.input
        yield self.target

    @property
    def size(self) -> int:
        return len(self.input)


class DataSet:
    """Base: iterate one epoch of elements; ``size`` = element count
    (reference AbstractDataSet: data(train)/size/shuffle :47-105)."""

    def __iter__(self) -> Iterator:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def shuffle(self, seed: Optional[int] = None) -> None:
        """Reshuffle the epoch order (reference CachedDistriDataSet.shuffle)."""

    def transform(self, t: Transformer) -> "TransformedDataSet":
        """(reference AbstractDataSet.transform/-> :74-88)"""
        return TransformedDataSet(self, t)

    def __rshift__(self, t: Transformer) -> "TransformedDataSet":
        return self.transform(t)


class TransformedDataSet(DataSet):
    def __init__(self, base: DataSet, t: Transformer):
        self.base, self.t = base, t

    def __iter__(self):
        return self.t(iter(self.base))

    def size(self):
        return self.base.size()

    def shuffle(self, seed=None):
        self.base.shuffle(seed)


class LocalArrayDataSet(DataSet):
    """In-memory sample array with per-epoch shuffling
    (reference DataSet.scala:111-157; the endless modulo-cursor train
    iterator becomes "the training loop re-iterates each epoch")."""

    def __init__(self, data: Sequence, shuffle: bool = False, seed: int = 0):
        self.data = list(data)
        self._shuffle = shuffle
        self._rng = np.random.RandomState(seed)
        self._order = np.arange(len(self.data))

    def __iter__(self):
        if self._shuffle:
            self._rng.shuffle(self._order)
        return (self.data[i] for i in self._order)

    def size(self):
        return len(self.data)

    def shuffle(self, seed=None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._rng.shuffle(self._order)


class BatchDataSet(DataSet):
    """Batches (features, labels) numpy arrays into MiniBatch objects —
    the terminal stage the Optimizer consumes (analog of SampleToBatch,
    dataset/Transformer.scala:73-140, including the drop-remainder semantics
    training needs for static XLA shapes)."""

    def __init__(self, features: np.ndarray, labels: np.ndarray,
                 batch_size: int, shuffle: bool = False, seed: int = 0,
                 drop_remainder: bool = True):
        assert len(features) == len(labels)
        self.features, self.labels = features, labels
        self.batch_size = batch_size
        self._shuffle = shuffle
        self._rng = np.random.RandomState(seed)
        self.drop_remainder = drop_remainder

    def __iter__(self):
        n = len(self.features)
        order = np.arange(n)
        if self._shuffle:
            self._rng.shuffle(order)
        end = (n - self.batch_size + 1) if self.drop_remainder else n
        for i in range(0, max(end, 0), self.batch_size):
            idx = order[i:i + self.batch_size]
            yield MiniBatch(self.features[idx], self.labels[idx])

    def size(self):
        return len(self.features)
