"""Mixup batch augmentation (Zhang et al.) — beyond the reference's
augment stages (dataset/image/*.scala are per-image; mixup is per-batch):
each batch is convexly combined with a shuffled copy of itself,
x' = lam*x + (1-lam)*x[perm], and the loss becomes the same convex
combination of the two labels' losses. Ships as a Transformer stage
(composes with ``>>`` like every other pipeline stage) plus the paired
criterion wrapper.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from bigdl_tpu.core.criterion import Criterion
from bigdl_tpu.dataset.dataset import MiniBatch
from bigdl_tpu.dataset.transformer import Transformer

__all__ = ["Mixup", "CutMix", "MixupCriterion"]


class Mixup(Transformer):
    """MiniBatch -> MiniBatch with ``target = (y_a, y_b, lam)``.

    ``lam ~ Beta(alpha, alpha)`` per batch (one scalar — the standard
    formulation keeps XLA shapes static). Train-time only; feed the
    resulting batches with :class:`MixupCriterion` wrapping the usual
    loss.
    """

    def __init__(self, alpha: float = 0.2, seed: int = 0):
        if alpha <= 0:
            raise ValueError(f"alpha must be > 0, got {alpha}")
        self.alpha = alpha
        self._rng = np.random.RandomState(seed)

    def __call__(self, it: Iterator) -> Iterator:
        for mb in it:
            x, y = np.asarray(mb.input), np.asarray(mb.target)
            lam = float(self._rng.beta(self.alpha, self.alpha))
            perm = self._rng.permutation(len(x))
            x_mixed = (lam * x + (1.0 - lam) * x[perm]).astype(x.dtype)
            yield MiniBatch(x_mixed,
                            (y, y[perm], np.float32(lam)))


class CutMix(Transformer):
    """CutMix (Yun et al.): paste a random rectangle from the permuted
    batch instead of blending — x keeps natural local statistics. Same
    ``(y_a, y_b, lam)`` target convention as :class:`Mixup` (lam = kept
    area fraction), so :class:`MixupCriterion` serves both. Expects NHWC
    image batches."""

    def __init__(self, alpha: float = 1.0, seed: int = 0):
        if alpha <= 0:
            raise ValueError(f"alpha must be > 0, got {alpha}")
        self.alpha = alpha
        self._rng = np.random.RandomState(seed)

    def __call__(self, it: Iterator) -> Iterator:
        for mb in it:
            x, y = np.asarray(mb.input), np.asarray(mb.target)
            n, h, w = x.shape[0], x.shape[1], x.shape[2]
            lam = float(self._rng.beta(self.alpha, self.alpha))
            perm = self._rng.permutation(n)
            # box with area (1-lam), clipped at the borders
            rh = int(round(h * np.sqrt(1.0 - lam)))
            rw = int(round(w * np.sqrt(1.0 - lam)))
            cy = int(self._rng.randint(0, h))
            cx = int(self._rng.randint(0, w))
            y0, y1 = max(0, cy - rh // 2), min(h, cy + rh // 2)
            x0, x1 = max(0, cx - rw // 2), min(w, cx + rw // 2)
            out = x.copy()
            out[:, y0:y1, x0:x1] = x[perm][:, y0:y1, x0:x1]
            # true kept fraction after clipping (the paper's adjustment)
            lam_eff = 1.0 - ((y1 - y0) * (x1 - x0)) / float(h * w)
            yield MiniBatch(out, (y, y[perm], np.float32(lam_eff)))


class MixupCriterion(Criterion):
    """loss = lam * inner(out, y_a) + (1-lam) * inner(out, y_b)."""

    def __init__(self, inner: Criterion):
        super().__init__(size_average=getattr(inner, "size_average", True))
        self.inner = inner

    def forward(self, input, target):
        y_a, y_b, lam = target
        return (lam * self.inner(input, y_a)
                + (1.0 - lam) * self.inner(input, y_b))
