"""Mixup batch augmentation (Zhang et al.) — beyond the reference's
augment stages (dataset/image/*.scala are per-image; mixup is per-batch):
each batch is convexly combined with a shuffled copy of itself,
x' = lam*x + (1-lam)*x[perm], and the loss becomes the same convex
combination of the two labels' losses. Ships as a Transformer stage
(composes with ``>>`` like every other pipeline stage) plus the paired
criterion wrapper.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from bigdl_tpu.core.criterion import Criterion
from bigdl_tpu.dataset.dataset import MiniBatch
from bigdl_tpu.dataset.transformer import Transformer

__all__ = ["Mixup", "MixupCriterion"]


class Mixup(Transformer):
    """MiniBatch -> MiniBatch with ``target = (y_a, y_b, lam)``.

    ``lam ~ Beta(alpha, alpha)`` per batch (one scalar — the standard
    formulation keeps XLA shapes static). Train-time only; feed the
    resulting batches with :class:`MixupCriterion` wrapping the usual
    loss.
    """

    def __init__(self, alpha: float = 0.2, seed: int = 0):
        if alpha <= 0:
            raise ValueError(f"alpha must be > 0, got {alpha}")
        self.alpha = alpha
        self._rng = np.random.RandomState(seed)

    def __call__(self, it: Iterator) -> Iterator:
        for mb in it:
            x, y = np.asarray(mb.input), np.asarray(mb.target)
            lam = float(self._rng.beta(self.alpha, self.alpha))
            perm = self._rng.permutation(len(x))
            x_mixed = (lam * x + (1.0 - lam) * x[perm]).astype(x.dtype)
            yield MiniBatch(x_mixed,
                            (y, y[perm], np.float32(lam)))


class MixupCriterion(Criterion):
    """loss = lam * inner(out, y_a) + (1-lam) * inner(out, y_b)."""

    def __init__(self, inner: Criterion):
        super().__init__(size_average=getattr(inner, "size_average", True))
        self.inner = inner

    def forward(self, input, target):
        y_a, y_b, lam = target
        return (lam * self.inner(input, y_a)
                + (1.0 - lam) * self.inner(input, y_b))
