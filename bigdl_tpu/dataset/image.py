"""Image transformer stages (reference dataset/image/, 19 files ~1,300 LoC).

Stages operate on numpy sample dicts/arrays host-side; heavy per-image work
is vectorized numpy (and the C++ prefetch pipeline in bigdl_tpu.runtime
parallelizes decode across worker threads — the analog of
MTLabeledBGRImgToBatch, image/MTLabeledBGRImgToBatch.scala:48-133).

Images are NHWC float32; grey images have C=1.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np

from bigdl_tpu.dataset.transformer import Transformer

__all__ = [
    "GreyImgNormalizer", "BGRImgNormalizer", "BGRImgPixelNormalizer",
    "HFlip", "BGRImgCropper", "BGRImgRdmCropper", "ColorJitter", "Lighting",
    "compute_mean_std",
]


def compute_mean_std(images: np.ndarray, per_channel: bool = True):
    """Two-pass dataset mean/std (reference BGRImgNormalizer.scala:132's
    accumulation, vectorized)."""
    axes = (0, 1, 2) if per_channel else None
    mean = images.mean(axis=axes, dtype=np.float64)
    std = images.std(axis=axes, dtype=np.float64)
    return mean, std


class _SampleTransform(Transformer):
    """Per-(image, label) map stage."""

    def _map(self, img: np.ndarray, rng: np.random.RandomState) -> np.ndarray:
        raise NotImplementedError

    def __init__(self, seed: int = 0):
        self._rng = np.random.RandomState(seed)

    def __call__(self, it: Iterator) -> Iterator:
        for img, label in it:
            yield self._map(img, self._rng), label


class GreyImgNormalizer(_SampleTransform):
    """(x - mean) / std with scalar stats (reference
    dataset/image/GreyImgNormalizer.scala)."""

    def __init__(self, mean: float, std: float):
        super().__init__()
        self.mean, self.std = float(mean), float(std)

    def _map(self, img, rng):
        return (img.astype(np.float32) - self.mean) / self.std


class BGRImgNormalizer(_SampleTransform):
    """Per-channel (x - mean) / std (reference BGRImgNormalizer.scala)."""

    def __init__(self, mean, std):
        super().__init__()
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def _map(self, img, rng):
        return (img.astype(np.float32) - self.mean) / self.std


class BGRImgPixelNormalizer(_SampleTransform):
    """Subtract a full per-pixel mean image (reference
    BGRImgPixelNormalizer.scala, used by Caffe-style pipelines)."""

    def __init__(self, mean_image: np.ndarray):
        super().__init__()
        self.mean_image = mean_image.astype(np.float32)

    def _map(self, img, rng):
        return img.astype(np.float32) - self.mean_image


class HFlip(_SampleTransform):
    """Random horizontal flip (reference dataset/image/HFlip.scala)."""

    def __init__(self, threshold: float = 0.5, seed: int = 0):
        super().__init__(seed)
        self.threshold = threshold

    def _map(self, img, rng):
        return img[:, ::-1] if rng.rand() < self.threshold else img


class BGRImgCropper(_SampleTransform):
    """Center crop (reference BGRImgCropper.scala with CropCenter)."""

    def __init__(self, crop_w: int, crop_h: int):
        super().__init__()
        self.crop_w, self.crop_h = crop_w, crop_h

    def _map(self, img, rng):
        h, w = img.shape[:2]
        y0 = (h - self.crop_h) // 2
        x0 = (w - self.crop_w) // 2
        return img[y0:y0 + self.crop_h, x0:x0 + self.crop_w]


class BGRImgRdmCropper(_SampleTransform):
    """Random crop after optional padding (reference BGRImgRdmCropper.scala)."""

    def __init__(self, crop_w: int, crop_h: int, padding: int = 0,
                 seed: int = 0):
        super().__init__(seed)
        self.crop_w, self.crop_h, self.padding = crop_w, crop_h, padding

    def _map(self, img, rng):
        if self.padding:
            p = self.padding
            img = np.pad(img, ((p, p), (p, p), (0, 0)))
        h, w = img.shape[:2]
        y0 = rng.randint(0, h - self.crop_h + 1)
        x0 = rng.randint(0, w - self.crop_w + 1)
        return img[y0:y0 + self.crop_h, x0:x0 + self.crop_w]


class ColorJitter(_SampleTransform):
    """Random brightness/contrast/saturation in random order
    (reference dataset/image/ColoJitter.scala, 93 LoC)."""

    def __init__(self, brightness: float = 0.4, contrast: float = 0.4,
                 saturation: float = 0.4, seed: int = 0):
        super().__init__(seed)
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation

    @staticmethod
    def _grs(img):
        # BGR grayscale weights (reference uses BGR layout)
        return (0.114 * img[..., 0] + 0.587 * img[..., 1]
                + 0.299 * img[..., 2])[..., None]

    def _map(self, img, rng):
        img = img.astype(np.float32)
        ops = [self._bright, self._contrast, self._saturate]
        rng.shuffle(ops)
        for op in ops:
            img = op(img, rng)
        return img

    def _bright(self, img, rng):
        a = 1.0 + rng.uniform(-self.brightness, self.brightness)
        return img * a

    def _contrast(self, img, rng):
        a = 1.0 + rng.uniform(-self.contrast, self.contrast)
        mean = self._grs(img).mean()
        return img * a + mean * (1 - a)

    def _saturate(self, img, rng):
        a = 1.0 + rng.uniform(-self.saturation, self.saturation)
        grey = self._grs(img)
        return img * a + grey * (1 - a)


class Lighting(_SampleTransform):
    """PCA lighting noise (reference dataset/image/Lighting.scala) with the
    standard ImageNet eigen-decomposition, BGR order."""

    EIGVAL = np.asarray([0.2175, 0.0188, 0.0045], np.float32)
    EIGVEC = np.asarray([[0.4009, 0.7192, -0.5675],
                         [-0.8140, -0.0045, -0.5808],
                         [0.4203, -0.6948, -0.5836]], np.float32)

    def __init__(self, alpha_std: float = 0.1, seed: int = 0):
        super().__init__(seed)
        self.alpha_std = alpha_std

    def _map(self, img, rng):
        alpha = rng.normal(0, self.alpha_std, 3).astype(np.float32)
        noise = (self.EIGVEC * alpha * self.EIGVAL).sum(axis=1)
        return img.astype(np.float32) + noise
