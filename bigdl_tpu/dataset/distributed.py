"""Multi-host data sharding (replaces the reference's DistributedDataSet /
CachedDistriDataSet and its host-locality machinery,
dataset/DataSet.scala:164-260 + ZippedPartitionsWithLocalityRDD,
spark-version/2.0/.../ZippedPartitionsWithLocalityRDD.scala:28-111).

The reference keeps "partition count == executor count" load-bearing
(DistriOptimizer.scala:357-359) and zips the data RDD with the model RDD by
host so a task always lands where its model replica lives. On TPU the same
locality is structural: each *process* (host) owns 1/P of every global
batch, feeds its local devices, and
``jax.make_array_from_process_local_data`` assembles the logically-global
sharded array — no shuffle, no block exchange.

Single-process (the common test/dev case) degenerates to "shard 0 of 1".
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from bigdl_tpu.dataset.dataset import DataSet, MiniBatch

__all__ = ["ShardedDataSet", "host_shard"]


def host_shard(n: int, process_index: Optional[int] = None,
               process_count: Optional[int] = None) -> slice:
    """This host's contiguous slice of an n-element dataset (equal shards,
    remainder dropped so every host steps the same number of batches —
    SPMD collectives require lockstep iteration counts). Feed the slice to a
    per-host pipeline (e.g. ``LocalArrayDataSet``) — not to
    :class:`ShardedDataSet`, which expects the full global arrays."""
    import jax

    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    per = n // pc
    return slice(pi * per, (pi + 1) * per)


class ShardedDataSet(DataSet):
    """Every host holds the **full global arrays**; each yields its own
    host-local part of every global batch.

    ``global_batch_size`` is the logical batch across all hosts; each host
    yields ``global_batch_size // process_count`` samples per step, selected
    from a *shared* epoch-advanced permutation of the global index space so
    shards stay disjoint and exhaustive (the analog of the reference's
    driver-computed shuffled-index RDD, DataSet.scala:252-257).

    Do NOT pass a :func:`host_shard` slice here — indexing is global. When a
    host can only hold 1/P of the data (ImageNet-scale), use
    :func:`host_shard` to select files and feed a per-host pipeline
    (``ImageFolderDataSet``/``LocalArrayDataSet``) instead.
    """

    def __init__(self, features: np.ndarray, labels: np.ndarray,
                 global_batch_size: int, shuffle: bool = False, seed: int = 0,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None):
        import jax

        self.features, self.labels = features, labels
        self.pi = jax.process_index() if process_index is None else process_index
        self.pc = jax.process_count() if process_count is None else process_count
        assert global_batch_size % self.pc == 0, (
            f"global batch {global_batch_size} not divisible by "
            f"{self.pc} processes")
        self.global_batch_size = global_batch_size
        self.local_batch = global_batch_size // self.pc
        self._shuffle = shuffle
        self._seed = seed
        self._epoch = 0

    def __iter__(self) -> Iterator[MiniBatch]:
        n = len(self.features)
        if self._shuffle:
            # same permutation on every host: seed is shared, epoch-advanced
            order = np.random.RandomState(
                self._seed + self._epoch).permutation(n)
        else:
            order = np.arange(n)
        steps = n // self.global_batch_size
        for s in range(steps):
            base = s * self.global_batch_size + self.pi * self.local_batch
            idx = order[base:base + self.local_batch]
            yield MiniBatch(self.features[idx], self.labels[idx])

    def size(self) -> int:
        return len(self.features)

    def shuffle(self, seed=None):
        if seed is not None:
            self._seed = seed
        self._epoch += 1
