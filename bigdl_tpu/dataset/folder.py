"""Label-by-folder image datasets (reference DataSet.ImageFolder,
dataset/DataSet.scala:322-379 — images under ``root/<class>/xxx.jpg``, one
folder per class, sorted folder names -> label ids).

Decode uses PIL on the host (the reference uses javax.imageio through
``BGRImage.readImage``, dataset/image/LocalImageFiles); decoded samples can
feed either the pure-python transformers (``bigdl_tpu.dataset.image``) or the
native C++ prefetch pipeline (``bigdl_tpu.dataset.native``) for the
multi-threaded augment path.
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Sequence

import numpy as np

__all__ = ["list_image_folder", "load_image_folder", "ImageFolderDataSet",
           "IMAGENET_MEAN", "IMAGENET_STD"]

_EXTS = {".jpg", ".jpeg", ".png", ".bmp", ".ppm", ".webp"}

# Per-channel RGB stats on raw 0-255 pixels, baked into the reference's
# ImageNet pipeline (BGRImgNormalizer defaults) — every imagenet-style CLI
# (inception/loadmodel/predict) trains and evaluates with these, so they
# live here, next to the loader they parameterize.
IMAGENET_MEAN = (123.0, 117.0, 104.0)
IMAGENET_STD = (58.4, 57.1, 57.4)


def list_image_folder(root: str) -> tuple[list[str], np.ndarray, list[str]]:
    """Scan ``root/<class>/*`` -> (paths, labels, class_names). Labels are
    0-based ids of the sorted class-folder names (reference ImageFolder
    assigns consecutive ids by folder, DataSet.scala:322-344)."""
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    paths: list[str] = []
    labels: list[int] = []
    for ci, cls in enumerate(classes):
        cdir = os.path.join(root, cls)
        for fn in sorted(os.listdir(cdir)):
            if os.path.splitext(fn)[1].lower() in _EXTS:
                paths.append(os.path.join(cdir, fn))
                labels.append(ci)
    return paths, np.asarray(labels, np.int32), classes


def _decode(path: str, size: Optional[tuple[int, int]]) -> np.ndarray:
    """Scale-to-fill + center crop, the standard eval transform (reference
    BGRImage.readImage). The resize convention lives in ONE place —
    streaming.decode_resize — so eval/predict numerics can't drift from
    the training pipeline's."""
    from bigdl_tpu.dataset.streaming import decode_resize

    with open(path, "rb") as f:
        raw = f.read()
    if size is None:
        import io

        from PIL import Image

        with Image.open(io.BytesIO(raw)) as im:
            return np.asarray(im.convert("RGB"), dtype=np.uint8)
    img = decode_resize(raw, short_side=None, fill=size)
    th, tw = size
    top = (img.shape[0] - th) // 2
    left = (img.shape[1] - tw) // 2
    return img[top:top + th, left:left + tw]


def load_image_folder(root: str, size: tuple[int, int] = (224, 224),
                      n_threads: int = 8,
                      limit: Optional[int] = None):
    """Eagerly decode a whole image folder into (images[N,H,W,3] uint8,
    labels[N] int32, class_names). Threaded decode (the reference's
    MT decode path, image/MTLabeledBGRImgToBatch.scala)."""
    paths, labels, classes = list_image_folder(root)
    if limit is not None:
        paths, labels = paths[:limit], labels[:limit]
    with ThreadPoolExecutor(max_workers=n_threads) as ex:
        images = list(ex.map(lambda p: _decode(p, size), paths))
    return np.stack(images) if images else np.zeros(
        (0, *size, 3), np.uint8), labels, classes


def ImageFolderDataSet(root: str, batch_size: int,
                       size: tuple[int, int] = (224, 224),
                       train: bool = False,
                       mean: Optional[Sequence[float]] = None,
                       std: Optional[Sequence[float]] = None,
                       seed: int = 0, n_threads: int = 8,
                       drop_remainder: bool = True, **kw):
    """Lazy batched image-folder dataset, streaming from disk (the ImageNet
    path — reference DataSet.SeqFileFolder streams Hadoop SequenceFiles).

    Backed by :class:`bigdl_tpu.dataset.streaming.StreamingImageFolder`:
    ``train=True`` gets **per-sample** random crop + horizontal flip inside
    the multithreaded decode pool (reference MTLabeledBGRImgToBatch
    semantics); eval decodes scale-to-fill + center crop. Extra keyword
    arguments (``short_side``, ``augment``, ``window``, ``hflip``) pass
    through to the streaming pipeline.
    """
    from bigdl_tpu.dataset.streaming import StreamingImageFolder

    return StreamingImageFolder(
        root, batch_size, crop=tuple(size), train=train, mean=mean,
        std=std, seed=seed, n_threads=n_threads,
        drop_remainder=drop_remainder, **kw)
