"""Composable data-pipeline stages
(reference dataset/Transformer.scala:39-61).

A Transformer maps an iterator to an iterator; stages compose with ``>>``
(the reference's ``->`` combinator, :44). Unlike the reference there is no
cloneTransformer/broadcast machinery — pipelines run per host process and
feed device arrays via bigdl_tpu.parallel.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator

__all__ = ["Transformer", "ChainedTransformer", "FnTransformer"]


class Transformer:
    """Iterator -> Iterator stage. Subclasses implement __call__."""

    def __call__(self, it: Iterator) -> Iterator:
        raise NotImplementedError

    def __rshift__(self, other: "Transformer") -> "ChainedTransformer":
        """Compose: (a >> b)(it) == b(a(it)) (reference Transformer.-> :44)."""
        return ChainedTransformer(self, other)

    def apply(self, data: Iterable) -> Iterator:
        return self(iter(data))


class ChainedTransformer(Transformer):
    """(reference ChainedTransformer :56)"""

    def __init__(self, first: Transformer, last: Transformer):
        self.first, self.last = first, last

    def __call__(self, it: Iterator) -> Iterator:
        return self.last(self.first(it))


class FnTransformer(Transformer):
    """Lift a per-element function into a stage."""

    def __init__(self, fn: Callable[[Any], Any]):
        self.fn = fn

    def __call__(self, it: Iterator) -> Iterator:
        return (self.fn(x) for x in it)
