"""Double-buffered host→device staging (ISSUE 13 tentpole #2).

While the device runs step N, a staging thread ``jax.device_put``s batch
N+1 — committed to the run's sharded layout when a ``--strategy`` object
is given (``strategy.shard_batch``: ``NamedSharding`` single-host,
``make_array_from_process_local_data`` multi-host), so staged batches
compose with dp/tp/sp and with ``--elastic`` mesh rebuilds (the staging
wrapper is rebuilt with the fresh strategy on every supervised retry).

Staged batches arrive as :class:`DeviceBatch` — the Optimizer's h2d
block recognizes device-committed inputs and skips its conversion, so
dispatch no longer pays the host→device copy. The producer thread's
``device_put`` runs under an ``h2d`` span (the span ring is
thread-safe), keeping the copy visible on the obs timeline even though
it no longer stalls the loop thread.

Backpressure is a bounded queue of ``depth`` batches; shutdown drains
until the producer THREAD exits (the same contract as the fixed
``PrefetchDataSet`` — an empty-queue check alone races a producer
blocked in ``put()``).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Callable, Iterator, Optional

from bigdl_tpu.dataset.dataset import DataSet
from bigdl_tpu.obs.spans import span as _span

__all__ = ["DeviceBatch", "StagedDataSet", "staged_batches", "make_put_fn",
           "STAGE_CHOICES"]

logger = logging.getLogger("bigdl_tpu")

STAGE_CHOICES = ("off", "host", "device")

_DONE = object()


class DeviceBatch:
    """An (input, target) pair already committed to device (and to the
    strategy's sharded layout) — consumers skip their h2d conversion.
    Iterates like MiniBatch for tuple unpacking."""

    __slots__ = ("input", "target")

    def __init__(self, input: Any, target: Any):
        self.input = input
        self.target = target

    def __iter__(self):
        yield self.input
        yield self.target

    @property
    def size(self) -> int:
        return len(self.input)


def make_put_fn(strategy=None) -> Callable:
    """The host→device commit for one (x, y) batch: the strategy's
    sharded placement when one is given, plain device arrays otherwise
    (target may be a pytree — Mixup's ``(y_a, y_b, lam)``)."""
    if strategy is not None:
        return strategy.shard_batch
    import jax
    import jax.numpy as jnp

    def put(x, y):
        return jnp.asarray(x), jax.tree_util.tree_map(jnp.asarray, y)

    return put


def staged_batches(batches, put_fn: Optional[Callable] = None,
                   depth: int = 2, stage: str = "device",
                   join_timeout: float = 5.0) -> Iterator:
    """Drive ``batches`` (any (x, y) iterable) through a staging thread.

    ``stage="host"``: prepare-ahead only (host batches pass through);
    ``stage="device"``: also commit each batch via ``put_fn`` on the
    staging thread, yielding :class:`DeviceBatch`; ``stage="off"``:
    passthrough, no thread."""
    if stage not in STAGE_CHOICES:
        raise ValueError(f"stage must be one of {STAGE_CHOICES}, "
                         f"got {stage!r}")
    if stage == "off":
        yield from batches
        return
    put = put_fn
    if stage == "device" and put is None:
        put = make_put_fn()
    if stage == "host":
        put = None
    q: queue.Queue = queue.Queue(maxsize=max(1, int(depth)))
    err: list = []
    stop = threading.Event()  # set when the consumer abandons the stream

    def offer(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def produce():
        try:
            for mb in batches:
                if put is not None:
                    x, y = mb
                    with _span("h2d", staged=True):
                        x, y = put(x, y)
                    mb = DeviceBatch(x, y)
                if not offer(mb):
                    return  # consumer gone — unwind, don't block forever
        except BaseException as e:  # surfaced on the consumer side
            err.append(e)
        finally:
            offer(_DONE)

    t = threading.Thread(target=produce, daemon=True, name="bigdl-stage")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _DONE:
                break
            yield item
    finally:
        stop.set()
        # drain until the THREAD exits, not until the queue momentarily
        # looks empty — the producer can refill between an empty-check
        # and the join (the PrefetchDataSet race, fixed here too)
        deadline = time.monotonic() + join_timeout
        while t.is_alive() and time.monotonic() < deadline:
            try:
                q.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=0.05)
        if t.is_alive():
            logger.warning(
                "staging: producer thread failed to exit within %.1fs "
                "(daemon thread leaked past shutdown — a device_put or "
                "the wrapped feed is stuck)", join_timeout)
    if err:
        raise err[0]


class StagedDataSet(DataSet):
    """DataSet front over :func:`staged_batches` — what the CLI wiring
    wraps around the executor (or any feed) under ``--stage``."""

    def __init__(self, inner: DataSet, stage: str = "device",
                 depth: int = 2, strategy=None,
                 put_fn: Optional[Callable] = None):
        if stage not in STAGE_CHOICES:
            raise ValueError(f"stage must be one of {STAGE_CHOICES}, "
                             f"got {stage!r}")
        self.inner = inner
        self.stage = stage
        self.depth = max(1, int(depth))
        self.strategy = strategy
        self._put_fn = put_fn

    @property
    def plan(self):
        """Expose the wrapped executor's epoch plan (checkpoint driver
        blobs stamp its signature through this)."""
        return getattr(self.inner, "plan", None)

    def __iter__(self) -> Iterator:
        put = self._put_fn
        if put is None and self.stage == "device":
            put = make_put_fn(self.strategy)
        yield from staged_batches(iter(self.inner), put_fn=put,
                                  depth=self.depth, stage=self.stage)

    def size(self) -> int:
        return self.inner.size()

    def shuffle(self, seed: Optional[int] = None) -> None:
        self.inner.shuffle(seed)

    def signature(self) -> dict:
        sig = {"stage": self.stage, "depth": self.depth}
        inner_sig = getattr(self.inner, "signature", None)
        if inner_sig is not None:
            sig.update(inner_sig())
        return sig
