"""Deterministic per-host epoch plans (ISSUE 13 tentpole #3).

One object answers "which sample indices does THIS host load for step s
of epoch e" for both sharding families the repo grew separately:

* ``mode="global"`` — every host draws from ONE shared epoch-advanced
  permutation of the global index space and takes its interleaved
  per-step slice (:class:`~bigdl_tpu.dataset.distributed.ShardedDataSet`
  semantics: shards stay disjoint and exhaustive, the analog of the
  reference's driver-computed shuffled-index RDD, DataSet.scala:252-257);
* ``mode="shard"`` — each host owns the contiguous
  :func:`~bigdl_tpu.dataset.distributed.host_shard` slice (file-level
  sharding for data too big to replicate) and permutes within it.

The plan is a pure function of ``(seed, epoch)``: the executor's worker
threads can race over its tickets in any order and the assembled batch
stream is still bit-identical — and the Optimizer's resume replay
(one ``shuffle()`` per completed epoch, PR 2 contract) lands back on the
exact same schedule. ``signature()`` is the compact provenance dict that
rides in perf JSON lines and checkpoint driver blobs.

Remainder samples are always dropped: static XLA shapes need full
batches, and equal per-host step counts keep SPMD collectives in
lockstep (the :func:`host_shard` rationale).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["EpochPlan", "sample_rng"]

PLAN_MODES = ("global", "shard")


def sample_rng(seed: int, epoch: int, index: int) -> np.random.RandomState:
    """Per-(epoch, sample) RNG, independent of which worker thread runs
    the sample — the ticket-seeding idea of the reference's C++ pipeline
    applied per sample (same mix as ``_StreamingImageBase._load_sample``,
    so record streams keep their bit-identity contract)."""
    mix = (seed * 0x9E3779B9 + epoch * 0x85EBCA6B + index) & 0xFFFFFFFF
    return np.random.RandomState(mix)


class EpochPlan:
    """``batch_size`` is the LOCAL (per-host) batch; the logical global
    batch is ``batch_size * process_count``. ``epoch`` advances via
    :meth:`advance` (the DataSet ``shuffle()`` contract — iteration does
    NOT advance it), so kill+resume replays land on the same schedule."""

    def __init__(self, n_samples: int, batch_size: int, seed: int = 0,
                 shuffle: bool = True, mode: str = "global",
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None, epoch: int = 0):
        if mode not in PLAN_MODES:
            raise ValueError(f"mode must be one of {PLAN_MODES}, got {mode!r}")
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        if process_index is None or process_count is None:
            import jax

            process_index = (jax.process_index() if process_index is None
                             else process_index)
            process_count = (jax.process_count() if process_count is None
                             else process_count)
        self.n = int(n_samples)
        self.batch_size = int(batch_size)
        self.seed = int(seed)
        self.shuffle = bool(shuffle)
        self.mode = mode
        self.pi = int(process_index)
        self.pc = int(process_count)
        self.epoch = int(epoch)
        self.global_batch = self.batch_size * self.pc

    # ----------------------------------------------------------- schedule
    @property
    def steps(self) -> int:
        """Batches per epoch on THIS host (identical on every host)."""
        if self.mode == "global":
            return self.n // self.global_batch
        return (self.n // self.pc) // self.batch_size

    def order(self, epoch: Optional[int] = None) -> np.ndarray:
        """This host's full sample order for ``epoch`` (before batching).
        ``mode="global"``: the shared permutation — same array on every
        host. ``mode="shard"``: the host_shard slice, locally permuted."""
        e = self.epoch if epoch is None else int(epoch)
        if self.mode == "global":
            if not self.shuffle:
                return np.arange(self.n)
            return np.random.RandomState(
                (self.seed + e) & 0xFFFFFFFF).permutation(self.n)
        per = self.n // self.pc
        base = self.pi * per
        if not self.shuffle:
            return base + np.arange(per)
        return base + np.random.RandomState(
            (self.seed + e) & 0xFFFFFFFF).permutation(per)

    def batch_indices(self, epoch: Optional[int] = None) -> np.ndarray:
        """``(steps, batch_size)`` int array: row s = the samples this
        host loads for step s. mode="global" takes the per-host
        interleaved slice of each global batch (ShardedDataSet's
        ``order[s*gb + pi*lb : +lb]``); mode="shard" batches the local
        order directly."""
        order = self.order(epoch)
        steps = self.steps
        if steps == 0:
            return np.empty((0, self.batch_size), dtype=order.dtype)
        if self.mode == "global":
            rows = [order[s * self.global_batch + self.pi * self.batch_size:
                          s * self.global_batch
                          + (self.pi + 1) * self.batch_size]
                    for s in range(steps)]
            return np.stack(rows)
        return order[:steps * self.batch_size].reshape(steps,
                                                       self.batch_size)

    # ------------------------------------------------------------ mutation
    def advance(self, seed: Optional[int] = None) -> None:
        """The DataSet ``shuffle()`` contract (ShardedDataSet semantics):
        advance to the next epoch's permutation; an explicit seed also
        rebases the schedule."""
        if seed is not None:
            self.seed = int(seed)
        self.epoch += 1

    # ---------------------------------------------------------- provenance
    def signature(self) -> dict:
        """Compact provenance — stamped into perf JSON lines and the
        checkpoint driver blob so a resumed/audited run can verify it is
        replaying the same schedule."""
        return {"n": self.n, "batch": self.batch_size,
                "global_batch": self.global_batch, "seed": self.seed,
                "shuffle": self.shuffle, "mode": self.mode,
                "host": self.pi, "hosts": self.pc, "epoch": self.epoch}
