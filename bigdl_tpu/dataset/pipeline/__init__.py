"""Async sharded input-pipeline executor (ISSUE 13).

The production feed path closing the resnet50_pipe gap (0.99% MFU
real-data vs 33.2% synthetic-fed, PERF.md §4): a pool of decode/augment
worker threads races an :class:`EpochPlan`'s sample tickets
(:mod:`executor` — the reference's MTLabeledBGRImgToBatch model), a
staging thread double-buffers the host→device commit against the
running step (:mod:`staging`), and one plan object owns per-host epoch
sharding for both the shared-permutation and the contiguous host-shard
families (:mod:`plan`).

CLI surface: ``--dataWorkers N --prefetchDepth D --stage {off,host,
device}`` (wired through ``cli/common.build_feed``); provenance lands in
perf JSON lines as the ``pipeline`` column.
"""

from bigdl_tpu.dataset.pipeline.plan import EpochPlan, sample_rng
from bigdl_tpu.dataset.pipeline.executor import (
    SampleSource, ArraySampleSource, StreamingSampleSource,
    ExecutorDataSet, as_executor,
)
from bigdl_tpu.dataset.pipeline.staging import (
    DeviceBatch, StagedDataSet, staged_batches, make_put_fn, STAGE_CHOICES,
)

__all__ = ["EpochPlan", "sample_rng", "SampleSource", "ArraySampleSource",
           "StreamingSampleSource", "ExecutorDataSet", "as_executor",
           "DeviceBatch", "StagedDataSet", "staged_batches", "make_put_fn",
           "STAGE_CHOICES", "wrap_pipeline"]


def wrap_pipeline(dataset, workers: int = 0, depth: int = 2,
                  stage: str = "off", strategy=None, seed: int = 0):
    """Wrap a training DataSet in the async pipeline stack per the
    ``(--dataWorkers, --prefetchDepth, --stage)`` triple.

    Returns ``(dataset, provenance)`` — provenance is the dict stamped
    into perf JSON lines (None when the surface is untouched). Datasets
    with no executor decomposition fall back to the single-threaded
    prefetch wrapper so ``--dataWorkers`` still buys prepare-ahead."""
    import logging

    workers = int(workers or 0)
    depth = max(1, int(depth or 2))
    stage = stage or "off"
    if stage not in STAGE_CHOICES:
        raise ValueError(f"stage must be one of {STAGE_CHOICES}, "
                         f"got {stage!r}")
    if workers <= 0 and stage == "off":
        return dataset, None
    prov = {"workers": workers, "depth": depth, "stage": stage}
    ds = dataset
    if workers > 0:
        ex = as_executor(ds, workers=workers, depth=depth, seed=seed)
        if ex is None:
            logging.getLogger("bigdl_tpu").warning(
                "--dataWorkers: %s has no executor decomposition; using "
                "the single-threaded prefetch wrapper instead",
                type(ds).__name__)
            prov["executor"] = False
            if stage == "off":
                from bigdl_tpu.dataset.prefetch import PrefetchDataSet
                ds = PrefetchDataSet(ds, depth)
        else:
            ds = ex
            prov["executor"] = True
            prov["plan"] = ex.plan.signature()
    if stage != "off":
        ds = StagedDataSet(ds, stage=stage, depth=depth, strategy=strategy)
    return ds, prov
