"""Parallel decode/augment executor (ISSUE 13 tentpole #1).

The TPU-native translation of the reference's MTLabeledBGRImgToBatch
(image/MTLabeledBGRImgToBatch.scala:48-133): coreNumber cloned
transformer pipelines race on an atomic batch counter and write into
preallocated per-batch buffers. Here a pool of N worker THREADS races an
atomic sample-ticket counter over an :class:`EpochPlan`'s schedule —
threads suffice because the hot per-sample work (PIL/libjpeg decode,
numpy/native augment) releases the GIL — and the consumer hands batches
out strictly in submission order.

Determinism contract (the load-bearing property):

* which sample lands in batch ``b`` slot ``i`` is fixed by the plan
  (pure in ``(seed, epoch)``), not by thread scheduling;
* any per-sample randomness derives from ``(seed, epoch, index)``
  (:func:`~bigdl_tpu.dataset.pipeline.plan.sample_rng`), not from a
  shared RNG stream;

so the assembled batch stream is **bit-identical for any worker count**
and under kill+resume (the PR 2 resume-equivalence contract: the
Optimizer replays ``shuffle()`` once per completed epoch and skips the
consumed head of the open one).

Backpressure: a worker may not claim a ticket more than ``depth``
batches past the last consumed batch — at most ``depth`` batches of
samples exist at once (``stats["max_inflight"]`` proves the bound).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Iterator, Optional

import numpy as np

from bigdl_tpu.dataset.dataset import DataSet, MiniBatch
from bigdl_tpu.dataset.pipeline.plan import EpochPlan

__all__ = ["SampleSource", "ArraySampleSource", "StreamingSampleSource",
           "ExecutorDataSet", "as_executor"]

logger = logging.getLogger("bigdl_tpu")


class SampleSource:
    """What the executor's workers pull samples from. ``load`` MUST be
    pure in ``(index, epoch)`` and thread-safe (workers call it
    concurrently) — that purity is the whole determinism contract."""

    def __len__(self) -> int:
        raise NotImplementedError

    def load(self, index: int, epoch: int):
        """Return one sample ``(x, y)`` for dataset index ``index`` of
        epoch ``epoch`` (the epoch feeds per-sample augmentation seeds)."""
        raise NotImplementedError

    def collate(self, samples: list) -> MiniBatch:
        """Assemble one ordered slot list into a MiniBatch."""
        xs = [s[0] for s in samples]
        ys = [s[1] for s in samples]
        x = np.stack(xs)
        if isinstance(ys[0], (np.ndarray, np.generic)):
            y = np.stack(ys)
        else:
            y = np.asarray(ys, np.int32)
        return MiniBatch(x, y)

    def signature(self) -> dict:
        return {"source": type(self).__name__, "n": len(self)}


class ArraySampleSource(SampleSource):
    """In-memory (features, labels) arrays — the BatchDataSet /
    ShardedDataSet payload behind an executor front."""

    def __init__(self, features: np.ndarray, labels: np.ndarray):
        assert len(features) == len(labels)
        self.features, self.labels = features, labels

    def __len__(self) -> int:
        return len(self.features)

    def load(self, index: int, epoch: int):
        return self.features[index], self.labels[index]


class StreamingSampleSource(SampleSource):
    """Adapter over a ``_StreamingImageBase`` (RecordImageDataSet /
    StreamingImageFolder): delegates the per-sample decode+augment path
    (``_load_sample``, which already derives its RNG from
    ``(seed, epoch, index)``), so an executor-fed record stream is
    bit-identical to the legacy window feed on the same schedule."""

    def __init__(self, ds):
        self.ds = ds

    def __len__(self) -> int:
        return self.ds._num_samples()

    def load(self, index: int, epoch: int):
        return self.ds._load_sample(int(index), int(epoch))

    def collate(self, samples: list) -> MiniBatch:
        # exactly _StreamingImageBase.__iter__'s assembly
        x = np.stack([s[0] for s in samples])
        y = np.asarray([s[1] for s in samples], np.int32)
        return MiniBatch(x, y)

    def signature(self) -> dict:
        sig = {"source": type(self.ds).__name__, "n": len(self)}
        crop = getattr(self.ds, "crop", None)
        if crop is not None:
            sig["crop"] = list(crop)
            sig["train"] = bool(getattr(self.ds, "train", False))
        return sig


class ExecutorDataSet(DataSet):
    """``ExecutorDataSet(source, batch_size, workers=4, depth=2)`` — the
    production feed path replacing the single-threaded PrefetchDataSet.

    DataSet contract: ``__iter__`` yields one epoch at the plan's CURRENT
    epoch without advancing it; ``shuffle()`` advances (ShardedDataSet
    semantics), which is what the Optimizer's end-of-epoch call and
    resume replay rely on."""

    def __init__(self, source: SampleSource, batch_size: Optional[int] = None,
                 workers: int = 4, depth: int = 2, seed: int = 0,
                 shuffle: bool = True, mode: str = "global",
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 plan: Optional[EpochPlan] = None, join_timeout: float = 5.0):
        if plan is None:
            if batch_size is None:
                raise ValueError("ExecutorDataSet needs batch_size (or an "
                                 "explicit plan)")
            plan = EpochPlan(len(source), batch_size, seed=seed,
                             shuffle=shuffle, mode=mode,
                             process_index=process_index,
                             process_count=process_count)
        self.source = source
        self.plan = plan
        self.workers = max(1, int(workers))
        self.depth = max(1, int(depth))
        self.join_timeout = float(join_timeout)
        # max_inflight proves the backpressure bound (<= depth);
        # join_timeouts counts shutdowns that leaked a worker thread
        self.stats = {"max_inflight": 0, "batches": 0, "join_timeouts": 0}

    # ------------------------------------------------------------ iteration
    def __iter__(self) -> Iterator[MiniBatch]:
        epoch = int(self.plan.epoch)
        idx = self.plan.batch_indices(epoch)
        steps = int(idx.shape[0])
        if steps == 0:
            return
        bs = int(idx.shape[1])
        total = steps * bs
        depth = self.depth
        cond = threading.Condition()
        state = {"ticket": 0, "consumed": 0, "stop": False, "err": None}
        buffers: dict = {}  # batch -> fixed slot list (the ticket buffers)
        filled: dict = {}   # batch -> slots filled so far

        def work():
            try:
                while True:
                    with cond:
                        while True:
                            if state["stop"] or state["err"] is not None:
                                return
                            t = state["ticket"]
                            if t >= total:
                                return
                            b = t // bs
                            # backpressure: never more than `depth`
                            # batches past the consumer
                            if b - state["consumed"] < depth:
                                state["ticket"] = t + 1
                                break
                            cond.wait(0.1)
                        inflight = b - state["consumed"] + 1
                        if inflight > self.stats["max_inflight"]:
                            self.stats["max_inflight"] = inflight
                    sample = self.source.load(int(idx[b, t % bs]), epoch)
                    with cond:
                        slot = buffers.get(b)
                        if slot is None:
                            slot = buffers[b] = [None] * bs
                        slot[t % bs] = sample
                        filled[b] = filled.get(b, 0) + 1
                        if filled[b] == bs:
                            cond.notify_all()
            except BaseException as e:  # surfaced on the consumer side
                with cond:
                    if state["err"] is None:
                        state["err"] = e
                    cond.notify_all()

        threads = [threading.Thread(target=work, daemon=True,
                                    name=f"bigdl-pipe-w{i}")
                   for i in range(self.workers)]
        for t in threads:
            t.start()
        try:
            for b in range(steps):
                with cond:
                    while filled.get(b, 0) < bs and state["err"] is None:
                        cond.wait(0.1)
                    if state["err"] is not None:
                        raise state["err"]
                    samples = buffers.pop(b)
                    filled.pop(b, None)
                    state["consumed"] = b + 1
                    cond.notify_all()
                self.stats["batches"] += 1
                yield self.source.collate(samples)
        finally:
            # normal exhaustion AND early exit (break / GeneratorExit /
            # a raised worker error): unwind the pool
            with cond:
                state["stop"] = True
                cond.notify_all()
            deadline = time.monotonic() + self.join_timeout
            for t in threads:
                t.join(timeout=max(0.0, deadline - time.monotonic()))
            leaked = [t.name for t in threads if t.is_alive()]
            if leaked:
                self.stats["join_timeouts"] += 1
                logger.warning(
                    "pipeline executor: %d worker thread(s) failed to exit "
                    "within %.1fs: %s (daemon threads — they cannot block "
                    "process exit, but a stuck sample source should be "
                    "investigated)", len(leaked), self.join_timeout, leaked)

    # ------------------------------------------------------------- DataSet
    def size(self) -> int:
        return len(self.source)

    def shuffle(self, seed: Optional[int] = None) -> None:
        self.plan.advance(seed)

    def signature(self) -> dict:
        """Pipeline provenance for perf JSON lines."""
        return {"workers": self.workers, "depth": self.depth,
                "plan": self.plan.signature(),
                **self.source.signature()}


def as_executor(ds, workers: int, depth: int = 2,
                seed: int = 0) -> Optional[ExecutorDataSet]:
    """Convert a known DataSet into its executor-fed equivalent, or None
    when the type carries no (source, plan) decomposition — callers fall
    back to the thread-wrapper prefetch for those."""
    from bigdl_tpu.dataset.dataset import BatchDataSet
    from bigdl_tpu.dataset.distributed import ShardedDataSet
    from bigdl_tpu.dataset.streaming import _StreamingImageBase

    if isinstance(ds, ExecutorDataSet):
        ds.workers = max(1, int(workers))
        ds.depth = max(1, int(depth))
        return ds
    if isinstance(ds, _StreamingImageBase):
        if getattr(ds, "_batch_cap", None) is not None:
            # partitioned record sets cap batches at the smallest
            # partition — schedule lives outside the plan; keep legacy
            return None
        src = StreamingSampleSource(ds)
        plan = EpochPlan(len(src), ds.batch_size, seed=ds.seed,
                         shuffle=ds.train, process_index=0,
                         process_count=1, epoch=ds._epoch)
        return ExecutorDataSet(src, workers=workers, depth=depth, plan=plan)
    if isinstance(ds, ShardedDataSet):
        src = ArraySampleSource(ds.features, ds.labels)
        plan = EpochPlan(len(src), ds.local_batch, seed=ds._seed,
                         shuffle=ds._shuffle, mode="global",
                         process_index=ds.pi, process_count=ds.pc,
                         epoch=ds._epoch)
        return ExecutorDataSet(src, workers=workers, depth=depth, plan=plan)
    if isinstance(ds, BatchDataSet):
        src = ArraySampleSource(ds.features, ds.labels)
        plan = EpochPlan(len(src), ds.batch_size, seed=seed,
                         shuffle=ds._shuffle, process_index=0,
                         process_count=1)
        return ExecutorDataSet(src, workers=workers, depth=depth, plan=plan)
    return None
