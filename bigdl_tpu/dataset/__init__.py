from bigdl_tpu.dataset.transformer import (
    Transformer, ChainedTransformer, FnTransformer,
)
from bigdl_tpu.dataset.dataset import (
    DataSet, LocalArrayDataSet, BatchDataSet, MiniBatch,
)
from bigdl_tpu.dataset import mnist, cifar, image, text, native
from bigdl_tpu.dataset.native import NativePrefetchDataSet
from bigdl_tpu.dataset.prefetch import PrefetchDataSet
from bigdl_tpu.dataset.folder import (
    ImageFolderDataSet, load_image_folder, list_image_folder,
)
from bigdl_tpu.dataset.distributed import ShardedDataSet, host_shard
from bigdl_tpu.dataset.recordfile import (
    RecordWriter, RecordReader, write_image_shards, list_shards,
)
from bigdl_tpu.dataset.streaming import (
    StreamingImageFolder, RecordImageDataSet,
)
from bigdl_tpu.dataset.mixup import CutMix, Mixup, MixupCriterion
from bigdl_tpu.dataset.pipeline import (
    EpochPlan, ExecutorDataSet, ArraySampleSource, StreamingSampleSource,
    DeviceBatch, StagedDataSet, wrap_pipeline,
)
