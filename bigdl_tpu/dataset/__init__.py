from bigdl_tpu.dataset.transformer import (
    Transformer, ChainedTransformer, FnTransformer,
)
from bigdl_tpu.dataset.dataset import (
    DataSet, LocalArrayDataSet, BatchDataSet, MiniBatch,
)
from bigdl_tpu.dataset import mnist, cifar, image, text, native
from bigdl_tpu.dataset.native import NativePrefetchDataSet
