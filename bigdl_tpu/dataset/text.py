"""Text pipeline (reference dataset/text/: LabeledSentence,
LabeledSentenceToSample; models/rnn/Utils.scala WordTokenizer + dictionary).

Provides tokenization, dictionary building with vocab-size cap (rare words
-> UNK), fixed-length padding (the reference pads sentences to max length,
dataset/text/LabeledSentenceToSample.scala), and one-hot/ids batch export.
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Iterable, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.transformer import Transformer

__all__ = ["tokenize", "Dictionary", "pad_sequences", "pack_sequences",
           "LabeledSentence", "sentences_to_ids", "LabeledSentenceToSample"]

PAD, UNK = "<pad>", "<unk>"
_WORD_RE = re.compile(r"[A-Za-z']+|[.,!?;]")


def tokenize(text: str) -> list[str]:
    """Simple word tokenizer (reference WordTokenizer in models/rnn/Utils)."""
    return _WORD_RE.findall(text.lower())


class LabeledSentence:
    """(tokens, label) pair (reference dataset/text/LabeledSentence)."""

    __slots__ = ("data", "label")

    def __init__(self, data: Sequence, label: int):
        self.data = list(data)
        self.label = label


class Dictionary:
    """Word->id mapping capped at vocab_size by frequency
    (reference models/rnn/Utils dictionary builder: keeps the vocabSize most
    frequent words, the rest map to UNK). id 0 = PAD, id 1 = UNK."""

    def __init__(self, corpus_tokens: Iterable[Sequence[str]],
                 vocab_size: Optional[int] = None):
        counts = Counter()
        for toks in corpus_tokens:
            counts.update(toks)
        most = counts.most_common(vocab_size)
        self.word2id = {PAD: 0, UNK: 1}
        for w, _ in most:
            self.word2id[w] = len(self.word2id)
        self.id2word = {i: w for w, i in self.word2id.items()}

    def __len__(self):
        return len(self.word2id)

    def lookup(self, word: str) -> int:
        return self.word2id.get(word, 1)

    def ids(self, tokens: Sequence[str]) -> list[int]:
        return [self.lookup(t) for t in tokens]


def pad_sequences(seqs: Sequence[Sequence[int]], max_len: int,
                  pad_id: int = 0, truncate_from_end: bool = True):
    """Fixed-length (N, max_len) int32 — static shapes for XLA (reference
    LabeledSentenceToSample pads to the batch max; we pad to a fixed
    max_len because jit recompiles per shape)."""
    out = np.full((len(seqs), max_len), pad_id, np.int32)
    for i, s in enumerate(seqs):
        s = list(s)[:max_len] if truncate_from_end else list(s)[-max_len:]
        out[i, :len(s)] = s
    return out


def pack_sequences(seqs: Sequence[Sequence[int]], max_len: int,
                   pad_id: int = 0):
    """Greedy first-fit packing of variable-length token sequences into
    fixed (N, max_len) rows plus a parallel segment-id array for
    ``nn.make_segment_mask`` — the static-shape packed-LM recipe (one
    row holds several documents; attention stays within each). Documents
    longer than max_len are truncated. Returns (tokens, segments), both
    int32; segment ids start at 1 per row, 0 marks padding."""
    rows: list[list[int]] = []     # flattened token ids per row
    segs: list[list[int]] = []
    free: list[int] = []           # remaining capacity per row
    for s in seqs:
        s = list(s)[:max_len]
        if not s:
            continue
        for i, cap in enumerate(free):
            if len(s) <= cap:
                seg_id = segs[i][-1] + 1
                rows[i].extend(s)
                segs[i].extend([seg_id] * len(s))
                free[i] = cap - len(s)
                break
        else:
            rows.append(list(s))
            segs.append([1] * len(s))
            free.append(max_len - len(s))
    tokens = np.full((len(rows), max_len), pad_id, np.int32)
    segments = np.zeros((len(rows), max_len), np.int32)
    for i, (r, g) in enumerate(zip(rows, segs)):
        tokens[i, :len(r)] = r
        segments[i, :len(g)] = g
    return tokens, segments


def sentences_to_ids(sentences: Sequence[LabeledSentence],
                     dictionary: Dictionary, max_len: int):
    """-> (ids (N, max_len) int32, labels (N,) int32)"""
    ids = pad_sequences([dictionary.ids(s.data) for s in sentences], max_len)
    labels = np.asarray([s.label for s in sentences], np.int32)
    return ids, labels


class LabeledSentenceToSample(Transformer):
    """Transformer stage: LabeledSentence -> (ids[max_len] int32, label)
    sample pairs (reference dataset/text/LabeledSentenceToSample.scala —
    fixed-length padding; fixed here rather than per-batch because XLA
    recompiles per shape). Composes with ``>>`` like any Transformer."""

    def __init__(self, dictionary: Dictionary, max_len: int):
        self.dictionary = dictionary
        self.max_len = max_len

    def __call__(self, it):
        for s in it:
            ids = pad_sequences([self.dictionary.ids(s.data)],
                                self.max_len)[0]
            yield ids, np.int32(s.label)
