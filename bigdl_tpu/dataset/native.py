"""ctypes bindings for the native C++ input pipeline
(``native/bigdl_native.cpp``) — the TPU-native analog of the reference's
multi-threaded decode/augment path (image/MTLabeledBGRImgToBatch.scala:48-133)
and its raw dataset readers (models/lenet/Utils.scala idx parsing,
models/vgg CIFAR bins).

``NativePrefetchDataSet`` plugs into the same :class:`DataSet` protocol the
Optimizer consumes: worker threads crop/flip/normalize raw uint8 samples on
the host while the device runs the previous step, so step time is
max(compute, input) instead of their sum.

Falls back cleanly: :func:`available` is False when the shared library
can't be built (no g++); callers then use the pure-python transformers in
``bigdl_tpu.dataset.image``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.dataset import DataSet, MiniBatch

__all__ = ["available", "NativePrefetchDataSet", "read_idx", "read_cifar10"]

# Native sources ship as package data (bigdl_tpu/native/); when the install
# is read-only (system site-packages) the build happens in a per-user cache
# dir instead, so `pip install bigdl-tpu` degrades gracefully rather than
# failing at first import.
_PKG_NATIVE_DIR = os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "native")


def _build_dir() -> str:
    if (os.access(_PKG_NATIVE_DIR, os.W_OK)
            or os.path.exists(os.path.join(_PKG_NATIVE_DIR,
                                           "libbigdl_native.so"))):
        # writable (dev checkout / user install) or a wheel shipped a
        # prebuilt .so — build/load in place
        return _PKG_NATIVE_DIR
    cache = os.path.join(
        os.environ.get("XDG_CACHE_HOME",
                       os.path.join(os.path.expanduser("~"), ".cache")),
        "bigdl_tpu", "native")
    os.makedirs(cache, exist_ok=True)
    import filecmp
    import shutil
    for fname in ("bigdl_native.cpp", "Makefile"):
        src = os.path.join(_PKG_NATIVE_DIR, fname)
        dst = os.path.join(cache, fname)
        # copy only on content change, with a fresh dst mtime: mtime
        # comparison alone misfires on SOURCE_DATE_EPOCH wheels (stale .so
        # after upgrade), while unconditional copying would force a full
        # g++ rebuild on every process start
        if os.path.exists(src) and not (
                os.path.exists(dst) and filecmp.cmp(src, dst, shallow=False)):
            shutil.copyfile(src, dst)
    return cache


# resolved lazily in _load_impl(): computing the cache dir at import can
# raise (read-only install + unwritable HOME) and would break the
# graceful-degrade contract for every `import bigdl_tpu.dataset`
_NATIVE_DIR: Optional[str] = None
_LIB_PATH: Optional[str] = None

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False
_load_lock = threading.Lock()  # streaming workers probe concurrently


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    with _load_lock:
        if _load_attempted:
            return _lib
        _load_attempted = True
        _lib = _load_impl()
        return _lib


def _load_impl() -> Optional[ctypes.CDLL]:
    global _NATIVE_DIR, _LIB_PATH
    try:
        _NATIVE_DIR = _build_dir()
    except OSError:
        return None
    _LIB_PATH = os.path.join(_NATIVE_DIR, "libbigdl_native.so")
    try:  # always run make: incremental, and rebuilds a stale .so whose
        # symbols predate the current bindings (g++ is in the toolchain).
        # flock serializes concurrent builds across PROCESSES sharing the
        # filesystem (multi-host runs) — dlopen of a half-linked .so is
        # undefined behavior
        import fcntl

        with open(os.path.join(_NATIVE_DIR, ".build.lock"), "w") as lk:
            fcntl.flock(lk, fcntl.LOCK_EX)
            try:
                subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                               capture_output=True, timeout=120)
            finally:
                fcntl.flock(lk, fcntl.LOCK_UN)
    except Exception:
        if not os.path.exists(_LIB_PATH):
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
        _bind(lib)
    except (OSError, AttributeError):
        # AttributeError: prebuilt .so missing a newer symbol — fall back
        # to the pure-python paths rather than crashing available()
        return None
    return lib


def _bind(lib: ctypes.CDLL) -> None:
    lib.bt_pipeline_create.restype = ctypes.c_void_p
    lib.bt_pipeline_create.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
        ctypes.c_int, ctypes.c_int,
    ]
    lib.bt_pipeline_next.restype = ctypes.c_long
    lib.bt_pipeline_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                     ctypes.c_void_p]
    lib.bt_pipeline_batches_per_epoch.restype = ctypes.c_long
    lib.bt_pipeline_batches_per_epoch.argtypes = [ctypes.c_void_p]
    lib.bt_pipeline_destroy.restype = None
    lib.bt_pipeline_destroy.argtypes = [ctypes.c_void_p]
    lib.bt_read_idx.restype = ctypes.c_int64
    lib.bt_read_idx.argtypes = [ctypes.c_char_p,
                                ctypes.POINTER(ctypes.c_void_p),
                                ctypes.POINTER(ctypes.c_int64),
                                ctypes.POINTER(ctypes.c_int)]
    lib.bt_read_cifar10.restype = ctypes.c_int64
    lib.bt_read_cifar10.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                    ctypes.c_void_p, ctypes.c_int64]
    lib.bt_free.restype = None
    lib.bt_free.argtypes = [ctypes.c_void_p]
    lib.bt_augment_sample.restype = ctypes.c_int
    lib.bt_augment_sample.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p,
    ]
    lib.bt_jpeg_available.restype = ctypes.c_int
    lib.bt_jpeg_available.argtypes = []
    lib.bt_decode_jpeg.restype = ctypes.c_int
    lib.bt_decode_jpeg.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
    ]


def available() -> bool:
    """True when the native library is loadable (builds it if needed)."""
    return _load() is not None


def augment_sample_native(img: np.ndarray, out: np.ndarray, off_h: int,
                          off_w: int, flip: bool, mean: np.ndarray,
                          std: np.ndarray) -> None:
    """One-pass crop+flip+normalize (C ``bt_augment_sample``; GIL released
    during the call, so the streaming decode pool scales across cores).
    ``img``: contiguous uint8 (H, W, C); ``out``: float32 (ch, cw, C)."""
    lib = _load()
    mean = np.ascontiguousarray(mean, np.float32)
    std = np.ascontiguousarray(std, np.float32)
    ok = lib.bt_augment_sample(
        img.ctypes.data_as(ctypes.c_void_p), img.shape[0], img.shape[1],
        img.shape[2], out.ctypes.data_as(ctypes.c_void_p), out.shape[0],
        out.shape[1], off_h, off_w, int(flip),
        mean.ctypes.data_as(ctypes.c_void_p),
        std.ctypes.data_as(ctypes.c_void_p))
    if not ok:
        raise ValueError(
            f"crop {out.shape[:2]} at offset ({off_h}, {off_w}) falls "
            f"outside source image {img.shape[:2]} — is short_side "
            f"smaller than the crop?")


def jpeg_available() -> bool:
    """True when the native lib was built against libjpeg.
    BIGDL_NO_NATIVE_JPEG=1 forces the PIL path (A/B benchmarking)."""
    if os.environ.get("BIGDL_NO_NATIVE_JPEG"):
        return False
    lib = _load()
    try:
        return bool(lib and lib.bt_jpeg_available())
    except AttributeError:  # stale .so predating the decode symbols
        return False


def decode_jpeg(raw: bytes, short_side: Optional[int] = None,
                fill: Optional[tuple[int, int]] = None):
    """Native JPEG decode+resize (libjpeg DCT scaling + bilinear to the
    exact target — the C counterpart of streaming.decode_resize). Returns
    an RGB uint8 (H, W, 3) array, or None when the native path can't
    serve this input (caller falls back to PIL). GIL released by ctypes,
    so a thread pool of decoders scales across cores."""
    if not jpeg_available():
        return None
    lib = _load()
    if short_side is not None:
        mode, th, tw = 0, int(short_side), 0
    else:
        mode, (th, tw) = 1, (int(fill[0]), int(fill[1]))
    out = ctypes.c_void_p()
    oh, ow = ctypes.c_int(), ctypes.c_int()
    rc = lib.bt_decode_jpeg(raw, len(raw), mode, th, tw,
                            ctypes.byref(out), ctypes.byref(oh),
                            ctypes.byref(ow))
    if rc != 0:
        return None
    try:
        n = oh.value * ow.value * 3
        img = np.frombuffer(
            ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8 * n)).contents,
            dtype=np.uint8).reshape(oh.value, ow.value, 3).copy()
    finally:
        lib.bt_free(out)
    return img


class NativePrefetchDataSet(DataSet):
    """Endless-or-one-epoch batch source backed by the C++ worker pool.

    ``images``: uint8 array [n, h, w, c]; ``labels``: int array [n].
    ``crop`` crops to (crop_h, crop_w) (random when training, else center);
    ``mean``/``std`` are per-channel, applied as ``(x - mean)/std`` on raw
    0-255 values. One python iterator epoch yields ``batches_per_epoch``
    minibatches; with ``train=True`` the C++ side keeps prefetching across
    the epoch boundary (reshuffling every epoch), so epoch N+1's first batch
    is already in the queue when epoch N ends.
    """

    def __init__(self, images: np.ndarray, labels: np.ndarray,
                 batch_size: int, crop: Optional[tuple[int, int]] = None,
                 train: bool = False, hflip: Optional[bool] = None,
                 mean: Optional[Sequence[float]] = None,
                 std: Optional[Sequence[float]] = None,
                 shuffle: Optional[bool] = None, seed: int = 0,
                 n_threads: int = 4, queue_cap: int = 8):
        lib = _load()
        if lib is None:
            raise RuntimeError(
                "native library unavailable — use the python pipeline "
                "(bigdl_tpu.dataset.image) instead")
        self._lib = lib
        images = np.ascontiguousarray(images, dtype=np.uint8)
        if images.ndim == 3:
            images = images[..., None]
        n, h, w, c = images.shape
        labels = np.ascontiguousarray(labels, dtype=np.int32)
        assert len(labels) == n
        self._images, self._labels = images, labels  # keep alive (borrowed)
        crop_h, crop_w = crop if crop is not None else (h, w)
        self.batch_size = batch_size
        self.crop_h, self.crop_w, self.channels = crop_h, crop_w, c
        mean_arr = (np.asarray(mean, np.float32) if mean is not None
                    else np.zeros(c, np.float32))
        std_arr = (np.asarray(std, np.float32) if std is not None
                   else np.ones(c, np.float32))
        assert mean_arr.size == c and std_arr.size == c
        self._mean, self._std = mean_arr, std_arr
        self._shuffle = train if shuffle is None else shuffle
        self._hflip = train if hflip is None else hflip
        self._train = train
        self._seed = seed
        self._n_threads, self._queue_cap = n_threads, queue_cap
        self.batches_per_epoch = n // batch_size
        # train mode: one persistent endless pipeline that prefetches across
        # epoch boundaries; eval mode: a fresh one-epoch pipeline per
        # iteration (the Validator re-iterates the dataset every trigger)
        self._handle = self._create(loop=True) if train else None

    def _create(self, loop: bool):
        h_, w_ = self._images.shape[1:3]
        handle = self._lib.bt_pipeline_create(
            self._images.ctypes.data_as(ctypes.c_void_p),
            len(self._images), h_, w_, self.channels,
            self._labels.ctypes.data_as(ctypes.c_void_p), self.batch_size,
            self.crop_h, self.crop_w, int(self._train), int(self._hflip),
            self._mean.ctypes.data_as(ctypes.c_void_p),
            self._std.ctypes.data_as(ctypes.c_void_p),
            int(self._shuffle), int(loop), self._seed,
            self._n_threads, self._queue_cap)
        if not handle:
            raise ValueError("bt_pipeline_create failed (check shapes/batch)")
        return handle

    def __iter__(self):
        img_buf = np.empty((self.batch_size, self.crop_h, self.crop_w,
                            self.channels), np.float32)
        lab_buf = np.empty(self.batch_size, np.int32)
        handle = self._handle if self._train else self._create(loop=False)
        try:
            for _ in range(self.batches_per_epoch):
                t = self._lib.bt_pipeline_next(
                    handle, img_buf.ctypes.data_as(ctypes.c_void_p),
                    lab_buf.ctypes.data_as(ctypes.c_void_p))
                if t < 0:
                    return
                yield MiniBatch(img_buf.copy(), lab_buf.copy())
        finally:
            if not self._train:
                self._lib.bt_pipeline_destroy(handle)

    def size(self) -> int:
        return len(self._images)

    def shuffle(self, seed=None):
        """No-op: the native side reshuffles each epoch from its seed."""

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.bt_pipeline_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def read_idx(path: str) -> np.ndarray:
    """Read an MNIST idx/ubyte file via the native reader (reference
    models/lenet/Utils.scala raw readers). ``.gz`` files are transparently
    decompressed first (parity with the python loader in
    ``bigdl_tpu.dataset.mnist``, which stays the fallback when the native
    lib is unavailable)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    if path.endswith(".gz"):
        import gzip
        import tempfile
        with gzip.open(path, "rb") as f:
            raw = f.read()
        with tempfile.NamedTemporaryFile(suffix=".idx") as tmp:
            tmp.write(raw)
            tmp.flush()
            return read_idx(tmp.name)
    out = ctypes.c_void_p()
    dims = (ctypes.c_int64 * 8)()
    ndim = ctypes.c_int()
    total = lib.bt_read_idx(path.encode(), ctypes.byref(out), dims,
                            ctypes.byref(ndim))
    if total < 0:
        raise IOError(f"failed to read idx file {path!r}")
    try:
        shape = tuple(dims[i] for i in range(ndim.value))
        buf = ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8 * total))
        arr = np.frombuffer(buf.contents, dtype=np.uint8).reshape(shape).copy()
    finally:
        lib.bt_free(out)
    return arr


def read_cifar10(paths: Sequence[str]) -> tuple[np.ndarray, np.ndarray]:
    """Read CIFAR-10 .bin shards via the native reader; returns NHWC uint8
    images + int32 labels (reference dataset CIFAR bin format)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    per_shard = 10000
    images = np.empty((per_shard * len(paths), 32, 32, 3), np.uint8)
    labels = np.empty(per_shard * len(paths), np.int32)
    count = 0
    for p in paths:
        got = lib.bt_read_cifar10(
            p.encode(),
            images[count:].ctypes.data_as(ctypes.c_void_p),
            labels[count:].ctypes.data_as(ctypes.c_void_p),
            len(images) - count)
        if got < 0:
            raise IOError(f"failed to read cifar bin {p!r}")
        count += got
    return images[:count], labels[:count]
