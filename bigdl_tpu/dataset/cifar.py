"""CIFAR-10 binary reader (reference models/vgg & resnet pipelines load
CIFAR via dataset/image BGR transformers; the on-disk format here is the
standard cifar-10-binary 3073-byte records: 1 label + 3072 CHW pixels).

Returns NHWC uint8 images (N, 32, 32, 3) in RGB order and int32 labels.
The reference's per-channel training stats are exposed as TRAIN_MEAN/STD
(models/vgg/Train uses 0.4-ish RGB means over [0,1] pixels).
"""

from __future__ import annotations

import os
from typing import Sequence

import numpy as np

__all__ = ["load_cifar10", "TRAIN_MEAN", "TRAIN_STD"]

# RGB, over pixels scaled to [0,1]
TRAIN_MEAN = (0.4914, 0.4822, 0.4465)
TRAIN_STD = (0.2470, 0.2435, 0.2616)

_REC = 3073


def _read_bin(path: str):
    raw = np.fromfile(path, np.uint8)
    assert raw.size % _REC == 0, f"{path}: not a cifar-10 binary file"
    raw = raw.reshape(-1, _REC)
    labels = raw[:, 0].astype(np.int32)
    imgs = raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return imgs, labels


def load_cifar10(folder: str, train: bool = True):
    names = ([f"data_batch_{i}.bin" for i in range(1, 6)] if train
             else ["test_batch.bin"])
    imgs, labels = [], []
    for n in names:
        p = os.path.join(folder, n)
        if not os.path.exists(p):
            raise FileNotFoundError(p)
        i, l = _read_bin(p)
        imgs.append(i)
        labels.append(l)
    return np.concatenate(imgs), np.concatenate(labels)
