"""Streaming image input pipeline with per-sample augmentation.

The ImageNet-scale *training* path (reference
image/MTLabeledBGRImgToBatch.scala:48-133 feeding
models/inception/ImageNet2012.scala:28-64): a pool of worker threads
decodes JPEGs and applies **per-sample** random-crop + horizontal-flip +
normalize, assembling fixed-shape float batches while the device runs the
previous step — without ever materializing the dataset in memory (the
round-1 gap: the C++ prefetcher needed the full uint8 array host-side).

Division of labor per sample:
* JPEG decode — PIL → libjpeg, GIL released, with draft-mode DCT
  downscaling (decode at ~the target scale instead of full resolution);
* crop/flip/normalize — one pass in C (``bt_augment_sample``,
  native/bigdl_native.cpp), GIL released via ctypes; numpy fallback when
  the native library is unavailable;
* crop offsets / flip coin — per-(epoch, sample) seeded host RNG, so a
  batch is bit-reproducible regardless of thread scheduling (the ticket
  seeding idea of the C++ pipeline, applied per sample).

Batches are delivered in order via a bounded sliding window of per-sample
futures — the python analog of the C++ pipeline's ticket queue.

Sources: :class:`StreamingImageFolder` (files on disk) and
:class:`RecordImageDataSet` (sharded record files, bigdl_tpu.dataset.
recordfile — the SequenceFile-analog ImageNet path).
"""

from __future__ import annotations

import io
import threading
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterator, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.dataset import DataSet, MiniBatch
from bigdl_tpu.dataset import recordfile as rf

__all__ = ["StreamingImageFolder", "RecordImageDataSet",
           "decode_resize", "augment_sample", "random_resized_crop"]


def random_resized_crop(target: tuple[int, int],
                        scale: tuple[float, float] = (0.08, 1.0),
                        ratio: tuple[float, float] = (3 / 4, 4 / 3),
                        attempts: int = 10):
    """Inception-style train augmentation: sample a crop covering a
    random area fraction at a random aspect ratio, resized to ``target``
    (reference-era pipelines use fixed-scale random crops; this is the
    modern ImageNet recipe). Returns an ``augment`` callable for the
    streaming datasets — pair with ``short_side=None`` disabled cropping
    by setting the dataset ``crop=target`` (the final center/random crop
    then becomes a no-op on an exactly-target-sized image).

    Usage::

        ds = RecordImageDataSet(shards, batch, crop=(224, 224),
                                train=True, short_side=256,
                                augment=random_resized_crop((224, 224)))
    """
    th, tw = target

    def aug(img: np.ndarray, rng: np.random.RandomState) -> np.ndarray:
        from PIL import Image

        h, w = img.shape[:2]
        area = h * w
        for _ in range(attempts):
            a = rng.uniform(*scale) * area
            log_r = rng.uniform(np.log(ratio[0]), np.log(ratio[1]))
            r = float(np.exp(log_r))
            cw = int(round(np.sqrt(a * r)))
            ch = int(round(np.sqrt(a / r)))
            if cw <= w and ch <= h:
                y0 = rng.randint(0, h - ch + 1)
                x0 = rng.randint(0, w - cw + 1)
                crop = img[y0:y0 + ch, x0:x0 + cw]
                break
        else:  # fallback: center crop of the largest fitting window
            cw = ch = min(h, w)
            y0, x0 = (h - ch) // 2, (w - cw) // 2
            crop = img[y0:y0 + ch, x0:x0 + cw]
        if crop.shape[:2] != (th, tw):
            crop = np.asarray(
                Image.fromarray(crop).resize((tw, th), Image.BILINEAR))
        return crop

    return aug


def decode_resize(raw: bytes, short_side: Optional[int],
                  fill: Optional[tuple[int, int]] = None) -> np.ndarray:
    """Decode encoded image bytes -> RGB uint8 HWC, resized.

    ``short_side`` given: scale so min(h, w) == short_side (the train
    convention — leaves room for random crops). Else ``fill`` (th, tw):
    scale so the crop fills the image (the eval scale-to-fill convention of
    the round-1 folder loader / reference BGRImage.readImage).

    JPEG sources decode in C (libjpeg + DCT scaling + bilinear,
    native/bigdl_native.cpp bt_decode_jpeg) when the native lib is built
    with jpeg support — the whole decode runs GIL-free so the worker pool
    scales across cores; PIL serves every other case.
    """
    if raw[:2] == b"\xff\xd8":  # JPEG magic
        from bigdl_tpu.dataset import native

        img = native.decode_jpeg(raw, short_side=short_side,
                                 fill=None if short_side else fill)
        if img is not None:
            return img

    from PIL import Image

    with Image.open(io.BytesIO(raw)) as im:
        if short_side is not None:
            # JPEG draft mode: the decoder downscales during DCT — the
            # single biggest win in a JPEG input pipeline
            im.draft("RGB", (short_side, short_side))
            scale = short_side / min(im.width, im.height)
            tw = max(short_side, int(round(im.width * scale)))
            th = max(short_side, int(round(im.height * scale)))
        else:
            fh, fw = fill
            im.draft("RGB", (fw, fh))
            scale = max(fh / im.height, fw / im.width)
            tw = max(fw, int(round(im.width * scale)))
            th = max(fh, int(round(im.height * scale)))
        im = im.convert("RGB")
        if (tw, th) != im.size:
            im = im.resize((tw, th))
        return np.asarray(im, dtype=np.uint8)


def augment_sample(img: np.ndarray, crop: tuple[int, int],
                   mean: np.ndarray, std: np.ndarray,
                   rng: Optional[np.random.RandomState],
                   hflip: bool) -> np.ndarray:
    """Crop (random when ``rng`` given, else center) + optional flip +
    per-channel normalize. One C pass when the native lib is loadable."""
    ch, cw = crop
    h, w = img.shape[:2]
    if h < ch or w < cw:
        # validate before either backend: the C path would reject this and
        # the numpy path would silently mis-crop
        raise ValueError(
            f"crop {crop} larger than decoded image ({h}, {w}) — is "
            f"short_side smaller than the crop?")
    if rng is not None:
        off_h = rng.randint(0, h - ch + 1) if h > ch else 0
        off_w = rng.randint(0, w - cw + 1) if w > cw else 0
        flip = hflip and rng.rand() < 0.5
    else:
        off_h, off_w = (h - ch) // 2, (w - cw) // 2
        flip = False

    from bigdl_tpu.dataset import native

    if native.available():
        img = np.ascontiguousarray(img)
        out = np.empty((ch, cw, img.shape[2]), np.float32)
        native.augment_sample_native(img, out, off_h, off_w, flip,
                                     mean, std)
        return out
    cropped = img[off_h:off_h + ch, off_w:off_w + cw]
    if flip:
        cropped = cropped[:, ::-1]
    return (cropped.astype(np.float32) - mean) / std


class _StreamingImageBase(DataSet):
    """Shared pool/window/permutation machinery; subclasses supply
    ``_read_raw(j) -> (encoded bytes, label)`` and ``_num_samples``."""

    def __init__(self, batch_size: int, crop: tuple[int, int] = (224, 224),
                 train: bool = False, short_side: Optional[int] = None,
                 mean: Optional[Sequence[float]] = None,
                 std: Optional[Sequence[float]] = None,
                 hflip: Optional[bool] = None,
                 augment: Optional[Callable] = None,
                 seed: int = 0, n_threads: int = 8, window: int = 4,
                 drop_remainder: bool = True):
        self.batch_size = batch_size
        self.crop = tuple(crop)
        self.train = train
        # train default: the standard 256-for-224 headroom ratio so random
        # crops see translation jitter; eval default: scale-to-fill
        self.short_side = (short_side if short_side is not None
                           else (int(round(max(crop) * 8 / 7)) if train
                                 else None))
        self.mean = (np.asarray(mean, np.float32) if mean is not None
                     else np.zeros(3, np.float32))
        self.std = (np.asarray(std, np.float32) if std is not None
                    else np.ones(3, np.float32))
        self.hflip = train if hflip is None else hflip
        self.augment = augment  # optional (uint8 img, rng) -> uint8 img
        self.seed = seed
        self.n_threads = n_threads
        self.window = max(1, window)
        self.drop_remainder = drop_remainder
        self._epoch = 0

    # ---- subclass API
    def _read_raw(self, j: int) -> tuple[bytes, int]:
        raise NotImplementedError

    def _num_samples(self) -> int:
        raise NotImplementedError

    # ---- per-sample path (runs on a worker thread)
    def _load_sample(self, j: int, epoch: int) -> tuple[np.ndarray, int]:
        raw, label = self._read_raw(j)
        img = decode_resize(raw, self.short_side,
                            fill=None if self.short_side else self.crop)
        rng = None
        if self.train:
            # per-(epoch, sample) seed: reproducible independent of which
            # worker thread runs this sample
            mix = (self.seed * 0x9E3779B9 + epoch * 0x85EBCA6B + j) \
                & 0xFFFFFFFF
            rng = np.random.RandomState(mix)
            if self.augment is not None:
                img = self.augment(img, rng)
        x = augment_sample(img, self.crop, self.mean, self.std, rng,
                           self.hflip)
        return x, label

    def __iter__(self) -> Iterator[MiniBatch]:
        n = self._num_samples()
        bs = self.batch_size
        epoch = self._epoch
        if self.train:
            self._epoch += 1
            order = np.random.RandomState(
                (self.seed + epoch) & 0xFFFFFFFF).permutation(n)
        else:
            order = np.arange(n)
        n_batches = n // bs if self.drop_remainder else -(-n // bs)
        cap = getattr(self, "_batch_cap", None)
        if cap is not None:
            n_batches = min(n_batches, cap(bs))
        with ThreadPoolExecutor(max_workers=self.n_threads) as ex:
            pending: deque = deque()

            def submit(bi: int) -> None:
                idx = order[bi * bs:(bi + 1) * bs]
                pending.append([ex.submit(self._load_sample, int(j), epoch)
                                for j in idx])

            for bi in range(min(self.window, n_batches)):
                submit(bi)
            nxt = min(self.window, n_batches)
            for _ in range(n_batches):
                futs = pending.popleft()
                samples = [f.result() for f in futs]
                if nxt < n_batches:
                    submit(nxt)
                    nxt += 1
                x = np.stack([s[0] for s in samples])
                y = np.asarray([s[1] for s in samples], np.int32)
                yield MiniBatch(x, y)

    def size(self) -> int:
        return self._num_samples()

    def shuffle(self, seed=None):
        """Reshuffle happens per epoch from (seed + epoch); an explicit
        seed restarts the schedule."""
        if seed is not None:
            self.seed, self._epoch = seed, 0


class StreamingImageFolder(_StreamingImageBase):
    """Stream ``root/<class>/*.jpg`` with per-sample train augmentation —
    the lazy ImageNet folder path (files are read and decoded per batch;
    nothing is materialized)."""

    def __init__(self, root: str, batch_size: int, **kw):
        from bigdl_tpu.dataset.folder import list_image_folder

        self.paths, self.labels, self.classes = list_image_folder(root)
        super().__init__(batch_size, **kw)

    def _read_raw(self, j: int) -> tuple[bytes, int]:
        with open(self.paths[j], "rb") as f:
            return f.read(), int(self.labels[j])

    def _num_samples(self) -> int:
        return len(self.paths)


class RecordImageDataSet(_StreamingImageBase):
    """Stream image records from sharded record files (the
    SequenceFile-analog ImageNet path, bigdl_tpu.dataset.recordfile).

    ``shards``: directory, glob, or explicit list. ``shard=(i, k)``
    restricts to shard files ``i::k`` — per-host partitioning for
    multi-process training (the locality feeding that replaces
    ZippedPartitionsWithLocalityRDD). Partitioned datasets cap their
    batch count at the SMALLEST partition's so every host steps the same
    number of times (unequal counts would deadlock the first collective
    after the shortest host stops — same guarantee as ShardedDataSet).
    """

    def __init__(self, shards, batch_size: int,
                 shard: Optional[tuple[int, int]] = None, **kw):
        all_files = (list(shards) if isinstance(shards, (list, tuple))
                     else rf.list_shards(shards))
        if not all_files:
            raise FileNotFoundError(f"no record shards under {shards!r}")
        counts = dict(zip(all_files, self._count_records(all_files)))
        files = all_files
        if shard is not None:
            i, k = shard
            files = all_files[i::k]
            # every host sees the full shard list, so each can compute the
            # global minimum partition size without communicating
            min_part = min(sum(counts[p] for p in all_files[j::k])
                           for j in range(k))
            self._batch_cap = lambda bs: max(min_part // bs, 0)
        self.shard_files = files
        # global sample id j -> (shard, record) via cumulative counts
        self._cum = np.cumsum([0] + [counts[p] for p in files])
        self._tls = threading.local()  # per-thread reader handles
        super().__init__(batch_size, **kw)

    @staticmethod
    def _count_records(files: list) -> list:
        """Parallel index reads — thousands of shards on network storage
        would otherwise serialize open+seek round-trips at startup."""
        def count(p):
            with rf.RecordReader(p) as r:
                return len(r)
        with ThreadPoolExecutor(max_workers=min(16, len(files))) as ex:
            return list(ex.map(count, files))

    def _reader(self, s: int) -> rf.RecordReader:
        cache = getattr(self._tls, "readers", None)
        if cache is None:
            cache = self._tls.readers = {}
        if s not in cache:
            cache[s] = rf.RecordReader(self.shard_files[s])
        return cache[s]

    def _read_raw(self, j: int) -> tuple[bytes, int]:
        s = int(np.searchsorted(self._cum, j, side="right")) - 1
        label, img = rf.unpack_image_record(
            self._reader(s).read(j - int(self._cum[s])))
        return img, label

    def _num_samples(self) -> int:
        return int(self._cum[-1])
