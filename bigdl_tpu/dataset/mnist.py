"""MNIST idx-ubyte reader (reference models/lenet/Utils.scala raw readers).

Returns NHWC float arrays — images (N, 28, 28, 1) uint8->float32, labels
(N,) int32 0-based (the reference emits 1-based labels for Lua parity; we
use 0-based throughout).
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

__all__ = ["load_images", "load_labels", "load_mnist",
           "TRAIN_MEAN", "TRAIN_STD"]

# Canonical MNIST training-set statistics (reference models/lenet/Utils.scala
# trainMean/trainStd constants).
TRAIN_MEAN = 0.13066047740239506
TRAIN_STD = 0.3081078

_IMG_MAGIC = 2051
_LBL_MAGIC = 2049


def _open(path: str):
    return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")


def load_images(path: str) -> np.ndarray:
    with _open(path) as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != _IMG_MAGIC:
            raise ValueError(f"bad MNIST image magic {magic} in {path}")
        data = np.frombuffer(f.read(n * rows * cols), np.uint8)
    return data.reshape(n, rows, cols, 1)


def load_labels(path: str) -> np.ndarray:
    with _open(path) as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != _LBL_MAGIC:
            raise ValueError(f"bad MNIST label magic {magic} in {path}")
        return np.frombuffer(f.read(n), np.uint8).astype(np.int32)


def load_mnist(folder: str, train: bool = True):
    """Load (images, labels) from the standard file names."""
    stem = "train" if train else "t10k"
    imgs = labels = None
    for suffix in ("", ".gz"):
        ip = os.path.join(folder, f"{stem}-images-idx3-ubyte{suffix}")
        lp = os.path.join(folder, f"{stem}-labels-idx1-ubyte{suffix}")
        if os.path.exists(ip) and os.path.exists(lp):
            imgs, labels = load_images(ip), load_labels(lp)
            break
    if imgs is None:
        raise FileNotFoundError(f"MNIST files for '{stem}' not in {folder}")
    return imgs, labels
