"""Sharded record-file dataset format + generator.

The reference ingests ImageNet as 512-image Hadoop SequenceFiles
(dl/.../dataset/DataSet.scala:384-455, generator
dl/.../models/utils/ImageNetSeqFileGenerator.scala): millions of small
JPEGs become a few thousand large sequential files, which is the only way a
pod-scale input pipeline avoids being metadata/IOPS-bound. This module is
the TPU-native analog — an ArrayRecord/TFRecord-style container:

Shard layout (``<prefix>-00000-of-00042.btr``)::

    [8B magic "BTRECv1\\n"]
    [record]*          record = [uint32 payload_len][payload bytes]
    [index]            uint64 file-offset of each record (count entries)
    [trailer]          [uint64 index_offset][uint64 count][8B magic]

The embedded index makes every record randomly addressable (seek + one
read), so a global shuffle is a permutation over (shard, record) pairs —
no windowed pseudo-shuffle needed. Image records carry
``[int32 label][encoded image bytes]`` (the original JPEG/PNG bytes,
NOT re-encoded — generation is IO-bound, not CPU-bound).

Writer/reader are pure python (sequential IO is already at disk speed);
the decode/augment hot path lives in ``bigdl_tpu.dataset.streaming``.
"""

from __future__ import annotations

import glob
import os
import struct
from concurrent.futures import ThreadPoolExecutor
from typing import Iterable, Iterator, Optional, Sequence

import numpy as np

__all__ = [
    "RecordWriter", "RecordReader", "pack_image_record",
    "unpack_image_record", "write_image_shards", "list_shards",
]

MAGIC = b"BTRECv1\n"
_TRAILER = struct.Struct("<QQ8s")  # index_offset, count, magic
_LEN = struct.Struct("<I")


class RecordWriter:
    """Append-only shard writer with an embedded index.

    >>> with RecordWriter(path) as w:
    ...     w.write(b"payload")
    """

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "wb")
        self._f.write(MAGIC)
        self._offsets: list[int] = []

    def write(self, payload: bytes) -> int:
        """Append one record; returns its index within the shard."""
        self._offsets.append(self._f.tell())
        self._f.write(_LEN.pack(len(payload)))
        self._f.write(payload)
        return len(self._offsets) - 1

    def close(self) -> None:
        if self._f is None:
            return
        index_offset = self._f.tell()
        if self._offsets:
            self._f.write(np.asarray(self._offsets, "<u8").tobytes())
        self._f.write(_TRAILER.pack(index_offset, len(self._offsets), MAGIC))
        self._f.close()
        self._f = None

    def __enter__(self) -> "RecordWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __len__(self) -> int:
        return len(self._offsets)


class RecordReader:
    """Random-access shard reader. Thread-compat: use one reader per
    thread (each holds its own file handle; offsets array is shared-safe).
    """

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "rb")
        self._f.seek(0, os.SEEK_END)
        end = self._f.tell()
        if end < len(MAGIC) + _TRAILER.size:
            raise IOError(f"{path}: truncated record file")
        self._f.seek(end - _TRAILER.size)
        index_offset, count, magic = _TRAILER.unpack(
            self._f.read(_TRAILER.size))
        if magic != MAGIC:
            raise IOError(f"{path}: bad trailer magic {magic!r}")
        self._f.seek(0)
        if self._f.read(len(MAGIC)) != MAGIC:
            raise IOError(f"{path}: bad header magic")
        self._f.seek(index_offset)
        self.offsets = np.frombuffer(
            self._f.read(8 * count), dtype="<u8")
        if len(self.offsets) != count:
            raise IOError(f"{path}: truncated index")

    def __len__(self) -> int:
        return len(self.offsets)

    def read(self, i: int) -> bytes:
        """Random-access read of record ``i`` (seek + two reads)."""
        self._f.seek(int(self.offsets[i]))
        (n,) = _LEN.unpack(self._f.read(_LEN.size))
        return self._f.read(n)

    def __iter__(self) -> Iterator[bytes]:
        for i in range(len(self)):
            yield self.read(i)

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self) -> "RecordReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ------------------------------------------------------------ image records

_IMG_HDR = struct.Struct("<i")  # label


def pack_image_record(label: int, img_bytes: bytes) -> bytes:
    """[int32 label][encoded image bytes] (the reference's SeqFile value is
    label + raw bytes too, dataset/DataSet.scala:437-447)."""
    return _IMG_HDR.pack(label) + img_bytes


def unpack_image_record(payload: bytes) -> tuple[int, bytes]:
    (label,) = _IMG_HDR.unpack(payload[:_IMG_HDR.size])
    return label, payload[_IMG_HDR.size:]


def list_shards(path_or_glob: str) -> list[str]:
    """Expand a directory, glob, or single file into a sorted shard list."""
    if os.path.isdir(path_or_glob):
        return sorted(glob.glob(os.path.join(path_or_glob, "*.btr")))
    if any(ch in path_or_glob for ch in "*?["):
        return sorted(glob.glob(path_or_glob))
    return [path_or_glob]


def write_image_shards(root: str, out_dir: str, prefix: str = "imagenet",
                       images_per_shard: int = 512, workers: int = 8,
                       limit: Optional[int] = None) -> list[str]:
    """Convert a label-by-folder image tree into record shards (the
    ImageNetSeqFileGenerator analog: parallel workers, N images per shard,
    label packed with the bytes). Returns the shard paths.

    Class ids follow sorted folder names — identical to
    ``list_image_folder`` so folder- and record-trained models agree.
    """
    from bigdl_tpu.dataset.folder import list_image_folder

    paths, labels, classes = list_image_folder(root)
    if limit is not None:
        paths, labels = paths[:limit], labels[:limit]
    os.makedirs(out_dir, exist_ok=True)
    n = len(paths)
    n_shards = max(1, (n + images_per_shard - 1) // images_per_shard)

    def write_shard(s: int) -> str:
        shard_path = os.path.join(
            out_dir, f"{prefix}-{s:05d}-of-{n_shards:05d}.btr")
        lo, hi = s * images_per_shard, min(n, (s + 1) * images_per_shard)
        with RecordWriter(shard_path) as w:
            for i in range(lo, hi):
                with open(paths[i], "rb") as f:
                    w.write(pack_image_record(int(labels[i]), f.read()))
        return shard_path

    with ThreadPoolExecutor(max_workers=workers) as ex:
        shards = list(ex.map(write_shard, range(n_shards)))
    # class-name manifest so readers can map ids back to folder names
    with open(os.path.join(out_dir, f"{prefix}.classes.txt"), "w") as f:
        f.write("\n".join(classes))
    return shards
