"""Host-side prefetching: overlap batch preparation with device compute.

The reference overlaps JPEG decode with training via MTLabeledBGRImgToBatch
(coreNumber cloned transformer pipelines racing on an atomic batch counter,
image/MTLabeledBGRImgToBatch.scala:48-133). Two TPU-native layers replace
it:

* the C++ prefetcher in ``native/`` for raw-format readers
  (``NativePrefetchDataSet``), and
* this pure-Python :class:`PrefetchDataSet`, which wraps ANY DataSet in a
  background thread + bounded queue. While the device runs step N, the
  host prepares batches N+1..N+depth. Python threads are enough here: the
  wrapped pipeline's hot work (PIL decode, numpy ops) releases the GIL,
  and the training thread spends its time blocked in device dispatch.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Iterator, Optional

from bigdl_tpu.dataset.dataset import DataSet

__all__ = ["PrefetchDataSet"]

logger = logging.getLogger("bigdl_tpu")

_DONE = object()


class PrefetchDataSet(DataSet):
    """``PrefetchDataSet(inner, depth=2)`` — iterate ``inner`` on a daemon
    thread, handing batches over a bounded queue (depth = max batches
    prepared ahead). Exceptions in the producer re-raise in the consumer.
    """

    def __init__(self, inner: DataSet, depth: int = 2):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self.inner = inner
        self.depth = depth

    def __iter__(self) -> Iterator:
        q: queue.Queue = queue.Queue(maxsize=self.depth)
        err: list[BaseException] = []
        stop = threading.Event()  # set when the consumer abandons the epoch

        def put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                for item in self.inner:
                    if not put(item):
                        return  # consumer gone — unwind, don't block forever
            except BaseException as e:  # surfaced on the consumer side
                err.append(e)
            finally:
                put(_DONE)

        t = threading.Thread(target=produce, daemon=True,
                             name="bigdl-prefetch")
        t.start()
        try:
            while True:
                item = q.get()
                if item is _DONE:
                    break
                yield item
        finally:
            # normal exhaustion AND early exit (break / GeneratorExit):
            # release the producer if it is blocked on a full queue.
            # Drain until the THREAD exits — a single empty-queue sweep
            # races a producer blocked in put(), which can refill the
            # queue between the emptiness check and the join and leak
            # the daemon thread past the timeout.
            stop.set()
            deadline = time.monotonic() + 5.0
            while t.is_alive() and time.monotonic() < deadline:
                try:
                    q.get_nowait()
                except queue.Empty:
                    pass
                t.join(timeout=0.05)
            if t.is_alive():
                logger.warning(
                    "prefetch: producer thread failed to exit within 5s "
                    "(daemon thread leaked past shutdown — the wrapped "
                    "dataset is stuck mid-batch)")
        if err:
            raise err[0]

    def size(self) -> int:
        return self.inner.size()

    def shuffle(self, seed: Optional[int] = None) -> None:
        self.inner.shuffle(seed)
