"""Fault injection + supervised recovery (ISSUE 6).

BigDL's headline operational property — survive executor loss, task
failure, slow nodes — came free with Spark. The single-process JAX
stack has the *ingredients* (bit-identical step-equivalent resume,
corruption-safe caches, admission control) but nothing that exercises
or automates them. This package closes that gap:

* :mod:`faults`     — a deterministic, seeded fault injector: a plan
  (``--faultPlan``) fires simulated preemptions, transient dispatch
  errors, checkpoint I/O errors, corrupted-checkpoint bytes, and
  slow-step stalls at instrumented sites in the training loop,
  checkpoint I/O, and the serving request path — all no-ops unless a
  plan is installed;
* :mod:`supervisor` — retry with exponential backoff + deterministic
  jitter under an injectable clock, auto-resume from the newest VALID
  (checksum-verified) checkpoint, a bounded retry budget, and a
  structured fault/recovery log stamped into result JSON; plus
  :func:`~supervisor.supervise_command` for process-fatal preemptions
  (the engine of ``scripts/chaos_run.py``).

The serving-side hardening (per-request deadlines, dead-worker
fast-fail, the watchdog, tiered shedding) lives in
:mod:`bigdl_tpu.serving` next to the components it protects.
"""

from bigdl_tpu.resilience.faults import (ChecksumError, FaultInjector,
                                         FaultPlan, FaultRule, PREEMPT_RC,
                                         SimulatedPreemption,
                                         TransientFault, WorkerKillFault,
                                         clear_plan, hook, injected_events,
                                         install_plan, parse_plan)
from bigdl_tpu.resilience.supervisor import (RETRYABLE_EXCEPTIONS,
                                             RetryPolicy, Supervisor,
                                             SupervisorGaveUp,
                                             supervise_command)

__all__ = [
    "ChecksumError", "FaultInjector", "FaultPlan", "FaultRule",
    "PREEMPT_RC", "RETRYABLE_EXCEPTIONS", "RetryPolicy",
    "SimulatedPreemption", "Supervisor", "SupervisorGaveUp",
    "TransientFault", "WorkerKillFault", "clear_plan", "hook",
    "injected_events", "install_plan", "parse_plan", "supervise_command",
]
