"""Fault injection + supervised recovery (ISSUE 6).

BigDL's headline operational property — survive executor loss, task
failure, slow nodes — came free with Spark. The single-process JAX
stack has the *ingredients* (bit-identical step-equivalent resume,
corruption-safe caches, admission control) but nothing that exercises
or automates them. This package closes that gap:

* :mod:`faults`     — a deterministic, seeded fault injector: a plan
  (``--faultPlan``) fires simulated preemptions, transient dispatch
  errors, checkpoint I/O errors, corrupted-checkpoint bytes, and
  slow-step stalls at instrumented sites in the training loop,
  checkpoint I/O, and the serving request path — all no-ops unless a
  plan is installed;
* :mod:`supervisor` — retry with exponential backoff + deterministic
  jitter under an injectable clock, auto-resume from the newest VALID
  (checksum-verified) checkpoint, a bounded retry budget, and a
  structured fault/recovery log stamped into result JSON; plus
  :func:`~supervisor.supervise_command` for process-fatal preemptions
  (the engine of ``scripts/chaos_run.py``);
* :mod:`elastic`    — elastic data-parallelism (ISSUE 11): on a
  ``kill_device`` fault the :class:`~elastic.ElasticSupervisor`
  re-forms the mesh at the surviving device count, reshards optimizer
  state from the topology-independent checkpoint layout, re-resolves
  the grad-comm bucket bound for the new ``n_devices``, and holds or
  scales the global batch (``--elastic {hold,scale}``) — Spark's
  lineage-based executor recovery, minus Spark.

The serving-side hardening (per-request deadlines, dead-worker
fast-fail, the watchdog, tiered shedding) lives in
:mod:`bigdl_tpu.serving` next to the components it protects.
"""

from bigdl_tpu.resilience.faults import (ChecksumError, DeviceLossFault,
                                         FaultInjector, FaultPlan,
                                         FaultRule, PREEMPT_RC,
                                         SimulatedPreemption,
                                         TransientFault, WorkerKillFault,
                                         clear_plan, healthy_devices, hook,
                                         injected_events, install_plan,
                                         parse_plan, restore_devices)
from bigdl_tpu.resilience.supervisor import (RETRYABLE_EXCEPTIONS,
                                             RetryPolicy, Supervisor,
                                             SupervisorGaveUp,
                                             supervise_command)

__all__ = [
    "ChecksumError", "DeviceLossFault", "ElasticDataParallel",
    "ElasticSupervisor", "FaultInjector", "FaultPlan", "FaultRule",
    "PREEMPT_RC", "RETRYABLE_EXCEPTIONS", "RetryPolicy",
    "SimulatedPreemption", "Supervisor", "SupervisorGaveUp",
    "TransientFault", "WorkerKillFault", "clear_plan", "healthy_devices",
    "hook", "injected_events", "install_plan", "parse_plan",
    "restore_devices", "supervise_command",
]


def __getattr__(name):
    # elastic pulls in the parallel layer (jax, mesh machinery) — load it
    # only when someone actually asks for the elastic classes, keeping
    # `from bigdl_tpu.resilience.faults import hook` cheap for the hot
    # training path that imports utils/file everywhere.
    if name in ("ElasticDataParallel", "ElasticSupervisor",
                "ELASTIC_POLICIES"):
        from bigdl_tpu.resilience import elastic
        return getattr(elastic, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
