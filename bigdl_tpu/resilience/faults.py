"""Deterministic, seeded fault injection (ISSUE 6 tentpole).

BigDL inherited fault tolerance from Spark for free: a lost executor is
re-run, the driver holds the parameter state, and nobody had to *test*
it because the substrate enforced it. A single-process JAX stack gets no
such substrate, so the recovery machinery (step-equivalent resume,
checksum-verified checkpoints, supervised retry) has to be exercised on
purpose. This module is the "on purpose": a fault *plan* — parsed from a
``--faultPlan`` spec string or JSON file — that fires simulated faults
at instrumented sites in the training and serving paths.

Sites (each instrumented call is one *visit*; counters are per-process):

* ``data``         — one per training batch fetched;
* ``step``         — one per optimizer dispatch (before the step runs,
  so a preemption here loses the step, like a real SIGKILL would);
* ``ckpt_save``    — one per checkpoint artifact written
  (``utils/file.save_pytree``);
* ``ckpt_restore`` — one per checkpoint artifact read;
* ``infer``        — one per serving engine forward
  (``InferenceEngine.predict_scores``);
* ``request``      — one per HTTP request dispatched
  (``ServingApp.dispatch_post``).

Kinds:

* ``preempt``      — PROCESS-FATAL: logs the event then ``os._exit(75)``
  (EX_TEMPFAIL), the closest in-process stand-in for a TPU-VM
  preemption. Only a *supervising parent process* (``supervise_command``,
  ``scripts/chaos_run.py``) can recover;
* ``preempt_soft`` — raises :class:`SimulatedPreemption` instead of
  exiting: same semantics for the in-process supervisor, testable
  without subprocesses;
* ``dispatch``     — raises :class:`TransientFault` (a retryable
  transient dispatch/``device_put`` error);
* ``io``           — raises ``OSError`` (checkpoint I/O failure);
* ``corrupt``      — AFTER the artifact (and its checksum sidecar) is
  written, flips bytes in the blob — simulated bit-rot that only a
  checksum-verified restore can catch;
* ``stall``        — sleeps ``arg`` seconds (slow-step straggler);
* ``worker_kill``  — raises :class:`WorkerKillFault`
  (``worker_fatal=True``): serving worker threads treat it as fatal and
  die, exercising the dead-worker fast-fail + watchdog path;
* ``kill_device``  — marks ``arg`` devices (default 1, taken from the
  tail of the healthy roster) as LOST process-wide and raises
  :class:`DeviceLossFault`. Only an ``ElasticSupervisor`` treats it as
  retryable — recovery means re-forming the mesh at the surviving count
  from :func:`healthy_devices`, not restarting the same topology.

Everything is a no-op unless a plan is installed (``install_plan``); the
inactive hook is one global load and a ``None`` check, cheap enough to
live on the host side of the hot training loop (the fault-free
``--supervise`` overhead acceptance in ISSUE 6 bounds this).

Determinism: probabilistic rules (``p0.05``) decide per-visit via a
SHA-256 hash of ``(seed, site, visit)`` — the same seed always yields
the same fault schedule (the injector-determinism test contract), with
no shared mutable RNG to be perturbed by unrelated draws.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Callable, Dict, List, Optional, Sequence

__all__ = [
    "FAULT_SITES", "FAULT_KINDS", "PREEMPT_RC", "ChecksumError",
    "DeviceLossFault", "FaultPlan", "FaultRule", "FaultInjector",
    "SimulatedPreemption", "TransientFault", "WorkerKillFault", "active",
    "clear_plan", "healthy_devices", "hook", "injected_events",
    "install_plan", "lost_device_ids", "parse_plan", "post_write_hook",
    "restore_devices",
]

FAULT_SITES = ("data", "step", "ckpt_save", "ckpt_restore", "infer",
               "request", "worker_boot")
FAULT_KINDS = ("preempt", "preempt_soft", "dispatch", "io", "corrupt",
               "stall", "worker_kill", "kill_device")

# EX_TEMPFAIL: the rc a simulated preemption dies with — supervising
# parents treat exactly this as "retry with resume" (a real crash keeps
# its own rc and is NOT retried blindly)
PREEMPT_RC = 75


class TransientFault(RuntimeError):
    """Retryable transient failure (simulated dispatch/device_put error)."""


class SimulatedPreemption(RuntimeError):
    """In-process stand-in for a preemption: retryable under
    supervision, fatal without (the ``preempt`` kind skips even this and
    ``os._exit``\\ s — only a parent process can catch that one)."""


class WorkerKillFault(RuntimeError):
    """Fatal-to-the-worker-thread failure: serving workers propagate it
    (after failing the in-flight batch) instead of swallowing it, so the
    dead-worker detection path can be exercised end to end."""

    worker_fatal = True


class ChecksumError(ValueError):
    """Checkpoint blob does not match its checksum sidecar (torn write
    or bit-rot). Defined here — next to the fault that causes it — so
    ``utils/file`` and the supervisor's retryable set share one type
    without an import cycle."""


class DeviceLossFault(RuntimeError):
    """Simulated loss of one or more devices (ICI link drop, host
    eviction from a pod). RETRYABLE only under an ``ElasticSupervisor``
    — the plain PR 6 supervisor would rebuild the same mesh and trip
    over the missing devices again, so it does NOT list this type. The
    injector marks the victims in :data:`_LOST_DEVICE_IDS` before
    raising; :func:`healthy_devices` is the survivors' roster every
    elastic rebuild reads."""


# ids of devices the kill_device fault has "lost" in this process — jax
# can't actually detach a CPU device, so elasticity is simulated by
# making every mesh builder go through healthy_devices() instead of
# jax.devices(). clear_plan() heals them: no plan, no simulated losses.
_LOST_DEVICE_IDS: set = set()


def lost_device_ids() -> set:
    return set(_LOST_DEVICE_IDS)


def healthy_devices() -> list:
    """The devices still usable after injected losses, in jax.devices()
    order — the roster elastic mesh re-formation builds from."""
    import jax

    return [d for d in jax.devices() if d.id not in _LOST_DEVICE_IDS]


def restore_devices() -> None:
    """Forget all simulated device losses (tests; also part of
    :func:`clear_plan`)."""
    _LOST_DEVICE_IDS.clear()


def _u01(seed: int, tag: str, n: int) -> float:
    """Deterministic uniform in [0, 1) from (seed, tag, n) — a pure
    function, so fault schedules and backoff jitter never depend on
    draw order or anyone else's RNG use."""
    h = hashlib.sha256(f"{seed}:{tag}:{n}".encode()).digest()
    return int.from_bytes(h[:8], "big") / float(1 << 64)


class FaultRule:
    """One line of a plan: fire ``kind`` at ``site`` on explicit visit
    numbers (``at``) or per-visit with probability ``rate``."""

    __slots__ = ("kind", "site", "at", "rate", "arg")

    def __init__(self, kind: str, site: str,
                 at: Optional[Sequence[int]] = None,
                 rate: Optional[float] = None, arg: Optional[str] = None):
        if kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {kind!r} "
                             f"(kinds: {', '.join(FAULT_KINDS)})")
        if site not in FAULT_SITES:
            raise ValueError(f"unknown fault site {site!r} "
                             f"(sites: {', '.join(FAULT_SITES)})")
        if (at is None) == (rate is None):
            raise ValueError(f"{kind}@{site}: exactly one of explicit "
                             f"visits or a pNNN rate is required")
        self.kind, self.site, self.arg = kind, site, arg
        self.at = frozenset(int(n) for n in at) if at is not None else None
        self.rate = float(rate) if rate is not None else None

    def fires(self, n: int, seed: int) -> bool:
        if self.at is not None:
            return n in self.at
        return _u01(seed, f"{self.kind}@{self.site}", n) < self.rate

    def __repr__(self):
        tgt = (",".join(str(n) for n in sorted(self.at))
               if self.at is not None else f"p{self.rate}")
        a = f":{self.arg}" if self.arg is not None else ""
        return f"{self.kind}@{self.site}:{tgt}{a}"


class FaultPlan:
    """An ordered rule list + the seed that fixes probabilistic rules."""

    def __init__(self, rules: Sequence[FaultRule], seed: int = 0):
        self.rules = list(rules)
        self.seed = int(seed)

    def rules_for(self, site: str) -> List[FaultRule]:
        return [r for r in self.rules if r.site == site]

    def schedule(self, site: str, horizon: int) -> List[tuple]:
        """The (visit, kind) pairs that would fire over ``horizon``
        visits of ``site`` — a pure preview used by tests and by
        ``chaos_run`` to report what it injected."""
        out = []
        for n in range(1, horizon + 1):
            for r in self.rules_for(site):
                if r.fires(n, self.seed):
                    out.append((n, r.kind))
        return out

    def __repr__(self):
        return ";".join(repr(r) for r in self.rules) + f";seed={self.seed}"


def parse_plan(spec: str) -> FaultPlan:
    """Parse ``--faultPlan``. Two spellings:

    * inline spec — ``;``-separated entries
      ``kind@site:VISITS[:ARG]`` where VISITS is ``3`` / ``3,7`` /
      ``p0.05`` (per-visit probability), plus an optional ``seed=N``
      entry: ``"preempt@step:7"``,
      ``"dispatch@step:p0.1;stall@step:4:0.25;seed=3"``;
    * a path to a JSON file: ``{"seed": 3, "rules": [{"kind": ...,
      "site": ..., "at": [3, 7] | "rate": 0.05, "arg": ...}]}``.
    """
    spec = spec.strip()
    if os.path.isfile(spec):
        with open(spec) as f:
            doc = json.load(f)
        rules = [FaultRule(r["kind"], r["site"], at=r.get("at"),
                           rate=r.get("rate"), arg=r.get("arg"))
                 for r in doc.get("rules", [])]
        return FaultPlan(rules, seed=doc.get("seed", 0))
    rules, seed = [], 0
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        if entry.startswith("seed="):
            seed = int(entry[len("seed="):])
            continue
        try:
            kind, rest = entry.split("@", 1)
            site, _, tail = rest.partition(":")
            if not tail:
                raise ValueError("missing visit spec")
            visits, _, arg = tail.partition(":")
            at, rate = None, None
            if visits.startswith("p"):
                rate = float(visits[1:])
            else:
                at = [int(t) for t in visits.split(",") if t]
            rules.append(FaultRule(kind.strip(), site.strip(), at=at,
                                   rate=rate, arg=arg or None))
        except ValueError as e:
            raise ValueError(
                f"bad --faultPlan entry {entry!r}: {e} (expected "
                f"kind@site:VISITS[:ARG], e.g. preempt@step:7 or "
                f"dispatch@step:p0.05)") from None
    return FaultPlan(rules, seed=seed)


def corrupt_file(path: str, seed: int = 0) -> None:
    """Flip a run of bytes in the middle of ``path`` in place (local
    files only — the simulated bit-rot target). Deterministic per
    (path basename, seed)."""
    size = os.path.getsize(path)
    if size == 0:
        return
    off = int(_u01(seed, os.path.basename(path), 1) * max(size - 8, 1))
    with open(path, "r+b") as f:
        f.seek(off)
        chunk = f.read(8)
        f.seek(off)
        f.write(bytes(b ^ 0xFF for b in chunk))


class FaultInjector:
    """Counts visits per site, fires matching rules, records every
    fired fault as a structured event (and optionally appends it as a
    JSON line to ``log_path`` — written BEFORE process-fatal kinds act,
    so even an ``os._exit`` preemption leaves its evidence)."""

    def __init__(self, plan: FaultPlan, *, log_path: Optional[str] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 exit_fn: Callable[[int], None] = os._exit):
        self.plan = plan
        self.log_path = log_path
        self.events: List[dict] = []
        self.counts: Dict[str, int] = {}
        self._sleep = sleep
        self._exit = exit_fn

    # ------------------------------------------------------------ recording
    def _record(self, site: str, visit: int, rule: FaultRule,
                action: str) -> dict:
        ev = {"fault": rule.kind, "site": site, "visit": visit,
              "action": action}
        if rule.arg is not None:
            ev["arg"] = rule.arg
        self.events.append(ev)
        try:  # shared-registry fault counter (ISSUE 7): scrapable live
            from bigdl_tpu.obs.metrics import get_registry
            get_registry().counter(
                "faults_injected_total",
                "faults fired by the installed --faultPlan").inc()
        except Exception:
            pass  # observability must never change fault semantics
        try:  # span-timeline marker (ISSUE 12): the injection shows up
            # at its wall-clock position in the Chrome trace
            from bigdl_tpu.obs.spans import instant
            instant(f"fault:{rule.kind}", site=site, visit=visit,
                    action=action)
        except Exception:
            pass
        if self.log_path:
            # append + close per event: survives os._exit on the next line
            with open(self.log_path, "a") as f:
                f.write(json.dumps(ev) + "\n")
                f.flush()
                os.fsync(f.fileno())
        return ev

    # --------------------------------------------------------------- firing
    def fire(self, site: str) -> None:
        """One visit of ``site``: bump the counter, act on every
        matching rule (``corrupt`` is deferred to :meth:`post_write` —
        there is nothing to corrupt before the artifact exists)."""
        n = self.counts[site] = self.counts.get(site, 0) + 1
        for rule in self.plan.rules_for(site):
            if rule.kind == "corrupt" or not rule.fires(n, self.plan.seed):
                continue
            self._act(rule, site, n)

    def _act(self, rule: FaultRule, site: str, n: int) -> None:
        kind = rule.kind
        if kind == "preempt":
            self._record(site, n, rule, f"os._exit({PREEMPT_RC})")
            self._exit(PREEMPT_RC)
            return  # only reached with an injected exit_fn (tests)
        if kind == "preempt_soft":
            self._record(site, n, rule, "raise SimulatedPreemption")
            raise SimulatedPreemption(
                f"injected preemption at {site} visit {n}")
        if kind == "dispatch":
            self._record(site, n, rule, "raise TransientFault")
            raise TransientFault(
                f"injected transient dispatch failure at {site} visit {n}")
        if kind == "io":
            self._record(site, n, rule, "raise OSError")
            raise OSError(f"injected I/O failure at {site} visit {n}")
        if kind == "worker_kill":
            self._record(site, n, rule, "raise WorkerKillFault")
            raise WorkerKillFault(
                f"injected worker-fatal failure at {site} visit {n}")
        if kind == "kill_device":
            k = int(rule.arg or 1)
            import jax

            alive = [d for d in jax.devices()
                     if d.id not in _LOST_DEVICE_IDS]
            victims = alive[-k:] if 0 < k < len(alive) else alive[1:]
            for d in victims:
                _LOST_DEVICE_IDS.add(d.id)
            survivors = len(alive) - len(victims)
            self._record(site, n, rule,
                         f"kill {len(victims)} device(s) -> "
                         f"{survivors} healthy")
            raise DeviceLossFault(
                f"injected loss of {len(victims)} device(s) at {site} "
                f"visit {n}; {survivors} healthy device(s) remain")
        if kind == "stall":
            secs = float(rule.arg or 0.1)
            self._record(site, n, rule, f"stall {secs}s")
            self._sleep(secs)

    def post_write(self, site: str, path: str) -> None:
        """Corruption pass for the artifact just written at the CURRENT
        visit of ``site`` (the checksum sidecar is already on disk, so
        the damage is detectable — exactly the bit-rot scenario)."""
        n = self.counts.get(site, 0)
        for rule in self.plan.rules_for(site):
            if rule.kind != "corrupt" or not rule.fires(n, self.plan.seed):
                continue
            if "://" in path or not os.path.isfile(path):
                continue  # local blobs only
            corrupt_file(path, self.plan.seed)
            self._record(site, n, rule, f"corrupted {path}")


# ------------------------------------------------------------- global hook
_ACTIVE: Optional[FaultInjector] = None


def install_plan(plan: FaultPlan, *, log_path: Optional[str] = None
                 ) -> FaultInjector:
    """Activate a plan process-wide; returns the injector (its
    ``events`` list is what supervisors stamp into result JSON)."""
    global _ACTIVE
    _ACTIVE = FaultInjector(plan, log_path=log_path)
    return _ACTIVE


def clear_plan() -> None:
    global _ACTIVE
    _ACTIVE = None
    restore_devices()  # no plan, no simulated device losses


def active() -> Optional[FaultInjector]:
    return _ACTIVE


def hook(site: str) -> None:
    """The instrumented-site entry point: a no-op (one global load, one
    ``None`` check) unless a plan is installed."""
    inj = _ACTIVE
    if inj is not None:
        inj.fire(site)


def post_write_hook(site: str, path: str) -> None:
    inj = _ACTIVE
    if inj is not None:
        inj.post_write(site, path)


def injected_events() -> List[dict]:
    """Snapshot of every fault fired so far in this process (empty when
    no plan is active) — merged into supervisor annotations."""
    inj = _ACTIVE
    return list(inj.events) if inj is not None else []
