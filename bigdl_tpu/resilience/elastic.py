"""Elastic data-parallelism: survive device loss and RESHAPE (ISSUE 11).

The reference's ``DistriOptimizer`` outlives executor loss because Spark
re-forms the job from lineage and the driver still holds the last
synchronized weights (PAPER.md layers 5-6) — the job continues with
fewer workers, it does not merely restart. The PR 6 :class:`Supervisor`
only knew how to restart the *same* topology; this module composes the
existing pieces (checksummed topology-independent checkpoints, seeded
``kill_device`` fault injection, per-``n_devices`` autotuned grad-comm)
into the Spark behavior:

* on a :class:`DeviceLossFault` the :class:`ElasticSupervisor` re-probes
  ``faults.healthy_devices()``, and the next attempt re-forms the mesh at
  the surviving count (``make_mesh(axes, devices)``), rebuilds the
  strategy — a fresh trace re-resolves the ``grad_comm`` bucket bound
  through the autotune cache, which is keyed by ``n_devices``, so the
  new topology gets ITS OWN cached decision, never the old bound — and
  resumes from the last valid checkpoint pair via the gathered-logical
  blob layout (``utils/file.restore_resharded`` is the standalone
  spelling; the Optimizer's resume + ``place()`` path reshards the same
  way);
* the global batch is held (``--elastic hold``: per-device batches are
  padded with wrap-around rows to the next multiple of the surviving
  count) or scaled (``--elastic scale``: trimmed down to divisibility)
  by :class:`ElasticDataParallel`;
* dropping below ``--minDevices`` is a clean :class:`SupervisorGaveUp`
  — there is no point thrashing retries on a pod that has lost too much;
* every reshape is recorded (from/to device counts, restore_ms, bucket
  bound before/after), published as ``elastic_reshapes_total`` /
  ``elastic_devices`` on the shared ``/metrics`` registry, and stamped
  into the perf JSON line as the ``reshape`` dict.

What IS bit-identical across a reshape: the restored params/opt state
(blobs hold gathered logical arrays; placement is just sharding). What
is NOT: the forward loss after the reshape under ``hold`` (padded rows
enter the batch mean) and any step math at a different device count
(reduction orders differ) — PERF.md §18 documents the contract.
"""

from __future__ import annotations

import logging
from typing import List, Optional

import numpy as np

from bigdl_tpu.parallel.data_parallel import DataParallel
from bigdl_tpu.resilience.faults import DeviceLossFault, healthy_devices
from bigdl_tpu.resilience.supervisor import (RETRYABLE_EXCEPTIONS,
                                             RetryPolicy, Supervisor,
                                             SupervisorGaveUp)

logger = logging.getLogger("bigdl_tpu")

__all__ = ["ELASTIC_POLICIES", "ElasticDataParallel", "ElasticSupervisor"]

# --elastic choices: how the global batch reacts when the device count
# changes. `hold` keeps every real row and pads to divisibility (the
# DistriOptimizer behavior — global batch is a training hyperparameter);
# `scale` trims rows so the per-device batch stays constant.
ELASTIC_POLICIES = ("hold", "scale")


class ElasticSupervisor(Supervisor):
    """A :class:`Supervisor` that treats device loss as retryable and
    owns the reshape ledger.

    The attempt callable drives the protocol:

    * ``probe()`` at the top of each attempt returns the healthy device
      roster — or raises :class:`SupervisorGaveUp` once fewer than
      ``min_devices`` survive (a clean give-up, not budget exhaustion);
    * ``observe_topology(n_devices, ...)`` once the mesh/strategy is
      (re)built: the first call records the baseline, and the first call
      *after* a caught :class:`DeviceLossFault` closes out a reshape
      event (from/to counts, restore_ms, bucket bound before/after) and
      bumps the shared-registry metrics.
    """

    def __init__(self, policy: Optional[RetryPolicy] = None, *,
                 min_devices: int = 1, batch_policy: str = "hold",
                 name: str = "elastic", **kwargs):
        if batch_policy not in ELASTIC_POLICIES:
            raise ValueError(f"unknown --elastic policy {batch_policy!r} "
                             f"(choices: {', '.join(ELASTIC_POLICIES)})")
        if min_devices < 1:
            raise ValueError(f"--minDevices must be >= 1, got {min_devices}")
        retryable = tuple(kwargs.pop("retryable", RETRYABLE_EXCEPTIONS))
        if DeviceLossFault not in retryable:
            retryable = retryable + (DeviceLossFault,)
        super().__init__(policy, retryable=retryable, name=name, **kwargs)
        self.min_devices = int(min_devices)
        self.batch_policy = batch_policy
        self.reshapes: List[dict] = []
        self._last_seen: Optional[dict] = None
        self._pending_loss: Optional[str] = None

    # ------------------------------------------------------------- protocol
    def probe(self) -> list:
        """The surviving device roster for this attempt's mesh. Raising
        :class:`SupervisorGaveUp` here (below ``min_devices``) escapes
        ``run()`` unretried — give-up is not a retryable fault."""
        devs = healthy_devices()
        if len(devs) < self.min_devices:
            raise SupervisorGaveUp(
                f"{len(devs)} healthy device(s) < --minDevices "
                f"{self.min_devices} — cannot re-form a viable mesh",
                self.annotation()["events"])
        return devs

    def observe_topology(self, n_devices: int,
                         bucket_bytes: Optional[int] = None,
                         restore_ms: Optional[float] = None) -> None:
        """Record the topology an attempt actually built. Closes out a
        pending reshape (device loss was caught since the last call)."""
        prev, self._last_seen = self._last_seen, {
            "n_devices": int(n_devices),
            "bucket_bytes": (int(bucket_bytes)
                             if bucket_bytes is not None else None)}
        try:  # shared registry backs the live /metrics endpoint
            from bigdl_tpu.obs.metrics import get_registry
            get_registry().gauge(
                "elastic_devices",
                "devices in the current elastic mesh").set(int(n_devices))
        except Exception:
            pass  # observability must never break recovery
        if self._pending_loss is None or prev is None:
            return
        ev = {"event": "reshape",
              "from_devices": prev["n_devices"],
              "to_devices": int(n_devices),
              "restore_ms": (round(float(restore_ms), 3)
                             if restore_ms is not None else None),
              "bucket_bytes_before": prev["bucket_bytes"],
              "bucket_bytes_after": self._last_seen["bucket_bytes"]}
        self.reshapes.append(ev)
        self.events.append(dict(ev))
        self._pending_loss = None
        try:
            from bigdl_tpu.obs.metrics import get_registry
            get_registry().counter(
                "elastic_reshapes_total",
                "mesh re-formations after device loss").inc()
        except Exception:
            pass
        try:  # span-timeline marker (ISSUE 12): the reshape shows up at
            # its wall-clock position next to the step phases
            from bigdl_tpu.obs.spans import instant
            instant("reshape", from_devices=ev["from_devices"],
                    to_devices=ev["to_devices"],
                    restore_ms=ev["restore_ms"])
        except Exception:
            pass
        logger.info("elastic[%s]: reshaped %d -> %d devices "
                    "(restore %.1f ms, bucket %s -> %s)", self.name,
                    ev["from_devices"], ev["to_devices"],
                    ev["restore_ms"] or 0.0, ev["bucket_bytes_before"],
                    ev["bucket_bytes_after"])

    # ------------------------------------------------------------------ run
    def run(self, attempt_fn):
        def wrapped(attempt: int):
            try:
                return attempt_fn(attempt)
            except DeviceLossFault as e:
                self._pending_loss = str(e)
                raise

        return super().run(wrapped)

    # ------------------------------------------------------------ reporting
    def reshape_annotation(self) -> Optional[dict]:
        """The ``reshape`` dict for the perf JSON line: the most recent
        reshape plus the total count — None when the topology never
        changed (schema-stable null column)."""
        if not self.reshapes:
            return None
        last = {k: v for k, v in self.reshapes[-1].items() if k != "event"}
        last["count"] = len(self.reshapes)
        return last

    def annotation(self) -> dict:
        out = super().annotation()
        out["reshapes"] = len(self.reshapes)
        out["min_devices"] = self.min_devices
        out["batch_policy"] = self.batch_policy
        return out


class ElasticDataParallel(DataParallel):
    """:class:`DataParallel` whose batch placement tolerates a global
    batch that no longer divides the (post-loss) device count.

    ``hold`` keeps the global batch: rows are padded with wrap-around
    copies of leading rows up to the next multiple of the data-axis
    size — every real example still contributes, at the cost of a few
    duplicated rows in the batch mean. ``scale`` keeps the per-device
    batch: trailing rows are trimmed down to divisibility. Both are
    identity when the batch already divides, so at full topology this
    class is bit-identical to :class:`DataParallel`.
    """

    def __init__(self, mesh=None, axis: str = "data",
                 batch_policy: str = "hold", **kwargs):
        if batch_policy not in ELASTIC_POLICIES:
            raise ValueError(
                f"unknown --elastic policy {batch_policy!r} "
                f"(choices: {', '.join(ELASTIC_POLICIES)})")
        super().__init__(mesh, axis, **kwargs)
        self.batch_policy = batch_policy

    def _fit_rows(self, arr):
        n = int(self.mesh.shape[self.axis])
        b = int(arr.shape[0])
        if n <= 1 or b % n == 0:
            return arr
        if self.batch_policy == "hold":
            per = -(-b // n)  # ceil
            idx = np.arange(per * n - b) % b
            return np.concatenate([arr, arr[idx]], axis=0)
        keep = (b // n) * n
        if keep == 0:
            raise ValueError(
                f"batch of {b} rows cannot be scaled onto {n} "
                f"devices (fewer rows than devices)")
        return arr[:keep]

    def shard_batch(self, x, y):
        return super().shard_batch(self._fit_rows(np.asarray(x)),
                                   self._fit_rows(np.asarray(y)))
