"""Supervised recovery: retry with backoff, resume from the newest
valid checkpoint (ISSUE 6 tentpole).

The reference's recovery story is Spark's: a failed task is re-executed,
a lost executor's partitions are recomputed, and the driver-held
``Optimizer`` loop is restartable by construction. Here the equivalent
is explicit: a :class:`Supervisor` wraps "one training attempt" and

* catches RETRYABLE faults (transient dispatch errors, checkpoint I/O
  errors, checksum mismatches, soft preemptions) — anything else
  (a real bug, a NaN guard trip) propagates unchanged;
* sleeps exponential backoff with DETERMINISTIC jitter before the next
  attempt (clock and sleep are injectable, so the backoff sequence is a
  unit-testable pure function of (seed, attempt));
* enforces a bounded retry budget (:class:`SupervisorGaveUp` past it);
* records every fault and recovery action as structured events, merged
  with the fault injector's own log, and exposes :meth:`annotation` for
  stamping into perf JSON lines next to ``bn_fused``/``lint``.

The attempt callable is responsible for resuming: training attempts
rebuild their Optimizer and ``resume()`` from the checkpoint directory,
where ``utils/file.latest_valid_checkpoint_pair`` skips corrupt
(checksum-mismatched) snapshots and falls back to the previous valid
pair.

For PROCESS-FATAL faults (the ``preempt`` kind ``os._exit``\\ s — no
in-process supervisor can catch that) there is
:func:`supervise_command`: the same policy applied to a child process,
restarting it while it dies with ``PREEMPT_RC`` — the engine of
``scripts/chaos_run.py``.
"""

from __future__ import annotations

import logging
import subprocess
import time
from typing import Callable, List, Optional, Sequence, Tuple

from bigdl_tpu.obs.spans import span as _obs_span
from bigdl_tpu.resilience.faults import (ChecksumError, PREEMPT_RC,
                                         SimulatedPreemption,
                                         TransientFault, _u01,
                                         injected_events)

logger = logging.getLogger("bigdl_tpu")

__all__ = ["RETRYABLE_EXCEPTIONS", "RetryPolicy", "Supervisor",
           "SupervisorGaveUp", "supervise_command"]

# What a supervisor may retry: simulated/infrastructure failures, never
# program bugs. OSError covers checkpoint I/O (including the injected
# `io` kind); ChecksumError is a corrupt snapshot discovered at restore
# (the NEXT attempt's latest_valid_checkpoint_pair skips it).
RETRYABLE_EXCEPTIONS = (TransientFault, SimulatedPreemption, OSError,
                        ChecksumError)


class SupervisorGaveUp(RuntimeError):
    """Retry budget exhausted; ``events`` carries the full fault log."""

    def __init__(self, msg: str, events: List[dict]):
        super().__init__(msg)
        self.events = events


class RetryPolicy:
    """Exponential backoff with deterministic jitter.

    ``delay(attempt)`` = ``min(base * multiplier**(attempt-1), max)``
    scaled by ``1 + jitter * u`` where ``u`` is the hash-uniform of
    (seed, attempt) — reproducible under test, decorrelated across
    supervisors with different seeds (the thundering-herd fix real
    preemption storms need)."""

    def __init__(self, budget: int = 5, base_s: float = 0.5,
                 multiplier: float = 2.0, max_s: float = 30.0,
                 jitter: float = 0.5, seed: int = 0):
        if budget < 0:
            raise ValueError(f"retry budget must be >= 0, got {budget}")
        self.budget = int(budget)
        self.base_s = float(base_s)
        self.multiplier = float(multiplier)
        self.max_s = float(max_s)
        self.jitter = float(jitter)
        self.seed = int(seed)

    def delay(self, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (1-based)."""
        d = min(self.base_s * self.multiplier ** (attempt - 1), self.max_s)
        return d * (1.0 + self.jitter * _u01(self.seed, "backoff", attempt))


class Supervisor:
    """Run an attempt callable under the retry policy.

    ``attempt_fn(attempt)`` is called with the 0-based attempt number
    (0 = first try; > 0 means "you are a retry — resume"). ``clock``
    and ``sleep`` are injectable for deterministic tests.
    """

    def __init__(self, policy: Optional[RetryPolicy] = None, *,
                 retryable: Tuple = RETRYABLE_EXCEPTIONS,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 name: str = "train"):
        self.policy = policy or RetryPolicy()
        self.retryable = retryable
        self.clock = clock
        self.sleep = sleep
        self.name = name
        self.events: List[dict] = []
        self.attempts = 0
        self._t0: Optional[float] = None

    # ----------------------------------------------------------------- run
    def run(self, attempt_fn: Callable[[int], object]):
        self._t0 = self.clock()
        retries = 0
        while True:
            self.attempts += 1
            try:
                with _obs_span("supervisor_attempt",
                               attempt=self.attempts):
                    result = attempt_fn(self.attempts - 1)
            except self.retryable as e:
                retries += 1
                self.events.append({
                    "event": "fault", "attempt": self.attempts,
                    "error": f"{type(e).__name__}: {e}"[:300],
                    "t_s": round(self.clock() - self._t0, 3)})
                if retries > self.policy.budget:
                    self.events.append({"event": "gave_up",
                                        "retries": retries - 1})
                    logger.error(
                        "supervisor[%s]: retry budget (%d) exhausted "
                        "after %s", self.name, self.policy.budget, e)
                    raise SupervisorGaveUp(
                        f"retry budget ({self.policy.budget}) exhausted; "
                        f"last fault: {type(e).__name__}: {e}",
                        self.annotation()["events"]) from e
                d = self.policy.delay(retries)
                self.events.append({"event": "retry", "attempt": retries,
                                    "backoff_s": round(d, 3),
                                    "action": "resume from newest valid "
                                              "checkpoint"})
                try:  # shared-registry retry counter (ISSUE 7)
                    from bigdl_tpu.obs.metrics import get_registry
                    get_registry().counter(
                        "supervisor_retries_total",
                        "supervised retries after retryable "
                        "faults").inc()
                except Exception:
                    pass  # never let observability break recovery
                logger.warning(
                    "supervisor[%s]: %s: %s — retry %d/%d in %.2fs",
                    self.name, type(e).__name__, e, retries,
                    self.policy.budget, d)
                self.sleep(d)
                continue
            if retries:
                self.events.append({"event": "recovered",
                                    "after_retries": retries})
                logger.info("supervisor[%s]: recovered after %d "
                            "retr%s", self.name, retries,
                            "y" if retries == 1 else "ies")
            return result

    # ------------------------------------------------------------ reporting
    def annotation(self) -> dict:
        """The structured fault/recovery log for result JSON: supervisor
        events interleaved with everything the injector fired in this
        process (one list, chronologically grouped by source)."""
        retries = sum(1 for e in self.events if e.get("event") == "retry")
        return {
            "attempts": self.attempts,
            "retries": retries,
            "budget": self.policy.budget,
            "gave_up": any(e.get("event") == "gave_up"
                           for e in self.events),
            "events": injected_events() + self.events,
        }


def supervise_command(make_argv: Callable[[int], Sequence[str]], *,
                      policy: Optional[RetryPolicy] = None,
                      retryable_rcs: Tuple[int, ...] = (PREEMPT_RC,),
                      sleep: Callable[[float], None] = time.sleep,
                      env: Optional[dict] = None,
                      cwd: Optional[str] = None) -> Tuple[int, List[dict]]:
    """Cross-process supervision: run ``make_argv(attempt)`` as a child,
    restarting (with the same backoff policy) while it exits with a
    retryable rc — by default exactly ``PREEMPT_RC``, the code the
    ``preempt`` fault kind dies with. Any other nonzero rc is a real
    failure and is returned immediately. Returns ``(rc, events)``."""
    policy = policy or RetryPolicy()
    events: List[dict] = []
    restarts = 0
    while True:
        argv = list(make_argv(restarts))
        rc = subprocess.call(argv, env=env, cwd=cwd)
        if rc == 0:
            if restarts:
                events.append({"event": "recovered",
                               "after_restarts": restarts})
            return 0, events
        events.append({"event": "process_exit", "rc": rc,
                       "attempt": restarts + 1,
                       "retryable": rc in retryable_rcs})
        if rc not in retryable_rcs:
            return rc, events
        restarts += 1
        if restarts > policy.budget:
            events.append({"event": "gave_up", "restarts": restarts - 1})
            return rc, events
        d = policy.delay(restarts)
        events.append({"event": "restart", "attempt": restarts,
                       "backoff_s": round(d, 3),
                       "action": "restart + resume from newest valid "
                                 "checkpoint"})
        logger.warning("supervise_command: child exited rc=%d — restart "
                       "%d/%d in %.2fs", rc, restarts, policy.budget, d)
        sleep(d)
