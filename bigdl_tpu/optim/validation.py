"""Validation methods and result monoids
(reference optim/ValidationMethod.scala:28-213, optim/EvaluateMethods.scala).

Results are monoids (``+``) so they reduce across batches, devices, and hosts
exactly like the reference reduces them across Spark partitions (:38-51).
The per-batch computation is jit-friendly: each method has a
``stats(output, target) -> (correct_or_sum, count)`` device-side part and the
monoid lives host-side.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["ValidationResult", "AccuracyResult", "LossResult",
           "PerplexityResult", "ValidationMethod", "Top1Accuracy",
           "Top5Accuracy", "Loss", "Perplexity"]


class ValidationResult:
    def __add__(self, other):
        raise NotImplementedError

    def result(self) -> tuple[float, int]:
        raise NotImplementedError


class AccuracyResult(ValidationResult):
    """(reference AccuracyResult — correct/count with + merge)"""

    def __init__(self, correct: int, count: int):
        self.correct, self.count = int(correct), int(count)

    def __add__(self, other):
        return AccuracyResult(self.correct + other.correct,
                              self.count + other.count)

    def result(self):
        acc = self.correct / self.count if self.count else 0.0
        return acc, self.count

    def __repr__(self):
        acc, _ = self.result()
        return f"AccuracyResult({acc:.4f}, {self.correct}/{self.count})"

    def __eq__(self, other):
        return (self.correct, self.count) == (other.correct, other.count)


class LossResult(ValidationResult):
    def __init__(self, loss_sum: float, count: int):
        self.loss_sum, self.count = float(loss_sum), int(count)

    def __add__(self, other):
        return LossResult(self.loss_sum + other.loss_sum,
                          self.count + other.count)

    def result(self):
        mean = self.loss_sum / self.count if self.count else 0.0
        return mean, self.count

    def __repr__(self):
        mean, _ = self.result()
        return f"LossResult({mean:.4f}, n={self.count})"


class ValidationMethod:
    """Device part: :meth:`stats`; host part: :meth:`to_result`."""

    name = "validation"

    def stats(self, output, target):
        """Returns (value, count) jnp scalars, computed on device."""
        raise NotImplementedError

    def to_result(self, value, count) -> ValidationResult:
        raise NotImplementedError


class _TopK(ValidationMethod):
    k = 1

    def stats(self, output, target):
        # output (B, C) scores or log-probs; target (B,) int labels
        if self.k == 1:
            pred = jnp.argmax(output, axis=-1)
            correct = jnp.sum(pred == target.astype(pred.dtype))
        else:
            _, topk = jax.lax.top_k(output, self.k)
            correct = jnp.sum(
                jnp.any(topk == target.astype(topk.dtype)[:, None], axis=-1))
        return correct, output.shape[0]

    def to_result(self, value, count):
        return AccuracyResult(int(value), int(count))


class Top1Accuracy(_TopK):
    """(reference ValidationMethod.Top1Accuracy :87)"""
    name = "top1 accuracy"
    k = 1


class Top5Accuracy(_TopK):
    """(reference ValidationMethod.Top5Accuracy :122)"""
    name = "top5 accuracy"
    k = 5


import jax  # noqa: E402  (lax.top_k used above)


class Loss(ValidationMethod):
    """Mean criterion value over the validation set (reference
    ValidationMethod.Loss :202)."""

    name = "loss"

    def __init__(self, criterion):
        self.criterion = criterion

    def stats(self, output, target):
        n = output.shape[0]
        return self.criterion(output, target) * n, n

    def to_result(self, value, count):
        return LossResult(float(value), int(count))


class PerplexityResult(ValidationResult):
    """exp(mean token NLL) — the LM counterpart of LossResult."""

    def __init__(self, nll_sum: float, count: int):
        self.nll_sum, self.count = float(nll_sum), int(count)

    def __add__(self, other):
        return PerplexityResult(self.nll_sum + other.nll_sum,
                                self.count + other.count)

    def result(self):
        import math
        ppl = math.exp(self.nll_sum / self.count) if self.count else 0.0
        return ppl, self.count

    def __repr__(self):
        ppl, _ = self.result()
        return f"PerplexityResult({ppl:.3f}, n={self.count})"


class Perplexity(ValidationMethod):
    """Token-level perplexity over (B, S, V) log-probs with (B, S) int
    targets (the language-model validation the reference's Loss can't
    express). Optional packed form: target = (targets, weights) from
    ``models.packed_lm_targets`` — boundary/padding tokens carry weight 0
    and drop out of both the sum and the count."""

    name = "perplexity"

    def stats(self, output, target):
        if isinstance(target, (tuple, list)):
            target, weights = target
        else:
            weights = jnp.ones(target.shape, output.dtype)
        nll = -jnp.take_along_axis(
            output, target[..., None].astype(jnp.int32), axis=-1)[..., 0]
        w = weights.astype(nll.dtype)
        return jnp.sum(nll * w), jnp.sum(w)

    def to_result(self, value, count):
        return PerplexityResult(float(value), int(count))
