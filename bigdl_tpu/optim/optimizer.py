"""Training loop facade (reference optim/Optimizer.scala:30-129,
DistriOptimizer.scala, LocalOptimizer.scala).

One loop for local and distributed: the reference's LocalOptimizer (clone per
core, fork-join) and DistriOptimizer (two Spark jobs per iteration, block
all-reduce) collapse into a single jitted train step; when a
:class:`~bigdl_tpu.parallel.DataParallel` strategy is supplied, the same step
is sharded over a device mesh and XLA inserts the gradient all-reduce that
the reference hand-rolls through the BlockManager (SURVEY.md §3.2).

API parity: ``Optimizer(model, dataset, criterion)`` then
``set_state/set_optim_method/set_end_when/set_validation/set_checkpoint`` and
``optimize()`` (reference setters :66-124, factory :151-186). The canonical
log line "Train N in Xs. Throughput is R records/second. Loss is L"
(DistriOptimizer.scala:241-244) is preserved.
"""

from __future__ import annotations

import logging
import math
import os
import time
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.core.module import Module
from bigdl_tpu.core.criterion import Criterion
from bigdl_tpu.obs.spans import enabled as _obs_enabled, span as _span
from bigdl_tpu.optim.method import OptimMethod, SGD
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.optim.triggers import Trigger
from bigdl_tpu.optim.validation import ValidationMethod
from bigdl_tpu.resilience.faults import hook as _fault_hook
from bigdl_tpu.utils.file import (save_pytree, load_pytree,
                                  exists as file_exists)

logger = logging.getLogger("bigdl_tpu")

__all__ = ["Optimizer", "TrainedModel"]


def _canon_ckpt_path(p: str) -> str:
    """Spelling-insensitive checkpoint path identity (ADVICE r5 #3): a
    trailing slash or relative-vs-absolute difference between the
    resume() dir and the set_checkpoint() dir must not disable the
    orphan-overwrite allowance (which would kill resume with
    FileExistsError at the first re-reached snapshot name). Remote URLs
    only get redundant slashes collapsed — abspath would mangle the
    scheme."""
    p = str(p)
    if "://" in p:
        scheme, rest = p.split("://", 1)
        return scheme + "://" + "/".join(s for s in rest.split("/") if s)
    return os.path.abspath(os.path.normpath(p))


class TrainedModel:
    """What optimize() returns: the module description plus trained pytrees."""

    def __init__(self, module: Module, params, mod_state):
        self.module = module
        self.params = params
        self.mod_state = mod_state

    def predict(self, x, batch_size: Optional[int] = None):
        return self.module.forward(self.params, x, self.mod_state,
                                   training=False)


class Optimizer:
    def __init__(self, model: Module, dataset, criterion: Criterion,
                 optim_method: Optional[OptimMethod] = None,
                 end_when: Optional[Trigger] = None,
                 strategy=None, seed: int = 42, log_every: int = 1,
                 compute_dtype=None, accum_steps: int = 1,
                 nan_check: bool = True, aux_loss_weight: float = 0.01,
                 steps_per_dispatch: int = 1):
        self.model = model
        self.dataset = dataset
        self.criterion = criterion
        self.optim_method = optim_method or SGD(learning_rate=1e-2)
        self.end_when = end_when or Trigger.max_epoch(1)
        self.strategy = strategy  # None => single-device
        self.seed = seed
        # bf16 activations/grad math with fp32 params+loss — the native
        # replacement for the reference's truncated-fp16 gradient codec
        # (parameters/FP16CompressedTensor.scala)
        self.compute_dtype = compute_dtype
        # accum_steps > 1: each optimizer update averages grads over that
        # many microbatches (batch_size must be divisible by it)
        self.accum_steps = accum_steps
        # NaN guard at every log point (SURVEY.md §5: functional purity
        # removes the reference's race class; divergence detection is the
        # failure mode left worth watching). Free: piggybacks on the loss
        # sync the log line already pays for.
        self.nan_check = nan_check
        # modules may surface auxiliary losses through their state tree as
        # scalar leaves named "aux_loss" (nn.MoE load balancing); they are
        # added to the criterion loss with this weight (Switch Transformer's
        # 0.01 default). Set 0.0 to disable.
        self.aux_loss_weight = aux_loss_weight
        # steps_per_dispatch > 1: lax.scan K optimizer steps over K
        # prefetched batches inside ONE jitted program, amortizing the
        # per-dispatch host->device overhead (~2.5-3.5 ms through the
        # tunneled runtime; measured +1.6% ResNet-50 throughput at K=10,
        # PERF.md §8.2). Update math and the per-step RNG sequence are
        # IDENTICAL to K dispatches (keys are pre-split host-side);
        # iteration-counted triggers fire at the first dispatch boundary
        # at or after their threshold (Trigger.several_iteration is
        # crossing-based). Single-device path only: under a distributed
        # strategy the per-dispatch overhead is already pipelined by the
        # multi-controller runtime and batches arrive pre-sharded.
        self.steps_per_dispatch = int(steps_per_dispatch)
        if self.steps_per_dispatch < 1:
            raise ValueError(
                f"steps_per_dispatch must be >= 1, got {steps_per_dispatch}")
        if self.steps_per_dispatch > 1 and strategy is not None:
            raise ValueError(
                "steps_per_dispatch > 1 is a single-device dispatch "
                "amortization; it cannot be combined with a distributed "
                "strategy (whose runtime pipelines dispatch already)")
        self._val_trigger = None
        self._val_dataset = None
        self._val_methods: Sequence[ValidationMethod] = ()
        self._ckpt_trigger = None
        self._ckpt_path = None
        self._init_params = None
        self._init_mod_state = None
        self._init_opt_state = None
        self.metrics = Metrics()
        # log_every > 1 avoids the per-step host<->device loss sync on the
        # hot path (the float() below blocks until the step finishes, which
        # serializes dispatch on TPU)
        self.log_every = max(1, log_every)
        self._last_val_iter = -1
        self._last_ckpt_iter = -1
        # step-phase accounting (ISSUE 7): cumulative seconds per phase
        # (obs.metrics.TRAIN_PHASES taxonomy). data_wait/dispatch/ckpt
        # are metered in EVERY run (the measurements were already being
        # taken — the reported feed-stall gap, PERF.md §4, was dropped on
        # the floor); h2d and the true device wait need a per-step sync
        # and are only split out when the span tracer is on (--obs).
        self._phase_totals: dict = {}
        self._obs_hists = None
        self._obs_capture = None  # CaptureController (cli wiring)

    # ---------------------------------------------------------------- setters
    def set_end_when(self, trigger: Trigger) -> "Optimizer":
        self.end_when = trigger
        return self

    def set_optim_method(self, method: OptimMethod) -> "Optimizer":
        self.optim_method = method
        return self

    def set_validation(self, trigger: Trigger, dataset,
                       methods: Sequence[ValidationMethod]) -> "Optimizer":
        """(reference Optimizer.setValidation :97-105)"""
        self._val_trigger = trigger
        self._val_dataset = dataset
        self._val_methods = list(methods)
        return self

    def set_checkpoint(self, trigger: Trigger, path: str,
                       overwrite: bool = False,
                       sharded: bool = False,
                       async_save: bool = False,
                       keep_last: Optional[int] = None) -> "Optimizer":
        """(reference Optimizer.setCheckpoint :87-94 +
        overWriteCheckpoint flag: refuse to clobber an existing snapshot
        unless ``overwrite``). ``sharded=True`` writes orbax shards
        directly from each host instead of gathering to one blob —
        the pod-scale path (utils/orbax_ckpt.py). ``async_save=True``
        snapshots the pytrees to host memory and serializes on a
        background thread, so the step loop only pays the device->host
        copy, not the disk/remote write (single-blob path only; a prior
        in-flight write is joined — and its errors re-raised — before
        the next snapshot starts and at the end of optimize()).
        ``keep_last=k`` garbage-collects older snapshots after each
        write, never deleting the newest checksum-VALID pair
        (utils/file.gc_checkpoints)."""
        if async_save and sharded:
            raise ValueError("async_save supports the single-blob path; "
                             "orbax sharded writes are per-host streaming "
                             "already")
        if keep_last is not None and keep_last < 1:
            raise ValueError(f"keep_last must be >= 1, got {keep_last}")
        self._ckpt_trigger = trigger
        self._ckpt_path = path
        self._ckpt_overwrite = overwrite
        self._ckpt_sharded = sharded
        self._ckpt_async = async_save
        self._ckpt_keep_last = keep_last
        return self

    def set_gradient_clipping_by_l2_norm(self, max_norm: float
                                         ) -> "Optimizer":
        """Global-L2-norm gradient clipping before the optimizer update
        (reference Optimizer.setGradientClippingByl2Norm)."""
        self._clip_norm = float(max_norm)
        return self

    def set_constant_gradient_clipping(self, lo: float, hi: float
                                       ) -> "Optimizer":
        """Elementwise gradient clipping to [lo, hi] (reference
        Optimizer.setConstantGradientClipping)."""
        self._clip_const = (float(lo), float(hi))
        return self

    def set_state(self, params=None, mod_state=None,
                  opt_state=None) -> "Optimizer":
        """Warm-start from explicit pytrees (reference setState :66 +
        --model/--state resume flags)."""
        self._init_params = params
        self._init_mod_state = mod_state
        self._init_opt_state = opt_state
        return self

    def resume(self, checkpoint_dir: str) -> "Optimizer":
        """Load the newest model.<n>/state.<n> pair from a directory
        (either single-blob or orbax-sharded snapshots).

        Step-equivalence (ADVICE r5 #4): snapshots written by this
        version also carry the host-RNG split count, the records consumed
        in the open epoch, and the completed-epoch count; optimize() then
        fast-forwards the PRNG stream, skips the already-consumed leading
        records of the interrupted epoch, and replays the per-epoch
        ``dataset.shuffle()`` calls — so for datasets whose order is
        driven by a seeded ``shuffle()`` (BatchDataSet, LocalArrayDataSet
        and friends), kill+resume replays exactly the dropout keys and
        batches an uninterrupted run would have used. Residual
        non-equivalence: datasets that advance their own RNG inside
        ``__iter__`` (e.g. LocalArrayDataSet(shuffle=True)) or stream
        from non-deterministic sources re-order the skipped records, and
        older snapshots without the counters resume with a fresh stream
        from the seed (counters-only semantics, as before)."""
        from bigdl_tpu.utils.file import (isdir, latest_checkpoint,
                                          latest_valid_checkpoint_pair,
                                          verify_checkpoint)
        # newest MATCHED *VALID* pair: a kill between the model.<n> and
        # state.<n> writes must not mix params from n with optimizer
        # state from n-k, and a checksum-mismatched (torn/bit-rotted)
        # pair must fall back to the previous one instead of crashing at
        # deserialize (ISSUE 6: recovery costs one checkpoint interval,
        # not the run)
        with _span("ckpt_restore", dir=str(checkpoint_dir)):
            m, s = latest_valid_checkpoint_pair(checkpoint_dir)
            if m is None:
                # accept a model-only snapshot (predict/eval-style dirs
                # with no optimizer state at all) — still checksum-gated
                m = latest_checkpoint(checkpoint_dir, "model.")
                s = None
                if m is not None and not verify_checkpoint(m):
                    from bigdl_tpu.resilience.faults import ChecksumError
                    raise ChecksumError(
                        f"the only snapshot in {checkpoint_dir} ({m}) "
                        f"fails checksum verification and there is no "
                        f"earlier one to fall back to")
            if m and isdir(m):  # orbax checkpoints are directories
                from bigdl_tpu.utils.orbax_ckpt import restore_sharded
                blob = restore_sharded(m)
                self._init_params = blob["params"]
                self._init_mod_state = blob["mod_state"]
                self._set_resume_driver(blob, m)
                if s:
                    self._init_opt_state = restore_sharded(s)
                return self
            if m:
                blob = load_pytree(m)
                self._init_params = blob["params"]
                self._init_mod_state = blob["mod_state"]
                self._set_resume_driver(blob, m)
            if s:
                self._init_opt_state = load_pytree(s)
            return self

    def _set_resume_driver(self, blob, model_path: str) -> None:
        """Resumed training continues the epoch/iteration numbering
        (reference semantics: maxEpoch/maxIteration are CUMULATIVE across
        resume, checkpoint files keep ascending names, and harnesses can
        compare pre-kill vs post-resume progress — soak finding, round
        5). Newer snapshots carry the counters in the blob; older ones
        fall back to the iteration encoded in the ``model.<n>`` name."""
        drv = blob.get("driver")
        if drv is None:
            tail = str(model_path).rstrip("/").rsplit(".", 1)[-1]
            if tail.isdigit():
                drv = {"iteration": int(tail)}
        if drv:
            self._resume_driver = {k: int(v) for k, v in dict(drv).items()
                                   if k in ("epoch", "iteration",
                                            "rng_splits", "epoch_records")}
            saved_plan = dict(drv).get("plan")
            if saved_plan:
                # blob round-trip turns scalars into 0-d arrays; epoch is
                # expected to differ (the snapshot's cursor, not identity)
                theirs = {k: (v.item() if hasattr(v, "item") else v)
                          for k, v in dict(saved_plan).items()
                          if k != "epoch"}
                cur = getattr(self.dataset, "plan", None)
                if cur is not None and hasattr(cur, "signature"):
                    mine = {k: v for k, v in cur.signature().items()
                            if k != "epoch"}
                    if mine != theirs:
                        logger.warning(
                            "resume: checkpoint epoch plan %s differs "
                            "from this run's %s — the replayed batch "
                            "stream will NOT match the killed run's",
                            theirs, mine)
            # a kill between the model.<n> and state.<n> writes leaves an
            # unmatched (unusable) newer snapshot; with counters resuming,
            # the deterministic trigger will re-reach exactly that name —
            # allow overwriting those specific paths without the global
            # overwrite flag
            it = self._resume_driver.get("iteration")
            if it is not None:
                from bigdl_tpu.utils.file import orphaned_snapshots
                d = os.path.dirname(str(model_path).rstrip("/"))
                # canonicalized so the later membership test is immune to
                # trailing-slash / relative-vs-absolute spelling drift
                # between resume() and set_checkpoint() (ADVICE r5 #3)
                orphans = {_canon_ckpt_path(o)
                           for o in orphaned_snapshots(d, it)}
                if orphans:
                    logger.warning(
                        "resume: %d unmatched snapshot file(s) newer than "
                        "the loaded pair (unclean shutdown mid-write); the "
                        "resumed run may overwrite them: %s",
                        len(orphans), sorted(orphans))
                self._resume_orphans = orphans

    # ---------------------------------------------------------------- build
    def _build_step(self):
        # conv-layout decision for this device AND dispatch configuration
        # (PERF.md §8.2/§9; no-op when a --convLayout/API policy is
        # already installed). The measured decision is positive on the
        # plain path but negative chained with multi-step dispatch
        # (window-2 combination matrix), so the K>1 variant resolves its
        # own key — installing the all-NHWC default until a measurement
        # exists, instead of skipping and leaking a previous K=1 install
        # (ADVICE r5 #1)
        from bigdl_tpu import tuning
        tuning.install_conv_layouts(
            "inner" if self.steps_per_dispatch > 1 else "plain")

        model, criterion, opt = self.model, self.criterion, self.optim_method

        dtype = self.compute_dtype
        accum = max(1, self.accum_steps)
        aux_w = self.aux_loss_weight

        def sum_aux_losses(state):
            # modules surface auxiliary losses as scalar "aux_loss" state
            # leaves (nn/moe.py); collect them so Optimizer-driven training
            # gets load balancing without a hand-written step
            total = jnp.zeros((), jnp.float32)
            for path, leaf in jax.tree_util.tree_leaves_with_path(state):
                last = path[-1] if path else None
                if (isinstance(last, jax.tree_util.DictKey)
                        and last.key == "aux_loss"):
                    total = total + leaf.astype(jnp.float32)
            return total

        def grads_of(params, mod_state, x, y, rng):
            if dtype is not None and jnp.issubdtype(x.dtype, jnp.floating):
                x = x.astype(dtype)

            def loss_fn(p):
                out, new_ms = model.apply(p, mod_state, x,
                                          training=True, rng=rng)
                if dtype is not None:
                    out = out.astype(jnp.float32)  # fp32 loss/softmax
                loss = criterion(out, y)
                if aux_w:
                    loss = loss + aux_w * sum_aux_losses(new_ms)
                return loss, new_ms

            (loss, new_ms), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            return loss, new_ms, grads

        def train_step(params, mod_state, opt_state, x, y, rng):
            if accum == 1:
                loss, new_ms, grads = grads_of(params, mod_state, x, y, rng)
            else:
                # gradient accumulation: the batch is split into `accum`
                # microbatches scanned inside ONE jitted step — same HBM
                # profile as a small batch, same update as the large one
                if x.shape[0] % accum:
                    raise ValueError(
                        f"batch size {x.shape[0]} not divisible by "
                        f"accum_steps={accum}")
                xm = x.reshape((accum, x.shape[0] // accum) + x.shape[1:])
                ym = y.reshape((accum, y.shape[0] // accum) + y.shape[1:])

                def body(carry, mb):
                    ms, g_acc, l_acc, i = carry
                    xb, yb = mb
                    r = jax.random.fold_in(rng, i)
                    loss, ms, grads = grads_of(params, ms, xb, yb, r)
                    g_acc = jax.tree_util.tree_map(jnp.add, g_acc, grads)
                    return (ms, g_acc, l_acc + loss, i + 1), None

                g0 = jax.tree_util.tree_map(
                    lambda p_: jnp.zeros(p_.shape, jnp.float32), params)
                (new_ms, grads, loss, _), _ = jax.lax.scan(
                    body, (mod_state, g0, jnp.zeros((), jnp.float32), 0),
                    (xm, ym))
                grads = jax.tree_util.tree_map(lambda g: g / accum, grads)
                loss = loss / accum
            if self.strategy is not None:
                grads, loss = self.strategy.reduce_grads(grads, loss)
            clip_const = getattr(self, "_clip_const", None)
            if clip_const is not None:
                from bigdl_tpu.optim.method import clip_by_value
                grads = clip_by_value(grads, *clip_const)
            clip_norm = getattr(self, "_clip_norm", None)
            if clip_norm is not None:
                from bigdl_tpu.optim.method import clip_by_global_norm
                grads, _ = clip_by_global_norm(grads, clip_norm)
            new_params, new_opt = opt.update(grads, opt_state, params)
            return new_params, new_ms, new_opt, loss

        if self.strategy is not None:
            mesh = getattr(self.strategy, "mesh", None)
            n_dev = mesh.size if mesh is not None else jax.device_count()
            from bigdl_tpu.nn.norm import unfuse_bn_for_spmd
            unfused = unfuse_bn_for_spmd(self.model, n_dev)
            if unfused:
                logger.warning(
                    "fused BN disabled on %d module(s): pallas_call has no "
                    "GSPMD partitioning rule, so the single-read stats "
                    "kernel would replicate sharded activations under the "
                    "%d-device mesh (jnp stats path used instead)",
                    unfused, n_dev)
            return self.strategy.compile_step(train_step), None
        step = jax.jit(train_step, donate_argnums=(0, 1, 2))
        chunk = None
        if self.steps_per_dispatch > 1:
            # K steps scanned inside one program over K stacked batches +
            # K pre-split rng keys; returns the LAST step's loss (what K
            # sequential dispatches would have left in driver["loss"])
            def chunk_step(params, mod_state, opt_state, xs, ys, keys):
                def body(carry, inp):
                    p, m, o = carry
                    xb, yb, kb = inp
                    p, m, o, loss = train_step(p, m, o, xb, yb, kb)
                    return (p, m, o), loss

                (p, m, o), losses = jax.lax.scan(
                    body, (params, mod_state, opt_state), (xs, ys, keys))
                return p, m, o, losses[-1]

            chunk = jax.jit(chunk_step, donate_argnums=(0, 1, 2))
        return step, chunk

    def _build_eval(self):
        from bigdl_tpu.optim.validator import build_eval_fn
        return build_eval_fn(self.model, self._val_methods, self.strategy)

    # ------------------------------------------------------------ obs phases
    def _obs_phase(self, name: str, dt: float) -> None:
        """Account ``dt`` seconds to a step phase: always into the
        cumulative totals (a dict add), and into the shared registry's
        per-step histograms when --obs is on."""
        self._phase_totals[name] = self._phase_totals.get(name, 0.0) + dt
        h = self._obs_hists
        if h is not None:
            hist = h.get(name)
            if hist is not None:
                hist.observe(dt * 1000.0)

    def phase_totals(self) -> dict:
        """Cumulative per-phase seconds for this run — what the perf
        harness stamps as the ``*_s`` phase columns (ISSUE 7)."""
        return dict(self._phase_totals)

    def set_capture(self, controller) -> "Optimizer":
        """Attach an :class:`~bigdl_tpu.obs.capture.CaptureController`;
        ``on_step`` is driven once per dispatch (--traceSteps/SIGUSR2/
        touch-file mid-run profile windows)."""
        self._obs_capture = controller
        return self

    # -------------------------------------------------------------- optimize
    def optimize(self) -> TrainedModel:
        # per-run conv-policy isolation (ADVICE r5 #1): _build_step
        # installs a layout decision for THIS run's dispatch config; the
        # pre-run policy comes back afterwards so a later run in the same
        # process starts clean
        from bigdl_tpu.ops.conv2d import policy_snapshot, restore_policy
        snap = policy_snapshot()
        try:
            return self._optimize()
        finally:
            restore_policy(snap)

    def _optimize(self) -> TrainedModel:
        rng = jax.random.PRNGKey(self.seed)
        # every consumption of the host PRNG stream goes through _next_key
        # so its position is a single counter — checkpointed, and
        # fast-forwarded on resume (ADVICE r5 #4: kill+resume replays the
        # exact dropout/rng keys of an uninterrupted run)
        self._rng_splits = 0

        def _next_key():
            nonlocal rng
            rng, k = jax.random.split(rng)
            self._rng_splits += 1
            return k

        k_init = _next_key()
        params = (self._init_params if self._init_params is not None
                  else self.model.init(k_init))
        mod_state = (self._init_mod_state if self._init_mod_state is not None
                     else self.model.init_state())
        opt_state = (self._init_opt_state if self._init_opt_state is not None
                     else self.optim_method.init(params))
        if self.strategy is not None:
            params, mod_state, opt_state = self.strategy.place(
                params, mod_state, opt_state)

        step_fn, chunk_fn = self._build_step()
        eval_fn = self._build_eval() if self._val_methods else None

        # --obs: per-step phase histograms flow into the shared registry
        # (scraped live by the --metricsPort listener); the device-wait
        # split needs a per-dispatch sync, so it only runs under obs —
        # obs-off keeps the async dispatch pipeline untouched
        obs_on = _obs_enabled()
        if obs_on:
            from bigdl_tpu.obs.metrics import get_registry, phase_histograms
            self._obs_hists = phase_histograms(get_registry(), "train")
        capture = self._obs_capture

        driver = {"epoch": 1, "iteration": 0, "prev_iteration": 0,
                  "epoch_finished": False, "loss": float("inf")}
        rd = getattr(self, "_resume_driver", None)
        self._skip_records = 0
        if rd:
            driver["iteration"] = rd.get("iteration", 0)
            driver["prev_iteration"] = driver["iteration"]
            driver["epoch"] = rd.get("epoch", 1)
            # step-equivalent resume (ADVICE r5 #4): put the PRNG stream,
            # the per-epoch shuffle chain, and the data cursor back where
            # the killed process left them. Older snapshots carry no
            # counters and keep the counters-only behavior.
            while self._rng_splits < rd.get("rng_splits", 0):
                _next_key()
            for _ in range(driver["epoch"] - 1):  # one shuffle per rollover
                self.dataset.shuffle()
            self._skip_records = rd.get("epoch_records", 0)
            logger.info("Resuming at epoch %d, iteration %d (rng stream at "
                        "%d splits, skipping %d consumed records)",
                        driver["epoch"], driver["iteration"],
                        self._rng_splits, self._skip_records)
        wall_start = time.time()
        self._wall_start = wall_start
        records_this_epoch = 0
        _end = object()  # end-of-epoch sentinel (None could be a real batch)
        last_log_t = time.time()
        fetch_accum = 0.0

        def after_dispatch(n_rec, n_iters, t0, loss):
            """Advance counters and emit the log point after one dispatch
            (one step, or a steps_per_dispatch chunk of n_iters steps)."""
            nonlocal last_log_t, fetch_accum, records_this_epoch
            prev_it = driver["iteration"]
            driver["prev_iteration"] = prev_it
            driver["iteration"] = prev_it + n_iters
            # keep `loss` a device array between log points so dispatch
            # N+1 can be enqueued while N still runs on device
            driver["loss"] = loss
            records_this_epoch += n_rec
            driver["epoch_records"] = records_this_epoch  # resume cursor
            # crossing-based (== modulo for n_iters=1): a chunk that jumps
            # the counter past a multiple of log_every still logs
            if driver["iteration"] // self.log_every != prev_it // self.log_every:
                loss_f = float(loss)
                driver["loss"] = loss_f
                if self.nan_check and not math.isfinite(loss_f):
                    raise FloatingPointError(
                        f"loss became {loss_f} at iteration "
                        f"{driver['iteration']} (epoch "
                        f"{driver['epoch']}) — NaN guard tripped; last "
                        f"checkpoint is the recovery point")
                dt = time.time() - t0
                # both counters cover the SAME interval (since the last
                # log point), so their sums are comparable: host wall
                # time = batch fetch + compute/dispatch/device wait
                now = time.time()
                self.metrics.add("get batch time", fetch_accum)
                self.metrics.add("computing time",
                                 (now - last_log_t) - fetch_accum)
                last_log_t, fetch_accum = now, 0.0
                logger.info(
                    "Train %d in %.4fs. Throughput is %.1f "
                    "records/second. Loss is %.4f",
                    n_rec, dt, n_rec / max(dt, 1e-9), loss_f)
                self._summary_write("train", {
                    "iteration": driver["iteration"],
                    "epoch": driver["epoch"],
                    "loss": loss_f,
                    "records_per_second": n_rec / max(dt, 1e-9)})
                # reference logs metrics.summary() at debug each
                # iteration (DistriOptimizer.scala:245); guard so the
                # string is only built when it will be emitted
                if logger.isEnabledFor(logging.DEBUG):
                    logger.debug("%s", self.metrics.summary())

        def _shape_sig(b):
            bx, by = b
            return (np.shape(bx), tuple(
                np.shape(l) for l in jax.tree_util.tree_leaves(by)))

        K = self.steps_per_dispatch
        while not self.end_when(driver):
            driver["epoch_finished"] = False
            epoch_start = time.time()
            ph_snap = dict(self._phase_totals)  # epoch-delta baseline
            records_this_epoch = 0
            driver["epoch_records"] = 0
            opt_state = self.optim_method.set_epoch(opt_state, driver["epoch"])
            data_iter = iter(self.dataset)
            if self._skip_records:
                # mid-epoch resume: drop the leading records the killed
                # process already trained on, so the epoch continues at
                # the same cursor instead of replaying from its start
                skip, self._skip_records = self._skip_records, 0
                skipped = 0
                while skipped < skip:
                    b = next(data_iter, _end)
                    if b is _end:
                        break
                    bx, _by = b
                    skipped += len(bx)
                records_this_epoch = skipped
                driver["epoch_records"] = skipped
            pending = None  # batch fetched but shape-incompatible w/ chunk
            epoch_done = False
            while not epoch_done:
                # fetch one dispatch group: a single batch (K=1), or up to
                # K same-shape batches to scan inside one program
                t_fetch = time.time()
                buf = []
                with _span("data_wait"):
                    while len(buf) < K:
                        if pending is not None:
                            b, pending = pending, None
                        else:
                            b = next(data_iter, _end)
                            if b is not _end:
                                _fault_hook("data")  # one visit per fetch
                        if b is _end:
                            epoch_done = True
                            break
                        if buf and _shape_sig(b) != _shape_sig(buf[0]):
                            pending = b  # ragged tail: flush, retry next
                            break
                        buf.append(b)
                dt_fetch = time.time() - t_fetch
                fetch_accum += dt_fetch
                self._obs_phase("data_wait", dt_fetch)
                if not buf:
                    break
                if chunk_fn is not None and len(buf) == K:
                    if capture is not None:
                        capture.on_step(driver["iteration"])
                    t0 = time.time()
                    t_h = time.perf_counter()
                    with _span("h2d", batches=K):
                        xs = jnp.stack([jnp.asarray(bx) for bx, _ in buf])
                        ys = jax.tree_util.tree_map(
                            lambda *ls: jnp.stack(
                                [jnp.asarray(l) for l in ls]),
                            *[by for _, by in buf])
                    self._obs_phase("h2d", time.perf_counter() - t_h)
                    # fault site BEFORE the dispatch and BEFORE the rng
                    # splits: a preemption here loses the whole chunk,
                    # exactly like a kill between dispatches would
                    _fault_hook("step")
                    # same host key sequence as K=1 (counted for resume)
                    keys = [_next_key() for _ in range(K)]
                    t_d = time.perf_counter()
                    try:
                        with _span("dispatch", steps=K):
                            params, mod_state, opt_state, loss = chunk_fn(
                                params, mod_state, opt_state, xs, ys,
                                jnp.stack(keys))
                    except Exception as e:
                        # RESOURCE_EXHAUSTED autopsy (ISSUE 12): write
                        # the MemoryReport to --traceDir + fault log,
                        # then crash exactly as before
                        from bigdl_tpu.obs import memory as _obs_mem
                        _obs_mem.handle_oom(e, "train_dispatch")
                        raise
                    self._obs_phase("dispatch", time.perf_counter() - t_d)
                    if obs_on:
                        # true device wait: only metered under --obs (the
                        # sync costs dispatch pipelining; that delta is
                        # the obs overhead A/B in tpu_capture_r12.sh)
                        t_w = time.perf_counter()
                        with _span("device"):
                            jax.block_until_ready(loss)
                        self._obs_phase("device",
                                        time.perf_counter() - t_w)
                    after_dispatch(sum(len(bx) for bx, _ in buf), K, t0,
                                   loss)
                    self._maybe_validate(eval_fn, params, mod_state, driver)
                    self._maybe_checkpoint(params, mod_state, opt_state,
                                           driver)
                    if self.end_when(driver):
                        break
                    continue
                for x, y in buf:  # K == 1, or a ragged/short group
                    if capture is not None:
                        capture.on_step(driver["iteration"])
                    t0 = time.time()
                    # fault site before the step's rng split + dispatch:
                    # a preemption loses this step, as a real kill would
                    _fault_hook("step")
                    t_h = time.perf_counter()
                    with _span("h2d"):
                        if isinstance(x, jax.Array):
                            # staged upstream (pipeline --stage device):
                            # the batch is already committed to device
                            # (and to the strategy's sharded layout) —
                            # dispatch no longer pays the h2d copy
                            pass
                        elif self.strategy is not None:
                            x, y = self.strategy.shard_batch(x, y)
                        else:
                            # target may be a pytree (Mixup's
                            # (y_a, y_b, lam))
                            x = jnp.asarray(x)
                            y = jax.tree_util.tree_map(jnp.asarray, y)
                    self._obs_phase("h2d", time.perf_counter() - t_h)
                    k_step = _next_key()
                    t_d = time.perf_counter()
                    try:
                        with _span("dispatch"):
                            params, mod_state, opt_state, loss = step_fn(
                                params, mod_state, opt_state, x, y,
                                k_step)
                    except Exception as e:
                        from bigdl_tpu.obs import memory as _obs_mem
                        _obs_mem.handle_oom(e, "train_dispatch")
                        raise
                    self._obs_phase("dispatch", time.perf_counter() - t_d)
                    if obs_on:
                        t_w = time.perf_counter()
                        with _span("device"):
                            jax.block_until_ready(loss)
                        self._obs_phase("device",
                                        time.perf_counter() - t_w)
                    after_dispatch(len(x), 1, t0, loss)
                    self._maybe_validate(eval_fn, params, mod_state, driver)
                    self._maybe_checkpoint(params, mod_state, opt_state,
                                           driver)
                    if self.end_when(driver):
                        epoch_done = True
                        break
            driver["epoch"] += 1
            driver["epoch_finished"] = True
            driver["epoch_records"] = 0  # next epoch starts at cursor 0
            self.dataset.shuffle()
            dt_e = time.time() - epoch_start
            # surface the phase split EVERY epoch (ISSUE 7 satellite: the
            # old fetch_accum was measured then dropped — the feed-stall
            # gap behind resnet50_pipe's 0.99% MFU, PERF.md §4, was
            # invisible in normal runs). data_wait/dispatch meter in
            # every run; h2d/device only split out under --obs.
            d_wait = (self._phase_totals.get("data_wait", 0.0)
                      - ph_snap.get("data_wait", 0.0))
            d_disp = (self._phase_totals.get("dispatch", 0.0)
                      - ph_snap.get("dispatch", 0.0))
            logger.info(
                "Epoch %d done: %d records in %.2fs (%.1f rec/s; "
                "data_wait %.2fs, dispatch %.2fs, feed stall %.1f%%)",
                driver["epoch"] - 1, records_this_epoch, dt_e,
                records_this_epoch / max(dt_e, 1e-9), d_wait, d_disp,
                100.0 * d_wait / max(dt_e, 1e-9))
            # cumulative phase seconds into the shared registry — live
            # on the --metricsPort listener, or read post-hoc by callers
            from bigdl_tpu.obs.metrics import TRAIN_PHASES, get_registry
            _reg = get_registry()
            for _ph in TRAIN_PHASES:
                d = (self._phase_totals.get(_ph, 0.0)
                     - ph_snap.get(_ph, 0.0))
                if d > 0.0:
                    _reg.counter(
                        f"train_phase_{_ph}_seconds_total",
                        f"cumulative {_ph} phase seconds").inc(d)
            if jax.process_count() > 1:
                # reference driver logs "computing time for each node"
                # via Spark accumulators (Metrics.scala:25-117); the
                # aggregate is a collective, so it runs UNCONDITIONALLY
                # on every host (a log-level guard could deadlock gloo)
                logger.info("%s", self.metrics.summary(aggregate=True))
            self._maybe_validate(eval_fn, params, mod_state, driver)
            self._maybe_checkpoint(params, mod_state, opt_state, driver)

        self._join_ckpt_writer()  # drain any in-flight async write
        logger.info("Training finished after %d iterations in %.1fs",
                    driver["iteration"], time.time() - wall_start)
        return TrainedModel(self.model, params, mod_state)

    # ------------------------------------------------------------- callbacks
    def _maybe_validate(self, eval_fn, params, mod_state, driver):
        if (eval_fn is None or self._val_trigger is None
                or not self._val_trigger(driver)
                or driver["iteration"] == self._last_val_iter):
            return None
        self._last_val_iter = driver["iteration"]
        from bigdl_tpu.optim.validator import run_evaluation
        results = run_evaluation(eval_fn, self._val_dataset,
                                 self._val_methods, params, mod_state,
                                 self.strategy)
        for m, r in zip(self._val_methods, results):
            logger.info("%s is %r", m.name, r)
        self._summary_write("val", {
            "iteration": driver["iteration"],
            "epoch": driver["epoch"],
            **{m.name.replace(" ", "_"): r.result()[0]
               for m, r in zip(self._val_methods, results)}})
        driver["val_results"] = results
        if results:
            # first method's scalar drives Trigger.max_score (time-to-acc)
            driver["val_score"] = float(results[0].result()[0])
        return results

    # -------------------------------------------------------- summaries
    def set_summary(self, directory: str) -> "Optimizer":
        """Append per-log-point train scalars and per-validation metric
        values as JSON lines to <dir>/train.jsonl and <dir>/val.jsonl —
        the plottable training-curve record (the observability the
        reference left to log scraping)."""
        os.makedirs(directory, exist_ok=True)
        self._summary_dir = directory
        return self

    def _summary_write(self, which: str, row: dict) -> None:
        d = getattr(self, "_summary_dir", None)
        if d is None:
            return
        import json
        start = getattr(self, "_wall_start", None)
        if start is not None:  # accuracy-vs-wall-clock curves need time
            row = {**row, "wall_s": round(time.time() - start, 3)}
        with open(os.path.join(d, f"{which}.jsonl"), "a") as f:
            f.write(json.dumps(row) + "\n")

    def _maybe_checkpoint(self, params, mod_state, opt_state, driver):
        if (self._ckpt_path is None or self._ckpt_trigger is None
                or not self._ckpt_trigger(driver)
                or driver["iteration"] == self._last_ckpt_iter):
            return
        # ckpt phase: what the loop thread pays for this snapshot (the
        # async path only pays the device->host copy here; the disk
        # write runs on the worker and is not loop-thread stall)
        t_ck = time.perf_counter()
        try:
            with _span("ckpt", iteration=driver["iteration"]):
                self._write_checkpoint(params, mod_state, opt_state,
                                       driver)
        finally:
            self._obs_phase("ckpt", time.perf_counter() - t_ck)

    def _write_checkpoint(self, params, mod_state, opt_state, driver):
        self._last_ckpt_iter = driver["iteration"]
        n = driver["iteration"]
        target = os.path.join(self._ckpt_path, f"model.{n}")
        overwrite = (getattr(self, "_ckpt_overwrite", False)
                     or _canon_ckpt_path(target)
                     in getattr(self, "_resume_orphans", ()))
        if file_exists(target) and not overwrite:
            raise FileExistsError(
                f"{target} exists; pass overwrite=True to set_checkpoint "
                f"(--overWriteCheckpoint) to clobber it")
        drv = {"epoch": driver["epoch"], "iteration": n,
               # step-equivalent resume counters (ADVICE r5 #4): the host
               # PRNG stream position and the records already consumed in
               # the open epoch (0 at an epoch boundary)
               "rng_splits": int(getattr(self, "_rng_splits", 0)),
               "epoch_records": int(driver.get("epoch_records", 0))}
        plan = getattr(self.dataset, "plan", None)
        if plan is not None and hasattr(plan, "signature"):
            # the executor feed's epoch-plan signature: resume verifies
            # the replayed batch schedule matches the killed run's
            drv["plan"] = plan.signature()
        if getattr(self, "_ckpt_sharded", False):
            # pod-scale path: every host writes its own shards, no gather
            from bigdl_tpu.utils.orbax_ckpt import save_sharded
            save_sharded({"params": params, "mod_state": mod_state,
                          "driver": drv},
                         target, overwrite=overwrite)
            save_sharded(opt_state,
                         os.path.join(self._ckpt_path, f"state.{n}"),
                         overwrite=overwrite)
        else:
            layout = None
            if self.strategy is not None:
                params, mod_state, opt_state = self.strategy.gather(
                    params, mod_state, opt_state)
                # dp layout signature for the topology manifest: the
                # blobs below hold gathered LOGICAL arrays, so a later
                # resume may re-place them into any mesh (ISSUE 11)
                sig = getattr(self.strategy, "layout_signature", None)
                if sig is not None:
                    layout = sig()
            state_target = os.path.join(self._ckpt_path, f"state.{n}")
            if getattr(self, "_ckpt_async", False):
                self._join_ckpt_writer()  # one in-flight write at a time
                # device->host snapshot on the loop thread (cheap, and the
                # arrays must be frozen before the next step mutates them);
                # serialization + IO move to the worker
                snap_model = jax.device_get(
                    {"params": params, "mod_state": mod_state,
                     "driver": drv})
                snap_opt = jax.device_get(opt_state)

                def _write():
                    save_pytree(snap_model, target, layout=layout)
                    save_pytree(snap_opt, state_target, layout=layout)
                    self._gc_ckpts()
                    logger.info("Checkpoint written at iteration %d to %s "
                                "(async)", n, self._ckpt_path)

                import threading
                self._ckpt_thread = threading.Thread(
                    target=self._ckpt_worker, args=(_write,), daemon=True)
                self._ckpt_thread.start()
                return
            save_pytree({"params": params, "mod_state": mod_state,
                         "driver": drv}, target, layout=layout)
            save_pytree(opt_state, state_target, layout=layout)
        self._gc_ckpts()
        logger.info("Checkpoint written at iteration %d to %s", n,
                    self._ckpt_path)

    def _gc_ckpts(self):
        """keep-last-k snapshot GC (set_checkpoint keep_last) — the
        newest checksum-valid pair survives unconditionally."""
        k = getattr(self, "_ckpt_keep_last", None)
        if k:
            from bigdl_tpu.utils.file import gc_checkpoints
            gc_checkpoints(self._ckpt_path, k)

    def _ckpt_worker(self, write_fn):
        try:
            write_fn()
        except BaseException as e:  # surfaced at the next join
            self._ckpt_error = e

    def _join_ckpt_writer(self):
        t = getattr(self, "_ckpt_thread", None)
        if t is not None:
            t.join()
            self._ckpt_thread = None
        err = getattr(self, "_ckpt_error", None)
        if err is not None:
            self._ckpt_error = None
            raise RuntimeError("async checkpoint write failed") from err
