"""Standalone model evaluation (reference optim/Validator.scala:24-40,
LocalValidator.scala:30, DistriValidator.scala:33 — one implementation here;
the local/distributed split is just whether a parallel strategy is supplied).

The Optimizer's in-training validation reuses these helpers, so batch
sharding and result accumulation live in exactly one place.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from bigdl_tpu.optim.validation import ValidationMethod

__all__ = ["Validator", "build_eval_fn", "run_evaluation"]


def build_eval_fn(model, methods: Sequence[ValidationMethod], strategy=None):
    """Jit-compile the device-side half of validation."""

    def eval_step(params, mod_state, x, y):
        out, _ = model.apply(params, mod_state, x, training=False)
        return [m.stats(out, y) for m in methods]

    if strategy is not None:
        return strategy.compile_eval(eval_step)
    return jax.jit(eval_step)


def run_evaluation(eval_fn, dataset, methods: Sequence[ValidationMethod],
                   params, mod_state, strategy=None):
    """One pass over ``dataset``, reducing each method's (value, count)
    monoid across batches (the reference reduces across partitions,
    ValidationMethod.scala:38-51)."""
    accs = None
    for batch in dataset:
        x, y = batch
        if strategy is not None:
            x, y = strategy.shard_batch(x, y)
        else:
            x, y = jnp.asarray(x), jnp.asarray(y)
        stats = [(float(v), int(c)) for v, c in eval_fn(params, mod_state, x, y)]
        accs = stats if accs is None else [
            (a + v, b + c) for (a, b), (v, c) in zip(accs, stats)]
    return [m.to_result(v, c) for m, (v, c) in zip(methods, accs or [])]


class Validator:
    def __init__(self, model, dataset, strategy=None):
        self.model = model
        self.dataset = dataset
        self.strategy = strategy

    def test(self, params, mod_state, methods: Sequence[ValidationMethod]):
        eval_fn = build_eval_fn(self.model, methods, self.strategy)
        return run_evaluation(eval_fn, self.dataset, methods, params,
                              mod_state, self.strategy)
