"""Optimization methods (reference optim/{OptimMethod,SGD,Adagrad,LBFGS}.scala).

Functional form: ``opt.init(params) -> opt_state``;
``opt.update(grads, opt_state, params) -> (new_params, new_opt_state)`` —
pure, jittable, shardable. The step/epoch counters the reference keeps in its
``state: Table`` live inside opt_state so schedules evaluate inside jit.

ZeRO-1 note: opt_state has the same pytree structure as params, so sharding
specs for optimizer-state partitioning (bigdl_tpu.parallel) map 1:1.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.optim.schedules import Default, LearningRateSchedule

__all__ = ["OptimMethod", "SGD", "Adagrad", "Adam", "AdamW", "EMA",
           "LAMB", "LARS", "RMSprop"]


class OptimMethod:
    """Base optimizer (reference optim/OptimMethod.scala:38-47 — its
    ``optimize(feval, x, config, state)`` contract becomes init/update)."""

    def init(self, params) -> Any:
        raise NotImplementedError

    def update(self, grads, opt_state, params):
        """Returns (new_params, new_opt_state)."""
        raise NotImplementedError

    def set_epoch(self, opt_state, epoch: int):
        """Record the current epoch into opt_state (driver loop calls this at
        epoch rollover, mirroring DistriOptimizer's state("epoch"))."""
        if isinstance(opt_state, dict) and "epoch" in opt_state:
            return {**opt_state, "epoch": jnp.asarray(epoch, jnp.float32)}
        return opt_state

    def learning_rate(self, opt_state):
        """Effective lr at the current step (for logging)."""
        return None


class SGD(OptimMethod):
    """SGD with weight decay / momentum / dampening / nesterov and pluggable
    schedules (reference optim/SGD.scala:26-186). Update order matches the
    reference: grad += wd*w; v = mu*v + (1-damp)*grad;
    step = grad + mu*v (nesterov) or v; w -= lr*step.

    ``learning_rates``/``weight_decays`` per-parameter tensors
    (SGD.scala:43) are supported as pytrees matching params.
    """

    def __init__(self, learning_rate: float = 1e-3, weight_decay: float = 0.0,
                 momentum: float = 0.0, dampening: Optional[float] = None,
                 nesterov: bool = False,
                 schedule: Optional[LearningRateSchedule] = None,
                 learning_rates=None, weight_decays=None):
        self.base_lr = learning_rate
        self.weight_decay = weight_decay
        self.momentum = momentum
        self.dampening = momentum if dampening is None else dampening
        self.nesterov = nesterov
        if nesterov and (momentum <= 0 or self.dampening != 0):
            raise ValueError(
                "nesterov requires momentum > 0 and dampening = 0")
        self.schedule = schedule if schedule is not None else Default(0.0)
        self.learning_rates = learning_rates
        self.weight_decays = weight_decays

    def init(self, params):
        st = {"step": jnp.zeros((), jnp.float32),
              "epoch": jnp.zeros((), jnp.float32)}
        if self.momentum > 0:
            st["velocity"] = jax.tree_util.tree_map(jnp.zeros_like, params)
        return st

    def _lr(self, opt_state):
        return self.schedule(self.base_lr, opt_state["step"], opt_state["epoch"])

    def learning_rate(self, opt_state):
        return self._lr(opt_state)

    def update(self, grads, opt_state, params):
        lr = self._lr(opt_state)
        mu, damp = self.momentum, self.dampening

        def one(g, w, v, plr, pwd):
            g = g + pwd * w
            if mu > 0:
                v_new = mu * v + (1.0 - damp) * g
                d = g + mu * v_new if self.nesterov else v_new
            else:
                v_new = v
                d = g
            return w - lr * plr * d, v_new

        vel = opt_state.get("velocity",
                            jax.tree_util.tree_map(lambda x: 0.0, params))
        plrs = (self.learning_rates if self.learning_rates is not None
                else jax.tree_util.tree_map(lambda x: 1.0, params))
        pwds = (self.weight_decays if self.weight_decays is not None
                else jax.tree_util.tree_map(lambda x: self.weight_decay, params))
        out = jax.tree_util.tree_map(one, grads, params, vel, plrs, pwds)
        new_params = jax.tree_util.tree_map(
            lambda t: t[0], out, is_leaf=lambda t: isinstance(t, tuple))
        new_state = dict(opt_state)
        new_state["step"] = opt_state["step"] + 1
        if mu > 0:
            new_state["velocity"] = jax.tree_util.tree_map(
                lambda t: t[1], out, is_leaf=lambda t: isinstance(t, tuple))
        return new_params, new_state


class Adagrad(OptimMethod):
    """Adagrad (reference optim/Adagrad.scala): accumulate squared grads,
    scale by 1/sqrt(acc + eps)."""

    def __init__(self, learning_rate: float = 1e-2, lr_decay: float = 0.0,
                 weight_decay: float = 0.0, eps: float = 1e-10):
        self.base_lr = learning_rate
        self.lr_decay = lr_decay
        self.weight_decay = weight_decay
        self.eps = eps

    def init(self, params):
        return {"step": jnp.zeros((), jnp.float32),
                "epoch": jnp.zeros((), jnp.float32),
                "accum": jax.tree_util.tree_map(jnp.zeros_like, params)}

    def learning_rate(self, opt_state):
        return self.base_lr / (1.0 + opt_state["step"] * self.lr_decay)

    def update(self, grads, opt_state, params):
        lr = self.learning_rate(opt_state)

        def one(g, w, a):
            g = g + self.weight_decay * w
            a_new = a + jnp.square(g)
            return w - lr * g / (jnp.sqrt(a_new) + self.eps), a_new

        out = jax.tree_util.tree_map(one, grads, params, opt_state["accum"])
        first = lambda t: t[0]
        second = lambda t: t[1]
        is_pair = lambda t: isinstance(t, tuple)
        return (jax.tree_util.tree_map(first, out, is_leaf=is_pair),
                {"step": opt_state["step"] + 1,
                 "epoch": opt_state["epoch"],
                 "accum": jax.tree_util.tree_map(second, out, is_leaf=is_pair)})


class Adam(OptimMethod):
    """Adam — not in the reference snapshot but table stakes for a complete
    framework; kept in the same OptimMethod shape."""

    def __init__(self, learning_rate: float = 1e-3, beta1: float = 0.9,
                 beta2: float = 0.999, eps: float = 1e-8,
                 weight_decay: float = 0.0,
                 schedule: Optional[LearningRateSchedule] = None):
        self.base_lr = learning_rate
        self.beta1, self.beta2, self.eps = beta1, beta2, eps
        self.weight_decay = weight_decay
        self.schedule = schedule if schedule is not None else Default(0.0)

    def init(self, params):
        z = jax.tree_util.tree_map(jnp.zeros_like, params)
        return {"step": jnp.zeros((), jnp.float32),
                "epoch": jnp.zeros((), jnp.float32),
                "m": z,
                "v": jax.tree_util.tree_map(jnp.zeros_like, params)}

    def learning_rate(self, opt_state):
        return self.schedule(self.base_lr, opt_state["step"], opt_state["epoch"])

    def update(self, grads, opt_state, params):
        t = opt_state["step"] + 1
        lr = self.schedule(self.base_lr, opt_state["step"], opt_state["epoch"])
        b1, b2 = self.beta1, self.beta2
        bc1 = 1.0 - jnp.power(b1, t)
        bc2 = 1.0 - jnp.power(b2, t)

        def one(g, w, m, v):
            g = g + self.weight_decay * w
            m_new = b1 * m + (1 - b1) * g
            v_new = b2 * v + (1 - b2) * jnp.square(g)
            upd = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + self.eps)
            return w - lr * upd, m_new, v_new

        out = jax.tree_util.tree_map(one, grads, params,
                                     opt_state["m"], opt_state["v"])
        is_t = lambda t_: isinstance(t_, tuple)
        pick = lambda i: jax.tree_util.tree_map(lambda t_: t_[i], out,
                                                is_leaf=is_t)
        return pick(0), {"step": t, "epoch": opt_state["epoch"],
                         "m": pick(1), "v": pick(2)}


class RMSprop(OptimMethod):
    """RMSprop — companion method in the same functional shape."""

    def __init__(self, learning_rate: float = 1e-2, decay_rate: float = 0.99,
                 eps: float = 1e-8):
        self.base_lr = learning_rate
        self.decay_rate = decay_rate
        self.eps = eps

    def init(self, params):
        return {"step": jnp.zeros((), jnp.float32),
                "epoch": jnp.zeros((), jnp.float32),
                "sq": jax.tree_util.tree_map(jnp.zeros_like, params)}

    def update(self, grads, opt_state, params):
        d = self.decay_rate

        def one(g, w, s):
            s_new = d * s + (1 - d) * jnp.square(g)
            return w - self.base_lr * g / (jnp.sqrt(s_new) + self.eps), s_new

        out = jax.tree_util.tree_map(one, grads, params, opt_state["sq"])
        is_t = lambda t: isinstance(t, tuple)
        return (jax.tree_util.tree_map(lambda t: t[0], out, is_leaf=is_t),
                {"step": opt_state["step"] + 1, "epoch": opt_state["epoch"],
                 "sq": jax.tree_util.tree_map(lambda t: t[1], out, is_leaf=is_t)})


class AdamW(Adam):
    """Adam with decoupled weight decay (Loshchilov & Hutter) — the wd
    term scales the weight directly instead of entering the moments.
    Beyond the reference; same OptimMethod shape."""

    def update(self, grads, opt_state, params):
        t = opt_state["step"] + 1
        lr = self.schedule(self.base_lr, opt_state["step"],
                           opt_state["epoch"])
        b1, b2 = self.beta1, self.beta2
        bc1 = 1.0 - jnp.power(b1, t)
        bc2 = 1.0 - jnp.power(b2, t)

        def one(g, w, m, v):
            m_new = b1 * m + (1 - b1) * g
            v_new = b2 * v + (1 - b2) * jnp.square(g)
            upd = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + self.eps)
            return w - lr * (upd + self.weight_decay * w), m_new, v_new

        out = jax.tree_util.tree_map(one, grads, params,
                                     opt_state["m"], opt_state["v"])
        is_t = lambda t_: isinstance(t_, tuple)
        pick = lambda i: jax.tree_util.tree_map(lambda t_: t_[i], out,
                                                is_leaf=is_t)
        return pick(0), {"step": t, "epoch": opt_state["epoch"],
                         "m": pick(1), "v": pick(2)}


class LAMB(Adam):
    """Layer-wise adaptive large-batch Adam (You et al., the optimizer
    behind 76-minute BERT): the AdamW update direction is rescaled per
    layer by ||w|| / ||update||, so every layer moves a comparable
    relative distance regardless of its gradient scale. The transformer
    counterpart of LARS for the b512+ regime; bias/LN leaves (ndim <= 1)
    skip the trust-ratio and weight decay as in LARS."""

    def update(self, grads, opt_state, params):
        t = opt_state["step"] + 1
        lr = self.schedule(self.base_lr, opt_state["step"],
                           opt_state["epoch"])
        b1, b2 = self.beta1, self.beta2
        bc1 = 1.0 - jnp.power(b1, t)
        bc2 = 1.0 - jnp.power(b2, t)

        def one(g, w, m, v):
            m_new = b1 * m + (1 - b1) * g
            v_new = b2 * v + (1 - b2) * jnp.square(g)
            upd = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + self.eps)
            if w.ndim <= 1:
                return w - lr * upd, m_new, v_new
            upd = upd + self.weight_decay * w
            wn = jnp.sqrt(jnp.sum(jnp.square(w)))
            un = jnp.sqrt(jnp.sum(jnp.square(upd)))
            trust = jnp.where((wn > 0) & (un > 0), wn / un, 1.0)
            return w - lr * trust * upd, m_new, v_new

        out = jax.tree_util.tree_map(one, grads, params,
                                     opt_state["m"], opt_state["v"])
        is_t = lambda t_: isinstance(t_, tuple)
        pick = lambda i: jax.tree_util.tree_map(lambda t_: t_[i], out,
                                                is_leaf=is_t)
        return pick(0), {"step": t, "epoch": opt_state["epoch"],
                         "m": pick(1), "v": pick(2)}


class LARS(OptimMethod):
    """Layer-wise Adaptive Rate Scaling (You et al.) — the large-batch
    ImageNet optimizer: each layer's step is scaled by
    trust * ||w|| / (||g|| + wd*||w|| + eps), then momentum-SGD applies.
    Bias/BN leaves (ndim <= 1) skip both adaptation and weight decay, the
    standard exclusion. Pairs with the b512+ batch sizes the v5e MFU
    trajectory targets (PERF.md)."""

    def __init__(self, learning_rate: float = 1.0, momentum: float = 0.9,
                 weight_decay: float = 0.0, trust: float = 0.001,
                 eps: float = 1e-9,
                 schedule: Optional[LearningRateSchedule] = None):
        self.base_lr = learning_rate
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.trust = trust
        self.eps = eps
        self.schedule = schedule if schedule is not None else Default(0.0)

    def init(self, params):
        return {"step": jnp.zeros((), jnp.float32),
                "epoch": jnp.zeros((), jnp.float32),
                "velocity": jax.tree_util.tree_map(jnp.zeros_like, params)}

    def learning_rate(self, opt_state):
        return self.schedule(self.base_lr, opt_state["step"],
                             opt_state["epoch"])

    def update(self, grads, opt_state, params):
        lr = self.learning_rate(opt_state)
        mu, wd = self.momentum, self.weight_decay

        def one(g, w, v):
            if w.ndim <= 1:  # bias/BN: plain momentum SGD, no wd/adaptation
                v_new = mu * v + g
                return w - lr * v_new, v_new
            wn = jnp.sqrt(jnp.sum(jnp.square(w)))
            gn = jnp.sqrt(jnp.sum(jnp.square(g)))
            local = jnp.where(
                (wn > 0) & (gn > 0),
                self.trust * wn / (gn + wd * wn + self.eps), 1.0)
            v_new = mu * v + local * (g + wd * w)
            return w - lr * v_new, v_new

        out = jax.tree_util.tree_map(one, grads, params,
                                     opt_state["velocity"])
        is_t = lambda t: isinstance(t, tuple)
        return (jax.tree_util.tree_map(lambda t: t[0], out, is_leaf=is_t),
                {"step": opt_state["step"] + 1, "epoch": opt_state["epoch"],
                 "velocity": jax.tree_util.tree_map(lambda t: t[1], out,
                                                    is_leaf=is_t)})


def clip_by_global_norm(grads, max_norm: float):
    """Scale the whole gradient pytree so its global L2 norm <= max_norm
    (reference Optimizer.setGradientClippingByl2Norm — the later-BigDL API
    the Optimizer facade mirrors)."""
    sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
             for g in jax.tree_util.tree_leaves(grads))
    norm = jnp.sqrt(sq)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return jax.tree_util.tree_map(lambda g: (g * scale).astype(g.dtype),
                                  grads), norm


def clip_by_value(grads, lo: float, hi: float):
    """Elementwise constant clipping (reference
    Optimizer.setConstantGradientClipping)."""
    return jax.tree_util.tree_map(lambda g: jnp.clip(g, lo, hi), grads)


class EMA(OptimMethod):
    """Exponential moving average of the weights, wrapped around any
    inner OptimMethod: ema = decay*ema + (1-decay)*w after each update
    (seeded from the init weights, so no debias term is needed).
    Evaluate with :meth:`ema_params` — the standard eval-smoothing trick;
    beyond the reference."""

    def __init__(self, inner: OptimMethod, decay: float = 0.999):
        self.inner = inner
        self.decay = decay
        self.schedule = getattr(inner, "schedule", None)

    def init(self, params):
        return {"inner": self.inner.init(params),
                "ema": jax.tree_util.tree_map(jnp.array, params)}

    def learning_rate(self, opt_state):
        return self.inner.learning_rate(opt_state["inner"])

    def update(self, grads, opt_state, params):
        new_p, inner_st = self.inner.update(grads, opt_state["inner"],
                                            params)
        d = self.decay
        ema = jax.tree_util.tree_map(
            lambda e, w: d * e + (1 - d) * w, opt_state["ema"], new_p)
        return new_p, {"inner": inner_st, "ema": ema}

    def ema_params(self, opt_state):
        return opt_state["ema"]
