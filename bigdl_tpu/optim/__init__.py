from bigdl_tpu.optim.method import (
    OptimMethod, SGD, Adagrad, Adam, AdamW, EMA, LAMB, LARS, RMSprop,
    clip_by_global_norm, clip_by_value,
)
from bigdl_tpu.optim.schedules import (
    LearningRateSchedule, Default, Poly, Step, EpochDecay, EpochStep,
    Regime, EpochSchedule, CosineAnnealing, Warmup,
)
from bigdl_tpu.optim.triggers import Trigger
from bigdl_tpu.optim.validation import (
    ValidationMethod, ValidationResult, AccuracyResult, LossResult,
    PerplexityResult, Top1Accuracy, Top5Accuracy, Loss, Perplexity,
)
from bigdl_tpu.optim.lbfgs import LBFGS, line_search_wolfe
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.optim.optimizer import Optimizer, TrainedModel
from bigdl_tpu.optim.validator import Validator
