"""Triggers drive end-of-training, validation, and checkpoint cadence
(reference optim/Trigger.scala:26-70). A trigger is a predicate over the
driver's scalar state (host-side, never traced)."""

from __future__ import annotations

from typing import Callable, Dict

__all__ = ["Trigger"]


class Trigger:
    def __init__(self, fn: Callable[[Dict], bool], desc: str):
        self._fn = fn
        self.desc = desc

    def __call__(self, driver_state: Dict) -> bool:
        return self._fn(driver_state)

    def __repr__(self):
        return f"Trigger({self.desc})"

    # -- factories (names match the reference object Trigger) ---------------
    @staticmethod
    def every_epoch() -> "Trigger":
        """Fires at each epoch rollover (reference Trigger.everyEpoch :27)."""
        return Trigger(lambda s: s.get("epoch_finished", False), "everyEpoch")

    @staticmethod
    def several_iteration(n: int) -> "Trigger":
        """Fires every n iterations (reference Trigger.severalIteration :47).

        Crossing-based, not modulo-based: fires when the iteration counter
        crosses a multiple of ``n`` since the previous dispatch
        (``prev_iteration`` in the driver state). With one step per
        dispatch this is exactly the reference's ``iteration % n == 0``;
        with ``steps_per_dispatch > 1`` the counter advances in chunks and
        a modulo test would skip fires whenever the chunk size does not
        divide ``n``."""
        def fn(s):
            it = s["iteration"]
            prev = s.get("prev_iteration", it - 1)
            return it > 0 and it // n != prev // n
        return Trigger(fn, f"severalIteration({n})")

    @staticmethod
    def max_epoch(n: int) -> "Trigger":
        """True once epoch > n (reference Trigger.maxEpoch :56; epochs are
        1-based like the reference)."""
        return Trigger(lambda s: s["epoch"] > n, f"maxEpoch({n})")

    @staticmethod
    def max_iteration(n: int) -> "Trigger":
        """(reference Trigger.maxIteration :64)"""
        return Trigger(lambda s: s["iteration"] >= n, f"maxIteration({n})")

    @staticmethod
    def min_loss(v: float) -> "Trigger":
        return Trigger(lambda s: s.get("loss", float("inf")) < v, f"minLoss({v})")

    @staticmethod
    def max_score(v: float) -> "Trigger":
        """True once the latest validation score (first validation
        method, e.g. Top1Accuracy) reaches ``v`` — the stop condition for
        time-to-accuracy runs (reference Trigger.maxScore)."""
        return Trigger(lambda s: s.get("val_score", 0.0) >= v,
                       f"maxScore({v})")

    @staticmethod
    def and_(*ts: "Trigger") -> "Trigger":
        return Trigger(lambda s: all(t(s) for t in ts), "and")

    @staticmethod
    def or_(*ts: "Trigger") -> "Trigger":
        return Trigger(lambda s: any(t(s) for t in ts), "or")
