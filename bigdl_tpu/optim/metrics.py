"""Named metric counters (reference optim/Metrics.scala:25-117).

The reference aggregates counters across the cluster with Spark
accumulators (each executor adds into a driver-visible accumulator, so
the driver can log "computing time for each node"). Here counters are
host-side (per-process); :meth:`Metrics.aggregate` is the accumulator
analog — an ``process_allgather`` of the counter vector under
``jax.distributed``, giving every host the per-node values plus global
sum/mean. ``summary(aggregate=True)`` renders the per-node rows at the
same log points the reference does. The collective is symmetric: every
process must reach the same aggregate() call (the Optimizer calls it at
epoch end on all hosts).
"""

from __future__ import annotations

import threading
from typing import Dict

__all__ = ["Metrics"]


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._sum: Dict[str, float] = {}
        self._count: Dict[str, int] = {}

    def set(self, name: str, value: float) -> None:
        with self._lock:
            self._sum[name] = float(value)
            self._count[name] = 1

    def add(self, name: str, value: float) -> None:
        with self._lock:
            self._sum[name] = self._sum.get(name, 0.0) + float(value)
            self._count[name] = self._count.get(name, 0) + 1

    def get(self, name: str) -> tuple[float, int]:
        with self._lock:
            return self._sum.get(name, 0.0), self._count.get(name, 0)

    def mean(self, name: str) -> float:
        s, c = self.get(name)
        return s / c if c else 0.0

    def reset(self) -> None:
        with self._lock:
            self._sum.clear()
            self._count.clear()

    def aggregate(self) -> Dict[str, dict]:
        """Cross-process view of every counter:
        ``{name: {"per_host": [v0, v1, ...], "sum": s, "mean": m}}``
        (reference Metrics.scala distributed accumulators — "computing
        time for each node"). Single-process: per_host has one entry.
        Under ``jax.distributed`` this is a collective (one small
        allgather); every process must call it at the same point, and the
        key set must match across processes (same training loop ⇒ same
        counters)."""
        import jax

        with self._lock:
            keys = sorted(self._sum)
            vals = [self._sum[k] for k in keys]
        if jax.process_count() == 1:
            return {k: {"per_host": [v], "sum": v, "mean": v}
                    for k, v in zip(keys, vals)}
        import numpy as np
        from jax.experimental import multihost_utils

        vec = np.asarray(vals, np.float64)
        gathered = np.asarray(
            multihost_utils.process_allgather(vec))  # (n_proc, n_keys)
        out = {}
        for i, k in enumerate(keys):
            per_host = gathered[:, i].tolist()
            out[k] = {"per_host": per_host,
                      "sum": float(gathered[:, i].sum()),
                      "mean": float(gathered[:, i].mean())}
        return out

    def summary(self, unit: str = "s", scale: float = 1.0,
                aggregate: bool = False) -> str:
        """Pretty-print all counters (reference Metrics.summary :99).
        ``aggregate=True`` adds per-node rows via :meth:`aggregate`
        (collective — call symmetrically on every process)."""
        if aggregate:
            agg = self.aggregate()
            lines = []
            for k, a in sorted(agg.items()):
                nodes = " ".join(f"node{i}={v / scale:.4g}{unit}"
                                 for i, v in enumerate(a["per_host"]))
                lines.append(f"  {k}: sum={a['sum'] / scale:.4g}{unit} "
                             f"mean={a['mean'] / scale:.4g}{unit} [{nodes}]")
            return "\n".join(["Metrics (all nodes):"] + lines)
        with self._lock:
            lines = [f"  {k}: sum={v / scale:.4g}{unit} "
                     f"mean={v / max(1, self._count[k]) / scale:.4g}{unit}"
                     for k, v in sorted(self._sum.items())]
        return "\n".join(["Metrics:"] + lines)
