"""Named metric counters (reference optim/Metrics.scala:25-117).

The reference aggregates counters across the cluster with Spark
accumulators; here counters are host-side (per-process), and multi-host
aggregation — when running under jax.distributed — is a psum over a tiny
array done by the caller. The API (set/add/summary) matches the reference.
"""

from __future__ import annotations

import threading
from typing import Dict

__all__ = ["Metrics"]


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._sum: Dict[str, float] = {}
        self._count: Dict[str, int] = {}

    def set(self, name: str, value: float) -> None:
        with self._lock:
            self._sum[name] = float(value)
            self._count[name] = 1

    def add(self, name: str, value: float) -> None:
        with self._lock:
            self._sum[name] = self._sum.get(name, 0.0) + float(value)
            self._count[name] = self._count.get(name, 0) + 1

    def get(self, name: str) -> tuple[float, int]:
        with self._lock:
            return self._sum.get(name, 0.0), self._count.get(name, 0)

    def mean(self, name: str) -> float:
        s, c = self.get(name)
        return s / c if c else 0.0

    def reset(self) -> None:
        with self._lock:
            self._sum.clear()
            self._count.clear()

    def summary(self, unit: str = "s", scale: float = 1.0) -> str:
        """Pretty-print all counters (reference Metrics.summary :99)."""
        with self._lock:
            lines = [f"  {k}: sum={v / scale:.4g}{unit} "
                     f"mean={v / max(1, self._count[k]) / scale:.4g}{unit}"
                     for k, v in sorted(self._sum.items())]
        return "\n".join(["Metrics:"] + lines)
