"""Learning-rate schedules (reference optim/SGD.scala:103-186).

A schedule is a pure function of (step, epoch) -> multiplier-adjusted lr,
so it can be evaluated inside a jitted train step from traced counters.
Hyperparameter names follow the reference (Poly/Step/EpochStep/EpochDecay/
Default/Regime EpochSchedule).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import jax.numpy as jnp

__all__ = [
    "LearningRateSchedule", "Default", "Poly", "Step", "EpochDecay",
    "EpochStep", "Regime", "EpochSchedule",
]


class LearningRateSchedule:
    """lr(base_lr, step, epoch) -> effective learning rate (a jnp scalar)."""

    def __call__(self, base_lr, step, epoch):
        raise NotImplementedError


@dataclass
class Default(LearningRateSchedule):
    """base_lr / (1 + step * decay) (reference SGD.Default :174)."""

    decay: float = 0.0

    def __call__(self, base_lr, step, epoch):
        return base_lr / (1.0 + step * self.decay)


@dataclass
class Poly(LearningRateSchedule):
    """base_lr * (1 - step/max_iteration)^power, 0 after max_iteration
    (reference SGD.Poly :119 — the Inception-v1 ImageNet schedule,
    models/inception/Train.scala:77-83)."""

    power: float
    max_iteration: int

    def __call__(self, base_lr, step, epoch):
        frac = jnp.clip(step / self.max_iteration, 0.0, 1.0)
        return base_lr * jnp.power(1.0 - frac, self.power)


@dataclass
class Step(LearningRateSchedule):
    """base_lr * gamma^(step // step_size) (reference SGD.Step :134)."""

    step_size: int
    gamma: float

    def __call__(self, base_lr, step, epoch):
        return base_lr * jnp.power(self.gamma, jnp.floor(step / self.step_size))


@dataclass
class EpochDecay(LearningRateSchedule):
    """base_lr * decay_fn(epoch) with a host-side python decay function
    (reference SGD.EpochDecay :149). The callable must be jnp-traceable."""

    decay_fn: object

    def __call__(self, base_lr, step, epoch):
        return base_lr * self.decay_fn(epoch)


@dataclass
class EpochStep(LearningRateSchedule):
    """base_lr * gamma^(epoch // step_size) (reference SGD.EpochStep :160)."""

    step_size: int
    gamma: float

    def __call__(self, base_lr, step, epoch):
        return base_lr * jnp.power(self.gamma, jnp.floor(epoch / self.step_size))


@dataclass
class Regime:
    """[start_epoch, end_epoch] -> lr override (reference SGD.Regime)."""

    start_epoch: int
    end_epoch: int
    lr: float


@dataclass
class EpochSchedule(LearningRateSchedule):
    """Piecewise-constant lr by epoch regime (reference SGD.EpochSchedule :108)."""

    regimes: Sequence[Regime]

    def __call__(self, base_lr, step, epoch):
        lr = jnp.asarray(base_lr, jnp.float32)
        for r in self.regimes:
            hit = (epoch >= r.start_epoch) & (epoch <= r.end_epoch)
            lr = jnp.where(hit, r.lr, lr)
        return lr


@dataclass
class CosineAnnealing(LearningRateSchedule):
    """base_lr * (min_frac + (1-min_frac) * 0.5*(1+cos(pi*step/total)))
    — the standard TPU LLM/large-batch schedule (beyond the reference;
    pairs with Warmup and LARS for the b512+ regime)."""

    total_steps: int
    min_frac: float = 0.0

    def __call__(self, base_lr, step, epoch):
        frac = jnp.clip(step / self.total_steps, 0.0, 1.0)
        cos = 0.5 * (1.0 + jnp.cos(jnp.pi * frac))
        return base_lr * (self.min_frac + (1.0 - self.min_frac) * cos)


@dataclass
class Warmup(LearningRateSchedule):
    """Linear warmup over ``warmup_steps`` then hand off to ``after``
    (counted from the end of warmup). Large-batch recipes (LARS, b>=512)
    are unstable without it."""

    warmup_steps: int
    after: LearningRateSchedule = None  # None -> constant base_lr

    def __call__(self, base_lr, step, epoch):
        warm = base_lr * jnp.minimum(
            1.0, (step + 1.0) / jnp.maximum(1.0, self.warmup_steps))
        if self.after is None:
            rest = base_lr
        else:
            rest = self.after(base_lr, jnp.maximum(0.0,
                                                   step - self.warmup_steps),
                              epoch)
        return jnp.where(step < self.warmup_steps, warm, rest)
