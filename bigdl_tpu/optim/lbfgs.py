"""L-BFGS with strong-Wolfe line search (reference optim/LBFGS.scala:26-287,
optim/LineSearch.scala `lswolfe`).

The reference's L-BFGS consumes a ``feval: x -> (loss, grad)`` closure and
iterates full-batch quasi-Newton steps with an optional Wolfe line search.
That contract survives here unchanged — it is the one optimizer whose inner
loop (line search with data-dependent trip count) should NOT live inside a
single ``jit``: the *feval* is jitted (one XLA computation per probe), while
the two-loop recursion and the line search run as cheap host code on flat
vectors. History buffers (s, y, rho) are kept as device arrays so the
two-loop recursion is a handful of fused dot/axpy kernels.

API::

    opt = LBFGS(max_iter=100, line_search=True)
    params, losses = opt.optimize(feval, params)

where ``feval(params) -> (loss, grads)`` over the full batch — typically
``jax.jit(jax.value_and_grad(loss_fn))``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

__all__ = ["LBFGS", "line_search_wolfe"]


def _cubic_interpolate(x1, f1, g1, x2, f2, g2, bounds=None):
    """Minimizer of the cubic through (x1,f1,g1),(x2,f2,g2); falls back to
    bisection when the cubic has no minimum in the bracket (same fallback the
    reference's lswolfe uses, optim/LineSearch.scala)."""
    if bounds is not None:
        lo, hi = bounds
    else:
        lo, hi = (x1, x2) if x1 <= x2 else (x2, x1)
    d1 = g1 + g2 - 3 * (f1 - f2) / (x1 - x2)
    d2_sq = d1 * d1 - g1 * g2
    if d2_sq >= 0:
        d2 = d2_sq ** 0.5
        if x1 <= x2:
            t = x2 - (x2 - x1) * ((g2 + d2 - d1) / (g2 - g1 + 2 * d2))
        else:
            t = x1 - (x1 - x2) * ((g1 + d2 - d1) / (g1 - g2 + 2 * d2))
        return min(max(t, lo), hi)
    return (lo + hi) / 2.0


def line_search_wolfe(feval_dir: Callable[[float], tuple[float, float]],
                      t: float, f0: float, g0: float,
                      c1: float = 1e-4, c2: float = 0.9,
                      tol_change: float = 1e-9, max_ls: int = 25):
    """Strong-Wolfe line search along a fixed direction.

    ``feval_dir(t) -> (f(x + t*d), f'(x + t*d)·d)``. Returns
    ``(t, f_t, n_evals)`` satisfying sufficient decrease (c1) and curvature
    (c2), the same conditions as the reference's ``lswolfe``
    (optim/LineSearch.scala).
    """
    f_t, g_t = feval_dir(t)
    n_evals = 1

    # Bracketing phase.
    t_prev, f_prev, g_prev = 0.0, f0, g0
    bracket = None
    done = False
    while n_evals < max_ls:
        if f_t > f0 + c1 * t * g0 or (n_evals > 1 and f_t >= f_prev):
            bracket = (t_prev, f_prev, g_prev, t, f_t, g_t)
            break
        if abs(g_t) <= -c2 * g0:
            done = True
            break
        if g_t >= 0:
            bracket = (t, f_t, g_t, t_prev, f_prev, g_prev)
            break
        # expand
        min_step = t + 0.01 * (t - t_prev)
        max_step = t * 10
        tmp = t
        t = _cubic_interpolate(t_prev, f_prev, g_prev, t, f_t, g_t,
                               bounds=(min_step, max_step))
        t_prev, f_prev, g_prev = tmp, f_t, g_t
        f_t, g_t = feval_dir(t)
        n_evals += 1

    if done or bracket is None:
        return t, f_t, n_evals

    # Zoom phase on the bracket.
    t_lo, f_lo, g_lo, t_hi, f_hi, g_hi = bracket
    insuf_progress = False
    satisfied = False
    while n_evals < max_ls:
        if abs(t_hi - t_lo) * abs(g0) < tol_change:
            break
        t = _cubic_interpolate(t_lo, f_lo, g_lo, t_hi, f_hi, g_hi)
        # Guard against stagnation at the bracket edge (torch-style 0.1 eps
        # nudge; keeps the zoom making progress on flat cubics).
        eps = 0.1 * abs(t_hi - t_lo)
        lo_b, hi_b = min(t_lo, t_hi), max(t_lo, t_hi)
        if min(abs(t - lo_b), abs(hi_b - t)) < eps:
            if insuf_progress or t >= hi_b or t <= lo_b:
                t = hi_b - eps if abs(t - hi_b) < abs(t - lo_b) else lo_b + eps
                insuf_progress = False
            else:
                insuf_progress = True
        else:
            insuf_progress = False
        f_t, g_t = feval_dir(t)
        n_evals += 1
        if f_t > f0 + c1 * t * g0 or f_t >= f_lo:
            t_hi, f_hi, g_hi = t, f_t, g_t
        else:
            if abs(g_t) <= -c2 * g0:
                satisfied = True
                break
            if g_t * (t_hi - t_lo) >= 0:
                t_hi, f_hi, g_hi = t_lo, f_lo, g_lo
            t_lo, f_lo, g_lo = t, f_t, g_t
    if not satisfied:
        # zoom exhausted without meeting Wolfe: commit the best point in
        # hand (the low bracket endpoint), never the last rejected probe
        t, f_t = t_lo, f_lo
    return t, f_t, n_evals


class LBFGS:
    """Limited-memory BFGS (reference optim/LBFGS.scala:26-287).

    Parameters mirror the reference's config Table: ``max_iter`` (maxIter),
    ``max_eval`` (maxEval, default maxIter*1.25), ``tol_fun``/``tol_x``,
    ``n_correction`` (history size), ``learning_rate``, and ``line_search``
    (True => strong Wolfe, the reference's lswolfe; False => fixed step with
    the first-iteration 1/||g||_1 scaling, LBFGS.scala's no-lineSearch branch).
    """

    def __init__(self, max_iter: int = 20, max_eval: Optional[int] = None,
                 tol_fun: float = 1e-5, tol_x: float = 1e-9,
                 n_correction: int = 100, learning_rate: float = 1.0,
                 line_search: bool = True):
        self.max_iter = max_iter
        self.max_eval = max_eval if max_eval is not None else int(
            max_iter * 1.25)
        self.tol_fun = tol_fun
        self.tol_x = tol_x
        self.n_correction = n_correction
        self.learning_rate = learning_rate
        self.line_search = line_search

    def optimize(self, feval: Callable[[Any], tuple[Any, Any]], params):
        """Run up to max_iter L-BFGS iterations. Returns (params, losses)."""
        x, unravel = ravel_pytree(params)
        x = x.astype(jnp.float32)

        def feval_flat(xf):
            loss, grads = feval(unravel(xf))
            gf, _ = ravel_pytree(grads)
            return jnp.asarray(loss, jnp.float32), gf.astype(jnp.float32)

        f, g = feval_flat(x)
        losses = [float(f)]
        n_eval = 1
        if float(jnp.abs(g).max()) <= 1e-10:  # already at a critical point
            return unravel(x), losses

        s_hist: list[jax.Array] = []
        y_hist: list[jax.Array] = []
        rho_hist: list[float] = []
        g_prev = None
        t = self.learning_rate
        h_diag = 1.0

        for it in range(self.max_iter):
            # ---- direction via two-loop recursion -------------------------
            if g_prev is None:
                d = -g
            else:
                y = g - g_prev
                s = t * d
                ys = float(jnp.dot(y, s))
                if ys > 1e-10:  # curvature condition (LBFGS.scala history gate)
                    if len(s_hist) == self.n_correction:
                        s_hist.pop(0), y_hist.pop(0), rho_hist.pop(0)
                    s_hist.append(s)
                    y_hist.append(y)
                    rho_hist.append(1.0 / ys)
                    h_diag = ys / float(jnp.dot(y, y))
                # two-loop recursion: a_i/b_i stay 0-d device arrays so the
                # whole direction computation is dispatched without a single
                # host<->device sync (syncs happen only at the per-iteration
                # convergence checks below)
                q = -g
                alphas = []
                for s_i, y_i, rho_i in zip(reversed(s_hist), reversed(y_hist),
                                           reversed(rho_hist)):
                    a_i = rho_i * jnp.dot(s_i, q)
                    alphas.append(a_i)
                    q = q - a_i * y_i
                r = q * h_diag
                for (s_i, y_i, rho_i), a_i in zip(
                        zip(s_hist, y_hist, rho_hist), reversed(alphas)):
                    b_i = rho_i * jnp.dot(y_i, r)
                    r = r + (a_i - b_i) * s_i
                d = r
            g_prev = g

            gtd = float(jnp.dot(g, d))
            if gtd > -self.tol_x:  # not a descent direction
                break

            # ---- step size -----------------------------------------------
            if it == 0:
                t = min(1.0, 1.0 / float(jnp.abs(g).sum())) * self.learning_rate
            else:
                t = self.learning_rate

            if self.line_search:
                probe_cache: dict[str, Any] = {}

                def feval_dir(tt):
                    f_n, g_n = feval_flat(x + tt * d)
                    probe_cache["t"], probe_cache["f"], probe_cache["g"] = (
                        tt, f_n, g_n)
                    return float(f_n), float(jnp.dot(g_n, d))

                t, _, ls_evals = line_search_wolfe(
                    feval_dir, t, float(f), gtd)
                n_eval += ls_evals
                x = x + t * d
                if probe_cache.get("t") == t:  # reuse the accepted probe
                    f_new, g_new = probe_cache["f"], probe_cache["g"]
                else:
                    f_new, g_new = feval_flat(x)
                    n_eval += 1
            else:
                x = x + t * d
                f_new, g_new = feval_flat(x)
                n_eval += 1

            # ---- convergence checks (LBFGS.scala tolFun/tolX/maxEval) -----
            losses.append(float(f_new))
            d_f = abs(float(f_new) - float(f))
            f, g = f_new, g_new
            if float(jnp.abs(g).max()) <= 1e-10:
                break
            if d_f < self.tol_fun:
                break
            if float(jnp.abs(t * d).max()) <= self.tol_x:
                break
            if n_eval >= self.max_eval:
                break

        return unravel(x), losses
