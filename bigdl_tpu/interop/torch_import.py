"""Whole-model Torch ``.t7`` import: construct the module GRAPH, not just
the params (reference ``Module.loadTorch``, nn/Module.scala:32, backed by
the ~30-class mapping in utils/TorchFile.scala:136-181 ``readModuleWithType``
and the per-class readers :911-1000).

``load_torch_module(path)`` returns ``(module, params, state)`` ready for
``module.apply(params, state, x)`` — the reference's
``example/loadmodel`` Torch flow (ModelValidator.scala) reproduced.

Layout note (the one real divergence from a 1:1 mapping): Torch runs NCHW;
this framework runs NHWC (TPU-native — conv kernels are HWIO so the MXU
sees the channels-minor layout it wants). Weights are transposed at import
(OIHW→HWIO, (out,in)→(in,out)), and the conv→linear flatten — where the
element ORDER of the collapse differs between layouts — is imported as
:class:`TorchFlatten`, which restores torch's CHW order before
flattening, so the following Linear's rows line up verbatim with the
torch weights. Concat dimensions are remapped NCHW→NHWC the same way.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import numpy as np

from bigdl_tpu.core.module import Module, SimpleModule
from bigdl_tpu.interop.torchfile import TorchObject, load_t7

__all__ = ["load_torch_module", "save_torch_module", "TorchFlatten"]


class TorchFlatten(SimpleModule):
    """Flatten imported from a torch ``nn.View``/``nn.Reshape`` that sat on
    a 4-D NCHW feature map: transpose NHWC back to CHW element order before
    collapsing, so downstream imported Linear weights match torch
    bit-for-bit. On non-4-D input it is a plain batch-preserving reshape."""

    def __init__(self, size, name=None):
        super().__init__(name)
        self.size = tuple(int(s) for s in size)

    def _forward(self, params, x, *, training, rng):
        # same batch-sharding pin as nn.Reshape: without it, imported
        # models reintroduce the GSPMD full-remat cliff (parallel/hints.py)
        from bigdl_tpu.parallel.hints import constrain_batch

        x = constrain_batch(x)
        if x.ndim == 4:
            x = x.transpose(0, 3, 1, 2)  # NHWC -> NCHW order
        return constrain_batch(x.reshape((x.shape[0],) + self.size))


def _cls(obj: TorchObject) -> str:
    """``nn.SpatialConvolutionMM`` -> ``SpatialConvolutionMM``; cudnn
    aliases fold into nn (reference TorchFile.scala:139-143)."""
    name = obj.torch_typename
    if name.startswith("cudnn."):
        name = "nn." + name[len("cudnn."):]
    return name.rsplit(".", 1)[-1]


def _int(fields: dict, key: str, default: Optional[int] = None) -> int:
    v = fields.get(key, default)
    if v is None:
        raise ValueError(f"torch module missing field {key!r}")
    return int(v)


def _seq_size(arr) -> Tuple[int, ...]:
    """A torch ``size`` field is a LongStorage (numpy array) or a number."""
    if isinstance(arr, np.ndarray):
        return tuple(int(s) for s in arr.tolist())
    if isinstance(arr, (list, tuple)):
        return tuple(int(s) for s in arr)
    return (int(arr),)


def _map_concat_dim(dim: int) -> int:
    """Torch ``Concat``/``JoinTable`` dimension (1-based, NCHW incl. batch)
    -> our axis on NHWC. dim 2 (channels) -> -1; dim 1 (batch) -> 0;
    spatial dims shift left by the channel move."""
    return {1: 0, 2: -1, 3: 1, 4: 2}.get(dim, dim - 1)


# ---------------------------------------------------------------- builders
# each returns (module, params, state); containers recurse via _import

def _import_children(mods) -> Tuple[list, dict, dict]:
    built, params, state = [], {}, {}
    for i, child in enumerate(mods or []):
        m, p, s = _import(child)
        built.append(m)
        params[str(i)] = p
        state[str(i)] = s
    return built, params, state


def _linear(obj):
    from bigdl_tpu import nn

    fields = obj.fields
    w = np.asarray(fields["weight"], np.float32)      # torch (out, in)
    bias = fields.get("bias")
    mod = nn.Linear(w.shape[1], w.shape[0], with_bias=bias is not None)
    p = {"weight": np.ascontiguousarray(w.T)}
    if bias is not None:
        p["bias"] = np.asarray(bias, np.float32)
    return mod, p, {}


def _conv(obj):
    from bigdl_tpu import nn

    fields = obj.fields
    n_in = _int(fields, "nInputPlane")
    n_out = _int(fields, "nOutputPlane")
    kw, kh = _int(fields, "kW"), _int(fields, "kH")
    mod = nn.SpatialConvolution(
        n_in, n_out, kw, kh,
        stride_w=_int(fields, "dW", 1), stride_h=_int(fields, "dH", 1),
        pad_w=_int(fields, "padW", 0), pad_h=_int(fields, "padH", 0),
        with_bias=fields.get("bias") is not None)
    w = np.asarray(fields["weight"], np.float32)
    # SpatialConvolutionMM stores (out, in*kH*kW); plain stores OIHW
    w = w.reshape(n_out, n_in, kh, kw)
    p = {"weight": np.transpose(w, (2, 3, 1, 0)).copy()}  # OIHW -> HWIO
    if fields.get("bias") is not None:
        p["bias"] = np.asarray(fields["bias"], np.float32)
    return mod, p, {}


def _conv_map(obj):
    """Torch SpatialConvolutionMap (reference reader
    TorchFile.scala:922-939): ``weight`` is per-connection (nPairs, kH,
    kW), ``connTable`` (nPairs, 2) 1-based (in, out). Our module is the
    masked-dense MXU form, so scatter each pair's kernel into the dense
    HWIO weight — the fixed binary mask zeroes everything else."""
    from bigdl_tpu import nn

    f = obj.fields
    ct = np.asarray(f["connTable"], np.float32).astype(np.int64) - 1
    kw, kh = _int(f, "kW"), _int(f, "kH")
    # honor explicit plane counts when present: a legal table may leave
    # the highest-numbered plane unconnected, so inference undercounts
    n_in = _int(f, "nInputPlane", 0) or None
    n_out = _int(f, "nOutputPlane", 0) or None
    mod = nn.SpatialConvolutionMap(
        ct, kw, kh,
        stride_w=_int(f, "dW", 1), stride_h=_int(f, "dH", 1),
        pad_w=_int(f, "padW", 0), pad_h=_int(f, "padH", 0),
        n_input_plane=n_in, n_output_plane=n_out)
    w = np.asarray(f["weight"], np.float32).reshape(len(ct), kh, kw)
    dense = np.zeros((kh, kw, mod.n_input_plane, mod.n_output_plane),
                     np.float32)
    dense[:, :, ct[:, 0], ct[:, 1]] = np.transpose(w, (1, 2, 0))
    p = {"weight": dense,
         "bias": np.asarray(f["bias"], np.float32)}
    return mod, p, {}


def _maxpool(obj):
    from bigdl_tpu import nn

    f = obj.fields
    mod = nn.SpatialMaxPooling(
        _int(f, "kW"), _int(f, "kH"),
        _int(f, "dW", _int(f, "kW")), _int(f, "dH", _int(f, "kH")),
        pad_w=_int(f, "padW", 0), pad_h=_int(f, "padH", 0),
        ceil_mode=bool(f.get("ceil_mode", False)))
    return mod, {}, {}


def _avgpool(obj):
    from bigdl_tpu import nn

    f = obj.fields
    mod = nn.SpatialAveragePooling(
        _int(f, "kW"), _int(f, "kH"),
        _int(f, "dW", _int(f, "kW")), _int(f, "dH", _int(f, "kH")),
        pad_w=_int(f, "padW", 0), pad_h=_int(f, "padH", 0),
        ceil_mode=bool(f.get("ceil_mode", False)),
        count_include_pad=bool(f.get("count_include_pad", True)))
    return mod, {}, {}


def _batchnorm(obj, spatial: bool):
    from bigdl_tpu import nn

    f = obj.fields
    running_mean = np.asarray(f["running_mean"], np.float32)
    affine = f.get("weight") is not None
    cls = nn.SpatialBatchNormalization if spatial else nn.BatchNormalization
    mod = cls(running_mean.shape[0],
              eps=float(f.get("eps", 1e-5)),
              momentum=float(f.get("momentum", 0.1)),
              affine=affine)
    p = {}
    if affine:
        p = {"weight": np.asarray(f["weight"], np.float32),
             "bias": np.asarray(f["bias"], np.float32)}
    s = {"running_mean": running_mean,
         "running_var": np.asarray(f["running_var"], np.float32)}
    return mod, p, s


def _sequential(obj):
    from bigdl_tpu.core import Sequential

    built, params, state = _import_children(obj.fields.get("modules"))
    return Sequential(*built), params, state


def _concat(obj):
    from bigdl_tpu import nn

    built, params, state = _import_children(obj.fields.get("modules"))
    axis = _map_concat_dim(_int(obj.fields, "dimension", 2))
    return nn.Concat(*built, axis=axis), params, state


def _concat_table(obj):
    from bigdl_tpu import nn

    built, params, state = _import_children(obj.fields.get("modules"))
    return nn.ConcatTable(*built), params, state


def _view(obj):
    f = obj.fields
    size = _seq_size(f.get("size", f.get("numElements")))
    return TorchFlatten(size), {}, {}


def _dropout(obj):
    from bigdl_tpu import nn

    return nn.Dropout(float(obj.fields.get("p", 0.5))), {}, {}


def _threshold(obj):
    from bigdl_tpu import nn

    f = obj.fields
    return nn.Threshold(float(f.get("threshold", 1e-6)),
                        float(f.get("val", 0.0))), {}, {}


def _zero_padding(obj):
    from bigdl_tpu import nn

    f = obj.fields
    return nn.SpatialZeroPadding(
        _int(f, "pad_l", 0), _int(f, "pad_r", 0),
        _int(f, "pad_t", 0), _int(f, "pad_b", 0)), {}, {}


def _cadd_table(obj):
    from bigdl_tpu import nn

    return nn.CAddTable(), {}, {}


_BUILDERS = {
    "Linear": _linear,
    "SpatialConvolution": _conv,
    "SpatialConvolutionMM": _conv,
    "SpatialConvolutionMap": _conv_map,
    "SpatialMaxPooling": _maxpool,
    "SpatialAveragePooling": _avgpool,
    "BatchNormalization": lambda o: _batchnorm(o, spatial=False),
    "SpatialBatchNormalization": lambda o: _batchnorm(o, spatial=True),
    "Sequential": _sequential,
    "Concat": _concat,
    "ConcatTable": _concat_table,
    "CAddTable": _cadd_table,
    "View": _view,
    "Reshape": _view,
    "Dropout": _dropout,
    "Threshold": _threshold,
    "SpatialZeroPadding": _zero_padding,
}

# parameter-free classes resolved by name on bigdl_tpu.nn (the analog of
# the reference's createInstanceFor reflection fallback,
# TorchFile.scala:163-178)
_PARAM_FREE = {
    "ReLU", "Tanh", "Sigmoid", "LogSoftMax", "SoftMax", "Identity",
    "SoftPlus", "SoftSign", "ELU", "Abs", "Square", "Sqrt", "HardTanh",
    "LeakyReLU", "ReLU6", "SoftMin", "Exp", "Log",
}


def _import(obj: Any) -> Tuple[Module, Any, Any]:
    if not isinstance(obj, TorchObject):
        raise ValueError(f"expected a torch nn module, got {type(obj)}")
    cls = _cls(obj)
    builder = _BUILDERS.get(cls)
    if builder is not None:
        return builder(obj)
    if cls in _PARAM_FREE:
        from bigdl_tpu import nn

        return getattr(nn, cls)(), {}, {}
    # last resort, mirrors the reference's reflection: a same-named
    # parameter-free class on bigdl_tpu.nn (only safe when the torch
    # object carries no weights we would silently drop)
    from bigdl_tpu import nn

    target = getattr(nn, cls, None)
    has_params = (isinstance(obj.fields, dict)
                  and any(isinstance(obj.fields.get(k), np.ndarray)
                          for k in ("weight", "bias")))
    if target is not None and not has_params:
        try:
            return target(), {}, {}
        except TypeError:
            pass
    raise ValueError(f"unsupported torch module nn.{cls} "
                     f"(reference parity set: TorchFile.scala:136-181)")


def load_torch_module(path_or_obj) -> Tuple[Module, Any, Any]:
    """Reconstruct ``(module, params, state)`` from a ``.t7`` file or an
    already-parsed :class:`TorchObject` tree (reference Module.loadTorch,
    nn/Module.scala:32)."""
    import jax
    import jax.numpy as jnp

    obj = (load_t7(path_or_obj) if isinstance(path_or_obj, str)
           else path_or_obj)
    mod, params, state = _import(obj)
    to_dev = lambda t: jax.tree_util.tree_map(jnp.asarray, t)
    return mod, to_dev(params), to_dev(state)


# ---------------------------------------------------------------- export
# Inverse mapping: a repo module tree -> the TorchObject tree the reference
# reader (TorchFile.scala:136-181) and this file's importer both accept.
# Field spellings follow the reference readers exactly: ReLU requires
# "inplace", pooling requires "ceil_mode", Linear requires "bias", View
# requires "numElements" (all checked against the reference source).

def _perm_chw(h: int, w: int, c: int) -> np.ndarray:
    """perm[t] = HWC-flat index of the element at CHW-flat position t, so
    ``torch_rows = my_rows[perm]`` reorders a flattened feature dim from
    this framework's NHWC collapse to torch's NCHW collapse."""
    return np.arange(h * w * c).reshape(h, w, c).transpose(2, 0, 1).ravel()


def _np(t) -> np.ndarray:
    return np.asarray(t, np.float32)


class _ExportCtx:
    """Threads (a) the activation shape through Sequential chains so the
    conv->linear flatten can compute its row permutation, and (b) that
    pending permutation until the next Linear consumes it."""

    def __init__(self, example_input=None):
        self.aval = None
        if example_input is not None:
            import jax

            self.aval = jax.eval_shape(lambda x: x, example_input)
        self.perm: Optional[np.ndarray] = None
        # set when a Reshape/View is exported without a live aval: the CHW
        # permutation question could not be answered, so a following Linear
        # must refuse rather than silently write NHWC-ordered rows
        self.blind_flatten = False

    def advance(self, mod, p, s):
        if self.aval is None:
            return
        import jax

        try:
            self.aval = jax.eval_shape(
                lambda x: mod.apply(p, s, x, training=False)[0], self.aval)
        except Exception:
            self.aval = None  # shape tracking ends at exotic modules


_PASS_THROUGH = {  # elementwise: a pending flatten-perm flows through
    "ReLU", "Tanh", "Sigmoid", "Threshold", "Dropout", "LogSoftMax",
    "SoftMax", "Identity", "ELU", "LeakyReLU", "ReLU6", "Abs",
}


def _obj(cls: str, fields: dict) -> TorchObject:
    fields.setdefault("_type", "torch.FloatTensor")
    fields.setdefault("train", False)
    return TorchObject(f"nn.{cls}", fields)


def _export(mod, p, s, ctx: _ExportCtx) -> TorchObject:
    from bigdl_tpu import nn
    from bigdl_tpu.core import Sequential as CoreSequential

    name = type(mod).__name__
    in_aval = ctx.aval

    if isinstance(mod, CoreSequential):
        children = []
        for i, ch in enumerate(mod.children()):
            k = str(i)
            children.append(_export(ch, p.get(k, {}), s.get(k, {}), ctx))
        return _obj("Sequential", {"modules": children})

    if isinstance(mod, nn.Concat):
        children = []
        for i, ch in enumerate(mod.children()):
            k = str(i)
            branch = _ExportCtx()
            branch.aval, branch.perm = in_aval, None
            children.append(_export(ch, p.get(k, {}), s.get(k, {}), branch))
        ctx.advance(mod, p, s)
        axis = mod.axis
        dim = {0: 1, -1: 2, 3: 2, 1: 3, 2: 4}.get(axis)
        if dim is None:
            raise ValueError(f"cannot map Concat axis {axis} to torch")
        return _obj("Concat", {"modules": children,
                               "dimension": float(dim)})

    if isinstance(mod, nn.ConcatTable):
        children = []
        for i, ch in enumerate(mod.children()):
            k = str(i)
            branch = _ExportCtx()
            branch.aval, branch.perm = in_aval, None
            children.append(_export(ch, p.get(k, {}), s.get(k, {}), branch))
        ctx.aval = None
        return _obj("ConcatTable", {"modules": children})

    if isinstance(mod, nn.CAddTable):
        ctx.advance(mod, p, s)
        return _obj("CAddTable", {"inplace": False})

    if isinstance(mod, nn.Linear):
        if ctx.blind_flatten:
            raise ValueError(
                "Reshape->Linear export without shape tracking: pass "
                "example_input to save_torch_module so the CHW flatten "
                "permutation can be computed; exporting blind would write "
                "NHWC-ordered Linear rows that torch consumers misread")
        w = _np(p["weight"])                       # ours: (in, out)
        if ctx.perm is not None:
            if ctx.perm.shape[0] != w.shape[0]:
                raise ValueError(
                    "flatten permutation does not match Linear fan-in "
                    f"({ctx.perm.shape[0]} vs {w.shape[0]})")
            w = w[ctx.perm]
            ctx.perm = None
        bias = (_np(p["bias"]) if "bias" in p
                else np.zeros((w.shape[1],), np.float32))
        ctx.advance(mod, p, s)
        return _obj("Linear", {"weight": np.ascontiguousarray(w.T),
                               "bias": bias})

    if isinstance(mod, nn.SpatialConvolutionMap):
        w = _np(p["weight"])                       # dense HWIO, masked
        ct = mod.conn_table                        # (nPairs, 2) 0-based
        per_pair = np.transpose(w[:, :, ct[:, 0], ct[:, 1]], (2, 0, 1))
        ctx.advance(mod, p, s)
        return _obj("SpatialConvolutionMap", {
            "connTable": (ct + 1).astype(np.float64),   # torch is 1-based
            "kW": float(mod.kernel_w), "kH": float(mod.kernel_h),
            "dW": float(mod.stride_w), "dH": float(mod.stride_h),
            "padW": float(mod.pad_w), "padH": float(mod.pad_h),
            "nInputPlane": float(mod.n_input_plane),
            "nOutputPlane": float(mod.n_output_plane),
            "weight": np.ascontiguousarray(per_pair),
            "bias": _np(p["bias"]),
        })

    if isinstance(mod, nn.SpatialConvolution):
        w = _np(p["weight"])                       # HWIO
        kh, kw, cin_g, cout = w.shape
        oihw = np.transpose(w, (3, 2, 0, 1))
        bias = (_np(p["bias"]) if "bias" in p
                else np.zeros((cout,), np.float32))
        ctx.advance(mod, p, s)
        return _obj("SpatialConvolutionMM", {
            "nInputPlane": float(mod.n_input_plane),
            "nOutputPlane": float(mod.n_output_plane),
            "kW": float(mod.kernel_w), "kH": float(mod.kernel_h),
            "dW": float(mod.stride_w), "dH": float(mod.stride_h),
            "padW": float(mod.pad_w), "padH": float(mod.pad_h),
            "weight": np.ascontiguousarray(
                oihw.reshape(cout, cin_g * kh * kw)),
            "bias": bias,
        })

    if isinstance(mod, nn.SpatialMaxPooling) or \
            isinstance(mod, nn.SpatialAveragePooling):
        ctx.advance(mod, p, s)
        fields = {
            "kW": float(mod.kernel_w), "kH": float(mod.kernel_h),
            "dW": float(mod.stride_w), "dH": float(mod.stride_h),
            "padW": float(mod.pad_w), "padH": float(mod.pad_h),
            "ceil_mode": bool(mod.ceil_mode),
        }
        if isinstance(mod, nn.SpatialAveragePooling):
            fields["count_include_pad"] = bool(mod.count_include_pad)
            return _obj("SpatialAveragePooling", fields)
        return _obj("SpatialMaxPooling", fields)

    if isinstance(mod, nn.BatchNormalization):
        cls = ("SpatialBatchNormalization"
               if isinstance(mod, nn.SpatialBatchNormalization)
               else "BatchNormalization")
        fields = {
            "eps": float(mod.eps), "momentum": float(mod.momentum),
            "affine": bool(mod.affine),
            "running_mean": _np(s["running_mean"]),
            "running_var": _np(s["running_var"]),
        }
        if mod.affine:
            fields["weight"] = _np(p["weight"])
            fields["bias"] = _np(p["bias"])
        ctx.advance(mod, p, s)
        return _obj(cls, fields)

    if isinstance(mod, TorchFlatten):
        size = np.asarray(mod.size, np.int64)
        ctx.advance(mod, p, s)
        return _obj("View", {"size": size,
                             "numElements": float(int(np.prod(size)))})

    if isinstance(mod, nn.Reshape):             # includes nn.View alias
        size = np.asarray(mod.size, np.int64)
        if in_aval is not None and len(in_aval.shape) == 4:
            # our flatten collapses HWC; torch consumers expect CHW order
            # -> permute the next Linear's rows (consumed above)
            b, h, w_, c = in_aval.shape
            ctx.perm = _perm_chw(h, w_, c)
        elif in_aval is None:
            ctx.blind_flatten = True
        ctx.advance(mod, p, s)
        return _obj("View", {"size": size,
                             "numElements": float(int(np.prod(size)))})

    if isinstance(mod, nn.Threshold) and not isinstance(mod, nn.ReLU):
        ctx.advance(mod, p, s)
        return _obj("Threshold", {"threshold": float(mod.th),
                                  "val": float(mod.v), "inplace": False})

    if isinstance(mod, nn.Dropout):
        ctx.advance(mod, p, s)
        return _obj("Dropout", {"p": float(mod.p), "inplace": False})

    if name in _PASS_THROUGH:
        ctx.advance(mod, p, s)
        fields = {"inplace": False} if name == "ReLU" else {}
        return _obj(name, fields)

    raise ValueError(
        f"cannot export {name} to .t7 (reference writeModule parity set: "
        "TorchFile.scala:258-295)")


def save_torch_module(module, params, state, path: str,
                      example_input=None) -> None:
    """Write a repo module tree as a Torch7 ``.t7`` model file (reference
    ``Module.saveTorch`` / TorchFile.writeModule, TorchFile.scala:258-295).

    ``example_input`` (an array or ShapeDtypeStruct) enables shape tracking
    through Sequential chains; it is required for exact export of models
    with a conv->linear flatten, where torch's NCHW collapse order differs
    from this framework's NHWC one and the following Linear's rows must be
    permuted (see :func:`_perm_chw`)."""
    from bigdl_tpu.interop.torchfile import save_t7

    ctx = _ExportCtx(example_input)
    obj = _export(module, params, state, ctx)
    save_t7(path, obj)
