"""Caffe model import (reference utils/CaffeLoader.scala:33-149 +
the generated protobuf classes dl/src/main/java/caffe/Caffe.java).

The reference ships 96k lines of generated protobuf-java to read
``.caffemodel`` files. Here the wire format is decoded directly: a
``.caffemodel`` is a protobuf ``NetParameter`` message, and the handful of
fields needed for weight import (layer name / type / blobs, blob shape /
data) are parsed with a ~100-line varint/length-delimited reader — no
protoc, no generated code.

Field numbers (from the public caffe.proto schema):

* ``NetParameter``: name=1, layers(V1LayerParameter)=2, layer(LayerParameter)=100
* ``V1LayerParameter``: bottom=2, top=3, name=4, type=5(enum), blobs=6
* ``LayerParameter``: name=1, type=2, bottom=3, top=4, blobs=7
* ``BlobProto``: num=1, channels=2, height=3, width=4, data=5(float),
  diff=6, shape=7(BlobShape), double_data=8
* ``BlobShape``: dim=1 (packed int64)

``load_caffe(model, params, caffemodel)`` mirrors
``Module.loadCaffe`` (nn/Module.scala:36): match caffe layers to modules by
name, copy blob 0 -> weight and blob 1 -> bias, with layout conversion
(caffe OIHW -> our HWIO; caffe (out,in) -> our (in,out)). ``match_all``
keeps the reference's strictness flag (CaffeLoader.scala:141).
"""

from __future__ import annotations

import struct
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

__all__ = ["parse_caffemodel", "parse_prototxt", "load_caffe", "CaffeLayer"]


# ------------------------------------------------------------ wire reader

class _Wire:
    def __init__(self, buf: bytes, pos: int = 0, end: Optional[int] = None):
        self.buf = buf
        self.pos = pos
        self.end = len(buf) if end is None else end

    def eof(self) -> bool:
        return self.pos >= self.end

    def varint(self) -> int:
        shift = 0
        out = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def field(self) -> tuple[int, int]:
        key = self.varint()
        return key >> 3, key & 0x7

    def skip(self, wire_type: int) -> None:
        if wire_type == 0:
            self.varint()
        elif wire_type == 1:
            self.pos += 8
        elif wire_type == 2:
            n = self.varint()  # NB: must read the varint before adding — the
            self.pos += n      # augmented form would load pos pre-varint

        elif wire_type == 5:
            self.pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire_type}")

    def bytes_field(self) -> bytes:
        n = self.varint()
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def sub(self) -> "_Wire":
        n = self.varint()
        w = _Wire(self.buf, self.pos, self.pos + n)
        self.pos += n
        return w


class CaffeLayer:
    def __init__(self, name: str, type_: str, blobs: list[np.ndarray]):
        self.name = name
        self.type = type_
        self.blobs = blobs

    def __repr__(self):
        return (f"CaffeLayer({self.name!r}, {self.type!r}, "
                f"blobs={[b.shape for b in self.blobs]})")


# V1LayerParameter.LayerType enum values needed for weight-bearing layers.
_V1_TYPES = {4: "Convolution", 14: "InnerProduct", 39: "Deconvolution",
             6: "Data", 18: "ReLU", 17: "Pooling", 20: "Softmax",
             21: "SoftmaxWithLoss", 8: "Dropout", 15: "LRN", 33: "Scale"}


def _parse_blob(w: _Wire) -> np.ndarray:
    dims_legacy = {}
    shape: Optional[list[int]] = None
    data: list[np.ndarray] = []
    while not w.eof():
        fno, wt = w.field()
        if fno in (1, 2, 3, 4) and wt == 0:
            dims_legacy[fno] = w.varint()
        elif fno == 5:  # float data
            if wt == 2:  # packed
                raw = w.bytes_field()
                data.append(np.frombuffer(raw, dtype="<f4"))
            else:  # unpacked 32-bit
                data.append(np.array(
                    struct.unpack_from("<f", w.buf, w.pos), dtype=np.float32))
                w.pos += 4
        elif fno == 8:  # double data
            if wt == 2:
                raw = w.bytes_field()
                data.append(np.frombuffer(raw, dtype="<f8").astype(np.float32))
            else:
                data.append(np.array(
                    struct.unpack_from("<d", w.buf, w.pos), dtype=np.float32))
                w.pos += 8
        elif fno == 7 and wt == 2:  # BlobShape
            sw = w.sub()
            shape = []
            while not sw.eof():
                sfno, swt = sw.field()
                if sfno == 1 and swt == 2:  # packed dims
                    pw = _Wire(sw.bytes_field())
                    while not pw.eof():
                        shape.append(pw.varint())
                elif sfno == 1 and swt == 0:
                    shape.append(sw.varint())
                else:
                    sw.skip(swt)
        else:
            w.skip(wt)
    arr = (np.concatenate(data) if data
           else np.zeros(0, dtype=np.float32))
    if shape is None and dims_legacy:
        shape = [dims_legacy.get(i, 1) for i in (1, 2, 3, 4)]
    if shape:
        arr = arr.reshape(shape)
    return arr


def _parse_layer(w: _Wire, v1: bool) -> CaffeLayer:
    name = ""
    type_: Any = ""
    blobs: list[np.ndarray] = []
    name_field = 4 if v1 else 1
    type_field = 5 if v1 else 2
    blob_field = 6 if v1 else 7
    while not w.eof():
        fno, wt = w.field()
        if fno == name_field and wt == 2:
            name = w.bytes_field().decode("utf-8", "replace")
        elif fno == type_field:
            if v1 and wt == 0:
                type_ = _V1_TYPES.get(w.varint(), "Unknown")
            elif wt == 2:
                type_ = w.bytes_field().decode("utf-8", "replace")
            else:
                w.skip(wt)
        elif fno == blob_field and wt == 2:
            blobs.append(_parse_blob(w.sub()))
        else:
            w.skip(wt)
    return CaffeLayer(name, type_, blobs)


def parse_caffemodel(path: str) -> list[CaffeLayer]:
    """Parse a binary ``.caffemodel`` into layers with their weight blobs
    (reference CaffeLoader.loadBinary, CaffeLoader.scala:72-84 — which uses
    CodedInputStream with the 2GB limit lifted; here we just mmap-read)."""
    with open(path, "rb") as f:
        buf = f.read()
    w = _Wire(buf)
    layers: list[CaffeLayer] = []
    while not w.eof():
        fno, wt = w.field()
        if fno == 2 and wt == 2:  # V1LayerParameter
            layers.append(_parse_layer(w.sub(), v1=True))
        elif fno == 100 and wt == 2:  # LayerParameter
            layers.append(_parse_layer(w.sub(), v1=False))
        else:
            w.skip(wt)
    return layers


# -------------------------------------------------------- prototxt parser

def parse_prototxt(text: str) -> dict:
    """Minimal protobuf text-format parser (reference parses the .prototxt
    with TextFormat.merge, CaffeLoader.scala:72-78). Returns nested dicts;
    repeated keys become lists."""
    tokens: list[str] = []
    for raw_line in text.splitlines():
        line = raw_line.split("#", 1)[0]
        line = line.replace("{", " { ").replace("}", " } ").replace(":", ": ")
        tokens.extend(line.split())

    def parse_block(i: int) -> tuple[dict, int]:
        out: dict[str, Any] = {}

        def put(k: str, v: Any):
            if k in out:
                if not isinstance(out[k], list):
                    out[k] = [out[k]]
                out[k].append(v)
            else:
                out[k] = v

        while i < len(tokens):
            tok = tokens[i]
            if tok == "}":
                return out, i + 1
            if tok.endswith(":"):
                key = tok[:-1]
                val = tokens[i + 1]
                if val.startswith('"') or val.startswith("'"):
                    v: Any = val.strip("\"'")
                else:
                    try:
                        v = int(val)
                    except ValueError:
                        try:
                            v = float(val)
                        except ValueError:
                            v = {"true": True, "false": False}.get(val, val)
                put(key, v)
                i += 2
            elif i + 1 < len(tokens) and tokens[i + 1] == "{":
                sub, i = parse_block(i + 2)
                put(tok, sub)
            else:
                i += 1
        return out, i

    out, _ = parse_block(0)
    return out


# ---------------------------------------------------------- weight copy

def _convert_blob(blob: np.ndarray, target_shape) -> Optional[np.ndarray]:
    """Convert a caffe blob onto a target param layout.

    Layout rules come first (shape equality alone cannot decide: a square
    FC weight or a symmetric conv kernel still needs its transpose):

    * 4-D blob -> 4-D param: caffe OIHW -> our HWIO, always.
    * 2-D blob -> 2-D param: caffe (out,in) -> our (in,out), always.
    * legacy 4-D ``(1,1,out,in)`` InnerProduct blob -> 2-D param:
      squeeze then transpose.
    * otherwise shapes must match element count (bias vectors etc.).
    """
    ts = tuple(int(s) for s in target_shape)
    if blob.size != int(np.prod(ts)):
        return None
    if blob.ndim == 4 and len(ts) == 4:
        cand = np.transpose(blob, (2, 3, 1, 0))  # OIHW -> HWIO
        return cand if cand.shape == ts else None
    if len(ts) == 2:
        mat = blob
        if mat.ndim == 4 and mat.shape[:2] == (1, 1):  # legacy IP blob
            mat = mat.reshape(mat.shape[2], mat.shape[3])
        if mat.ndim == 2:
            cand = np.ascontiguousarray(mat.T)  # (out,in) -> (in,out)
            return cand if cand.shape == ts else None
    if blob.shape == ts:
        return blob
    return blob.reshape(ts)


def _walk(module, params, visit):
    visit(module, params)
    children = module.children()
    if children and isinstance(params, dict):
        for i, child in enumerate(children):
            key = str(i)
            if key in params:
                _walk(child, params[key], visit)


def load_caffe(model, params, caffemodel_path: str,
               prototxt_path: Optional[str] = None,
               match_all: bool = True):
    """Copy caffe weights into ``params`` by module name
    (reference CaffeLoader.copyParameters, CaffeLoader.scala:131-140).

    Modules are matched to caffe layers by their ``name`` attribute (set
    ``nn.SpatialConvolution(..., name="conv1")``). Returns a new params
    pytree; raises if ``match_all`` and some caffe weight layer found no
    module (CaffeLoader.scala:141 strictness).
    """
    del prototxt_path  # structure is given by `model`; kept for API parity
    layers = {l.name: l for l in parse_caffemodel(caffemodel_path)
              if l.blobs}
    # operate on a mutable deep copy of the dict structure (leaves shared)
    new_params = _deep_copy_tree(params)
    matched: set[str] = set()

    def visit(module, p):
        layer = layers.get(module.name)
        if layer is None or not isinstance(p, dict):
            return
        slots = [k for k in ("weight", "bias") if k in p]
        for slot, blob in zip(slots, layer.blobs):
            conv = _convert_blob(blob, p[slot].shape)
            if conv is None:
                raise ValueError(
                    f"caffe layer {layer.name!r} blob {blob.shape} does not "
                    f"fit param {slot!r} {tuple(p[slot].shape)}")
            p[slot] = jnp.asarray(conv, dtype=p[slot].dtype)
        matched.add(module.name)

    _walk(model, new_params, visit)
    unmatched = set(layers) - matched
    if match_all and unmatched:
        raise ValueError(
            f"caffe layers with weights not matched to modules: "
            f"{sorted(unmatched)} (set match_all=False to ignore)")
    return new_params


def _deep_copy_tree(tree):
    if isinstance(tree, dict):
        return {k: _deep_copy_tree(v) for k, v in tree.items()}
    return tree
