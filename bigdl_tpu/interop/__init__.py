from bigdl_tpu.interop.torchfile import (
    load_t7, save_t7, TorchObject, load_torch_params,
)
from bigdl_tpu.interop.caffe import (
    parse_caffemodel, parse_prototxt, load_caffe,
)

__all__ = [
    "load_t7", "save_t7", "TorchObject", "load_torch_params",
    "parse_caffemodel", "parse_prototxt", "load_caffe",
]
