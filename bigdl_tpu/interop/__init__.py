from bigdl_tpu.interop.torchfile import (
    load_t7, save_t7, TorchObject, load_torch_params,
)
from bigdl_tpu.interop.torch_import import (
    load_torch_module, save_torch_module, TorchFlatten,
)
from bigdl_tpu.interop.caffe import (
    parse_caffemodel, parse_prototxt, load_caffe,
)

__all__ = [
    "load_t7", "save_t7", "TorchObject", "load_torch_params",
    "load_torch_module", "save_torch_module", "TorchFlatten",
    "parse_caffemodel", "parse_prototxt", "load_caffe",
]
