"""Torch7 ``.t7`` binary serialization — reader and writer
(reference utils/TorchFile.scala: type tags :39-59,200-208, ``load`` :74,
``save`` :90, module mapping :214-335).

Written from scratch against the public Torch7 ``File:writeObject`` wire
format (little-endian):

* every value is ``<i32 type-tag><payload>``; tags: 0 nil, 1 number (f64),
  2 string, 3 table, 4 torch object, 5 boolean, 6/7/8 functions.
* tables and torch objects carry an ``i32`` heap index for reference
  sharing; re-reading an index returns the memoized object.
* a torch object payload is ``<string>`` which is either the class name
  (format version 0) or ``"V <n>"`` followed by a second ``<string>`` class
  name; tensors then store ``ndim, sizes[i64], strides[i64],
  storageOffset(i64, 1-based), <storage object>``; storages store
  ``size[i64]`` + raw element bytes.

The reference uses this for (a) Torch model import/export and (b) its
golden-oracle test harness (torch/TH.scala). Here it serves model interop;
golden tests use checked-in arrays instead (SURVEY.md §7 "Torch-oracle
tests").
"""

from __future__ import annotations

import struct
from typing import Any, BinaryIO, Optional

import numpy as np

__all__ = ["load_t7", "save_t7", "TorchObject", "load_torch_params"]

TYPE_NIL = 0
TYPE_NUMBER = 1
TYPE_STRING = 2
TYPE_TABLE = 3
TYPE_TORCH = 4
TYPE_BOOLEAN = 5
TYPE_FUNCTION = 6
TYPE_LEGACY_RECUR_FUNCTION = 7
TYPE_RECUR_FUNCTION = 8

_TENSOR_DTYPES = {
    "torch.ByteTensor": np.uint8,
    "torch.CharTensor": np.int8,
    "torch.ShortTensor": np.int16,
    "torch.IntTensor": np.int32,
    "torch.LongTensor": np.int64,
    "torch.FloatTensor": np.float32,
    "torch.DoubleTensor": np.float64,
}
_STORAGE_DTYPES = {
    k.replace("Tensor", "Storage"): v for k, v in _TENSOR_DTYPES.items()
}
_DTYPE_TO_TENSOR = {
    np.dtype(np.float32): "torch.FloatTensor",
    np.dtype(np.float64): "torch.DoubleTensor",
    np.dtype(np.int64): "torch.LongTensor",
    np.dtype(np.int32): "torch.IntTensor",
    np.dtype(np.int16): "torch.ShortTensor",
    np.dtype(np.int8): "torch.CharTensor",
    np.dtype(np.uint8): "torch.ByteTensor",
}


class TorchObject:
    """A non-tensor torch class instance: class name + its payload table."""

    def __init__(self, torch_typename: str, fields: Any):
        self.torch_typename = torch_typename
        self.fields = fields

    def __getitem__(self, k):
        return self.fields[k]

    def get(self, k, default=None):
        if isinstance(self.fields, dict):
            return self.fields.get(k, default)
        return default

    def __repr__(self):
        return f"TorchObject({self.torch_typename})"


# ---------------------------------------------------------------- reading

class _Reader:
    def __init__(self, f: BinaryIO):
        self.f = f
        self.memo: dict[int, Any] = {}

    def _read(self, fmt: str):
        size = struct.calcsize(fmt)
        data = self.f.read(size)
        if len(data) != size:
            raise EOFError("truncated .t7 file")
        return struct.unpack("<" + fmt, data)[0]

    def read_int(self) -> int:
        return self._read("i")

    def read_long(self) -> int:
        return self._read("q")

    def read_string(self) -> str:
        n = self.read_int()
        return self.f.read(n).decode("latin-1")

    def read_object(self) -> Any:
        tag = self.read_int()
        if tag == TYPE_NIL:
            return None
        if tag == TYPE_NUMBER:
            v = self._read("d")
            return int(v) if float(v).is_integer() else v
        if tag == TYPE_STRING:
            return self.read_string()
        if tag == TYPE_BOOLEAN:
            return bool(self.read_int())
        if tag == TYPE_TABLE:
            idx = self.read_int()
            if idx in self.memo:
                return self.memo[idx]
            table: dict[Any, Any] = {}
            self.memo[idx] = table
            n = self.read_int()
            for _ in range(n):
                k = self.read_object()
                table[k] = self.read_object()
            out = _maybe_list(table)
            # re-memo the converted list so later references share identity
            # (self-referencing array-tables keep the dict — acceptable)
            self.memo[idx] = out
            return out
        if tag == TYPE_TORCH:
            idx = self.read_int()
            if idx in self.memo:
                return self.memo[idx]
            name = self.read_string()
            if name.startswith("V "):  # versioned header
                name = self.read_string()
            obj = self._read_torch_class(name, idx)
            return obj
        if tag in (TYPE_FUNCTION, TYPE_RECUR_FUNCTION,
                   TYPE_LEGACY_RECUR_FUNCTION):
            # dumped lua bytecode: size + blob, then upvalue table. Parsed
            # and discarded (we cannot execute lua).
            size = self.read_int()
            self.f.read(size)
            upvalues = self.read_object()
            fn = TorchObject("function", upvalues)
            return fn
        raise ValueError(f"unknown .t7 type tag {tag}")

    def _read_torch_class(self, name: str, idx: int) -> Any:
        if name in _TENSOR_DTYPES:
            ndim = self.read_int()
            sizes = [self.read_long() for _ in range(ndim)]
            strides = [self.read_long() for _ in range(ndim)]
            offset = self.read_long() - 1  # 1-based
            storage = self.read_object()  # may be None for empty tensors
            if storage is None:
                arr = np.zeros(sizes, dtype=_TENSOR_DTYPES[name])
            elif ndim == 0:
                # 0-d tensor: one element at the storage offset
                arr = np.asarray(storage[offset],
                                 dtype=_TENSOR_DTYPES[name]).copy()
            else:
                itemsize = storage.dtype.itemsize
                arr = np.lib.stride_tricks.as_strided(
                    storage[offset:],
                    shape=sizes,
                    strides=[s * itemsize for s in strides],
                ).copy()
            self.memo[idx] = arr
            return arr
        if name in _STORAGE_DTYPES:
            dtype = np.dtype(_STORAGE_DTYPES[name])
            size = self.read_long()
            data = self.f.read(size * dtype.itemsize)
            if len(data) != size * dtype.itemsize:
                # must raise here: a short buffer + the as_strided view in
                # the tensor reader would read out-of-bounds memory
                raise EOFError("truncated .t7 storage")
            arr = np.frombuffer(data, dtype=dtype).copy()
            self.memo[idx] = arr
            return arr
        # generic torch class: payload is one serialized object (its table)
        placeholder = TorchObject(name, {})
        self.memo[idx] = placeholder
        fields = self.read_object()
        placeholder.fields = fields
        return placeholder


def _maybe_list(table: dict) -> Any:
    """Torch tables with consecutive 1..n int keys are arrays — surface
    them as python lists (keeps ``modules`` traversal natural)."""
    n = len(table)
    if n and all(isinstance(k, int) for k in table):
        keys = sorted(table)
        if keys == list(range(1, n + 1)):
            return [table[k] for k in keys]
    return table


# ---------------------------------------------------------------- writing

class _Writer:
    def __init__(self, f: BinaryIO):
        self.f = f
        self.next_index = 1
        self.memo: dict[int, int] = {}  # id(obj) -> heap index
        # memo keys are id()s: every memoized object must be kept alive for
        # the writer's lifetime or CPython may reuse the address for an
        # unrelated object and dedup it to a stale heap index
        self._keepalive: list[Any] = []

    def _w(self, fmt: str, v):
        self.f.write(struct.pack("<" + fmt, v))

    def write_int(self, v: int):
        self._w("i", v)

    def write_string(self, s: str):
        b = s.encode("latin-1")
        self.write_int(len(b))
        self.f.write(b)

    def write_object(self, obj: Any):
        if obj is None:
            self.write_int(TYPE_NIL)
        elif isinstance(obj, bool):
            self.write_int(TYPE_BOOLEAN)
            self.write_int(int(obj))
        elif isinstance(obj, (int, float)):
            self.write_int(TYPE_NUMBER)
            self._w("d", float(obj))
        elif isinstance(obj, str):
            self.write_int(TYPE_STRING)
            self.write_string(obj)
        elif isinstance(obj, np.ndarray):
            self._write_tensor(obj)
        elif isinstance(obj, (dict, list, tuple)):
            self._write_table(obj)
        elif isinstance(obj, TorchObject):
            self.write_int(TYPE_TORCH)
            if self._ref(obj):
                return
            self.write_string("V 1")
            self.write_string(obj.torch_typename)
            self.write_object(obj.fields)
        else:
            try:
                arr = np.asarray(obj)
            except Exception:
                raise TypeError(f"cannot serialize {type(obj)} to .t7")
            self._write_tensor(arr)

    def _ref(self, obj) -> bool:
        """Write the heap index; True if obj was already written."""
        key = id(obj)
        if key in self.memo:
            self.write_int(self.memo[key])
            return True
        self.memo[key] = self.next_index
        self._keepalive.append(obj)
        self.write_int(self.next_index)
        self.next_index += 1
        return False

    def _write_table(self, obj):
        if isinstance(obj, (list, tuple)):
            obj_dict = {i + 1: v for i, v in enumerate(obj)}
        else:
            obj_dict = obj
        self.write_int(TYPE_TABLE)
        if self._ref(obj):
            return
        self.write_int(len(obj_dict))
        for k, v in obj_dict.items():
            self.write_object(k)
            self.write_object(v)

    def _write_tensor(self, arr: np.ndarray):
        dtype = arr.dtype
        if dtype == np.bool_:
            arr, dtype = arr.astype(np.uint8), np.dtype(np.uint8)
        if dtype not in _DTYPE_TO_TENSOR:
            arr = arr.astype(np.float32)
            dtype = arr.dtype
        tname = _DTYPE_TO_TENSOR[dtype]
        self.write_int(TYPE_TORCH)
        if self._ref(arr):
            return
        self.write_string("V 1")
        self.write_string(tname)
        arr_c = np.ascontiguousarray(arr)
        self.write_int(arr.ndim)
        for s in arr.shape:
            self._w("q", s)
        stride = 1
        strides = []
        for s in reversed(arr.shape):
            strides.append(stride)
            stride *= s
        for s in reversed(strides):
            self._w("q", s)
        self._w("q", 1)  # storage offset, 1-based
        # storage object
        self.write_int(TYPE_TORCH)
        self.write_int(self.next_index)
        self.next_index += 1
        self.write_string("V 1")
        self.write_string(tname.replace("Tensor", "Storage"))
        self._w("q", arr_c.size)
        self.f.write(arr_c.tobytes())


def load_t7(path: str) -> Any:
    """Load a Torch7 ``.t7`` file (reference TorchFile.load :74). Tensors
    come back as numpy arrays, tables as dicts/lists, other torch classes
    as :class:`TorchObject`."""
    with open(path, "rb") as f:
        return _Reader(f).read_object()


def save_t7(path: str, obj: Any) -> None:
    """Write ``obj`` as a Torch7 ``.t7`` file (reference TorchFile.save :90).
    numpy arrays become torch tensors; dicts/lists become tables."""
    with open(path, "wb") as f:
        _Writer(f).write_object(obj)


# --------------------------------------------------- module param import

def _torch_class_basename(obj: TorchObject) -> str:
    return obj.torch_typename.rsplit(".", 1)[-1]


def _convert_torch_weight(cls: str, w: np.ndarray) -> np.ndarray:
    """Torch layout -> this framework's layout. Torch Linear stores
    ``(out,in)`` (ours: ``(in,out)``, nn/linear.py); torch spatial convs
    store ``(out,in,kH,kW)`` (ours: HWIO). LookupTable/CMul/etc. keep their
    shape. Applied unconditionally by ndim for unknown classes, since every
    torch 2-D weight is (out,in) and every 4-D is OIHW."""
    if cls in ("LookupTable", "CMul", "CAdd", "Mul", "Add",
               "BatchNormalization", "SpatialBatchNormalization", "PReLU"):
        return w
    if w.ndim == 2:
        return np.ascontiguousarray(w.T)          # (out,in) -> (in,out)
    if w.ndim == 4:
        return np.transpose(w, (2, 3, 1, 0)).copy()  # OIHW -> HWIO
    return w


def load_torch_params(obj: Any) -> Any:
    """Convert a parsed Torch nn module tree into a params pytree matching
    this framework's container layout (child params under "0", "1", ...).

    Covers the module families the reference's TorchFile maps
    (utils/TorchFile.scala:214-335): containers expose ``modules``; leaf
    layers expose ``weight``/``bias``. Weight layouts are converted
    (torch (out,in)/OIHW -> our (in,out)/HWIO) via
    :func:`_convert_torch_weight`. Layers without parameters map to ``{}``.
    """
    if isinstance(obj, TorchObject):
        fields = obj.fields if isinstance(obj.fields, dict) else {}
        mods = fields.get("modules")
        if mods is not None:
            return {str(i): load_torch_params(m) for i, m in enumerate(mods)}
        cls = _torch_class_basename(obj)
        out: dict[str, Any] = {}
        if isinstance(fields.get("weight"), np.ndarray):
            out["weight"] = _convert_torch_weight(cls, fields["weight"])
        if isinstance(fields.get("bias"), np.ndarray):
            out["bias"] = fields["bias"]
        return out
    if isinstance(obj, list):
        return {str(i): load_torch_params(m) for i, m in enumerate(obj)}
    return {}
