"""Autotuning subsystem: measured, cached per-shape kernel decisions.

See :mod:`bigdl_tpu.tuning.autotune` for the design; CLI surface is
``--autotune {off,cached,measure}`` (cli/common.py), consumers are
ops/conv2d.py (per-pass layouts), ops/attention_kernel.py (flash block
sizes) and ops/bn_kernel.py (stats row block).
"""

from bigdl_tpu.tuning.autotune import (MODES, QUANT_MATMUL_KINDS,
                                       annotation, bn_row_block,
                                       conv_geom_key, conv_geom_layout,
                                       dry_run, fba_row_block, flash_blocks,
                                       get_cache, get_mode,
                                       grad_bucket_bytes,
                                       kv_page_tokens, quant_matmul_kind,
                                       install_conv_layouts,
                                       make_key, put_geom_decisions,
                                       reset, reset_decisions,
                                       set_mode)
from bigdl_tpu.tuning.cache import (CACHE_VERSION, AutotuneCache, cache_dir,
                                    cache_path, device_kind, device_slug)

__all__ = ["MODES", "QUANT_MATMUL_KINDS",
           "set_mode", "get_mode", "dry_run", "make_key",
           "flash_blocks", "bn_row_block", "fba_row_block",
           "grad_bucket_bytes", "kv_page_tokens", "quant_matmul_kind",
           "install_conv_layouts", "conv_geom_key", "conv_geom_layout",
           "put_geom_decisions",
           "annotation", "reset", "reset_decisions", "get_cache",
           "AutotuneCache", "CACHE_VERSION", "cache_dir", "cache_path",
           "device_kind", "device_slug"]
