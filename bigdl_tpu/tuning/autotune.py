"""Per-shape kernel autotuner (ISSUE 1 tentpole).

The reference BigDL owed its single-node speed to shape-tuned MKL
primitives selected at runtime by its Engine; the TPU-native analogue is a
measured, cached decision per (op, shape, dtype, device-kind) over the
degrees of freedom XLA/Mosaic leave to us: conv per-pass activation
layouts, flash-attention block sizes, and the BN stats kernel's row block.

Three modes, process-global like the conv layout policy (decisions are
trace-time constants):

* ``off`` (default) — legacy behavior: shipped ``MEASURED_DECISIONS`` for
  conv on the plain path, fixed 512 flash blocks, fixed 512 BN row block.
* ``cached`` — read-only: use persisted decisions when present, defaults
  otherwise. Never measures, never writes; safe for production runs.
* ``measure`` — populate: on a cache miss (or a dry placeholder, once a
  real chip is present) time the candidates and persist the winner.

Dry mode: off-TPU (``JAX_PLATFORMS=cpu``), ``measure`` records the current
defaults without timing — the pipeline round-trips end-to-end in CPU tests
and the resulting cache is byte-identical across runs (deterministic
candidate order, no wall clock anywhere near the key or payload).

Consumers pull decisions at trace time through three entry points:
:func:`flash_blocks` (ops/attention_kernel), :func:`bn_row_block`
(ops/bn_kernel) and :func:`install_conv_layouts` (cli/perf, Optimizer).
Every consulted key is recorded and surfaced by :func:`annotation` so perf
JSON lines carry the decision (or ``"default"``) they ran under.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from bigdl_tpu.tuning.cache import AutotuneCache

__all__ = ["MODES", "set_mode", "get_mode", "dry_run", "make_key",
           "flash_blocks", "bn_row_block", "fba_row_block",
           "grad_bucket_bytes", "kv_page_tokens", "quant_matmul_kind",
           "install_conv_layouts", "conv_geom_layout", "conv_geom_key",
           "peek_geom_layout", "put_geom_decisions",
           "annotation", "reset", "reset_decisions", "get_cache"]

MODES = ("off", "cached", "measure")

_MODE = "off"
# consulted-key ledger for result-JSON provenance: key -> {"source", ...}
_DECISIONS: Dict[str, dict] = {}
_CACHE: Optional[AutotuneCache] = None

# standard TPU tilings searched for the flash kernel's block sizes — the
# same grid scripts/flash_block_sweep.py sweeps, plus 1024 for long-seq
# shapes where fewer/larger grid steps can win
FLASH_TILINGS = (128, 256, 512, 1024)
# BN row blocks: the (8, 128)-tile-legal heights around the shipped 512
BN_ROW_BLOCKS = (128, 256, 512, 1024, 2048)

# grad-comm dense-bucket byte bounds swept around the shipped 4 MiB
# default: small enough to keep several reduces in flight behind the
# backward, large enough to amortize per-collective launch latency
GRAD_BUCKET_BYTES = tuple(m * 2 ** 20 for m in (1, 2, 4, 8, 16))

# quantized-matmul spellings swept per shape (ISSUE 17): the dequant-
# fused epilogue (always correct, default) vs a native int8 dot_general
# with i32 accumulation (wins where the MXU multiplies int8 natively
# and the per-row activation-quant prologue amortizes)
QUANT_MATMUL_KINDS = ("dequant", "native-int8")

# KV page sizes swept for the paged decode cache (ISSUE 14): small pages
# cut allocation waste on short requests, large pages cut the gather's
# index fan-out and keep the (8, 128) sublane tiling dense — 128 is the
# shipped default where it divides max_len
KV_PAGE_TOKENS = (32, 64, 128, 256)

CONV_VARIANTS = ("plain", "inner", "s2d")

# per-geometry conv layout candidates (ISSUE 3 tentpole): the two
# activation layouts always, plus the dot_general spelling where the
# geometry is exactly a matmul (1x1, stride 1, unpadded, ungrouped)
CONV_GEOM_LAYOUTS = ("NHWC", "NCHW", "GEMM")


def set_mode(mode: str) -> str:
    """Install the process-global autotune mode (CLI ``--autotune``)."""
    global _MODE
    if mode not in MODES:
        raise ValueError(f"autotune mode must be one of {MODES}, "
                         f"got {mode!r}")
    _MODE = mode
    return _MODE


def get_mode() -> str:
    return _MODE


def dry_run() -> bool:
    """True off-TPU: measurement would time the interpret/CPU path, whose
    winners say nothing about the chip — return defaults instead."""
    try:
        import jax
        return jax.default_backend() != "tpu"
    except Exception:
        return True


def reset() -> None:
    """Back to a pristine state (tests): mode off, ledger and in-memory
    cache dropped (the on-disk file is untouched)."""
    global _MODE, _CACHE
    _MODE = "off"
    _DECISIONS.clear()
    _CACHE = None


def reset_decisions() -> None:
    """Clear the consulted-key ledger only — each perf run annotates just
    the decisions IT consulted, not a whole process's history."""
    _DECISIONS.clear()


def get_cache() -> AutotuneCache:
    global _CACHE
    if _CACHE is None:
        _CACHE = AutotuneCache()
    return _CACHE


def make_key(op: str, **facets) -> str:
    """Canonical cache key: op name + sorted facet pairs. Facets are the
    full shape/dtype signature — never anything run-dependent."""
    return "|".join([op] + [f"{k}={facets[k]}" for k in sorted(facets)])


def _dtype_name(dtype) -> str:
    """Canonical dtype spelling for keys ("float32", "bfloat16") — jnp
    scalar types, np dtypes and strings all normalize the same way."""
    import numpy as np
    try:
        return np.dtype(dtype).name
    except TypeError:
        return str(dtype)


def _record(key: str, config: Optional[dict], source: str) -> None:
    ent = {"source": source}
    if config:
        ent["config"] = dict(config)
    _DECISIONS[key] = ent


def annotation() -> Optional[dict]:
    """The run's tuning provenance for perf JSON lines: ``None`` in off
    mode; otherwise the mode plus, per consulted key, the decision config
    (with its source) or the literal string "default"."""
    if _MODE == "off":
        return None
    decisions = {}
    for k, v in sorted(_DECISIONS.items()):
        if v.get("config"):
            decisions[k] = dict(v["config"], source=v["source"])
        else:
            decisions[k] = "default"
    return {"mode": _MODE, "decisions": decisions}


def _resolve(key: str, default_config: dict, measure_fn) -> Tuple[dict, str]:
    """The shared resolution ladder: cache hit -> cached decision;
    cached-mode miss -> default; measure-mode miss (or a dry placeholder
    once a chip is present) -> measure & persist. Returns (config,
    source)."""
    if _MODE == "off":
        return dict(default_config), "off"
    cache = get_cache()
    ent = cache.get(key)
    if ent is not None and not (_MODE == "measure"
                                and ent.get("source") == "dry"
                                and not dry_run()):
        _record(key, ent.get("config"), "cached")
        return dict(ent["config"]), "cached"
    if _MODE == "cached":
        _record(key, None, "default")
        return dict(default_config), "default"
    if dry_run():
        ent = {"config": dict(default_config), "source": "dry"}
    else:
        config, best_ms = measure_fn()
        ent = {"config": dict(config), "source": "measured",
               "best_ms": round(best_ms, 4)}
    cache.put(key, ent)
    cache.save()
    _record(key, ent["config"], ent["source"])
    return dict(ent["config"]), ent["source"]


# --------------------------------------------------------------- surfaces
def flash_blocks(s_q: int, s_k: int, d: int, causal: bool,
                 dtype) -> Optional[Tuple[int, int]]:
    """Tuned (block_q, block_k) for one attention shape, or None when the
    mode is off / the shape admits no standard tiling (caller then keeps
    its 512 defaults + clamp)."""
    if _MODE == "off":
        return None
    from bigdl_tpu.ops.attention_kernel import _clamp_block

    cand_q = [b for b in FLASH_TILINGS if b <= s_q and s_q % b == 0]
    cand_k = [b for b in FLASH_TILINGS if b <= s_k and s_k % b == 0]
    if not cand_q or not cand_k:
        return None  # sub-128 or ragged: the clamp/fallback paths own it
    key = make_key("flash", seq_q=s_q, seq_k=s_k, head_dim=d,
                   causal=int(bool(causal)), dtype=_dtype_name(dtype))
    default = {"block_q": _clamp_block(512, s_q),
               "block_k": _clamp_block(512, s_k)}
    pairs = [(bq, bk) for bq in cand_q for bk in cand_k]

    def _measure():
        from bigdl_tpu.tuning.measure import measure_flash_blocks
        return measure_flash_blocks(s_q, s_k, d, causal, dtype, pairs)

    config, _ = _resolve(key, default, _measure)
    return int(config["block_q"]), int(config["block_k"])


def bn_row_block(rows: int, c: int, dtype) -> Optional[int]:
    """Tuned row-block height for the single-read BN stats kernels, or
    None when off / the shape admits no legal candidate (caller keeps the
    shipped 512 default)."""
    if _MODE == "off":
        return None
    from bigdl_tpu.ops.bn_kernel import _min_sublane

    ms = _min_sublane(dtype)
    cands = [rb for rb in BN_ROW_BLOCKS
             if rb <= rows and rows % rb == 0 and rb % ms == 0]
    if not cands or c % 128:
        return None
    key = make_key("bn_stats", rows=rows, channels=c,
                   dtype=_dtype_name(dtype))
    default_rb = min(512, rows)
    if rows % default_rb:  # default doesn't tile: smallest legal candidate
        default_rb = cands[0]
    default = {"row_block": default_rb}

    def _measure():
        from bigdl_tpu.tuning.measure import measure_bn_row_block
        return measure_bn_row_block(rows, c, dtype, cands)

    config, _ = _resolve(key, default, _measure)
    return int(config["row_block"])


def fba_row_block(rows: int, c: int, dtype,
                  relu: bool = False) -> Optional[int]:
    """Tuned row-block height for the FUSED BN block kernels (ISSUE 2:
    stats+apply forward / reductions+dx backward, ops/bn_kernel.py
    ``bn_fwd_apply``/``bn_bwd_fused``), or None when off / no legal
    candidate. Keyed separately from the stats-only kernel — the fused
    block keeps the activation resident across a two-phase sweep, so its
    best height need not match ``bn_stats``'s; ``relu`` is a key facet
    because the mask work changes the phase balance."""
    if _MODE == "off":
        return None
    from bigdl_tpu.ops.bn_kernel import _min_sublane

    ms = _min_sublane(dtype)
    cands = [rb for rb in BN_ROW_BLOCKS
             if rb <= rows and rows % rb == 0 and rb % ms == 0]
    if not cands or c % 128:
        return None
    key = make_key("bn_fba", rows=rows, channels=c,
                   dtype=_dtype_name(dtype), relu=int(bool(relu)))
    default_rb = min(512, rows)
    if rows % default_rb:  # default doesn't tile: smallest legal candidate
        default_rb = cands[0]
    default = {"row_block": default_rb}

    def _measure():
        from bigdl_tpu.tuning.measure import measure_fba_row_block
        return measure_fba_row_block(rows, c, dtype, relu, cands)

    config, _ = _resolve(key, default, _measure)
    return int(config["row_block"])


def grad_bucket_bytes(param_bytes: int, n_devices: int,
                      dtype) -> Optional[int]:
    """Tuned dense-bucket byte bound for the compressed gradient
    all-reduce (``grad_comm`` namespace), or None when the mode is off —
    the caller (parallel/grad_comm._resolve_bucket_bytes) then keeps its
    shipped 4 MiB default. Keyed per (param MiB rounded up, device
    count, wire dtype): bucket economics are a function of how much
    gradient crosses the wire, over how many links, at what element
    width — not of the model's name."""
    if _MODE == "off":
        return None
    param_mib = max(1, -(-int(param_bytes) // 2 ** 20))
    key = make_key("grad_comm", param_mib=param_mib, n_devices=n_devices,
                   dtype=_dtype_name(dtype))
    cands = [b for b in GRAD_BUCKET_BYTES if b <= param_bytes] or \
        [GRAD_BUCKET_BYTES[0]]
    from bigdl_tpu.parallel.grad_comm import DEFAULT_BUCKET_BYTES
    default_b = DEFAULT_BUCKET_BYTES
    if default_b not in cands:  # tiny trees: largest legal candidate
        default_b = cands[-1]
    default = {"bucket_bytes": default_b}

    def _measure():
        from bigdl_tpu.tuning.measure import measure_grad_buckets
        return measure_grad_buckets(param_bytes, n_devices, dtype, cands)

    config, _ = _resolve(key, default, _measure)
    return int(config["bucket_bytes"])


def kv_page_tokens(max_len: int, kv_heads: int, head_dim: int,
                   dtype) -> Optional[int]:
    """Tuned KV page size in tokens for the paged decode cache
    (``kv_pages`` namespace), or None when the mode is off — the caller
    (cli/serve ``--kvPageTokens auto``) then keeps the shipped default.
    Keyed per (max_len, kv_heads, head_dim, dtype): the gather/scatter
    cost a page size pays is a function of the cache geometry, not the
    model's name. Candidates must divide max_len (the engine requires
    it so the gathered view is exactly max_len)."""
    if _MODE == "off":
        return None
    cands = [c for c in KV_PAGE_TOKENS
             if c <= max_len and max_len % c == 0]
    if not cands:
        return None  # ragged max_len: the engine's explicit value owns it
    key = make_key("kv_pages", max_len=max_len, kv_heads=kv_heads,
                   head_dim=head_dim, dtype=_dtype_name(dtype))
    default = {"page_tokens": 128 if 128 in cands else cands[-1]}

    def _measure():
        from bigdl_tpu.tuning.measure import measure_kv_page_tokens
        return measure_kv_page_tokens(max_len, kv_heads, head_dim, dtype,
                                      cands)

    config, _ = _resolve(key, default, _measure)
    return int(config["page_tokens"])


def quant_matmul_kind(m: int, k: int, n: int, dtype) -> str:
    """Tuned quantized-matmul spelling for one (m, k, n, dtype) shape
    (``quant`` namespace; ISSUE 17): ``"dequant"`` — the fused
    dequant-epilogue matmul, always available — or ``"native-int8"`` —
    int8 ``dot_general`` with i32 accumulation plus dynamic per-row
    activation quant. Consulted at trace time by the serving engines'
    :class:`bigdl_tpu.serving.quant.QuantizedWeight` views; off mode
    keeps the shipped dequant default so ``--quantize`` alone never
    changes which kernel serves."""
    if _MODE == "off":
        return "dequant"
    key = make_key("quant", m=int(m), k=int(k), n=int(n),
                   dtype=_dtype_name(dtype))
    default = {"kind": "dequant"}

    def _measure():
        from bigdl_tpu.tuning.measure import measure_quant_matmul
        return measure_quant_matmul(int(m), int(k), int(n), dtype)

    config, _ = _resolve(key, default, _measure)
    kind = str(config.get("kind", "dequant"))
    return kind if kind in QUANT_MATMUL_KINDS else "dequant"


def conv_geom_key(pass_name: str, geom: tuple) -> str:
    """Canonical ``conv_geom`` cache key for one (geometry, pass, dtype):
    geom is ops.conv2d's 10-tuple (kh, kw, sh, sw, cin, cout, groups,
    dh, dw, dtype)."""
    kh, kw, sh, sw, cin, cout, groups, dh, dw, dtype = geom
    return make_key("conv_geom", kh=kh, kw=kw, stride=f"{sh}x{sw}",
                    cin=cin, cout=cout, groups=groups, dil=f"{dh}x{dw}",
                    dtype=dtype, **{"pass": pass_name})


def conv_geom_layout(pass_name: str, geom: tuple, x_shape: tuple,
                     gemm_ok: bool) -> Optional[str]:
    """Tuned layout for ONE conv geometry and pass (ISSUE 3 tentpole), or
    None — the caller (ops/conv2d._pass_layout) then falls back to the
    global triple. Unlike the other resolvers this one has no forced
    default on a cached-mode miss: "no per-geometry decision" must mean
    "use whatever the global policy says", not "pin NHWC".

    measure mode on a chip times the pass for this exact geometry at the
    traced activation shape ``x_shape`` (batch/spatial are not in the
    key — the first traced shape of a geometry decides for all of them,
    which is the right weighting since ResNet geometries recur at one
    spatial size each); off-TPU the dry run records NHWC without timing
    so the CPU pipeline round-trips deterministically."""
    if _MODE == "off":
        return None
    key = conv_geom_key(pass_name, geom)
    cache = get_cache()
    ent = cache.get(key)
    if ent is not None and not (_MODE == "measure"
                                and ent.get("source") == "dry"
                                and not dry_run()):
        lay = (ent.get("config") or {}).get("layout")
        if lay in CONV_GEOM_LAYOUTS and (lay != "GEMM" or gemm_ok):
            _record(key, ent.get("config"), "cached")
            return lay
        # unusable entry (corrupt edit, or a GEMM decision for a site
        # that can't run it): behave like a miss — cached mode falls back
        # to the global triple, measure mode re-measures below
    if _MODE == "cached":
        _record(key, None, "default")
        return None
    if dry_run():
        ent = {"config": {"layout": "NHWC"}, "source": "dry"}
    else:
        cands = [l for l in CONV_GEOM_LAYOUTS if l != "GEMM" or gemm_ok]
        from bigdl_tpu.tuning.measure import measure_conv_geom
        config, best_ms = measure_conv_geom(pass_name, geom, x_shape,
                                            cands)
        ent = {"config": dict(config), "source": "measured",
               "best_ms": round(best_ms, 4)}
    cache.put(key, ent)
    cache.save()
    _record(key, ent["config"], ent["source"])
    return ent["config"]["layout"]


def peek_geom_layout(pass_name: str, geom: tuple,
                     gemm_ok: bool) -> Optional[str]:
    """Read-only ``conv_geom`` lookup for static analysis (tpulint):
    the cached decision for this (pass, geometry) when one exists and is
    usable, else None. Never measures, never writes a dry entry, never
    records in the provenance ledger — a lint pass must not change what
    a later run resolves."""
    if _MODE == "off":
        return None
    ent = get_cache().get(conv_geom_key(pass_name, geom))
    lay = ((ent.get("config") or {}).get("layout")
           if isinstance(ent, dict) else None)
    if lay in CONV_GEOM_LAYOUTS and (lay != "GEMM" or gemm_ok):
        return lay
    return None


def put_geom_decisions(decisions, cache=None) -> int:
    """Write probe-derived per-geometry decisions (the
    ``apply_conv_probe.py --geom`` JSON) into the autotune cache under
    ``conv_geom`` keys with source "probe", so ``--autotune cached``
    replays them with zero measurement. Returns the number of (geometry,
    pass) entries written."""
    from bigdl_tpu.ops.conv2d import geom_from_json
    cache = cache or get_cache()
    n = 0
    for d in decisions:
        geom = geom_from_json(d.get("geom", {}))
        for p, lay in sorted((d.get("layouts") or {}).items()):
            if lay not in CONV_GEOM_LAYOUTS:
                raise ValueError(f"bad layout {lay!r} in decision {d!r}")
            cache.put(conv_geom_key(p, geom),
                      {"config": {"layout": lay}, "source": "probe"})
            n += 1
    cache.save()
    return n


def install_conv_layouts(variant: str = "plain", device=None
                         ) -> Dict[str, str]:
    """Resolve and install the per-pass conv layout policy for one run
    configuration, composing with inner-stepping/s2d instead of skipping
    (ADVICE r5 #1 / ISSUE 1): ``variant`` names the configuration facet —
    the window-2 matrix measured the wgrad-NCHW decision positive alone
    but negative composed with inner-stepping or the s2d stem, so each
    variant gets its own key (and its own measured decision, once a chip
    measures it).

    Off mode keeps the legacy ladder: shipped MEASURED_DECISIONS on the
    plain path, the all-NHWC default (installed, not skipped — the
    snapshot/restore fix) on guarded paths. An explicit ``--convLayout``
    still wins over every mode (``maybe_install_auto`` honors the
    explicit flag)."""
    if variant not in CONV_VARIANTS:
        raise ValueError(f"conv variant must be one of {CONV_VARIANTS}, "
                         f"got {variant!r}")
    from bigdl_tpu.ops import conv2d

    guarded = variant != "plain"
    if _MODE == "off":
        return conv2d.maybe_install_auto(device, guarded=guarded)
    default = (dict(conv2d._DEFAULT) if guarded
               else conv2d.resolve_layout_spec("auto", device))
    key = make_key("conv_layouts", variant=variant)

    def _measure():
        from bigdl_tpu.tuning.measure import measure_conv_layouts
        import jax.numpy as jnp
        return measure_conv_layouts(jnp.bfloat16)

    config, _ = _resolve(key, default, _measure)
    config = {p: config.get(p, "NHWC") for p in ("fwd", "dgrad", "wgrad")}
    return conv2d.maybe_install_auto(device, policy=config)
