"""Persistent autotune cache — one JSON file per device kind.

The reference BigDL's Engine picked shape-tuned MKL primitives at runtime
on every process start (spark/dl/.../Engine.scala convolution-algorithm
selection); re-measuring per process is wasteful on TPU where one candidate
sweep costs whole compile cycles through a tunneled runtime. So decisions
persist: ``~/.cache/bigdl_tpu/autotune/<device-kind>.json`` (override the
directory with ``BIGDL_TPU_AUTOTUNE_CACHE``), versioned so a format change
can never misread old decisions as current ones.

Determinism contract (ISSUE 1 acceptance): the serialized bytes are a pure
function of the entries — keys sorted, no timestamps, no environment
fingerprints — so two ``measure`` runs over identical keys on the same
device produce byte-identical files (dry mode) or files differing only in
measured milliseconds (chip mode). Corrupt or version-mismatched files
load as empty (the tuner then falls back to defaults) instead of raising:
a half-written cache after a tunnel drop must never take down a training
run.

Namespaces in one file (the key's leading ``op`` token): ``flash`` /
``bn_stats`` / ``bn_fba`` / ``conv_layouts`` (global per-variant triple)
and, from round 8, ``conv_geom`` — per-conv-geometry layout decisions
keyed by (kh, kw, stride, cin, cout, groups, dilation, dtype, pass),
written by measure mode or imported from probe output with source
``"probe"`` (tuning.put_geom_decisions). Entry sources: ``measured`` /
``dry`` / ``probe``.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Dict, Optional

__all__ = ["AutotuneCache", "CACHE_VERSION", "cache_dir", "cache_path",
           "device_kind", "device_slug"]

CACHE_VERSION = 1


def cache_dir() -> str:
    """Resolve the cache directory: BIGDL_TPU_AUTOTUNE_CACHE wins (tests,
    shared-filesystem clusters); default is a per-user path."""
    explicit = os.environ.get("BIGDL_TPU_AUTOTUNE_CACHE")
    if explicit:
        return explicit
    return os.path.join(os.path.expanduser("~"), ".cache", "bigdl_tpu",
                        "autotune")


def device_kind() -> str:
    """The ambient accelerator kind ("TPU v5 lite", ...); "cpu" when no
    backend resolves (e.g. jax not initialized yet in a dry test)."""
    try:
        import jax
        return getattr(jax.devices()[0], "device_kind", "cpu") or "cpu"
    except Exception:
        return "cpu"


def device_slug(kind: str) -> str:
    """Filesystem-safe spelling of a device kind ("TPU v5 lite" ->
    "tpu-v5-lite")."""
    slug = "".join(c if c.isalnum() else "-" for c in kind.lower())
    while "--" in slug:
        slug = slug.replace("--", "-")
    return slug.strip("-") or "unknown"


def cache_path(kind: Optional[str] = None) -> str:
    return os.path.join(cache_dir(),
                        device_slug(kind or device_kind()) + ".json")


class AutotuneCache:
    """In-memory view over one device kind's JSON decision file.

    ``get``/``put`` operate on the in-memory layer; ``save()`` writes the
    whole store atomically (temp file + rename) so readers never see a
    torn file. Loading tolerates every corruption mode by falling back to
    an empty store — decisions are an optimization, never a dependency.
    """

    def __init__(self, kind: Optional[str] = None,
                 path: Optional[str] = None):
        self.kind = kind or device_kind()
        self.path = path or cache_path(self.kind)
        self.entries: Dict[str, dict] = {}
        self._load()

    def _load(self) -> None:
        try:
            with open(self.path) as f:
                blob = json.load(f)
        except (OSError, ValueError):
            return  # missing or corrupt: start empty
        if not isinstance(blob, dict) or blob.get("version") != CACHE_VERSION:
            return  # version mismatch: stale decisions are not decisions
        entries = blob.get("entries")
        if isinstance(entries, dict):
            self.entries = {str(k): dict(v) for k, v in entries.items()
                            if isinstance(v, dict) and "config" in v}

    def get(self, key: str) -> Optional[dict]:
        ent = self.entries.get(key)
        return dict(ent) if ent is not None else None

    def put(self, key: str, entry: dict) -> None:
        self.entries[key] = dict(entry)

    def save(self) -> None:
        """Atomic, deterministic write: sorted keys, fixed separators, no
        wall-clock anywhere in the payload."""
        blob = {"version": CACHE_VERSION, "device_kind": self.kind,
                "entries": dict(sorted(self.entries.items()))}
        payload = json.dumps(blob, sort_keys=True, indent=1) + "\n"
        d = os.path.dirname(self.path) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".autotune_")
        try:
            with os.fdopen(fd, "w") as f:
                f.write(payload)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
