"""Candidate timing for the autotuner — chip mode only.

Every routine here follows the tunnel timing rules learned in round 5
(PERF.md §8.2, scripts/flash_block_sweep.py): chain each timed call on the
previous result so executions cannot be elided or pipelined, and sync by
FETCHING a scalar to host — through the axon runtime ``block_until_ready``
acks before device completion and "times" impossible TF/s numbers.

These functions never run in dry mode (``autotune.dry_run()`` gates them),
so they may assume a real backend; candidate order is deterministic and a
candidate only wins on a strictly lower time, keeping ties stable across
runs.
"""

from __future__ import annotations

import functools
import time
from typing import Dict, List, Sequence, Tuple

__all__ = ["time_fn", "measure_flash_blocks", "measure_bn_row_block",
           "measure_fba_row_block", "measure_conv_layouts",
           "measure_conv_geom", "measure_grad_buckets",
           "measure_kv_page_tokens", "measure_quant_matmul",
           "CONV_PROBE_SHAPES"]

_WARMUP = 1
_ITERS = 3


def _sync(x) -> float:
    """Host-fetch barrier (the only trustworthy sync through the tunnel)."""
    import jax
    import jax.numpy as jnp

    leaf = jax.tree_util.tree_leaves(x)[0]
    return float(jnp.sum(leaf.astype(jnp.float32)))


def time_fn(fn, *args, iters: int = _ITERS) -> float:
    """Milliseconds per call of ``fn(*args)``: compile+warmup outside the
    timed region, then ``iters`` chained calls closed by a host fetch.
    ``fn`` must return something tree-like whose first leaf has the shape
    of ``args[0]`` so calls can chain; non-chainable fns are re-invoked
    on the original args (still sync-fetched each sequence end)."""
    cur = fn(*args)
    _sync(cur)  # compile + warmup
    chain = (getattr(cur, "shape", None) == getattr(args[0], "shape", None)
             and getattr(cur, "dtype", None) == getattr(args[0], "dtype",
                                                        None))
    t0 = time.perf_counter()
    for _ in range(iters):
        cur = fn(cur, *args[1:]) if chain else fn(*args)
    _sync(cur)
    return (time.perf_counter() - t0) / iters * 1e3


def _pick(timed: Sequence[Tuple[dict, float]]) -> Tuple[dict, float]:
    """First strictly-fastest candidate in presentation order (stable under
    exact ties, so re-measuring identical timings re-picks identically)."""
    best, best_ms = timed[0]
    for cfg, ms in timed[1:]:
        if ms < best_ms:
            best, best_ms = cfg, ms
    return best, best_ms


def measure_flash_blocks(s_q: int, s_k: int, d: int, causal: bool,
                         dtype, candidates: Sequence[Tuple[int, int]]
                         ) -> Tuple[dict, float]:
    """Time fwd+bwd of the flash kernel per (block_q, block_k) candidate on
    a small fixed (b=1, h=8) problem of the target sequence geometry."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.ops.attention_kernel import _flash

    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (1, 8, s_q, d), dtype)
    k = jax.random.normal(kk, (1, 8, s_k, d), dtype)
    v = jax.random.normal(kv, (1, 8, s_k, d), dtype)

    timed: List[Tuple[dict, float]] = []
    for bq, bk in candidates:
        def loss(q_, k_, v_, bq=bq, bk=bk):
            return jnp.sum(_flash(q_, k_, v_, causal, bq, bk)
                           .astype(jnp.float32))

        g = jax.jit(jax.grad(loss, argnums=0))
        ms = time_fn(g, q, k, v)
        timed.append(({"block_q": bq, "block_k": bk}, ms))
    return _pick(timed)


def measure_bn_row_block(rows: int, c: int, dtype,
                         candidates: Sequence[int]) -> Tuple[dict, float]:
    """Time the single-read BN stats kernel per row-block candidate on the
    exact (rows, C) shape being tuned."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.ops.bn_kernel import bn_stats

    x = jax.random.normal(jax.random.PRNGKey(0), (rows, c), dtype)
    timed: List[Tuple[dict, float]] = []
    for rb in candidates:
        fn = jax.jit(functools.partial(bn_stats, row_block=rb))
        # bn_stats returns (sum, sumsq), not x-shaped: time_fn re-invokes
        ms = time_fn(fn, x)
        timed.append(({"row_block": rb}, ms))
    return _pick(timed)


def measure_fba_row_block(rows: int, c: int, dtype, relu: bool,
                          candidates: Sequence[int]) -> Tuple[dict, float]:
    """Time fwd+bwd of the FUSED BN block (stats+apply(+ReLU) forward,
    reductions+dx backward — ops/bn_kernel.fused_bn_apply_train) per
    row-block candidate on the exact (rows, C) shape being tuned. Both
    kernels share the decision, so the timed unit is a full grad step."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.ops.bn_kernel import fused_bn_apply_train

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (rows, c), dtype)
    gamma = jnp.ones((c,), jnp.float32)
    beta = jnp.zeros((c,), jnp.float32)

    timed: List[Tuple[dict, float]] = []
    for rb in candidates:
        def loss(x_, rb=rb):
            return jnp.sum(fused_bn_apply_train(
                x_, gamma, beta, 1e-5, relu, rb)[0].astype(jnp.float32))

        g = jax.jit(jax.grad(loss))
        ms = time_fn(g, x)  # grad is x-shaped: calls chain
        timed.append(({"row_block": rb}, ms))
    return _pick(timed)


def measure_grad_buckets(param_bytes: int, n_devices: int, dtype,
                         candidates: Sequence[int]) -> Tuple[dict, float]:
    """Time one full compressed all-reduce of ``param_bytes`` worth of
    f32 gradient per bucket-bound candidate, over the ambient device
    mesh's ``data`` axis via grad_comm's explicit shard_map psum path —
    the wire cost a training step pays, minus the backward it would
    overlap with (overlap headroom rises as buckets shrink; the measured
    total captures the per-collective latency the bound amortizes).
    Returns ({"bucket_bytes": best}, best_ms)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from bigdl_tpu.parallel.grad_comm import compressed_psum

    mode = "fp16" if np.dtype(dtype).name == "float16" else "bf16"
    devs = jax.devices()[:n_devices]
    mesh = Mesh(np.array(devs), ("data",))
    n_elems = max(1, int(param_bytes) // 4)

    timed: List[Tuple[dict, float]] = []
    for bound in candidates:
        per_bucket = max(1, int(bound) // 4)
        lens = [per_bucket] * (n_elems // per_bucket)
        if n_elems % per_bucket:
            lens.append(n_elems % per_bucket)

        def reduce_all(x, lens=lens, mesh=mesh, mode=mode):
            outs = []
            off = 0
            for ln in lens:
                stacked = jax.lax.dynamic_slice_in_dim(
                    x, off, ln * n_devices).reshape(n_devices, ln)
                outs.append(compressed_psum(stacked, mesh, "data", mode))
                off += ln * n_devices
            return jnp.concatenate(outs)

        x = jax.random.normal(jax.random.PRNGKey(0),
                              (n_elems * n_devices,), jnp.float32)
        fn = jax.jit(reduce_all)
        ms = time_fn(fn, x)  # output is not x-shaped: re-invokes
        timed.append(({"bucket_bytes": int(bound)}, ms))
    return _pick(timed)


# Representative conv shape set: the distinct ResNet-50 b32 bottleneck
# geometries (n, h, w, cin, cout, kh, kw, stride) — a scaled-down version
# of scripts/conv_bwd_probe.py's sweep so one measure pass stays cheap.
# Total ms across the set approximates one step's conv time, so summing is
# the right weighting for a single global per-pass decision.
CONV_PROBE_SHAPES: Tuple[Tuple[int, int, int, int, int, int, int, int], ...] = (
    (32, 224, 224, 3, 64, 7, 7, 2),    # stem (the measured 7x wgrad case)
    (32, 56, 56, 64, 64, 1, 1, 1),
    (32, 56, 56, 64, 64, 3, 3, 1),
    (32, 28, 28, 128, 128, 3, 3, 1),
    (32, 14, 14, 256, 256, 3, 3, 1),
    (32, 7, 7, 512, 512, 3, 3, 1),
)


def measure_conv_geom(pass_name: str, geom: tuple, x_shape: tuple,
                      candidates: Sequence[str]) -> Tuple[dict, float]:
    """Time ONE conv pass of ONE geometry under each candidate layout
    (NHWC/NCHW, plus GEMM where eligible) at the exact activation shape
    the training trace presented — the per-geometry refinement of
    :func:`measure_conv_layouts` (ISSUE 3). Returns ({"layout": best},
    best_ms); candidate order is the deterministic CONV_GEOM_LAYOUTS
    order, so exact ties re-pick identically."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu.ops.conv2d import _conv_in_layout

    kh, kw, sh, sw, cin, cout, groups, dh, dw, dtype_name = geom
    n, h, w_ = int(x_shape[0]), int(x_shape[1]), int(x_shape[2])
    dtype = np.dtype(dtype_name)
    kx, kw_ = jax.random.split(jax.random.PRNGKey(0))
    x = jax.random.normal(kx, (n, h, w_, cin), dtype)
    wgt = jax.random.normal(kw_, (kh, kw, cin // groups, cout), dtype)
    # SAME-style symmetric padding approximates the training sites (the
    # geometry key carries no padding; for the k=1 GEMM-eligible sites
    # this is exactly zero padding)
    pad = ((kh // 2, kh // 2), (kw // 2, kw // 2))

    timed: List[Tuple[dict, float]] = []
    for layout in candidates:
        conv = functools.partial(
            _conv_in_layout, stride=(sh, sw), padding=pad,
            rhs_dilation=(dh, dw), groups=groups, layout=layout)
        if pass_name == "fwd":
            fn = jax.jit(lambda x_, w_c=wgt, c=conv: c(x_, w_c))
            ms = time_fn(fn, x)
        else:
            dy = jnp.ones_like(conv(x, wgt))
            if pass_name == "dgrad":
                fn = jax.jit(lambda dy_, x_=x, w_c=wgt, c=conv:
                             jax.linear_transpose(
                                 lambda xx: c(xx, w_c), x_)(dy_)[0])
            else:
                fn = jax.jit(lambda dy_, x_=x, w_c=wgt, c=conv:
                             jax.linear_transpose(
                                 lambda ww: c(x_, ww), w_c)(dy_)[0])
            ms = time_fn(fn, dy)
        timed.append(({"layout": layout}, ms))
    return _pick(timed)


def measure_conv_layouts(dtype) -> Tuple[dict, float]:
    """Per-pass independent layout decision (the generalized form of
    scripts/conv_bwd_probe.py + ops/conv2d.decide_from_probe): time each
    of fwd/dgrad/wgrad under NHWC and NCHW across the shape set and pick
    the per-pass minimum of the totals. Returns ({'fwd'|'dgrad'|'wgrad':
    layout}, total_best_ms)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.ops.conv2d import _conv_in_layout

    totals = {p: {"NHWC": 0.0, "NCHW": 0.0}
              for p in ("fwd", "dgrad", "wgrad")}
    for n, h, w, cin, cout, kh, kw, stride in CONV_PROBE_SHAPES:
        kx, kw_ = jax.random.split(jax.random.PRNGKey(0))
        x = jax.random.normal(kx, (n, h, w, cin), dtype)
        wgt = jax.random.normal(kw_, (kh, kw, cin, cout), dtype)
        pad = ((kh // 2, kh // 2), (kw // 2, kw // 2))
        for layout in ("NHWC", "NCHW"):
            conv = functools.partial(
                _conv_in_layout, stride=(stride, stride), padding=pad,
                rhs_dilation=(1, 1), groups=1, layout=layout)
            y = conv(x, wgt)
            dy = jnp.ones_like(y)

            fwd = jax.jit(lambda x_, w_=wgt: conv(x_, w_))
            totals["fwd"][layout] += time_fn(fwd, x)

            dgrad = jax.jit(lambda dy_, x_=x, w_=wgt: jax.linear_transpose(
                lambda xx: conv(xx, w_), x_)(dy_)[0])
            totals["dgrad"][layout] += time_fn(dgrad, dy)

            wgrad = jax.jit(lambda dy_, x_=x, w_=wgt: jax.linear_transpose(
                lambda ww: conv(x_, ww), w_)(dy_)[0])
            totals["wgrad"][layout] += time_fn(wgrad, dy)

    decision: Dict[str, str] = {}
    best_total = 0.0
    for p, per in totals.items():
        # NHWC wins ties: deterministic, and it is the framework default
        lay = "NCHW" if per["NCHW"] < per["NHWC"] else "NHWC"
        decision[p] = lay
        best_total += per[lay]
    return decision, best_total


def measure_kv_page_tokens(max_len: int, kv_heads: int, head_dim: int,
                           dtype, candidates: Sequence[int]
                           ) -> Tuple[dict, float]:
    """Time one paged decode-step memory roundtrip per page-size
    candidate: gather a slot's pages into the contiguous view the decode
    graph reads, then scatter one token's K/V back — the two data
    movements paging adds to every step. Small pages pay index fan-out
    (max_len/pt gather rows), large pages pay transfer granularity; the
    sweet spot is the chip's to declare. Returns
    ({"page_tokens": best}, best_ms)."""
    import jax
    import jax.numpy as jnp

    timed: List[Tuple[dict, float]] = []
    for pt in candidates:
        mp = max_len // pt
        pool = jax.random.normal(
            jax.random.PRNGKey(0),
            (1 + mp, kv_heads, pt, head_dim)).astype(dtype)
        pages = jnp.arange(1, mp + 1, dtype=jnp.int32)
        tok = jnp.ones((kv_heads, head_dim), dtype)

        def roundtrip(pool, pages=pages, tok=tok, mp=mp, pt=pt):
            x = jnp.take(pool, pages, axis=0)
            view = x.transpose(1, 0, 2, 3).reshape(
                kv_heads, mp * pt, head_dim)
            # fold the view back in so the gather cannot be elided
            upd = tok + view[:, -1, :]
            return pool.at[pages[-1], :, pt - 1, :].set(upd)

        fn = jax.jit(roundtrip)
        ms = time_fn(fn, pool)  # pool-shaped output: calls chain
        timed.append(({"page_tokens": int(pt)}, ms))
    return _pick(timed)


def measure_quant_matmul(m: int, k: int, n: int, dtype
                         ) -> Tuple[dict, float]:
    """Time the two quantized-matmul spellings for one (m, k, n)
    activation/weight shape (ISSUE 17): the dequant-fused epilogue
    (``(x @ q.astype(dt)) * s``) vs the native int8 ``dot_general``
    with i32 accumulation plus the dynamic activation-quant prologue.
    Candidate order puts dequant first so exact ties keep the shipped
    default. Returns ({"kind": best}, best_ms)."""
    import jax
    import jax.numpy as jnp

    kx, kw = jax.random.split(jax.random.PRNGKey(0))
    x = jax.random.normal(kx, (m, k), dtype)
    q = jax.random.randint(kw, (k, n), -127, 128, jnp.int8)
    s = jnp.full((n,), 0.01, jnp.float32)

    def dequant(x_):
        return (x_ @ q.astype(x_.dtype)) * s.astype(x_.dtype)

    def native(x_):
        xf = x_.astype(jnp.float32)
        xs = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1, keepdims=True),
                         1e-8) / 127.0
        xq = jnp.clip(jnp.round(xf / xs), -127, 127).astype(jnp.int8)
        acc = jax.lax.dot_general(
            xq, q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        return acc.astype(x_.dtype) * xs.astype(x_.dtype) \
            * s.astype(x_.dtype)

    timed: List[Tuple[dict, float]] = []
    for kind, fn in (("dequant", dequant), ("native-int8", native)):
        jitted = jax.jit(fn)
        ms = time_fn(jitted, x)  # (m, n) output: re-invokes
        timed.append(({"kind": kind}, ms))
    return _pick(timed)
