"""Fused batch-norm kernels for TPU — single-read Pallas passes.

Why this exists: the round-3 xplane profile (PERF.md §2) shows BN stat
reductions as the largest synchronous op category in the ResNet-50 step
(15.6 ms at b128 — more than the optimizer). The stats pass re-reads the
full activation from HBM, and XLA schedules the mean and mean-of-squares
reductions (plus the bf16→f32 convert) as separate fusion consumers of
that read. The reference never had this problem shape: its MKL BN
(nn/SpatialBatchNormalization.scala backed by the native batchnorm) ran
per-core on cache-resident tiles.

Two stats-only kernels, both one HBM pass:

* :func:`bn_stats` — (rows, C) activations → per-channel (sum, sumsq)
  accumulated in f32 VMEM scratch across a serial row-block grid. One
  read of x instead of XLA's convert+double-reduce chain.
* :func:`bn_bwd_stats` — the backward needs Σdy and Σ(dy·x̂) per channel;
  same pattern over (dy, x) with the normalization folded in, one read
  of each operand.

:func:`fused_bn_train` packages those stats under one ``jax.custom_vjp``
(the apply and dx elementwise stay in jnp) — the round-4 "stats" mode.
The round-5 chip A/B measured it NEGATIVE end-to-end (−46%, PERF.md
§8.2): ``pallas_call`` is an optimization barrier, so fusing ONLY the
reductions unfuses the elementwise neighbors XLA was already folding
them into, and the activation still crosses HBM once per extra pass.

The round-7 answer is to move the whole block inside the barrier:

* :func:`bn_fwd_apply` — one kernel whose two-phase row sweep first
  accumulates the stats, then applies ``(x−μ)·inv·γ+β`` (+ optional
  ReLU) — stats, normalize, affine and activation in a single launch.
* :func:`bn_bwd_fused` — one kernel fusing the Σdy/Σ(dy·x̂) reductions
  (with the ReLU mask recomputed from x, so no mask tensor is saved)
  with the dx elementwise expression in its second phase.

:func:`fused_bn_apply_train` wraps the pair in a ``jax.custom_vjp`` so
``nn.BatchNormalization(fused="apply")`` swaps in the full fused block
(ISSUE 2 tentpole). Per pass the activation is read twice and written
once inside ONE kernel — vs the three separate convert/reduce/
elementwise HBM round-trips of the unfused backward — and the ReLU
residual disappears entirely.

Non-TPU backends run interpret mode (tests); block specs follow the
(8, 128) tiling rule (validated by the Mosaic block-spec lint in
tests/test_flash_attention.py).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["bn_stats", "bn_bwd_stats", "fused_bn_train",
           "bn_fwd_apply", "bn_bwd_fused", "fused_bn_apply_train",
           "fused_bn_tileable", "fba_tileable", "min_sublane"]


def _vmem_scratch(shape):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, jnp.float32)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# row-block height per grid step; 512 f32 lanes × C_BLOCK channels of x
# plus two f32 scratch rows stay far under VMEM. The autotuner
# (bigdl_tpu.tuning) can override per (rows, C, dtype) shape; 512 is the
# shipped default
_ROW_BLOCK = 512
_C_BLOCK = 128


def _resolve_row_block(rows: int, c: int, *dtypes) -> int:
    """Effective row-block height: the autotuner's measured decision for
    this (rows, C, dtype) when one exists (no-op in off mode), else the
    shipped default clamped to the array."""
    from bigdl_tpu import tuning
    if tuning.get_mode() != "off":
        tuned = tuning.bn_row_block(rows, c, dtypes[0])
        if tuned:
            return min(tuned, rows)
    return min(_ROW_BLOCK, rows)


def _stats_kernel(x_ref, sum_ref, sq_ref, acc_ref):
    """Grid (c_blocks, row_blocks) — row dim innermost, so the f32 scratch
    accumulator persists across the row sweep of one channel block."""
    r = pl.program_id(1)
    x = x_ref[...].astype(jnp.float32)

    @pl.when(r == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[0, :] += jnp.sum(x, axis=0)
    acc_ref[1, :] += jnp.sum(x * x, axis=0)

    @pl.when(r == pl.num_programs(1) - 1)
    def _emit():
        # output block is a full (8, cb) f32 tile — broadcast the row so
        # lowering never depends on Mosaic's block-dim==array-dim escape
        # for sub-minimum (1, cb) tiles (the escape the round-3 flash
        # failure was about); the caller reads row 0
        sum_ref[...] = jnp.broadcast_to(acc_ref[0:1, :], sum_ref.shape)
        sq_ref[...] = jnp.broadcast_to(acc_ref[1:2, :], sq_ref.shape)


_OUT_SUBLANES = 8  # full f32 min tile for the (sum, sumsq) outputs


def _min_sublane(*dtypes) -> int:
    """Mosaic's minimum sublane count across operand dtypes: 8 for 4-byte,
    16 for 2-byte (bf16), 32 for 1-byte (pallas_guide.md tiling table)."""
    need = 8
    for d in dtypes:
        need = max(need, {4: 8, 2: 16, 1: 32}.get(jnp.dtype(d).itemsize, 8))
    return need


def bn_stats(x2d: jax.Array,
             row_block: "int | None" = None) -> Tuple[jax.Array, jax.Array]:
    """Per-channel (sum, sum-of-squares) of a (rows, C) array in ONE HBM
    read, f32 accumulation regardless of input dtype. Requires rows %
    {row block} == 0, rows % {dtype min sublane} == 0 and C % 128 == 0
    (the NHWC ResNet shapes satisfy all); callers fall back to jnp
    otherwise. ``row_block=None`` resolves through the autotuner."""
    rows, c = x2d.shape
    rb = row_block or _resolve_row_block(rows, c, x2d.dtype)
    cb = min(_C_BLOCK, c)
    ms = _min_sublane(x2d.dtype)
    # rows%{ms} / c%128 are Mosaic's sublane/lane minima — without them
    # the call lowers in interpret mode but compile-fails on real TPU
    if rows % rb or c % cb or rows % ms or c % 128:
        raise ValueError(f"bn_stats needs rows%{rb}==0, rows%{ms}==0 "
                         f"(dtype {x2d.dtype}), C%{cb}==0 and C%128==0, "
                         f"got {x2d.shape}")
    grid = (c // cb, rows // rb)
    out_shape = [
        jax.ShapeDtypeStruct((_OUT_SUBLANES, c), jnp.float32),
        jax.ShapeDtypeStruct((_OUT_SUBLANES, c), jnp.float32),
    ]
    s, sq = pl.pallas_call(
        _stats_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((rb, cb), lambda ci, ri: (ri, ci))],
        out_specs=[
            pl.BlockSpec((_OUT_SUBLANES, cb), lambda ci, ri: (0, ci)),
            pl.BlockSpec((_OUT_SUBLANES, cb), lambda ci, ri: (0, ci)),
        ],
        out_shape=out_shape,
        scratch_shapes=[_vmem_scratch((2, cb))],
        interpret=_interpret(),
    )(x2d)
    return s[0], sq[0]


def _bwd_kernel(dy_ref, xhat_ref, sdy_ref, sdyx_ref, acc_ref):
    r = pl.program_id(1)
    dy = dy_ref[...].astype(jnp.float32)
    xh = xhat_ref[...].astype(jnp.float32)

    @pl.when(r == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[0, :] += jnp.sum(dy, axis=0)
    acc_ref[1, :] += jnp.sum(dy * xh, axis=0)

    @pl.when(r == pl.num_programs(1) - 1)
    def _emit():
        sdy_ref[...] = jnp.broadcast_to(acc_ref[0:1, :], sdy_ref.shape)
        sdyx_ref[...] = jnp.broadcast_to(acc_ref[1:2, :], sdyx_ref.shape)


def bn_bwd_stats(dy2d: jax.Array, xhat2d: jax.Array,
                 row_block: "int | None" = None):
    """(Σdy, Σ(dy·x̂)) per channel — the two reductions of the BN backward
    — in one pass over each operand. ``row_block=None`` resolves through
    the autotuner."""
    rows, c = dy2d.shape
    rb = row_block or _resolve_row_block(rows, c, dy2d.dtype, xhat2d.dtype)
    cb = min(_C_BLOCK, c)
    ms = _min_sublane(dy2d.dtype, xhat2d.dtype)
    if rows % rb or c % cb or rows % ms or c % 128:
        raise ValueError(f"bn_bwd_stats needs rows%{rb}==0, rows%{ms}==0 "
                         f"(dtypes {dy2d.dtype}/{xhat2d.dtype}), "
                         f"C%{cb}==0 and C%128==0, got {dy2d.shape}")
    grid = (c // cb, rows // rb)
    sdy, sdyx = pl.pallas_call(
        _bwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((rb, cb), lambda ci, ri: (ri, ci)),
            pl.BlockSpec((rb, cb), lambda ci, ri: (ri, ci)),
        ],
        out_specs=[
            pl.BlockSpec((_OUT_SUBLANES, cb), lambda ci, ri: (0, ci)),
            pl.BlockSpec((_OUT_SUBLANES, cb), lambda ci, ri: (0, ci)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((_OUT_SUBLANES, c), jnp.float32),
            jax.ShapeDtypeStruct((_OUT_SUBLANES, c), jnp.float32),
        ],
        scratch_shapes=[_vmem_scratch((2, cb))],
        interpret=_interpret(),
    )(dy2d, xhat2d)
    return sdy[0], sdyx[0]


def _tileable(rows: int, c: int, *dtypes) -> bool:
    # routing uses the RESOLVED row block, so a tuned decision (e.g. 256
    # for rows=768, which the 512 default cannot tile) widens the set of
    # shapes that take the single-read kernel instead of the jnp fallback
    ms = _min_sublane(*dtypes)
    return rows % _resolve_row_block(rows, c, *dtypes) == 0 \
        and rows % ms == 0 \
        and c % min(_C_BLOCK, c) == 0 and c % 128 == 0


def fused_bn_tileable(rows: int, c: int, *dtypes) -> bool:
    """Public view of the stats-kernel routing predicate — the
    eligibility metadata tpulint (bigdl_tpu.analysis) and callers check
    before assuming the single-read kernel engages."""
    return _tileable(rows, c, *dtypes)


def min_sublane(*dtypes) -> int:
    """Public view of Mosaic's per-dtype minimum sublane count (8/16/32
    for 4/2/1-byte dtypes) — shared with analysis.rules' tile checker."""
    return _min_sublane(*dtypes)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_bn_train(x, gamma, beta, eps: float):
    """Training-mode BN over the last axis with fused single-read stats.
    x: (..., C); returns (y, mean, var) — mean/var are the BATCH stats the
    caller folds into its running estimates (the reference's EMA rule,
    BatchNormalization.scala updateOutput)."""
    y, mean, var, _ = _fused_fwd(x, gamma, beta, eps)
    return y, mean, var


def _fused_fwd(x, gamma, beta, eps):
    c = x.shape[-1]
    rows = x.size // c
    x2 = x.reshape(rows, c)
    if _tileable(rows, c, x.dtype):
        s, sq = bn_stats(x2)
    else:  # jnp fallback, same math
        xf = x2.astype(jnp.float32)
        s, sq = jnp.sum(xf, 0), jnp.sum(xf * xf, 0)
    mean = s / rows
    var = jnp.maximum(sq / rows - mean * mean, 0.0)
    inv = jax.lax.rsqrt(var + eps)
    scale = inv * gamma
    shift = beta - mean * scale
    y = (x.astype(jnp.float32) * scale + shift).astype(x.dtype)
    return y, mean, var, (x, mean, inv, gamma)


def _fused_vjp_fwd(x, gamma, beta, eps):
    y, mean, var, res = _fused_fwd(x, gamma, beta, eps)
    return (y, mean, var), res


def _fused_vjp_bwd(eps, res, cts):
    dy, d_mean, d_var = cts
    del d_mean, d_var  # running-stat EMA carries no gradient
    x, mean, inv, gamma = res
    c = x.shape[-1]
    rows = x.size // c
    dy2 = dy.reshape(rows, c)
    xhat2 = ((x.reshape(rows, c).astype(jnp.float32) - mean) * inv)
    if _tileable(rows, c, dy.dtype):   # xhat2 is f32; dy may be bf16
        # xhat stays f32 into the kernel (it upcasts per block anyway) so
        # dgamma precision matches the jnp fallback under mixed precision
        sdy, sdyx = bn_bwd_stats(dy2, xhat2)
    else:
        dyf = dy2.astype(jnp.float32)
        sdy, sdyx = jnp.sum(dyf, 0), jnp.sum(dyf * xhat2, 0)
    m_dy = sdy / rows
    m_dyx = sdyx / rows
    # the classic BN backward (batch stats differentiated through)
    dx = ((dy.reshape(rows, c).astype(jnp.float32)
           - m_dy - xhat2 * m_dyx) * (gamma * inv)).astype(x.dtype)
    dgamma = sdyx
    dbeta = sdy
    return dx.reshape(x.shape), dgamma, dbeta


fused_bn_train.defvjp(_fused_vjp_fwd, _fused_vjp_bwd)


# ---------------------------------------------------------------------------
# Fused BN block (ISSUE 2 tentpole): stats+apply(+ReLU) forward and
# reductions+dx backward, each a SINGLE kernel with a two-phase row sweep.
#
# Grid is (c_blocks, 2, row_blocks) — row dim innermost, phase in the
# middle — so for each channel block the serial order is: phase 0 sweeps
# every row block accumulating the per-channel reductions in f32 VMEM
# scratch, then phase 1 re-sweeps the rows doing the elementwise work with
# the finalized scalars still resident in scratch. The elementwise output's
# index map collapses every phase-0 step onto block (0, ci) (``ri * ph``),
# so Mosaic's revisit coalescing never flushes a garbage block: the first
# real write of (0, ci) happens at phase 1, row 0, before any transition
# away from that block index.
# ---------------------------------------------------------------------------


def _resolve_fba_row_block(rows: int, c: int, relu: bool, *dtypes) -> int:
    """Row-block height for the fused-block kernels: the autotuner's
    decision for this (rows, C, dtype, relu) under the ``bn_fba`` key when
    one exists, else the shipped default clamped to the array."""
    from bigdl_tpu import tuning
    if tuning.get_mode() != "off":
        tuned = tuning.fba_row_block(rows, c, dtypes[0], relu)
        if tuned:
            return min(tuned, rows)
    return min(_ROW_BLOCK, rows)


def _fba_check(name, rows, c, rb, *dtypes):
    cb = min(_C_BLOCK, c)
    ms = _min_sublane(*dtypes)
    if rows % rb or c % cb or rows % ms or c % 128:
        raise ValueError(f"{name} needs rows%{rb}==0, rows%{ms}==0 "
                         f"(dtypes {'/'.join(str(d) for d in dtypes)}), "
                         f"C%{cb}==0 and C%128==0, got ({rows}, {c})")
    return cb


def _pack_rows(*vecs) -> jax.Array:
    """Stack per-channel f32 vectors into a full (8, C) min-tile operand —
    tiny HBM traffic, and the block never relies on sub-minimum sublanes."""
    c = vecs[0].shape[-1]
    out = jnp.zeros((_OUT_SUBLANES, c), jnp.float32)
    for i, v in enumerate(vecs):
        out = out.at[i].set(v.astype(jnp.float32))
    return out


def _fba_fwd_kernel(x_ref, gb_ref, y_ref, mean_ref, var_ref, acc_ref, *,
                    rows: float, eps: float, relu: bool):
    ph = pl.program_id(1)
    r = pl.program_id(2)

    @pl.when(jnp.logical_and(ph == 0, r == 0))
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(ph == 0)
    def _accum():
        x = x_ref[...].astype(jnp.float32)
        acc_ref[0, :] += jnp.sum(x, axis=0)
        acc_ref[1, :] += jnp.sum(x * x, axis=0)

    @pl.when(jnp.logical_and(ph == 0, r == pl.num_programs(2) - 1))
    def _finalize():
        mean = acc_ref[0:1, :] / rows
        var = jnp.maximum(acc_ref[1:2, :] / rows - mean * mean, 0.0)
        inv = jax.lax.rsqrt(var + eps)
        scale = inv * gb_ref[0:1, :]
        mean_ref[...] = jnp.broadcast_to(mean, mean_ref.shape)
        var_ref[...] = jnp.broadcast_to(var, var_ref.shape)
        # stats are folded into the (scale, shift) the apply phase needs;
        # rows 0/1 are dead once mean/var left the kernel
        acc_ref[2:3, :] = scale
        acc_ref[3:4, :] = gb_ref[1:2, :] - mean * scale

    @pl.when(ph == 1)
    def _apply():
        y = x_ref[...].astype(jnp.float32) * acc_ref[2:3, :] \
            + acc_ref[3:4, :]
        if relu:
            y = jnp.maximum(y, 0.0)
        y_ref[...] = y.astype(y_ref.dtype)


def bn_fwd_apply(x2d: jax.Array, gamma: jax.Array, beta: jax.Array,
                 eps: float, relu: bool = False,
                 row_block: "int | None" = None):
    """Training-mode BN forward over a (rows, C) array in ONE kernel:
    per-channel stats (phase 0) then ``(x−μ)·inv·γ+β`` (+ ReLU) applied
    in phase 1 with the scalars still in VMEM. Returns ``(y, mean, var)``
    with mean/var f32. Same tiling contract as :func:`bn_stats`;
    ``row_block=None`` resolves through the autotuner (``bn_fba`` key)."""
    rows, c = x2d.shape
    rb = row_block or _resolve_fba_row_block(rows, c, relu, x2d.dtype)
    cb = _fba_check("bn_fwd_apply", rows, c, rb, x2d.dtype)
    grid = (c // cb, 2, rows // rb)
    y, mean, var = pl.pallas_call(
        functools.partial(_fba_fwd_kernel, rows=float(rows),
                          eps=float(eps), relu=bool(relu)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((rb, cb), lambda ci, ph, ri: (ri, ci)),
            pl.BlockSpec((_OUT_SUBLANES, cb), lambda ci, ph, ri: (0, ci)),
        ],
        out_specs=[
            pl.BlockSpec((rb, cb), lambda ci, ph, ri: (ri * ph, ci)),
            pl.BlockSpec((_OUT_SUBLANES, cb), lambda ci, ph, ri: (0, ci)),
            pl.BlockSpec((_OUT_SUBLANES, cb), lambda ci, ph, ri: (0, ci)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, c), x2d.dtype),
            jax.ShapeDtypeStruct((_OUT_SUBLANES, c), jnp.float32),
            jax.ShapeDtypeStruct((_OUT_SUBLANES, c), jnp.float32),
        ],
        scratch_shapes=[_vmem_scratch((4, cb))],
        interpret=_interpret(),
    )(x2d, _pack_rows(gamma, beta))
    return y, mean[0], var[0]


def _fba_bwd_kernel(dy_ref, x_ref, pp_ref, dx_ref, sdy_ref, sdyx_ref,
                    acc_ref, *, rows: float, relu: bool):
    ph = pl.program_id(1)
    r = pl.program_id(2)
    mean = pp_ref[0:1, :]
    inv = pp_ref[1:2, :]
    gamma = pp_ref[2:3, :]
    dy = dy_ref[...].astype(jnp.float32)
    xh = (x_ref[...].astype(jnp.float32) - mean) * inv
    if relu:
        # the ReLU mask is recomputed from x and the per-channel scalars
        # (y = x̂·γ+β > 0) — no mask/activation tensor is saved or re-read
        dy = jnp.where(xh * gamma + pp_ref[3:4, :] > 0.0, dy, 0.0)

    @pl.when(jnp.logical_and(ph == 0, r == 0))
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(ph == 0)
    def _accum():
        acc_ref[0, :] += jnp.sum(dy, axis=0)
        acc_ref[1, :] += jnp.sum(dy * xh, axis=0)

    @pl.when(jnp.logical_and(ph == 0, r == pl.num_programs(2) - 1))
    def _finalize():
        sdy_ref[...] = jnp.broadcast_to(acc_ref[0:1, :], sdy_ref.shape)
        sdyx_ref[...] = jnp.broadcast_to(acc_ref[1:2, :], sdyx_ref.shape)
        acc_ref[2:3, :] = acc_ref[0:1, :] / rows
        acc_ref[3:4, :] = acc_ref[1:2, :] / rows

    @pl.when(ph == 1)
    def _dx():
        dx = (dy - acc_ref[2:3, :] - xh * acc_ref[3:4, :]) * (gamma * inv)
        dx_ref[...] = dx.astype(dx_ref.dtype)


def bn_bwd_fused(dy2d: jax.Array, x2d: jax.Array, mean: jax.Array,
                 inv: jax.Array, gamma: jax.Array, beta: jax.Array,
                 relu: bool = False, row_block: "int | None" = None):
    """The whole BN(+ReLU) backward in ONE kernel: phase 0 accumulates
    (Σdy, Σ(dy·x̂)) with the ReLU mask folded in, phase 1 emits the classic
    dx expression with the finalized means still in VMEM. Returns
    ``(dx, sum_dy, sum_dy_xhat)`` — the sums are dbeta/dgamma."""
    rows, c = dy2d.shape
    rb = row_block or _resolve_fba_row_block(rows, c, relu,
                                             dy2d.dtype, x2d.dtype)
    cb = _fba_check("bn_bwd_fused", rows, c, rb, dy2d.dtype, x2d.dtype)
    grid = (c // cb, 2, rows // rb)
    dx, sdy, sdyx = pl.pallas_call(
        functools.partial(_fba_bwd_kernel, rows=float(rows),
                          relu=bool(relu)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((rb, cb), lambda ci, ph, ri: (ri, ci)),
            pl.BlockSpec((rb, cb), lambda ci, ph, ri: (ri, ci)),
            pl.BlockSpec((_OUT_SUBLANES, cb), lambda ci, ph, ri: (0, ci)),
        ],
        out_specs=[
            pl.BlockSpec((rb, cb), lambda ci, ph, ri: (ri * ph, ci)),
            pl.BlockSpec((_OUT_SUBLANES, cb), lambda ci, ph, ri: (0, ci)),
            pl.BlockSpec((_OUT_SUBLANES, cb), lambda ci, ph, ri: (0, ci)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, c), x2d.dtype),
            jax.ShapeDtypeStruct((_OUT_SUBLANES, c), jnp.float32),
            jax.ShapeDtypeStruct((_OUT_SUBLANES, c), jnp.float32),
        ],
        scratch_shapes=[_vmem_scratch((4, cb))],
        interpret=_interpret(),
    )(dy2d, x2d, _pack_rows(mean, inv, gamma, beta))
    return dx, sdy[0], sdyx[0]


def _fba_tileable(rows: int, c: int, relu: bool, *dtypes) -> bool:
    ms = _min_sublane(*dtypes)
    return rows % _resolve_fba_row_block(rows, c, relu, *dtypes) == 0 \
        and rows % ms == 0 \
        and c % min(_C_BLOCK, c) == 0 and c % 128 == 0


def fba_tileable(rows: int, c: int, relu: bool, *dtypes) -> bool:
    """Public view of the fused-block routing predicate (see
    :func:`fused_bn_tileable`) — keyed additionally by ``relu`` because
    the autotuned row block is."""
    return _fba_tileable(rows, c, relu, *dtypes)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def fused_bn_apply_train(x, gamma, beta, eps: float, relu: bool = False,
                         row_block: Optional[int] = None):
    """Training-mode BN(+ReLU) over the last axis with BOTH directions
    fully fused (stats+apply forward, reductions+dx backward — one Pallas
    launch each). x: (..., C); returns (y, mean, var) like
    :func:`fused_bn_train`; mean/var are the batch stats the caller folds
    into its running estimates. Untileable shapes fall back to the same
    math in jnp. ``row_block`` pins the kernels' row-block height
    (autotune measurement); ``None`` resolves through the cache."""
    y, mean, var, _ = _fba_fwd(x, gamma, beta, eps, relu, row_block)
    return y, mean, var


def _fba_fwd(x, gamma, beta, eps, relu, row_block):
    c = x.shape[-1]
    rows = x.size // c
    x2 = x.reshape(rows, c)
    if row_block or _fba_tileable(rows, c, relu, x.dtype):
        y2, mean, var = bn_fwd_apply(x2, gamma, beta, eps, relu, row_block)
        y = y2.reshape(x.shape)
    else:  # jnp fallback, same math
        xf = x2.astype(jnp.float32)
        mean = jnp.mean(xf, 0)
        var = jnp.maximum(jnp.mean(xf * xf, 0) - mean * mean, 0.0)
        scale = jax.lax.rsqrt(var + eps) * gamma
        y = xf * scale + (beta - mean * scale)
        if relu:
            y = jnp.maximum(y, 0.0)
        y = y.astype(x.dtype).reshape(x.shape)
    return y, mean, var, (x, mean, var, gamma, beta)


def _fba_vjp_fwd(x, gamma, beta, eps, relu, row_block):
    y, mean, var, res = _fba_fwd(x, gamma, beta, eps, relu, row_block)
    return (y, mean, var), res


def _fba_vjp_bwd(eps, relu, row_block, res, cts):
    dy, d_mean, d_var = cts
    del d_mean, d_var  # running-stat EMA carries no gradient
    x, mean, var, gamma, beta = res
    inv = jax.lax.rsqrt(var + eps)
    c = x.shape[-1]
    rows = x.size // c
    dy2 = dy.reshape(rows, c)
    if row_block or _fba_tileable(rows, c, relu, dy.dtype, x.dtype):
        dx2, sdy, sdyx = bn_bwd_fused(dy2, x.reshape(rows, c), mean, inv,
                                      gamma, beta, relu, row_block)
    else:
        xh = (x.reshape(rows, c).astype(jnp.float32) - mean) * inv
        dyf = dy2.astype(jnp.float32)
        if relu:
            dyf = jnp.where(xh * gamma + beta > 0.0, dyf, 0.0)
        sdy, sdyx = jnp.sum(dyf, 0), jnp.sum(dyf * xh, 0)
        dx2 = ((dyf - sdy / rows - xh * (sdyx / rows))
               * (gamma * inv)).astype(x.dtype)
    return dx2.reshape(x.shape), sdyx, sdy


fused_bn_apply_train.defvjp(_fba_vjp_fwd, _fba_vjp_bwd)
