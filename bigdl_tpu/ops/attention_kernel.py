"""Flash attention for TPU.

Currently the XLA-path implementation (blockwise-fused by the compiler); the
hand-tiled Pallas kernel lands behind the same signature so callers —
``nn.MultiHeadAttention(attn_impl="flash")`` — never change.
"""

from __future__ import annotations

from typing import Optional

import jax

from bigdl_tpu.nn import attention as _dense


def flash_attention(q, k, v, *, causal: bool = False,
                    mask: Optional[jax.Array] = None):
    """(b, h, s, d) attention; falls back to the dense XLA path until the
    Pallas kernel is wired in."""
    return _dense.dot_product_attention(q, k, v, causal=causal, mask=mask)
