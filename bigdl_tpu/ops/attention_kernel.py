"""Flash attention for TPU — hand-tiled Pallas forward kernel.

The reference has no attention at all (SURVEY.md §2.7); this kernel exists
for the long-context path the new framework treats as first-class. Design
per the TPU Pallas playbook:

* grid = (batch*heads, q_blocks); each program owns one (BLOCK_Q, d) query
  tile in VMEM and streams K/V tiles with an online (one-pass) softmax —
  O(s) memory instead of materializing the (s, s) score matrix in HBM.
* scores accumulate in fp32 (``preferred_element_type``) on the MXU while
  inputs may be bf16 — the same numerics as the XLA dense path.
* On non-TPU backends the kernel runs in interpret mode (tests), so one
  code path serves CPU tests and TPU execution.

Backward: hand-tiled Pallas dq and dk/dv kernels (the standard flash
backward split). The forward kernel emits the per-query logsumexp; the
backward preprocesses ``delta = rowsum(do * o)`` in one cheap jnp pass,
then dq runs on the forward's grid (one q tile per program, streaming K/V
blocks) while dk/dv runs transposed (one k tile per program, streaming
Q/dO blocks), both with causal block skipping. Probabilities are
recomputed from q,k,lse — O(seq) memory end to end. Non-tileable shapes
fall back to :func:`blockwise_attention` (remat-scan) under one
``jax.custom_vjp``.

``nn.MultiHeadAttention(attn_impl="flash")`` routes here.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn import attention as _dense

__all__ = ["flash_attention", "blockwise_attention",
           "online_softmax_update", "flash_block_plan",
           "kv_page_plan", "serving_prefill_buckets"]

_NEG_INF = -1e30

# lse/delta per-query vectors carry a replicated trailing lane dim inside
# the Pallas calls so their blocks satisfy the TPU tiling rules. 8 is legal
# only via the block-dim-equals-array-dim escape (the lane rule is
# otherwise %128 — see _fwd_kernel._emit); it is the cheapest layout that
# escape admits.
_LSE_LANES = 8


def online_softmax_update(q, kb, vb, m, l, acc, scale, valid=None):
    """One block step of the streaming softmax shared by
    :func:`blockwise_attention` and ring attention
    (bigdl_tpu.parallel.sequence): fold K/V block (kb, vb) into the
    running (max m, normalizer l, output accumulator acc) for queries q.
    ``valid`` is an optional (..., s_q, bk) bool mask. Stats (m, l, acc)
    are fp32; q/kb/vb keep their input dtype so bf16 operands take the
    fast MXU path, with fp32 accumulation via ``preferred_element_type``.
    """
    logits = jnp.einsum("...qd,...kd->...qk", q, kb,
                        preferred_element_type=jnp.float32) * scale
    if valid is not None:
        logits = jnp.where(valid, logits, _NEG_INF)
    blk_max = jnp.max(logits, axis=-1, keepdims=True)
    new_m = jnp.maximum(m, blk_max)
    p = jnp.exp(logits - new_m)
    if valid is not None:
        p = jnp.where(valid, p, 0.0)
    corr = jnp.exp(m - new_m)
    l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    # p down to V's dtype (flash-attention convention): both P@V operands
    # bf16 on the MXU, fp32 accumulate; fp32 inputs are untouched
    acc = acc * corr + jnp.einsum("...qk,...kd->...qd", p.astype(vb.dtype),
                                  vb, preferred_element_type=jnp.float32)
    return new_m, l, acc


def _as_key_padding(mask, b, s_k):
    """Normalize a mask to (b, s_k) bool when it is a key-padding mask
    ((b, s_k) or (b|1, 1, 1, s_k)); None when it is something richer."""
    if mask is None:
        return None
    if mask.ndim == 2 and mask.shape == (b, s_k):
        return mask
    if (mask.ndim == 4 and mask.shape[-1] == s_k
            and mask.shape[1] == 1 and mask.shape[2] == 1
            and mask.shape[0] in (1, b)):
        m = mask[:, 0, 0, :]
        return jnp.broadcast_to(m, (b, s_k))
    return None


def blockwise_attention(q, k, v, *, causal: bool = False,
                        mask: Optional[jax.Array] = None,
                        segments: Optional[jax.Array] = None,
                        block_k: int = 128):
    """O(seq) memory attention in pure JAX: ``lax.scan`` over K/V blocks
    with an online softmax, the scan body wrapped in ``jax.checkpoint`` so
    autodiff recomputes each block instead of saving the (s_q, block_k)
    probability tiles — the remat-scan formulation of flash attention.
    Differentiable end-to-end; serves as the flash kernel's backward path
    and as a standalone ``attn_impl``. q,k,v: (b, h, s, d).

    Key-padding masks ((b, s_k) or (b|1,1,1,s_k) bool, True=attend) and
    packed-document ``segments`` ((b, s) int ids, self-attention shapes)
    tile along the scan and stay on this path; richer (s_q, s_k) masks
    fall back to dense.
    """
    s_k = k.shape[-2]
    bk = min(block_k, s_k)
    if segments is not None and mask is not None:
        raise ValueError("segments and mask are mutually exclusive")
    if segments is not None and q.shape[-2] != s_k:
        raise ValueError("segments requires self-attention shapes "
                         f"(s_q={q.shape[-2]} != s_k={s_k})")
    kv_mask = _as_key_padding(mask, q.shape[0], s_k)
    if (mask is not None and kv_mask is None) or s_k % bk:
        # arbitrary masks don't tile; ragged tails aren't worth the
        # complexity — correctness over memory for those cases
        if segments is not None:
            mask = _dense.make_segment_mask(segments)
        return _dense.dot_product_attention(q, k, v, causal=causal,
                                            mask=mask)
    n_blk = s_k // bk
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s_q = q.shape[-2]
    q_offset = s_k - s_q  # bottom-right aligned causal
    q_pos = q_offset + jnp.arange(s_q)

    kb = k.reshape(k.shape[:-2] + (n_blk, bk, k.shape[-1]))
    vb = v.reshape(v.shape[:-2] + (n_blk, bk, v.shape[-1]))
    # scan carries move the block axis to the front
    kb = jnp.moveaxis(kb, -3, 0)
    vb = jnp.moveaxis(vb, -3, 0)
    scan_in = (kb, vb)
    if kv_mask is not None:
        # (b, n_blk, bk) -> (n_blk, b, 1, 1, bk): broadcasts against the
        # (b, h, s_q, bk) logits inside the block update
        mb = jnp.moveaxis(kv_mask.reshape(kv_mask.shape[0], n_blk, bk),
                          1, 0)[:, :, None, None, :]
        scan_in = (kb, vb, mb)
    elif segments is not None:
        # per-block k-segment slices scan alongside K/V; the (b, 1, s_q,
        # bk) equality tile is built inside the (remat'd) body, so only
        # O(s) ids are resident — same packing semantics as the Pallas
        # kernel (segment-0 padding attends itself, keeping rows live)
        sb = jnp.moveaxis(
            segments.astype(jnp.int32).reshape(
                segments.shape[0], n_blk, bk), 1, 0)
        scan_in = (kb, vb, sb)

    seg_q = None if segments is None else segments.astype(jnp.int32)

    @jax.checkpoint
    def body(carry, blk):
        m, l, acc, j = carry
        mj = None
        if kv_mask is not None:
            kj, vj, mj = blk
        elif seg_q is not None:
            kj, vj, sj = blk
            # (b, 1, s_q, 1) == (b, 1, 1, bk) -> (b, 1, s_q, bk)
            mj = (seg_q[:, None, :, None] == sj[:, None, None, :])
        else:
            kj, vj = blk
        valid = None
        if causal:
            k_pos = j * bk + jnp.arange(bk)
            valid = q_pos[:, None] >= k_pos[None, :]
        if mj is not None:
            valid = mj if valid is None else (valid & mj)
        m, l, acc = online_softmax_update(q, kj, vj, m, l, acc, scale,
                                          valid)
        return (m, l, acc, j + 1), None

    m0 = jnp.full(q.shape[:-1] + (1,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros(q.shape[:-1] + (1,), jnp.float32)
    a0 = jnp.zeros(q.shape, jnp.float32)
    (_, l, acc, _), _ = jax.lax.scan(body, (m0, l0, a0, 0), scan_in)
    out = (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)
    if segments is not None:
        # id-0 padding rows stayed live in the scan (finite backward);
        # zero them so this path agrees with the dense fallback above
        out = jnp.where((segments != 0)[:, None, :, None], out, 0)
    return out


def _block_valid(causal, q_ids, k_ids, bq, j, kk, block_q, block_k,
                 q_offset):
    """(bq, bk) bool validity tile combining the causal triangle and the
    segment equality mask; None when nothing is masked. Padded rows
    (segment 0) still attend segment-0 keys so no row is fully masked —
    the dense make_segment_mask kills them instead; those outputs are
    loss-masked garbage either way, but a live softmax row keeps the
    backward finite."""
    valid = None
    if causal:
        q_pos = (q_offset + j * block_q
                 + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0))
        k_pos = kk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        valid = q_pos >= k_pos
    if q_ids is not None:
        seg = q_ids == k_ids  # (bq, 1) == (1, bk) -> (bq, bk)
        valid = seg if valid is None else (valid & seg)
    return valid


def _fwd_kernel(q_ref, k_ref, v_ref, *rest, block_k: int, scale: float,
                causal: bool, block_q: int, q_offset: int, has_seg: bool):
    """3-D grid (bh, q_blocks, k_blocks): K/V stream block-by-block from
    HBM (Pallas double-buffers across the innermost grid dim), online
    softmax state lives in VMEM scratch — O(block) VMEM regardless of
    sequence length, so 128k-token sequences fit. With ``has_seg`` two
    extra refs carry packed-document segment ids (q ids lane-replicated,
    kv ids sublane-replicated — the official TPU kernel's layout)."""
    from jax.experimental import pallas as pl

    if has_seg:
        qs_ref, ks_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
        qs_ref = ks_ref = None

    j = pl.program_id(1)
    kk = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(kk == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    bq = q_ref.shape[1]
    # bottom-right aligned causal (matches dot_product_attention): query i
    # sees keys <= (s_k - s_q) + i. Fully-future K blocks are skipped
    # (grid step still runs, matmuls don't — half the causal FLOPs).
    q_end = q_offset + (j + 1) * block_q - 1
    live = True if not causal else kk * block_k <= q_end

    @pl.when(live)
    def _step():
        q = q_ref[0]  # (BQ, d) — input dtype on the MXU, fp32 accumulate
        kblk = k_ref[0]
        vblk = v_ref[0]
        m, l = m_scr[...], l_scr[...]
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (BQ, BK)
        valid = _block_valid(
            causal,
            None if qs_ref is None else qs_ref[0][:, :1],
            None if ks_ref is None else ks_ref[0][:1, :],
            bq, j, kk, block_q, block_k, q_offset)
        if valid is not None:
            s = jnp.where(valid, s, _NEG_INF)
        blk_max = jnp.max(s, axis=-1, keepdims=True)
        new_m = jnp.maximum(m, blk_max)
        p = jnp.exp(s - new_m)
        if valid is not None:
            p = jnp.where(valid, p, 0.0)
        corr = jnp.exp(m - new_m)
        m_scr[...] = new_m
        l_scr[...] = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        # p cast to V's dtype (flash convention): P@V is a bf16 MXU
        # matmul with fp32 accumulation
        acc_scr[...] = acc_scr[...] * corr + jax.lax.dot_general(
            p.astype(vblk.dtype), vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kk == n_k - 1)
    def _emit():
        l_safe = jnp.maximum(l_scr[...], 1e-30)
        o_ref[0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)
        # per-query logsumexp, saved for the backward kernels' recompute.
        # Replicated across a trailing 8-lane dim: Mosaic requires the last
        # two block dims to be (8k, 128k) or equal to the array dims, so a
        # per-(bh,q) 2-D layout with block (1, bq) cannot lower — same
        # reason jax's own TPU flash kernel stores lse as (..., seq, 128);
        # 8 lanes is the cheapest legal layout (last block dim == array
        # dim escape).
        lse_ref[0] = jnp.broadcast_to(
            m_scr[...] + jnp.log(l_safe), lse_ref.shape[1:])


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
               block_k: int, scale: float, causal: bool,
               block_q: int, q_offset: int, has_seg: bool):
    from jax.experimental import pallas as pl

    if has_seg:
        qs_ref, ks_ref, dq_ref, dq_scr = rest
    else:
        dq_ref, dq_scr = rest
        qs_ref = ks_ref = None

    j = pl.program_id(1)
    kk = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(kk == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    bq = q_ref.shape[1]
    q_end = q_offset + (j + 1) * block_q - 1
    live = True if not causal else kk * block_k <= q_end

    @pl.when(live)
    def _step():
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]      # (BQ, 1) f32 (lanes replicated)
        delta = delta_ref[0][:, :1]  # (BQ, 1) f32
        kblk = k_ref[0]
        vblk = v_ref[0]
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse)  # rows already normalized via lse
        valid = _block_valid(
            causal,
            None if qs_ref is None else qs_ref[0][:, :1],
            None if ks_ref is None else ks_ref[0][:1, :],
            bq, j, kk, block_q, block_k, q_offset)
        if valid is not None:
            p = jnp.where(valid, p, 0.0)
        dp = jax.lax.dot_general(   # dO @ V^T  (BQ, BK)
            do, vblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_scr[...] += jax.lax.dot_general(  # dS @ K  (BQ, d)
            ds.astype(kblk.dtype), kblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kk == n_k - 1)
    def _emit():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref, *rest,
                block_q: int, scale: float, causal: bool, block_k: int,
                q_offset: int, has_seg: bool):
    from jax.experimental import pallas as pl

    if has_seg:
        qs_ref, ks_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest
    else:
        dk_ref, dv_ref, dk_scr, dv_scr = rest
        qs_ref = ks_ref = None

    j = pl.program_id(1)   # k-block index
    qq = pl.program_id(2)  # q-block index (innermost: Q/dO stream)
    n_q = pl.num_programs(2)

    @pl.when(qq == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    bk = k_ref.shape[1]
    # q block is live iff its last query can see this k block
    q_last = q_offset + (qq + 1) * block_q - 1
    live = True if not causal else q_last >= j * block_k

    @pl.when(live)
    def _step():
        k = k_ref[0]  # (BK, d)
        v = v_ref[0]
        qblk = q_ref[0]
        doblk = do_ref[0]
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(  # Q @ K^T  (BQ, BK)
            qblk, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse)
        # note the grid transpose: this program's q-block index is qq and
        # its k-block index is j, so the roles swap vs _block_valid's
        # forward-grid signature
        valid = _block_valid(
            causal,
            None if qs_ref is None else qs_ref[0][:, :1],
            None if ks_ref is None else ks_ref[0][:1, :],
            block_q, qq, j, block_q, block_k, q_offset)
        if valid is not None:
            p = jnp.where(valid, p, 0.0)
        dv_scr[...] += jax.lax.dot_general(  # P^T @ dO  (BK, d)
            p.astype(doblk.dtype), doblk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(  # dO @ V^T  (BQ, BK)
            doblk, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(qblk.dtype)
        dk_scr[...] += jax.lax.dot_general(  # dS^T @ Q  (BK, d)
            ds, qblk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qq == n_q - 1)
    def _emit():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _live_block_pairs(sq, sk, bq, bk, causal, q_offset) -> int:
    """Exact number of (q-block, k-block) grid pairs whose matmuls run per
    (b, h) — the Python-side mirror of the kernels' ``live`` predicate
    (fully-future K blocks are skipped under causal). Segment masking is
    data-dependent and not reflected here."""
    n_q, n_k = sq // bq, sk // bk
    if not causal:
        return n_q * n_k
    total = 0
    for j in range(n_q):
        q_end = q_offset + (j + 1) * bq - 1
        total += min(n_k, max(0, q_end // bk + 1))
    return total


def _attn_cost(bh, n_pairs, bq, bk, d, dtype_bytes, units):
    """Author-declared ALGORITHMIC cost for one attention Pallas kernel
    (consumed by ``utils/flops.py``, which prefers it over grid x
    kernel-body counting): ``units`` matmuls of 2*bq*bk*d FLOPs per live
    block pair — the forward's qk+pv, the dq kernel's dP+dQ, the dkv
    kernel's dV+dK. The backward kernels' score RECOMPUTATION is
    deliberately excluded, per the module convention flops.py states for
    remat (algorithmic FLOPs, not executed): a dense-autodiff backward
    reuses stored P and performs exactly these four units, so MFU
    numerators stay comparable across attention implementations. Block
    skipping IS reflected (n_pairs is causal-aware), so causal MFU is no
    longer flattered by counting masked work."""
    from jax.experimental import pallas as pl

    return pl.CostEstimate(
        flops=int(2 * units * bh * n_pairs * bq * bk * d),
        transcendentals=int(bh * n_pairs * bq * bk),
        bytes_accessed=int(dtype_bytes * bh * n_pairs * (bq + 2 * bk) * d))


def _pad_to(x, mult, axis):
    pad = (-x.shape[axis]) % mult
    if pad == 0:
        return x, 0
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths), pad


def _interpret() -> bool:
    # compiled Mosaic lowering on TPU; interpret mode elsewhere (tests)
    return jax.default_backend() != "tpu"


def pltpu_scratch(shape):
    """fp32 VMEM scratch (online-softmax state carried across the
    innermost grid dimension)."""
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, jnp.float32)


def _tileable(s_q, s_k, block_k) -> bool:
    # ragged key length would need a validity mask woven into the online
    # softmax; the remat-scan path handles it (pad_to on K alone would
    # let padded keys win the softmax)
    bk = min(block_k, max(8, s_k))
    return s_k % bk == 0


_DEFAULT_BLOCK = 512  # per-program tile default (best at the benchmarked
# 1k/16k shapes, PERF.md §8.2); mid sequences clamp down, the autotuner
# overrides per shape


def _clamp_block(block: int, s: int) -> int:
    """Largest standard tiling <= ``block`` that divides ``s`` (falling
    through 256/128), else min(block, s) — applied to BOTH block dims so
    a mid sequence like s=768 runs 256-blocks instead of padding 768→1024
    and burning ~33% extra q-block work (ADVICE r5 #2; block_k already
    clamped this way since round 5)."""
    b = min(block, max(8, s))
    if s % b:
        for cand in (256, 128):
            if cand < b and s % cand == 0:
                return cand
    return b


def _resolve_blocks(s_q: int, s_k: int, d: int, causal: bool, dtype,
                    block_q: "int | None", block_k: "int | None"
                    ) -> "tuple[int, int]":
    """Static block-size resolution: explicit arguments win; otherwise
    consult the autotuner (bigdl_tpu.tuning, a no-op in off mode) and
    fall back to the 512 defaults. Both dims are then clamped to a
    standard tiling that divides their sequence."""
    if block_q is None or block_k is None:
        tuned = None
        from bigdl_tpu import tuning
        if tuning.get_mode() != "off":
            tuned = tuning.flash_blocks(s_q, s_k, d, causal, dtype)
        if block_q is None:
            block_q = tuned[0] if tuned else _DEFAULT_BLOCK
        if block_k is None:
            block_k = tuned[1] if tuned else _DEFAULT_BLOCK
    return _clamp_block(block_q, s_q), _clamp_block(block_k, s_k)


def flash_block_plan(s_q: int, s_k: int, d: int, causal: bool,
                     dtype) -> dict:
    """Static view of what :func:`flash_attention` would do at this
    shape — the block metadata tpulint (bigdl_tpu.analysis) evaluates
    without tracing a kernel:

    * ``block_q``/``block_k`` — the resolved (autotuner-consulted,
      clamped) tile sizes;
    * ``kernel_ok`` — False when the ragged key length knocks the call
      off the Pallas kernel onto the remat-scan fallback;
    * ``q_pad``/``k_pad`` — rows a padded final block would add (the
      pre-round-6 s=768 failure mode: nonzero means wasted grid work);
    * ``clamped`` — blocks sit below the 512 default because the seq
      admits no larger divisor (fine, but worth a note).
    """
    bq, bk = _resolve_blocks(int(s_q), int(s_k), int(d), bool(causal),
                             dtype, None, None)
    return {
        "block_q": bq, "block_k": bk,
        "kernel_ok": _tileable(int(s_q), int(s_k), bk),
        "q_pad": (-int(s_q)) % bq,
        "k_pad": (-int(s_k)) % bk,
        "clamped": (bq < _DEFAULT_BLOCK and bq < s_q)
                   or (bk < _DEFAULT_BLOCK and bk < s_k),
    }


def kv_page_plan(page_tokens: int, max_len: int, head_dim: int,
                 dtype, causal: bool = True) -> dict:
    """Static fit of a paged-KV layout (serving/kv_pages) against this
    shape's flash block plan — the metadata the decode tpulint rule
    (bigdl_tpu.analysis.run_decode_rules) evaluates without tracing:

    * ``divides_max_len`` — False is a hard engine error (the gathered
      view must be exactly max_len);
    * ``sublane_ok`` — pages whose token dim is not a multiple of the
      dtype's minimum sublane count (8 for 4-byte, 16 for bf16, 32 for
      int8 — the Mosaic tile rule) break the minimum tile on every pool
      leaf: each page then pays a padded sublane, and gathers re-lay
      the data. 8-bit KV pools (ISSUE 17 kv8) therefore need 32-token
      pages at minimum;
    * ``sublane`` — the minimum applied, for the lint message;
    * ``block_aligned`` — the prefill flash kernel reads K in
      ``block_k`` tiles; when neither divides the other, a single K
      block straddles a page boundary in the gathered view and the
      scatter back to pools splits every tile (misfit finding);
    * ``block_k`` — the plan consulted, for the lint message.
    """
    plan = flash_block_plan(max_len, max_len, head_dim, causal, dtype)
    bk = int(plan["block_k"])
    pt = int(page_tokens)
    sub = {4: 8, 2: 16, 1: 32}.get(np.dtype(dtype).itemsize, 8)
    return {
        "page_tokens": pt,
        "block_k": bk,
        "divides_max_len": max_len % pt == 0,
        "sublane": sub,
        "sublane_ok": pt % sub == 0,
        "block_aligned": (pt % bk == 0) or (bk % pt == 0),
    }


def serving_prefill_buckets(max_len: int, head_dim: int,
                            causal: bool = True, dtype=jnp.float32,
                            min_bucket: int = 16) -> tuple:
    """Prompt-length buckets for the serving prefill: a power-of-two
    ladder from ``min_bucket`` up to (and always including) ``max_len``,
    filtered to lengths whose :func:`flash_block_plan` stays ON the
    Pallas kernel with zero padded rows — so a prefill at any bucket
    reuses the tuned block plan the training benchmarks measured, never
    the remat-scan fallback or a padded grid. Off-TPU (dense attention)
    the same ladder simply bounds the compile cache; the filter is a
    no-op there because power-of-two lengths clamp cleanly."""
    if max_len < 1:
        raise ValueError(f"max_len must be >= 1, got {max_len}")
    ladder = []
    b = max(1, int(min_bucket))
    while b < max_len:
        ladder.append(b)
        b *= 2
    ladder.append(int(max_len))
    out = []
    for s in sorted(set(ladder)):
        plan = flash_block_plan(s, s, head_dim, causal, dtype)
        if plan["kernel_ok"] and plan["q_pad"] == 0 and plan["k_pad"] == 0:
            out.append(s)
    # never return empty: the full max_len bucket always works densely
    return tuple(out) or (int(max_len),)


def _seg_arrays(segments, sq, sk, bq):
    """Segment ids in the kernels' tileable layouts: q ids (b, sq, 8)
    lane-replicated (padded rows get id 0), kv ids (b, 8, sk)
    sublane-replicated — mirroring the lse layout trick."""
    seg = segments.astype(jnp.int32)
    qs = seg
    if qs.shape[1] != sq:  # q padded to a block multiple
        qs = jnp.pad(qs, ((0, 0), (0, sq - qs.shape[1])))
    qs3 = jnp.broadcast_to(qs[..., None], qs.shape + (_LSE_LANES,))
    ks3 = jnp.broadcast_to(seg[:, None, :],
                           (seg.shape[0], _LSE_LANES, sk))
    return qs3, ks3


def _flash_fwd(q, k, v, causal: bool, block_q: int, block_k: int,
               segments=None):
    """Pallas forward; returns (out, lse) with lse in (b*h, padded_sq).
    The kernel emits lse lane-replicated (see _LSE_LANES); the replica dim
    is squeezed off here so the custom_vjp residual stores 4 B/query, not
    32 B — the backward re-broadcasts next to its delta broadcast."""
    from jax.experimental import pallas as pl

    b, h, s_q, d = q.shape
    s_k = k.shape[-2]
    scale = 1.0 / (d ** 0.5)

    qf = q.reshape(b * h, s_q, d)
    kf = k.reshape(b * h, s_k, d)
    vf = v.reshape(b * h, s_k, d)

    bq = min(block_q, max(8, s_q))
    bk = min(block_k, max(8, s_k))
    qf, pad_q = _pad_to(qf, bq, 1)
    sq, sk = qf.shape[1], kf.shape[1]

    kernel = functools.partial(_fwd_kernel, block_k=bk, scale=scale,
                               causal=causal, block_q=bq,
                               q_offset=s_k - s_q,
                               has_seg=segments is not None)
    in_specs = [
        pl.BlockSpec((1, bq, d), lambda i, j, kk: (i, j, 0)),
        pl.BlockSpec((1, bk, d), lambda i, j, kk: (i, kk, 0)),
        pl.BlockSpec((1, bk, d), lambda i, j, kk: (i, kk, 0)),
    ]
    args = [qf, kf, vf]
    if segments is not None:
        qs3, ks3 = _seg_arrays(segments, sq, sk, bq)
        # seg arrays are per-batch; grid dim 0 walks b*h -> divide out h
        in_specs += [
            pl.BlockSpec((1, bq, _LSE_LANES),
                         lambda i, j, kk: (i // h, j, 0)),
            pl.BlockSpec((1, _LSE_LANES, bk),
                         lambda i, j, kk: (i // h, 0, kk)),
        ]
        args += [qs3, ks3]
    n_pairs = _live_block_pairs(sq, sk, bq, bk, causal, s_k - s_q)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, sq // bq, sk // bk),
        cost_estimate=_attn_cost(b * h, n_pairs, bq, bk, d,
                                 q.dtype.itemsize, units=2),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((1, bq, _LSE_LANES), lambda i, j, kk: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, _LSE_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu_scratch((bq, 1)), pltpu_scratch((bq, 1)),
            pltpu_scratch((bq, d)),
        ],
        interpret=_interpret(),
    )(*args)
    o = out[:, :s_q] if pad_q else out
    return o.reshape(b, h, s_q, d), lse[..., 0]


def _flash_bwd(q, k, v, o, lse, g, causal: bool, block_q: int,
               block_k: int, segments=None):
    """Pallas dq + dk/dv kernels over the recomputed probabilities."""
    from jax.experimental import pallas as pl

    b, h, s_q, d = q.shape
    s_k = k.shape[-2]
    scale = 1.0 / (d ** 0.5)

    qf = q.reshape(b * h, s_q, d)
    kf = k.reshape(b * h, s_k, d)
    vf = v.reshape(b * h, s_k, d)
    dof = g.reshape(b * h, s_q, d)
    of = o.reshape(b * h, s_q, d)

    bq = min(block_q, max(8, s_q))
    bk = min(block_k, max(8, s_k))
    # delta_i = sum_d dO_i * O_i — one cheap fused pass in plain XLA;
    # replicated over _LSE_LANES to match lse's TPU-tileable layout
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32),
                    axis=-1)
    qf, pad_q = _pad_to(qf, bq, 1)
    dof, _ = _pad_to(dof, bq, 1)
    delta, _ = _pad_to(delta, bq, 1)
    delta = jnp.broadcast_to(delta[..., None],
                             delta.shape + (_LSE_LANES,))
    lse = jnp.broadcast_to(lse[..., None], lse.shape + (_LSE_LANES,))
    sq, sk = qf.shape[1], kf.shape[1]
    q_offset = s_k - s_q
    interpret = _interpret()
    has_seg = segments is not None
    if has_seg:
        qs3, ks3 = _seg_arrays(segments, sq, sk, bq)

    dq_specs = [
        pl.BlockSpec((1, bq, d), lambda i, j, kk: (i, j, 0)),
        pl.BlockSpec((1, bk, d), lambda i, j, kk: (i, kk, 0)),
        pl.BlockSpec((1, bk, d), lambda i, j, kk: (i, kk, 0)),
        pl.BlockSpec((1, bq, d), lambda i, j, kk: (i, j, 0)),
        pl.BlockSpec((1, bq, _LSE_LANES), lambda i, j, kk: (i, j, 0)),
        pl.BlockSpec((1, bq, _LSE_LANES), lambda i, j, kk: (i, j, 0)),
    ]
    dq_args = [qf, kf, vf, dof, lse, delta]
    if has_seg:
        dq_specs += [
            pl.BlockSpec((1, bq, _LSE_LANES),
                         lambda i, j, kk: (i // h, j, 0)),
            pl.BlockSpec((1, _LSE_LANES, bk),
                         lambda i, j, kk: (i // h, 0, kk)),
        ]
        dq_args += [qs3, ks3]
    n_pairs = _live_block_pairs(sq, sk, bq, bk, causal, q_offset)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, block_k=bk, scale=scale,
                          causal=causal, block_q=bq, q_offset=q_offset,
                          has_seg=has_seg),
        grid=(b * h, sq // bq, sk // bk),
        cost_estimate=_attn_cost(b * h, n_pairs, bq, bk, d,
                                 qf.dtype.itemsize, units=2),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, bq, d), lambda i, j, kk: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        scratch_shapes=[pltpu_scratch((bq, d))],
        interpret=interpret,
    )(*dq_args)

    dkv_specs = [
        pl.BlockSpec((1, bk, d), lambda i, j, qq: (i, j, 0)),
        pl.BlockSpec((1, bk, d), lambda i, j, qq: (i, j, 0)),
        pl.BlockSpec((1, bq, d), lambda i, j, qq: (i, qq, 0)),
        pl.BlockSpec((1, bq, d), lambda i, j, qq: (i, qq, 0)),
        pl.BlockSpec((1, bq, _LSE_LANES), lambda i, j, qq: (i, qq, 0)),
        pl.BlockSpec((1, bq, _LSE_LANES), lambda i, j, qq: (i, qq, 0)),
    ]
    dkv_args = [kf, vf, qf, dof, lse, delta]
    if has_seg:
        dkv_specs += [
            pl.BlockSpec((1, bq, _LSE_LANES),
                         lambda i, j, qq: (i // h, qq, 0)),
            pl.BlockSpec((1, _LSE_LANES, bk),
                         lambda i, j, qq: (i // h, 0, j)),
        ]
        dkv_args += [qs3, ks3]
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, block_q=bq, scale=scale,
                          causal=causal, block_k=bk, q_offset=q_offset,
                          has_seg=has_seg),
        grid=(b * h, sk // bk, sq // bq),
        cost_estimate=_attn_cost(b * h, n_pairs, bq, bk, d,
                                 kf.dtype.itemsize, units=2),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda i, j, qq: (i, j, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j, qq: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), v.dtype),
        ],
        scratch_shapes=[pltpu_scratch((bk, d)), pltpu_scratch((bk, d))],
        interpret=interpret,
    )(*dkv_args)

    dq = (dq[:, :s_q] if pad_q else dq).reshape(b, h, s_q, d)
    return dq, dk.reshape(b, h, s_k, d), dv.reshape(b, h, s_k, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, block_q, block_k):
    if not _tileable(q.shape[-2], k.shape[-2], block_k):
        return _dense.dot_product_attention(q, k, v, causal=causal,
                                            mask=None)
    return _flash_fwd(q, k, v, causal, block_q, block_k)[0]


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k):
    if not _tileable(q.shape[-2], k.shape[-2], block_k):
        out = _dense.dot_product_attention(q, k, v, causal=causal,
                                           mask=None)
        return out, (q, k, v, None, None)
    out, lse = _flash_fwd(q, k, v, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, block_q, block_k, res, g):
    q, k, v, o, lse = res
    if lse is None:
        # non-tileable fallback: blockwise-remat recompute, O(seq) memory
        _, vjp = jax.vjp(
            lambda q_, k_, v_: blockwise_attention(
                q_, k_, v_, causal=causal, block_k=block_k), q, k, v)
        return vjp(g)
    return _flash_bwd(q, k, v, o, lse, g, causal, block_q, block_k)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_seg(q, k, v, segments, causal, block_q, block_k):
    return _flash_fwd(q, k, v, causal, block_q, block_k,
                      segments=segments)[0]


def _flash_seg_vjp_fwd(q, k, v, segments, causal, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, causal, block_q, block_k,
                          segments=segments)
    return out, (q, k, v, segments, out, lse)


def _flash_seg_vjp_bwd(causal, block_q, block_k, res, g):
    q, k, v, segments, o, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, g, causal, block_q, block_k,
                            segments=segments)
    return dq, dk, dv, None  # integer segment ids carry no cotangent


_flash_seg.defvjp(_flash_seg_vjp_fwd, _flash_seg_vjp_bwd)


def flash_attention(q, k, v, *, causal: bool = False,
                    mask: Optional[jax.Array] = None,
                    segments: Optional[jax.Array] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None):
    """(b, h, s, d) attention via the Pallas online-softmax kernel.

    ``segments``: (b, s) int document ids for packed rows (see
    dataset.text.pack_sequences) — the block-diagonal mask is applied
    *inside* the kernel, keeping packed long-context training O(seq)
    (self-attention shapes only; id 0 = padding). Key-padding masks
    route to :func:`blockwise_attention` (same O(seq) memory,
    XLA-fused); richer masks fall back to the dense path; ragged key
    lengths fall back inside the custom_vjp.

    ``block_q``/``block_k``: per-program tile sizes. ``None`` (default)
    asks the autotuner (bigdl_tpu.tuning) for this shape's measured
    decision and falls back to 512; explicit values are honored as
    before. Either way both dims clamp to a standard tiling that divides
    the sequence (no padded q blocks for mid sequences like 768).
    """
    s_q, s_k = q.shape[-2], k.shape[-2]
    block_q, block_k = _resolve_blocks(s_q, s_k, q.shape[-1], causal,
                                       q.dtype, block_q, block_k)
    if segments is not None:
        if mask is not None:
            raise ValueError("segments and mask are mutually exclusive")
        # the kv-segment block is (1, 8, bk), so Mosaic additionally
        # needs bk lane-aligned: a multiple of 128 or the whole s_k.
        # Clamp small block_k up to 128 when that still tiles; otherwise
        # fall back to the dense block-diagonal mask.
        bk = min(block_k, max(8, s_k))
        legal = s_k % bk == 0 and (bk == s_k or bk % 128 == 0)
        if not legal and s_k % 128 == 0:
            block_k, legal = 128, True
        if s_q != s_k or not legal:
            return _dense.dot_product_attention(
                q, k, v, causal=causal,
                mask=_dense.make_segment_mask(segments))
        out = _flash_seg(q, k, v, segments, causal, block_q, block_k)
        # in-kernel, id-0 padding rows attend id-0 keys (keeps softmax
        # rows live for a finite backward); the dense fallback above
        # fully masks them to 0 instead. Zero them here so the same call
        # returns the same values regardless of shape-driven path choice.
        return jnp.where((segments != 0)[:, None, :, None], out, 0)
    if mask is not None:
        if _as_key_padding(mask, q.shape[0], k.shape[-2]) is not None:
            return blockwise_attention(q, k, v, causal=causal, mask=mask,
                                       block_k=block_k)
        return _dense.dot_product_attention(q, k, v, causal=causal,
                                            mask=mask)
    # _resolve_blocks already clamped both dims to standard tilings that
    # divide their sequence (so a 512 default never demotes a
    # 128-tileable length like 768 to the dense fallback, and q no
    # longer pads 768→1024); genuinely ragged lengths still fall back
    # inside the custom_vjp
    return _flash(q, k, v, causal, block_q, block_k)
