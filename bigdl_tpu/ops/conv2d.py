"""Per-pass conv layout policy — the consumer of conv_bwd_probe results.

Why: the round-3 xplane profile (PERF.md §2) put the ResNet-50 backward at
~38% MFU vs the forward's 46%, and ``scripts/conv_bwd_probe.py`` measures
each conv pass (forward, input-grad, filter-grad) under both NHWC and NCHW
activation layouts to find out where the points go. This module is the
part that was missing in round 4 (VERDICT r4 weak #4): a way for a probe
*decision* to change what ``nn.SpatialConvolution`` actually compiles.

Mechanism: :func:`conv2d` is a ``jax.custom_vjp`` whose three passes each
run under an independently chosen activation layout. A non-NHWC pass is
expressed as transpose-in → conv in that layout → transpose-out; XLA fuses
the transposes into neighbors, so the net effect is steering XLA's layout
assignment per pass — exactly what the probe measures, so a probe win
transfers. The backward passes are derived with ``jax.linear_transpose``
of the pass-local conv (no primal recompute; the conv is linear in each
argument), which yields the same transposed-conv HLO autodiff would, but
under the chosen dimension numbers.

The policy is process-global trace-time state (layouts are static shape
decisions, not data), set via :func:`set_conv_pass_layouts` or decided
from probe output by :func:`decide_from_probe`. Default (all-NHWC) keeps
``nn.SpatialConvolution`` on its plain single-op path — zero change
unless a decision is installed.

The reference has no analog: its layout is fixed by im2col+gemm
(nn/SpatialConvolution.scala:403-430); layout choice on TPU is the
corresponding lever.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["conv2d", "set_conv_pass_layouts", "get_conv_pass_layouts",
           "decide_from_probe", "resolve_layout_spec",
           "install_layout_spec", "maybe_install_auto",
           "policy_snapshot", "restore_policy",
           "MEASURED_DECISIONS"]

_PASSES = ("fwd", "dgrad", "wgrad")
_DEFAULT = {"fwd": "NHWC", "dgrad": "NHWC", "wgrad": "NHWC"}
_POLICY: Dict[str, str] = dict(_DEFAULT)
# True once a caller installed a policy explicitly (CLI flag or API call);
# maybe_install_auto() then leaves the policy alone
_EXPLICIT = False

# Probe decisions measured on real hardware, shipped as the framework
# default for matching devices. Provenance: round-5 window-2 on-chip
# probe + same-window end-to-end A/B (PERF.md §8.2, CONV_PROBE_r05.jsonl)
# — on TPU v5 lite the filter-grad pass prefers NCHW (aggregate wgrad
# 0.26 ms NHWC vs 0.15 ms NCHW across the ResNet-50 shape set; the stem's
# wgrad alone is 7x: 0.146 vs 0.021 ms) and the decision measured
# +1.1% end-to-end train throughput on ResNet-50 b128 (2,634.8 ->
# 2,662.7 img/s). Unlisted devices resolve to the all-NHWC default.
MEASURED_DECISIONS: Dict[str, Dict[str, str]] = {
    "TPU v5 lite": {"fwd": "NHWC", "dgrad": "NHWC", "wgrad": "NCHW"},
}


def set_conv_pass_layouts(fwd: str = "NHWC", dgrad: str = "NHWC",
                          wgrad: str = "NHWC") -> Dict[str, str]:
    """Install the per-pass activation layouts (each "NHWC" or "NCHW").
    Call before jit-compiling the train step; layouts are trace-time
    constants. Returns the installed policy."""
    global _EXPLICIT
    for v in (fwd, dgrad, wgrad):
        if v not in ("NHWC", "NCHW"):
            raise ValueError(f"layout must be NHWC or NCHW, got {v!r}")
    _POLICY.update(fwd=fwd, dgrad=dgrad, wgrad=wgrad)
    _EXPLICIT = True
    return dict(_POLICY)


def reset_conv_pass_layouts() -> Dict[str, str]:
    """Restore the all-NHWC default AND clear the explicit flag, so a
    subsequent :func:`maybe_install_auto` resolves again (tests; a
    library user who wants plain all-NHWC should instead install it
    explicitly via ``set_conv_pass_layouts()``)."""
    global _EXPLICIT
    _POLICY.update(_DEFAULT)
    _EXPLICIT = False
    return dict(_POLICY)


def resolve_layout_spec(spec: str, device=None) -> Dict[str, str]:
    """Resolve a ``--convLayout`` value to a per-pass dict (not installed).

    ``"default"`` is all-NHWC; ``"auto"`` looks this device's kind up in
    :data:`MEASURED_DECISIONS` (all-NHWC when absent, so auto is safe on
    any backend); ``"FWD,DGRAD,WGRAD"`` is explicit. Raises ValueError on
    a malformed spec."""
    low = (spec or "auto").strip().lower()
    if low == "default":
        return dict(_DEFAULT)
    if low == "auto":
        if device is None:
            try:
                device = jax.devices()[0]
            except Exception:
                return dict(_DEFAULT)
        return dict(MEASURED_DECISIONS.get(
            getattr(device, "device_kind", ""), _DEFAULT))
    parts = spec.strip().upper().split(",")
    if len(parts) != 3 or any(p not in ("NHWC", "NCHW") for p in parts):
        raise ValueError("convLayout spec wants FWD,DGRAD,WGRAD "
                         "(NHWC|NCHW each), 'auto' or 'default'; "
                         f"got {spec!r}")
    return dict(zip(_PASSES, parts))


def install_layout_spec(spec: str, device=None) -> Dict[str, str]:
    """Resolve ``spec`` and install it as an explicit policy (wins over
    any later :func:`maybe_install_auto`). Returns the installed dict."""
    return set_conv_pass_layouts(**resolve_layout_spec(spec, device))


def conv_layouts_if_nondefault() -> "Dict[str, str] | None":
    """The active policy when it differs from all-NHWC, else None —
    result-JSON provenance helper for the perf/TTA harnesses."""
    return None if _POLICY == _DEFAULT else dict(_POLICY)


def maybe_install_auto(device=None, guarded: bool = False,
                       policy: "Dict[str, str] | None" = None
                       ) -> Dict[str, str]:
    """Install this device's measured decision (or an explicit ``policy``
    dict from the autotuner) unless a policy was already installed
    explicitly. Called by the training entry points (Optimizer, perf
    harness) right before compiling, when the backend is known — this is
    how a shipped probe decision becomes the framework default without
    overriding a user's ``--convLayout``.

    ``guarded=True`` marks a run configuration where the measured
    decision is known-negative (inner-stepping, the s2d stem — PERF.md
    §8.2 combination matrix): the all-NHWC default is INSTALLED, not
    merely skipped, so a K=1 run followed by a K>1 run in one process
    keeps plain-path semantics (ADVICE r5 #1). Returns the active
    policy."""
    if not _EXPLICIT:
        if guarded:
            _POLICY.update(_DEFAULT)
        elif policy is not None:
            for v in policy.values():
                if v not in ("NHWC", "NCHW"):
                    raise ValueError(
                        f"layout must be NHWC or NCHW, got {v!r}")
            _POLICY.update({p: policy[p] for p in _PASSES})
        else:
            _POLICY.update(resolve_layout_spec("auto", device))
    return dict(_POLICY)


def policy_snapshot() -> Tuple[Dict[str, str], bool]:
    """Capture (policy, explicit-flag) so a harness can restore the
    pre-run state afterwards — the per-run isolation half of the ADVICE
    r5 #1 fix (one process running K=1 then K>1 must not leak the
    measured layout into the guarded run)."""
    return dict(_POLICY), _EXPLICIT


def restore_policy(snap: Tuple[Dict[str, str], bool]) -> Dict[str, str]:
    """Restore a :func:`policy_snapshot`."""
    global _EXPLICIT
    pol, explicit = snap
    _POLICY.update({p: pol[p] for p in _PASSES})
    _EXPLICIT = bool(explicit)
    return dict(_POLICY)


def get_conv_pass_layouts() -> Dict[str, str]:
    return dict(_POLICY)


def is_default_policy() -> bool:
    return _POLICY == _DEFAULT


def probe_totals(lines: Iterable[str]) -> Dict[str, Dict[str, float]]:
    """Aggregate conv_bwd_probe JSONL rows into per-pass, per-layout total
    milliseconds across all probed shapes (total ms ≈ one ResNet-50-ish
    step's conv time, so the sum is the right weighting). Non-JSON lines
    are skipped. Raises on zero usable rows."""
    totals = {p: {"NHWC": 0.0, "NCHW": 0.0} for p in _PASSES}
    counts = {p: {"NHWC": 0, "NCHW": 0} for p in _PASSES}
    for line in lines:
        line = line.strip()
        if not line or not line.startswith("{"):
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        lay = row.get("layout")
        if lay not in ("NHWC", "NCHW"):
            continue
        for p in _PASSES:
            ms = row.get(f"{p}_ms")
            if ms is not None:
                totals[p][lay] += float(ms)
                counts[p][lay] += 1
    if not any(c for per in counts.values() for c in per.values()):
        raise ValueError("no probe rows found")
    for p in _PASSES:
        # a truncated probe (tunnel drop mid-run) can leave one layout
        # unmeasured at 0.0 ms — which min() would then always "win";
        # refuse to decide from asymmetric coverage
        if counts[p]["NHWC"] != counts[p]["NCHW"]:
            raise ValueError(
                f"asymmetric probe coverage for pass {p!r}: "
                f"{counts[p]['NHWC']} NHWC vs {counts[p]['NCHW']} NCHW "
                "rows — probe was truncated, re-run it")
    return totals


def decide_from_probe(lines: Iterable[str]) -> Dict[str, str]:
    """Per-pass layout decision from probe rows: the layout with the lower
    :func:`probe_totals` time wins each pass. Returns {'fwd'|'dgrad'|
    'wgrad': layout} without installing it."""
    totals = probe_totals(lines)
    return {p: min(totals[p], key=totals[p].get) for p in _PASSES}


def _to_nchw(x):
    return jnp.transpose(x, (0, 3, 1, 2))


def _to_nhwc(x):
    return jnp.transpose(x, (0, 2, 3, 1))


def _conv_in_layout(x, w, stride, padding, rhs_dilation, groups, layout):
    """NHWC/HWIO in, NHWC out — internal conv under ``layout``'s dimension
    numbers (the transposes are XLA-fused into neighbors)."""
    if layout == "NHWC":
        return lax.conv_general_dilated(
            x, w, stride, padding, rhs_dilation=rhs_dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=groups)
    y = lax.conv_general_dilated(
        _to_nchw(x), jnp.transpose(w, (3, 2, 0, 1)), stride, padding,
        rhs_dilation=rhs_dilation,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=groups)
    return _to_nhwc(y)


import functools


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def conv2d(x, w, stride: Tuple[int, int], padding, rhs_dilation,
           groups: int):
    """2-D conv, NHWC x / HWIO w, with the per-pass layout policy applied.
    stride/padding/rhs_dilation must be hashable tuples (static)."""
    return _conv_in_layout(x, w, stride, padding, rhs_dilation, groups,
                           _POLICY["fwd"])


def _fwd(x, w, stride, padding, rhs_dilation, groups):
    y = _conv_in_layout(x, w, stride, padding, rhs_dilation, groups,
                        _POLICY["fwd"])
    return y, (x, w)


def _bwd(stride, padding, rhs_dilation, groups, res, dy):
    x, w = res
    dx, = jax.linear_transpose(
        lambda xx: _conv_in_layout(xx, w, stride, padding, rhs_dilation,
                                   groups, _POLICY["dgrad"]), x)(dy)
    dw, = jax.linear_transpose(
        lambda ww: _conv_in_layout(x, ww, stride, padding, rhs_dilation,
                                   groups, _POLICY["wgrad"]), w)(dy)
    return dx, dw


conv2d.defvjp(_fwd, _bwd)
