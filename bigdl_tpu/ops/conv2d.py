"""Per-pass, per-geometry conv layout policy — the consumer of
conv_bwd_probe results.

Why: the round-3 xplane profile (PERF.md §2) put the ResNet-50 backward at
~38% MFU vs the forward's 46%, and ``scripts/conv_bwd_probe.py`` measures
each conv pass (forward, input-grad, filter-grad) under both NHWC and NCHW
activation layouts to find out where the points go. This module is the
part that was missing in round 4 (VERDICT r4 weak #4): a way for a probe
*decision* to change what ``nn.SpatialConvolution`` actually compiles.

Mechanism: :func:`conv2d` is a ``jax.custom_vjp`` whose three passes each
run under an independently chosen activation layout. A non-NHWC pass is
expressed as transpose-in → conv in that layout → transpose-out; XLA fuses
the transposes into neighbors, so the net effect is steering XLA's layout
assignment per pass — exactly what the probe measures, so a probe win
transfers. The backward passes are derived with ``jax.linear_transpose``
of the pass-local conv (no primal recompute; the conv is linear in each
argument), which yields the same transposed-conv HLO autodiff would, but
under the chosen dimension numbers.

Round 8 (ISSUE 3) adds two resolutions the single global triple threw
away:

* **per-geometry decisions** — CONV_PROBE_r05.jsonl records per-shape
  layout asymmetry up to 7x (the stem's wgrad: 0.146 ms NHWC vs 0.021 ms
  NCHW) while the 3x3 stages mildly prefer NHWC; one process-global
  triple can only take the aggregate. Decisions are now keyed by the conv
  *geometry* ``(kh, kw, stride, cin, cout, groups, dilation, dtype)``
  and resolved per pass: an installed :data:`_GEOM_POLICY` entry (probe
  decision via :func:`install_geom_decisions`) wins, then a tuned
  decision from ``bigdl_tpu.tuning`` (``conv_geom`` cache namespace,
  off/cached/measure modes, dry off-TPU), then the global triple.
  An explicit ``--convLayout`` spec still beats everything.
* **a GEMM "layout"** — a 1x1 stride-1 unpadded conv IS a matmul
  (roughly half of ResNet-50's FLOPs), and expressing it as
  ``lax.dot_general`` over ``(N*H*W, Cin) x (Cin, Cout)`` hands XLA the
  mature matmul path instead of the conv lowering. ``GEMM`` is a third
  per-pass choice; each of fwd/dgrad/wgrad independently picks
  NHWC/NCHW/GEMM, and an ineligible site (k>1, strided, padded, grouped
  or dilated) falls back to NHWC — exact parity, never an error.

The policy is process-global trace-time state (layouts are static shape
decisions, not data), set via :func:`set_conv_pass_layouts` /
:func:`install_geom_decisions` or decided from probe output by
:func:`decide_from_probe` / :func:`decide_geom_from_probe`. Default
(all-NHWC, no geometry entries, tuner off) keeps
``nn.SpatialConvolution`` on its plain single-op path — zero change
unless a decision is installed.

The reference has no analog: its layout is fixed by im2col+gemm
(nn/SpatialConvolution.scala:403-430); layout choice on TPU is the
corresponding lever (and GEMM is im2col's degenerate k=1 case, where
im2col is the identity).
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["conv2d", "set_conv_pass_layouts", "get_conv_pass_layouts",
           "decide_from_probe", "decide_geom_from_probe",
           "resolve_layout_spec",
           "install_layout_spec", "maybe_install_auto",
           "install_geom_decisions", "install_geom_file",
           "clear_geom_policy", "geom_policy_if_any", "gemm_eligible",
           "resolve_site_layouts",
           "policy_snapshot", "restore_policy", "policy_active",
           "MEASURED_DECISIONS"]

_PASSES = ("fwd", "dgrad", "wgrad")
_LAYOUTS = ("NHWC", "NCHW", "GEMM")
_DEFAULT = {"fwd": "NHWC", "dgrad": "NHWC", "wgrad": "NHWC"}
_POLICY: Dict[str, str] = dict(_DEFAULT)
# True once a caller installed a policy explicitly (CLI flag or API call);
# maybe_install_auto() then leaves the policy alone
_EXPLICIT = False

# Per-geometry decisions: geometry tuple (see _geom_of) -> partial
# per-pass layout dict, e.g. {(7, 7, 2, 2, 3, 64, 1, 1, 1, "bfloat16"):
# {"wgrad": "NCHW"}}. Consulted per conv site at trace time, before the
# global triple; installed from probe output (install_geom_decisions) —
# tuner-resolved decisions flow in live via bigdl_tpu.tuning instead.
_GEOM_POLICY: Dict[tuple, Dict[str, str]] = {}

# Probe decisions measured on real hardware, shipped as the framework
# default for matching devices. Provenance: round-5 window-2 on-chip
# probe + same-window end-to-end A/B (PERF.md §8.2, CONV_PROBE_r05.jsonl)
# — on TPU v5 lite the filter-grad pass prefers NCHW (aggregate wgrad
# 0.26 ms NHWC vs 0.15 ms NCHW across the ResNet-50 shape set; the stem's
# wgrad alone is 7x: 0.146 vs 0.021 ms) and the decision measured
# +1.1% end-to-end train throughput on ResNet-50 b128 (2,634.8 ->
# 2,662.7 img/s). Unlisted devices resolve to the all-NHWC default.
MEASURED_DECISIONS: Dict[str, Dict[str, str]] = {
    "TPU v5 lite": {"fwd": "NHWC", "dgrad": "NHWC", "wgrad": "NCHW"},
}


def set_conv_pass_layouts(fwd: str = "NHWC", dgrad: str = "NHWC",
                          wgrad: str = "NHWC") -> Dict[str, str]:
    """Install the per-pass activation layouts (each "NHWC", "NCHW" or
    "GEMM" — GEMM applies only at 1x1/stride-1/unpadded/ungrouped conv
    sites and falls back to NHWC elsewhere). Call before jit-compiling
    the train step; layouts are trace-time constants. Returns the
    installed policy."""
    global _EXPLICIT
    for v in (fwd, dgrad, wgrad):
        if v not in _LAYOUTS:
            raise ValueError(
                f"layout must be one of {_LAYOUTS}, got {v!r}")
    _POLICY.update(fwd=fwd, dgrad=dgrad, wgrad=wgrad)
    _EXPLICIT = True
    return dict(_POLICY)


def reset_conv_pass_layouts() -> Dict[str, str]:
    """Restore the all-NHWC default, clear the explicit flag AND drop
    every per-geometry decision, so a subsequent
    :func:`maybe_install_auto` resolves again (tests; a library user who
    wants plain all-NHWC should instead install it explicitly via
    ``set_conv_pass_layouts()``)."""
    global _EXPLICIT
    _POLICY.update(_DEFAULT)
    _EXPLICIT = False
    _GEOM_POLICY.clear()
    return dict(_POLICY)


def resolve_layout_spec(spec: str, device=None) -> Dict[str, str]:
    """Resolve a ``--convLayout`` value to a per-pass dict (not installed).

    ``"default"`` is all-NHWC; ``"auto"`` looks this device's kind up in
    :data:`MEASURED_DECISIONS` (all-NHWC when absent, so auto is safe on
    any backend); ``"FWD,DGRAD,WGRAD"`` is explicit. Raises ValueError on
    a malformed spec."""
    low = (spec or "auto").strip().lower()
    if low == "default":
        return dict(_DEFAULT)
    if low == "auto":
        if device is None:
            try:
                device = jax.devices()[0]
            except Exception:
                return dict(_DEFAULT)
        return dict(MEASURED_DECISIONS.get(
            getattr(device, "device_kind", ""), _DEFAULT))
    parts = spec.strip().upper().split(",")
    if len(parts) != 3 or any(p not in _LAYOUTS for p in parts):
        raise ValueError("convLayout spec wants FWD,DGRAD,WGRAD "
                         "(NHWC|NCHW|GEMM each), 'auto' or 'default'; "
                         f"got {spec!r}")
    return dict(zip(_PASSES, parts))


def install_layout_spec(spec: str, device=None) -> Dict[str, str]:
    """Resolve ``spec`` and install it as an explicit policy (wins over
    any later :func:`maybe_install_auto`). Returns the installed dict."""
    return set_conv_pass_layouts(**resolve_layout_spec(spec, device))


def conv_layouts_if_nondefault() -> "Dict[str, str] | None":
    """The active policy when it differs from all-NHWC, else None —
    result-JSON provenance helper for the perf/TTA harnesses."""
    return None if _POLICY == _DEFAULT else dict(_POLICY)


def maybe_install_auto(device=None, guarded: bool = False,
                       policy: "Dict[str, str] | None" = None
                       ) -> Dict[str, str]:
    """Install this device's measured decision (or an explicit ``policy``
    dict from the autotuner) unless a policy was already installed
    explicitly. Called by the training entry points (Optimizer, perf
    harness) right before compiling, when the backend is known — this is
    how a shipped probe decision becomes the framework default without
    overriding a user's ``--convLayout``.

    ``guarded=True`` marks a run configuration where the measured
    decision is known-negative (inner-stepping, the s2d stem — PERF.md
    §8.2 combination matrix): the all-NHWC default is INSTALLED, not
    merely skipped, so a K=1 run followed by a K>1 run in one process
    keeps plain-path semantics (ADVICE r5 #1). Returns the active
    policy."""
    if not _EXPLICIT:
        if guarded:
            _POLICY.update(_DEFAULT)
        elif policy is not None:
            for v in policy.values():
                if v not in _LAYOUTS:
                    raise ValueError(
                        f"layout must be one of {_LAYOUTS}, got {v!r}")
            _POLICY.update({p: policy[p] for p in _PASSES})
        else:
            _POLICY.update(resolve_layout_spec("auto", device))
    return dict(_POLICY)


def policy_snapshot() -> tuple:
    """Capture (policy, explicit-flag, per-geometry table) so a harness
    can restore the pre-run state afterwards — the per-run isolation half
    of the ADVICE r5 #1 fix (one process running K=1 then K>1 must not
    leak the measured layout into the guarded run). The geometry table
    rides along so mixed global+per-geometry state round-trips whole."""
    return (dict(_POLICY), _EXPLICIT,
            {g: dict(v) for g, v in _GEOM_POLICY.items()})


def restore_policy(snap: tuple) -> Dict[str, str]:
    """Restore a :func:`policy_snapshot` (pre-round-8 two-tuples restore
    with an empty geometry table)."""
    global _EXPLICIT
    pol, explicit = snap[0], snap[1]
    _POLICY.update({p: pol[p] for p in _PASSES})
    _EXPLICIT = bool(explicit)
    _GEOM_POLICY.clear()
    if len(snap) > 2:
        _GEOM_POLICY.update({g: dict(v) for g, v in snap[2].items()})
    return dict(_POLICY)


def get_conv_pass_layouts() -> Dict[str, str]:
    return dict(_POLICY)


def is_default_policy() -> bool:
    return _POLICY == _DEFAULT


def policy_active() -> bool:
    """True when a conv layout decision of ANY kind can apply: a
    non-default global triple, an installed per-geometry table, or a
    non-off autotune mode (which may hold per-geometry ``conv_geom``
    decisions to consult at trace time). ``nn.SpatialConvolution`` routes
    through :func:`conv2d` exactly when this is true — otherwise it keeps
    its plain single-op path."""
    if _POLICY != _DEFAULT or _GEOM_POLICY:
        return True
    try:
        from bigdl_tpu.tuning.autotune import get_mode
        return get_mode() != "off"
    except Exception:
        return False


def probe_totals(lines: Iterable[str]) -> Dict[str, Dict[str, float]]:
    """Aggregate conv_bwd_probe JSONL rows into per-pass, per-layout total
    milliseconds across all probed shapes (total ms ≈ one ResNet-50-ish
    step's conv time, so the sum is the right weighting). Non-JSON lines
    are skipped. Raises on zero usable rows."""
    totals = {p: {"NHWC": 0.0, "NCHW": 0.0} for p in _PASSES}
    counts = {p: {"NHWC": 0, "NCHW": 0} for p in _PASSES}
    for line in lines:
        line = line.strip()
        if not line or not line.startswith("{"):
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        lay = row.get("layout")
        if lay not in ("NHWC", "NCHW"):
            continue
        for p in _PASSES:
            ms = row.get(f"{p}_ms")
            if ms is not None:
                totals[p][lay] += float(ms)
                counts[p][lay] += 1
    if not any(c for per in counts.values() for c in per.values()):
        raise ValueError("no probe rows found")
    for p in _PASSES:
        # a truncated probe (tunnel drop mid-run) can leave one layout
        # unmeasured at 0.0 ms — which min() would then always "win";
        # refuse to decide from asymmetric coverage
        if counts[p]["NHWC"] != counts[p]["NCHW"]:
            raise ValueError(
                f"asymmetric probe coverage for pass {p!r}: "
                f"{counts[p]['NHWC']} NHWC vs {counts[p]['NCHW']} NCHW "
                "rows — probe was truncated, re-run it")
    return totals


def decide_from_probe(lines: Iterable[str]) -> Dict[str, str]:
    """Per-pass layout decision from probe rows: the layout with the lower
    :func:`probe_totals` time wins each pass. Returns {'fwd'|'dgrad'|
    'wgrad': layout} without installing it."""
    totals = probe_totals(lines)
    return {p: min(totals[p], key=totals[p].get) for p in _PASSES}


# ------------------------------------------------------ per-geometry policy
def _dtype_name(dtype) -> str:
    """Canonical dtype spelling for geometry keys ("float32",
    "bfloat16") — matches tuning.autotune's spelling so the two key
    spaces can never drift."""
    try:
        return np.dtype(dtype).name
    except TypeError:
        return str(dtype)


def _geom_of(x, w, stride, rhs_dilation, groups) -> tuple:
    """The geometry key of one conv site, from trace-time avals:
    (kh, kw, sh, sw, cin, cout, groups, dh, dw, dtype). Batch and spatial
    extent are deliberately NOT part of the key — the probe showed the
    asymmetry tracks kernel/channel/stride structure, and one decision
    per geometry keeps the table (and the measure cost) bounded."""
    return (int(w.shape[0]), int(w.shape[1]), int(stride[0]),
            int(stride[1]), int(x.shape[-1]), int(w.shape[-1]),
            int(groups), int(rhs_dilation[0]), int(rhs_dilation[1]),
            _dtype_name(x.dtype))


def geom_to_json(g: tuple) -> dict:
    """JSON spelling of a geometry key (stable field order via sort_keys
    at dump time)."""
    return {"kh": g[0], "kw": g[1], "stride": [g[2], g[3]],
            "cin": g[4], "cout": g[5], "groups": g[6],
            "dilation": [g[7], g[8]], "dtype": g[9]}


def geom_from_json(d: dict) -> tuple:
    """Inverse of :func:`geom_to_json`; raises ValueError on a malformed
    geometry dict."""
    try:
        s, dil = d["stride"], d.get("dilation", [1, 1])
        return (int(d["kh"]), int(d["kw"]), int(s[0]), int(s[1]),
                int(d["cin"]), int(d["cout"]), int(d.get("groups", 1)),
                int(dil[0]), int(dil[1]), str(d.get("dtype", "bfloat16")))
    except (KeyError, TypeError, IndexError) as e:
        raise ValueError(f"malformed conv geometry {d!r}: {e}")


def gemm_eligible(kh: int, kw: int, stride, padding, rhs_dilation,
                  groups: int) -> bool:
    """True when the conv site is exactly a matmul: 1x1 kernel, stride 1,
    zero padding, no dilation, no grouping. Everywhere else the GEMM
    choice silently degrades to NHWC (exact-parity fallback)."""
    if kh != 1 or kw != 1 or int(groups) != 1:
        return False
    if tuple(int(s) for s in stride) != (1, 1):
        return False
    if tuple(int(d) for d in rhs_dilation) != (1, 1):
        return False
    if isinstance(padding, str):  # "SAME"/"VALID" spellings: only VALID
        return padding.upper() == "VALID"  # is zero-pad, and 1x1 SAME ==
        # VALID anyway, but don't guess
    return all(int(lo) == 0 and int(hi) == 0 for lo, hi in padding)


def install_geom_decisions(decisions: Iterable[dict]) -> int:
    """Install per-geometry decisions (the JSON
    ``scripts/apply_conv_probe.py --geom`` emits): each item is
    ``{"geom": {...}, "layouts": {"fwd"|"dgrad"|"wgrad": layout}}``.
    Unknown passes/layouts raise — a typo'd decision file must not
    silently train differently. Returns the number of geometry entries
    installed. Explicit ``--convLayout`` still wins at lookup time."""
    n = 0
    for d in decisions:
        g = geom_from_json(d.get("geom", {}))
        lays = d.get("layouts") or {}
        for p, v in lays.items():
            if p not in _PASSES or v not in _LAYOUTS:
                raise ValueError(
                    f"bad per-geometry decision {p!r}={v!r} (passes "
                    f"{_PASSES}, layouts {_LAYOUTS})")
        if lays:
            _GEOM_POLICY.setdefault(g, {}).update(lays)
            n += 1
    return n


def install_geom_file(path: str) -> int:
    """Load a per-geometry decision JSON file (a list, or
    ``{"decisions": [...]}``) and install it — the ``--convGeom FILE``
    CLI spelling."""
    with open(path) as f:
        blob = json.load(f)
    if isinstance(blob, dict):
        blob = blob.get("decisions", [])
    return install_geom_decisions(blob)


def clear_geom_policy() -> None:
    _GEOM_POLICY.clear()


def geom_policy_if_any() -> "List[dict] | None":
    """The installed per-geometry decisions as a deterministic JSON-able
    list, or None when the table is empty — result-JSON provenance
    (every perf line says which per-geometry policy it ran under)."""
    if not _GEOM_POLICY:
        return None
    return [{"geom": geom_to_json(g), "layouts": dict(_GEOM_POLICY[g])}
            for g in sorted(_GEOM_POLICY)]


# conv_bwd_probe.py rows predating round 8 carry only a shape *name*;
# this maps the historical names (CONV_PROBE_r05.jsonl) to geometries so
# old probe archives still yield per-geometry decisions.
LEGACY_PROBE_SHAPES: Dict[str, tuple] = {
    "stem7x7s2": (7, 7, 2, 2, 3, 64, 1, 1, 1, "bfloat16"),
    "s1_3x3": (3, 3, 1, 1, 64, 64, 1, 1, 1, "bfloat16"),
    "s2_3x3": (3, 3, 1, 1, 128, 128, 1, 1, 1, "bfloat16"),
    "s3_3x3": (3, 3, 1, 1, 256, 256, 1, 1, 1, "bfloat16"),
    "s4_3x3": (3, 3, 1, 1, 512, 512, 1, 1, 1, "bfloat16"),
    "s2_1x1": (1, 1, 1, 1, 512, 128, 1, 1, 1, "bfloat16"),
}


def _row_geom(row: dict) -> "tuple | None":
    """Geometry of one probe row: explicit fields when present (round-8
    probe), the legacy name table otherwise."""
    if "kh" in row:
        try:
            return geom_from_json(row)
        except ValueError:
            return None
    return LEGACY_PROBE_SHAPES.get(row.get("shape", ""))


def decide_geom_from_probe(lines: Iterable[str]) -> List[dict]:
    """Per-geometry, per-pass layout decisions from probe rows: for each
    geometry, each pass independently takes the layout with the lowest
    measured time across the layouts probed for that geometry (NHWC/NCHW
    always; GEMM where the probe measured it). Deterministic: geometries
    sorted, ties broken by the fixed layout order NHWC < NCHW < GEMM.
    Returns the decision list without installing it."""
    best: Dict[tuple, Dict[str, Tuple[float, str]]] = {}
    for line in lines:
        line = line.strip()
        if not line or not line.startswith("{"):
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        lay = row.get("layout")
        if lay not in _LAYOUTS:
            continue
        g = _row_geom(row)
        if g is None:
            continue
        rank = _LAYOUTS.index(lay)
        per = best.setdefault(g, {})
        for p in _PASSES:
            ms = row.get(f"{p}_ms")
            if ms is None:
                continue
            cand = (float(ms), rank, lay)
            if p not in per or cand < per[p]:
                per[p] = cand
    if not best:
        raise ValueError("no usable probe rows (geometry fields or a "
                         "known legacy shape name required)")
    out = []
    for g in sorted(best):
        out.append({"geom": geom_to_json(g),
                    "layouts": {p: best[g][p][2] for p in _PASSES
                                if p in best[g]}})
    return out


def resolve_site_layouts(kh: int, kw: int, stride, padding, rhs_dilation,
                         groups: int, cin: int, cout: int,
                         dtype="bfloat16") -> Dict[str, str]:
    """What layout each pass of ONE conv site would resolve to under the
    currently-installed policy — the same precedence ladder
    :func:`_pass_layout` applies at trace time (explicit spec >
    per-geometry decision > cached tuner decision > global triple, GEMM
    degrading to NHWC at ineligible sites) but computed from static site
    metadata, with the tuner consulted READ-ONLY (no measuring, no cache
    writes, no ledger entries). This is tpulint's layout/fusion oracle
    (bigdl_tpu.analysis): a GEMM-eligible site resolving to a spatial
    layout is a fusion-opportunity finding."""
    stride = tuple(int(s) for s in stride)
    rhs_dilation = tuple(int(d) for d in rhs_dilation)
    geom = (int(kh), int(kw), stride[0], stride[1], int(cin), int(cout),
            int(groups), rhs_dilation[0], rhs_dilation[1],
            _dtype_name(dtype))
    ok = gemm_eligible(int(kh), int(kw), stride, padding, rhs_dilation,
                       int(groups))
    out: Dict[str, str] = {}
    for p in _PASSES:
        lay = None
        if not _EXPLICIT:
            per = _GEOM_POLICY.get(geom)
            if per:
                lay = per.get(p)
            if lay is None:
                lay = _peek_tuned_geom(p, geom, ok)
        if lay is None:
            lay = _POLICY[p]
        if lay == "GEMM" and not ok:
            lay = "NHWC"
        out[p] = lay
    return out


def _peek_tuned_geom(pass_name: str, geom: tuple,
                     gemm_ok: bool) -> "str | None":
    """Read-only view of the tuner's ``conv_geom`` decision for one
    (pass, geometry) — unlike :func:`_tuned_geom_layout` this can never
    measure, write a dry entry, or touch the provenance ledger."""
    try:
        from bigdl_tpu.tuning import autotune as _at
    except Exception:
        return None
    if _at.get_mode() == "off":
        return None
    return _at.peek_geom_layout(pass_name, geom, gemm_ok)


def _to_nchw(x):
    return jnp.transpose(x, (0, 3, 1, 2))


def _to_nhwc(x):
    return jnp.transpose(x, (0, 2, 3, 1))


def _conv_in_layout(x, w, stride, padding, rhs_dilation, groups, layout):
    """NHWC/HWIO in, NHWC out — internal conv under ``layout``'s dimension
    numbers (the transposes are XLA-fused into neighbors). ``GEMM``
    expresses the (already-validated 1x1/s1/unpadded) conv as a single
    ``dot_general`` over the flattened pixels — the contraction is
    identical (sum over Cin), so FLOPs and math match the conv spelling;
    only the lowering changes (XLA's matmul path instead of conv)."""
    if layout == "GEMM":
        n, h, wd, cin = x.shape
        cout = w.shape[-1]
        y = lax.dot_general(x.reshape(n * h * wd, cin),
                            w.reshape(cin, cout),
                            (((1,), (0,)), ((), ())))
        return y.reshape(n, h, wd, cout)
    if layout == "NHWC":
        return lax.conv_general_dilated(
            x, w, stride, padding, rhs_dilation=rhs_dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=groups)
    y = lax.conv_general_dilated(
        _to_nchw(x), jnp.transpose(w, (3, 2, 0, 1)), stride, padding,
        rhs_dilation=rhs_dilation,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=groups)
    return _to_nhwc(y)


def _pass_layout(pass_name, x, w, stride, padding, rhs_dilation, groups):
    """Resolve ONE pass's layout at trace time. Precedence: explicit
    ``--convLayout`` spec > installed per-geometry decision > tuned
    ``conv_geom`` decision (autotune cached/measure) > global triple.
    A GEMM choice at an ineligible site degrades to NHWC — exact-parity
    fallback, never an error (a probe decision file must not be able to
    crash a training run at a geometry it never measured)."""
    lay = None
    if not _EXPLICIT:
        g = _geom_of(x, w, stride, rhs_dilation, groups)
        per = _GEOM_POLICY.get(g)
        if per:
            lay = per.get(pass_name)
        if lay is None:
            lay = _tuned_geom_layout(pass_name, g, x.shape, padding)
    if lay is None:
        lay = _POLICY[pass_name]
    if lay == "GEMM" and not gemm_eligible(
            int(w.shape[0]), int(w.shape[1]), stride, padding,
            rhs_dilation, groups):
        lay = "NHWC"
    return lay


def _tuned_geom_layout(pass_name, geom, x_shape, padding):
    """Per-geometry decision from the autotuner's ``conv_geom`` cache
    namespace (None when the tuner is off / misses — the caller then
    falls back to the global triple). Imported lazily: ops must not pull
    the tuning package in at import time."""
    try:
        from bigdl_tpu.tuning import autotune as _at
    except Exception:
        return None
    if _at.get_mode() == "off":
        return None
    gemm_ok = gemm_eligible(geom[0], geom[1], (geom[2], geom[3]), padding,
                            (geom[7], geom[8]), geom[6])
    return _at.conv_geom_layout(
        pass_name, geom, tuple(int(d) for d in x_shape), gemm_ok)


import functools


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def conv2d(x, w, stride: Tuple[int, int], padding, rhs_dilation,
           groups: int):
    """2-D conv, NHWC x / HWIO w, with the per-pass (and per-geometry)
    layout policy applied. stride/padding/rhs_dilation must be hashable
    tuples (static)."""
    return _conv_in_layout(
        x, w, stride, padding, rhs_dilation, groups,
        _pass_layout("fwd", x, w, stride, padding, rhs_dilation, groups))


def _fwd(x, w, stride, padding, rhs_dilation, groups):
    y = _conv_in_layout(
        x, w, stride, padding, rhs_dilation, groups,
        _pass_layout("fwd", x, w, stride, padding, rhs_dilation, groups))
    return y, (x, w)


def _bwd(stride, padding, rhs_dilation, groups, res, dy):
    x, w = res
    dg = _pass_layout("dgrad", x, w, stride, padding, rhs_dilation, groups)
    wg = _pass_layout("wgrad", x, w, stride, padding, rhs_dilation, groups)
    dx, = jax.linear_transpose(
        lambda xx: _conv_in_layout(xx, w, stride, padding, rhs_dilation,
                                   groups, dg), x)(dy)
    dw, = jax.linear_transpose(
        lambda ww: _conv_in_layout(x, ww, stride, padding, rhs_dilation,
                                   groups, wg), w)(dy)
    return dx, dw


conv2d.defvjp(_fwd, _bwd)
