"""Per-pass conv layout policy — the consumer of conv_bwd_probe results.

Why: the round-3 xplane profile (PERF.md §2) put the ResNet-50 backward at
~38% MFU vs the forward's 46%, and ``scripts/conv_bwd_probe.py`` measures
each conv pass (forward, input-grad, filter-grad) under both NHWC and NCHW
activation layouts to find out where the points go. This module is the
part that was missing in round 4 (VERDICT r4 weak #4): a way for a probe
*decision* to change what ``nn.SpatialConvolution`` actually compiles.

Mechanism: :func:`conv2d` is a ``jax.custom_vjp`` whose three passes each
run under an independently chosen activation layout. A non-NHWC pass is
expressed as transpose-in → conv in that layout → transpose-out; XLA fuses
the transposes into neighbors, so the net effect is steering XLA's layout
assignment per pass — exactly what the probe measures, so a probe win
transfers. The backward passes are derived with ``jax.linear_transpose``
of the pass-local conv (no primal recompute; the conv is linear in each
argument), which yields the same transposed-conv HLO autodiff would, but
under the chosen dimension numbers.

The policy is process-global trace-time state (layouts are static shape
decisions, not data), set via :func:`set_conv_pass_layouts` or decided
from probe output by :func:`decide_from_probe`. Default (all-NHWC) keeps
``nn.SpatialConvolution`` on its plain single-op path — zero change
unless a decision is installed.

The reference has no analog: its layout is fixed by im2col+gemm
(nn/SpatialConvolution.scala:403-430); layout choice on TPU is the
corresponding lever.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["conv2d", "set_conv_pass_layouts", "get_conv_pass_layouts",
           "decide_from_probe"]

_PASSES = ("fwd", "dgrad", "wgrad")
_DEFAULT = {"fwd": "NHWC", "dgrad": "NHWC", "wgrad": "NHWC"}
_POLICY: Dict[str, str] = dict(_DEFAULT)


def set_conv_pass_layouts(fwd: str = "NHWC", dgrad: str = "NHWC",
                          wgrad: str = "NHWC") -> Dict[str, str]:
    """Install the per-pass activation layouts (each "NHWC" or "NCHW").
    Call before jit-compiling the train step; layouts are trace-time
    constants. Returns the installed policy."""
    for v in (fwd, dgrad, wgrad):
        if v not in ("NHWC", "NCHW"):
            raise ValueError(f"layout must be NHWC or NCHW, got {v!r}")
    _POLICY.update(fwd=fwd, dgrad=dgrad, wgrad=wgrad)
    return dict(_POLICY)


def get_conv_pass_layouts() -> Dict[str, str]:
    return dict(_POLICY)


def is_default_policy() -> bool:
    return _POLICY == _DEFAULT


def probe_totals(lines: Iterable[str]) -> Dict[str, Dict[str, float]]:
    """Aggregate conv_bwd_probe JSONL rows into per-pass, per-layout total
    milliseconds across all probed shapes (total ms ≈ one ResNet-50-ish
    step's conv time, so the sum is the right weighting). Non-JSON lines
    are skipped. Raises on zero usable rows."""
    totals = {p: {"NHWC": 0.0, "NCHW": 0.0} for p in _PASSES}
    counts = {p: {"NHWC": 0, "NCHW": 0} for p in _PASSES}
    for line in lines:
        line = line.strip()
        if not line or not line.startswith("{"):
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        lay = row.get("layout")
        if lay not in ("NHWC", "NCHW"):
            continue
        for p in _PASSES:
            ms = row.get(f"{p}_ms")
            if ms is not None:
                totals[p][lay] += float(ms)
                counts[p][lay] += 1
    if not any(c for per in counts.values() for c in per.values()):
        raise ValueError("no probe rows found")
    for p in _PASSES:
        # a truncated probe (tunnel drop mid-run) can leave one layout
        # unmeasured at 0.0 ms — which min() would then always "win";
        # refuse to decide from asymmetric coverage
        if counts[p]["NHWC"] != counts[p]["NCHW"]:
            raise ValueError(
                f"asymmetric probe coverage for pass {p!r}: "
                f"{counts[p]['NHWC']} NHWC vs {counts[p]['NCHW']} NCHW "
                "rows — probe was truncated, re-run it")
    return totals


def decide_from_probe(lines: Iterable[str]) -> Dict[str, str]:
    """Per-pass layout decision from probe rows: the layout with the lower
    :func:`probe_totals` time wins each pass. Returns {'fwd'|'dgrad'|
    'wgrad': layout} without installing it."""
    totals = probe_totals(lines)
    return {p: min(totals[p], key=totals[p].get) for p in _PASSES}


def _to_nchw(x):
    return jnp.transpose(x, (0, 3, 1, 2))


def _to_nhwc(x):
    return jnp.transpose(x, (0, 2, 3, 1))


def _conv_in_layout(x, w, stride, padding, rhs_dilation, groups, layout):
    """NHWC/HWIO in, NHWC out — internal conv under ``layout``'s dimension
    numbers (the transposes are XLA-fused into neighbors)."""
    if layout == "NHWC":
        return lax.conv_general_dilated(
            x, w, stride, padding, rhs_dilation=rhs_dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=groups)
    y = lax.conv_general_dilated(
        _to_nchw(x), jnp.transpose(w, (3, 2, 0, 1)), stride, padding,
        rhs_dilation=rhs_dilation,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=groups)
    return _to_nhwc(y)


import functools


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def conv2d(x, w, stride: Tuple[int, int], padding, rhs_dilation,
           groups: int):
    """2-D conv, NHWC x / HWIO w, with the per-pass layout policy applied.
    stride/padding/rhs_dilation must be hashable tuples (static)."""
    return _conv_in_layout(x, w, stride, padding, rhs_dilation, groups,
                           _POLICY["fwd"])


def _fwd(x, w, stride, padding, rhs_dilation, groups):
    y = _conv_in_layout(x, w, stride, padding, rhs_dilation, groups,
                        _POLICY["fwd"])
    return y, (x, w)


def _bwd(stride, padding, rhs_dilation, groups, res, dy):
    x, w = res
    dx, = jax.linear_transpose(
        lambda xx: _conv_in_layout(xx, w, stride, padding, rhs_dilation,
                                   groups, _POLICY["dgrad"]), x)(dy)
    dw, = jax.linear_transpose(
        lambda ww: _conv_in_layout(x, ww, stride, padding, rhs_dilation,
                                   groups, _POLICY["wgrad"]), w)(dy)
    return dx, dw


conv2d.defvjp(_fwd, _bwd)
