"""Custom TPU kernels (Pallas) with XLA fallbacks.

The reference's custom-kernel layer is the MKL JNI shim
(native/mkl/src/main/c/jni/mkl.c — 34 VML/BLAS wrappers, SURVEY.md §2.1);
under XLA nearly all of those lower to fused HLO automatically, so this
package only holds kernels where hand-tiling beats the compiler: flash
attention (and, as they land, LRN and other fused ops). Every kernel has a
pure-XLA fallback used off-TPU so the API is always importable.
"""

from bigdl_tpu.ops.attention_kernel import (
    blockwise_attention, flash_attention,
)
from bigdl_tpu.ops.bn_kernel import bn_stats, bn_bwd_stats, fused_bn_train
from bigdl_tpu.ops.conv2d import (MEASURED_DECISIONS, decide_from_probe,
                                  decide_geom_from_probe,
                                  get_conv_pass_layouts, gemm_eligible,
                                  geom_policy_if_any,
                                  install_geom_decisions,
                                  install_geom_file,
                                  install_layout_spec, maybe_install_auto,
                                  policy_active, policy_snapshot,
                                  resolve_layout_spec,
                                  restore_policy, set_conv_pass_layouts)

__all__ = ["flash_attention", "blockwise_attention",
           "bn_stats", "bn_bwd_stats", "fused_bn_train",
           "set_conv_pass_layouts", "get_conv_pass_layouts",
           "decide_from_probe", "decide_geom_from_probe",
           "resolve_layout_spec",
           "install_layout_spec", "maybe_install_auto",
           "install_geom_decisions", "install_geom_file",
           "gemm_eligible", "geom_policy_if_any", "policy_active",
           "policy_snapshot", "restore_policy",
           "MEASURED_DECISIONS"]
