"""Stdlib HTTP serving surface: JSON in, JSON out, no new dependencies.

BigDL 2.0's Cluster Serving put a full streaming stack (Redis + Flink)
in front of the model; the TPU-native equivalent starts smaller and
honest: a ``ThreadingHTTPServer`` (one thread per connection, fine at
micro-batcher concurrency levels) exposing

* ``POST /predict``  — ``{"inputs": [...]}`` -> argmax predictions
  (scores on request), routed through the dynamic micro-batcher so
  concurrent callers share bucketed forwards;
* ``POST /generate`` — ``{"tokens": [...], "max_new_tokens": N}`` ->
  generated token ids from the continuous-batching KV-cache decoder
  (LM models only); optional ``temperature`` / ``top_k`` / ``top_p`` /
  ``seed`` select and seed the sampling mode (per-request counter-based
  randomness: the same seed replays the same output);
* ``GET /healthz``   — LIVENESS: 200 while the process can answer HTTP
  at all (a degraded server is alive — restarting it would lose the
  still-working endpoints);
* ``GET /readyz``    — READINESS: 200 only while every worker is
  healthy and no deliberate overload shed is active — the signal a load
  balancer drains on;
* ``GET /metrics``   — plaintext counters/histograms with the serving
  config provenance stamped into every scrape;
* ``GET /debug/requests`` / ``GET /debug/slots`` — the flight recorder
  (in-flight + recent request lifecycle records; 404 with ``--reqTrace
  off``) and the decoder slot table / KV page-pool occupancy (ISSUE 15).

Every response echoes ``x-request-id`` (client-supplied id wins, else
one is minted) so callers can join server-side lifecycle records and
access-log lines to their own request logs.

Error contract: malformed JSON/fields -> 400, admission rejection or
overload shed (queue full / tiered degradation) -> 429 with
``Retry-After``, request deadline expired before compute -> 504, dead
or wedged worker -> 503 (fast, via the watchdog — not after the
client's timeout), engine failure -> 500; every error body is
``{"error": ...}``.

Graceful degradation is TIERED: under overload the server sheds
``/generate`` first (decode holds slots for seconds; one shed frees
real capacity) while ``/predict`` — cheap, micro-batched — keeps
admitting until its own queue limit; ``/healthz`` stays green
throughout so the process is drained, not killed.
"""

from __future__ import annotations

import json
import logging
import queue as _queue_mod
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from bigdl_tpu.obs.spans import span as _obs_span
from bigdl_tpu.resilience.faults import TransientFault, hook as _fault_hook
from bigdl_tpu.serving import reqtrace as _reqtrace
from bigdl_tpu.serving.batcher import (AdmissionError, DeadlineExceeded,
                                       WorkerDied)

logger = logging.getLogger(__name__)

__all__ = ["ServingApp", "make_server", "run_server"]

_MAX_BODY = 64 * 1024 * 1024  # refuse absurd payloads before np.asarray


class _GenerateStream:
    """Handle for one streamed /generate (ISSUE 18): the per-request
    emit queue the decode loop feeds (``(tokens, done)`` per emitting
    round), the request future (error surface for terminations that
    never emit — expiry in the waiting queue, shutdown), the decoder
    that owns the slot (the disconnect path calls ``cancel`` on exactly
    this one, which matters under dp routing), and the prompt length for
    the final frame."""

    __slots__ = ("rid", "queue", "future", "decoder", "prompt_len")

    def __init__(self, rid, queue, future, decoder, prompt_len):
        self.rid = rid
        self.queue = queue
        self.future = future
        self.decoder = decoder
        self.prompt_len = prompt_len


class ServingApp:
    """The wiring between HTTP handlers and the serving stack: engine
    (+ optional batcher) for /predict, decoder for /generate, one
    metrics registry for everything. Endpoint handlers return
    ``(status, payload_dict)`` so they are unit-testable without
    sockets.

    ``default_deadline_ms`` bounds every request (a per-request
    ``"deadline_ms"`` field overrides it); ``shed_generate_frac`` is the
    overload tier: when the predict queue or the decode waiting queue
    passes that fraction of its capacity, ``/generate`` sheds with 429
    while ``/predict`` keeps admitting. ``watchdog`` supplies the
    readiness verdict for ``/readyz``.

    dp mode (ISSUE 16): pass ``replicas`` (a
    :class:`bigdl_tpu.serving.replicas.ReplicaSet`) INSTEAD of
    engine/batcher/decoder/watchdog — every request routes to the
    least-loaded live replica, ``/readyz`` aggregates per-replica
    health (200 while >= 1 lives), and shedding goes fleet-level (only
    when every live replica is saturated)."""

    def __init__(self, *, name: str, metrics, engine=None, batcher=None,
                 decoder=None, request_timeout_s: float = 120.0,
                 default_deadline_ms: Optional[float] = None,
                 shed_generate_frac: float = 0.75,
                 watchdog=None, replicas=None, version: str = "v0",
                 clock=time.monotonic):
        if replicas is not None and (engine is not None
                                     or batcher is not None
                                     or decoder is not None):
            raise ValueError("pass either replicas= or "
                             "engine/batcher/decoder, not both")
        self.name = name
        self.metrics = metrics
        self.engine = engine
        self.batcher = batcher
        self.decoder = decoder
        self.watchdog = watchdog
        self.replicas = replicas
        self.clock = clock
        # the weights generation served right now — bumped by the fleet
        # rolling swap (ISSUE 20) and echoed as x-model-version on every
        # response so a client can prove which weights answered it
        self.model_version = str(version)
        # extension point for process-role routes (the fleet worker's
        # /control/state heartbeat and /admin/reload) — keyed
        # ("GET"|"POST", path), handler returns (status, body_dict)
        self.extra_routes = {}
        self.request_timeout_s = float(request_timeout_s)
        self.default_deadline_ms = (float(default_deadline_ms)
                                    if default_deadline_ms else None)
        if not 0.0 < shed_generate_frac <= 1.0:
            raise ValueError(f"shed_generate_frac must be in (0, 1], "
                             f"got {shed_generate_frac}")
        self.shed_generate_frac = float(shed_generate_frac)
        self._m_requests = {
            ep: metrics.counter(f"requests_{ep}_total",
                                f"completed /{ep} requests")
            for ep in ("predict", "generate")}
        self._m_errors = metrics.counter(
            "request_errors_total", "requests answered 4xx/5xx")
        self._m_expired = metrics.counter(
            "requests_expired_total",
            "requests answered 504 (deadline expired before compute)")
        self._m_shed = metrics.counter(
            "requests_shed_total",
            "requests shed 429 by tiered overload degradation")
        self._m_worker_dead = metrics.counter(
            "requests_worker_dead_total",
            "requests answered 503 fast (dead/wedged worker)")
        self._m_injected = metrics.counter(
            "faults_injected_requests_total",
            "requests failed by an installed --faultPlan")
        self._m_latency = {
            ep: metrics.histogram(f"latency_{ep}_ms",
                                  f"/{ep} request latency (receipt to "
                                  f"response ready)")
            for ep in ("predict", "generate")}

    # ------------------------------------------------------------ deadlines
    def _deadline_from(self, payload: dict) -> Optional[float]:
        """Absolute per-request deadline on the app clock, from the
        request's ``deadline_ms`` or the server default (None = no
        deadline)."""
        ms = payload.get("deadline_ms", self.default_deadline_ms)
        if ms is None:
            return None
        return self.clock() + float(ms) / 1000.0

    # ------------------------------------------------------------- overload
    def _shed_generate(self) -> bool:
        """Tiered degradation: past ``shed_generate_frac`` of either
        queue's capacity — or with the SLO burn rate saturated (ISSUE
        15: every recently finished request is missing its targets, so
        admitting more only makes the backlog later) — /generate sheds
        so /predict keeps breathing."""
        frac = self.shed_generate_frac
        if self.replicas is not None:
            if self.replicas.shed_generate(frac):
                return True
        if (self.batcher is not None
                and self.batcher.queue_depth
                >= frac * self.batcher.max_queue):
            return True
        if (self.decoder is not None
                and len(self.decoder._waiting)
                >= frac * self.decoder.max_waiting):
            return True
        rt = _reqtrace.get()
        if rt is not None and rt.slo is not None and rt.slo.should_shed():
            return True
        return False

    # ------------------------------------------------------------ endpoints
    def handle_healthz(self):
        """Liveness only — a degraded-but-serving process answers 200
        here (and 503 on /readyz) so orchestrators drain it instead of
        killing it."""
        return 200, {"status": "ok", "model": self.name}

    def handle_readyz(self):
        if self.replicas is not None:
            # fleet readiness: 200 while >= 1 replica can serve (dead
            # replicas are routed around); detail names every verdict
            ok, detail = self.replicas.ready_detail()
            detail["model"] = self.name
            if self._shed_generate():
                detail["shedding"] = "generate"
            detail["status"] = "ready" if ok else "unready"
            return (200 if ok else 503), detail
        detail = {"model": self.name}
        ok = True
        if self.watchdog is not None and not self.watchdog.ready():
            ok = False
            detail["failed_workers"] = self.watchdog.failures
        for comp_name, comp in (("batcher", self.batcher),
                                ("decoder", self.decoder)):
            if comp is not None and not comp.alive():
                ok = False
                detail.setdefault("dead", []).append(comp_name)
        if self._shed_generate():
            detail["shedding"] = "generate"
        detail["status"] = "ready" if ok else "unready"
        return (200 if ok else 503), detail

    def _route(self, endpoint: str, rid: Optional[str]):
        """dp routing (ISSUE 16): pick the least-loaded live replica
        (raises WorkerDied -> 503 when none live) and stamp the choice
        into the request's lifecycle record; single-replica mode returns
        the app's own components unchanged."""
        if self.replicas is None:
            return self.engine, self.batcher, self.decoder
        rep = (self.replicas.pick_predict() if endpoint == "predict"
               else self.replicas.pick_generate())
        rt = _reqtrace.get()
        if rt is not None:
            rt.note_replica(rid, rep.index)
        return rep.engine, rep.batcher, rep.decoder

    def handle_predict(self, payload: dict, rid: Optional[str] = None):
        engine, batcher, _ = self._route("predict", rid)
        if engine is None:
            return 400, {"error": "no /predict engine for this model"}
        inputs = payload.get("inputs")
        if inputs is None:
            return 400, {"error": "missing 'inputs'"}
        try:
            x = np.asarray(inputs)
            if x.dtype == object:
                raise ValueError("ragged inputs")
            if np.issubdtype(x.dtype, np.floating):
                x = x.astype(np.float32)
            elif np.issubdtype(x.dtype, np.integer):
                x = x.astype(np.int32)
            else:
                raise ValueError(f"unsupported dtype {x.dtype}")
        except ValueError as e:
            return 400, {"error": f"bad inputs: {e}"}
        if x.ndim < 2:
            return 400, {"error": "inputs must be a batch (rows on "
                                  "axis 0)"}
        deadline = self._deadline_from(payload)
        if batcher is not None:
            futs = [batcher.submit(row, deadline=deadline, rid=rid)
                    for row in x]
            scores = np.stack([f.result(self.request_timeout_s)
                               for f in futs])
        else:
            if deadline is not None and self.clock() >= deadline:
                raise DeadlineExceeded("deadline expired before compute")
            scores = engine.predict_scores(
                x, rids=([rid] * len(x) if rid is not None else None))
        preds = np.argmax(scores, axis=-1)
        out = {"predictions": preds.tolist()}
        if payload.get("return_scores"):
            out["scores"] = np.asarray(scores, np.float64).tolist()
        return 200, out

    @staticmethod
    def _parse_generate(payload: dict):
        """Validate the /generate payload; ``(parsed, None)`` or
        ``(None, error_string)`` — shared by the buffered and streamed
        paths so the two can never diverge on what they admit."""
        tokens = payload.get("tokens")
        if (not isinstance(tokens, (list, tuple)) or not tokens
                or not all(isinstance(t, int) for t in tokens)):
            return None, "'tokens' must be a non-empty list of ints"
        try:
            opts = {"max_new": payload.get("max_new_tokens", 16),
                    "temperature": payload.get("temperature", 0.0),
                    "stop": payload.get("stop_token"),
                    "top_k": int(payload.get("top_k", 0)),
                    "top_p": float(payload.get("top_p", 1.0)),
                    "seed": int(payload.get("seed", 0))}
        except (TypeError, ValueError):
            return None, "'top_k'/'seed' must be ints, 'top_p' a float"
        return (list(tokens), opts), None

    def handle_generate(self, payload: dict, rid: Optional[str] = None):
        _, _, decoder = self._route("generate", rid)
        if decoder is None:
            return 400, {"error": "no /generate decoder for this model "
                                  "(serve a transformer_lm* model)"}
        parsed, err = self._parse_generate(payload)
        if parsed is None:
            return 400, {"error": err}
        tokens, o = parsed
        try:
            fut = decoder.submit(tokens, o["max_new"], o["temperature"],
                                 o["stop"],
                                 deadline=self._deadline_from(payload),
                                 top_k=o["top_k"], top_p=o["top_p"],
                                 seed=o["seed"], rid=rid)
        except ValueError as e:
            return 400, {"error": str(e)}
        out_tokens = fut.result(self.request_timeout_s)
        return 200, {"tokens": out_tokens,
                     "prompt_len": len(tokens)}

    # ------------------------------------------------------------- streaming
    def start_generate_stream(self, payload: dict,
                              rid: Optional[str] = None):
        """Admission for a streamed /generate (ISSUE 18): same shed /
        validation / error ladder as :meth:`dispatch_post`, but instead
        of blocking on the future it submits with a queue-backed emit
        sink and returns ``(200, _GenerateStream)`` for the HTTP handler
        to drain. Every pre-stream failure returns a plain
        ``(status, body)`` — errors before the first byte stay ordinary
        JSON responses."""
        rt = _reqtrace.get()
        if rt is not None:
            toks = payload.get("tokens")
            prompt_n = (len(toks) if isinstance(toks, (list, tuple))
                        else None)
            try:
                max_new = int(payload.get("max_new_tokens", 16))
            except (TypeError, ValueError):
                max_new = None
            rid = rt.admit("generate", rid, prompt_tokens=prompt_n,
                           max_new=max_new)
        if self._shed_generate():
            self._m_shed.inc()
            self._m_errors.inc()
            if rt is not None:
                rt.finish(rid, "shed", status=429)
            return 429, {"error": "overloaded: shedding /generate "
                                  "(retry, or use /predict capacity)"}
        parsed, err = self._parse_generate(payload)
        if parsed is None:
            self._m_errors.inc()
            if rt is not None:
                rt.finish(rid, "bad_request", status=400, error=err)
            return 400, {"error": err}
        tokens, o = parsed
        q: _queue_mod.Queue = _queue_mod.Queue()
        try:
            _fault_hook("request")  # no-op unless --faultPlan installed
            _, _, decoder = self._route("generate", rid)
            if decoder is None:
                err = ("no /generate decoder for this model "
                       "(serve a transformer_lm* model)")
                self._m_errors.inc()
                if rt is not None:
                    rt.finish(rid, "bad_request", status=400, error=err)
                return 400, {"error": err}
            # emit runs under the engine lock: only hand the round's
            # tokens to the drain thread, never block
            fut = decoder.submit(
                tokens, o["max_new"], o["temperature"], o["stop"],
                deadline=self._deadline_from(payload),
                top_k=o["top_k"], top_p=o["top_p"], seed=o["seed"],
                rid=rid,
                emit=lambda new, done: q.put((list(new), done)))
        except ValueError as e:
            self._m_errors.inc()
            if rt is not None:
                rt.finish(rid, "bad_request", status=400, error=str(e))
            return 400, {"error": str(e)}
        except AdmissionError as e:
            self._m_errors.inc()
            if rt is not None:
                rt.finish(rid, "rejected", status=429, error=str(e))
            return 429, {"error": str(e)}
        except DeadlineExceeded as e:
            self._m_expired.inc()
            self._m_errors.inc()
            if rt is not None:
                rt.finish(rid, "expired", status=504, error=str(e))
            return 504, {"error": f"deadline exceeded: {e}"}
        except WorkerDied as e:
            self._m_worker_dead.inc()
            self._m_errors.inc()
            if rt is not None:
                rt.finish(rid, "worker_dead", status=503, error=str(e))
            return 503, {"error": str(e)}
        except TransientFault as e:
            self._m_injected.inc()
            self._m_errors.inc()
            if rt is not None:
                rt.finish(rid, "error", status=503,
                          error=f"injected fault: {e}")
            return 503, {"error": f"injected fault: {e}"}
        except Exception as e:
            logger.exception("/generate stream admission failed")
            self._m_errors.inc()
            if rt is not None:
                rt.finish(rid, "error", status=500,
                          error=f"{type(e).__name__}: {e}")
            return 500, {"error": f"{type(e).__name__}: {e}"}
        return 200, _GenerateStream(rid, q, fut, decoder, len(tokens))

    def finish_generate_stream(self, rid: Optional[str], ok: bool,
                               t0: float) -> None:
        """Account a drained stream the way :meth:`dispatch_post`
        accounts a buffered response: request/latency metrics and the
        lifecycle status annotation on success (the engine already
        terminalized the record — this only fills in HTTP 200), error
        counter otherwise (the terminal state was stamped where the
        failure happened)."""
        if ok:
            self._m_requests["generate"].inc()
            self._m_latency["generate"].observe(
                (time.perf_counter() - t0) * 1000.0)
            rt = _reqtrace.get()
            if rt is not None:
                rt.finish(rid, "finished", status=200)
        else:
            self._m_errors.inc()

    def handle_metrics(self) -> str:
        return self.metrics.render()

    def handle_debug_requests(self):
        """Live flight-recorder view (ISSUE 15): in-flight request
        states + the recent completed ring. 404 while ``--reqTrace`` is
        off — the recorder does not exist, which is itself the
        answer."""
        rt = _reqtrace.get()
        if rt is None:
            return 404, {"enabled": False,
                         "error": "request tracing off (start with "
                                  "--reqTrace on)"}
        return 200, rt.snapshot()

    def handle_debug_slots(self):
        """Decoder slot table + KV page-pool occupancy + batcher queue
        depth — works regardless of ``--reqTrace`` (it reads engine
        state, not lifecycle records). dp mode returns one snapshot per
        replica."""
        if self.replicas is not None:
            return 200, self.replicas.debug_snapshot()
        if self.decoder is not None:
            out = self.decoder.debug_snapshot()
        else:
            out = {"slots": [], "slots_total": 0, "slots_active": 0,
                   "waiting": 0, "kv": {"paged": False}}
        if self.batcher is not None:
            out["batcher"] = {
                "queue_depth": self.batcher.queue_depth,
                "max_queue": self.batcher.max_queue,
                "worker_up": self.batcher.alive()}
        return 200, out

    # ------------------------------------------------------------- dispatch
    def dispatch_post(self, path: str, payload: dict,
                      rid: Optional[str] = None):
        ep = path.strip("/")
        handler = {"predict": self.handle_predict,
                   "generate": self.handle_generate}.get(ep)
        if handler is None:
            return 404, {"error": f"unknown endpoint {path}"}
        # lifecycle record opens at admission (ISSUE 15): even a shed or
        # rejected request leaves an autopsy trail
        rt = _reqtrace.get()
        if rt is not None:
            prompt_n = max_new = None
            if ep == "generate":
                toks = payload.get("tokens")
                if isinstance(toks, (list, tuple)):
                    prompt_n = len(toks)
                try:
                    max_new = int(payload.get("max_new_tokens", 16))
                except (TypeError, ValueError):
                    max_new = None
            rid = rt.admit(ep, rid, prompt_tokens=prompt_n,
                           max_new=max_new)
        if ep == "generate" and self._shed_generate():
            # tiered degradation: /generate sheds first so /predict
            # keeps its admission headroom under overload
            self._m_shed.inc()
            self._m_errors.inc()
            if rt is not None:
                rt.finish(rid, "shed", status=429)
            return 429, {"error": "overloaded: shedding /generate "
                                  "(retry, or use /predict capacity)"}
        t0 = time.perf_counter()
        try:
            _fault_hook("request")  # no-op unless --faultPlan installed
            with _obs_span("request", endpoint=ep):
                status, body = handler(payload, rid=rid)
        except AdmissionError as e:
            self._m_errors.inc()
            if rt is not None:
                rt.finish(rid, "rejected", status=429, error=str(e))
            return 429, {"error": str(e)}
        except DeadlineExceeded as e:
            self._m_expired.inc()
            self._m_errors.inc()
            if rt is not None:
                rt.finish(rid, "expired", status=504, error=str(e))
            return 504, {"error": f"deadline exceeded: {e}"}
        except WorkerDied as e:
            self._m_worker_dead.inc()
            self._m_errors.inc()
            if rt is not None:
                rt.finish(rid, "worker_dead", status=503, error=str(e))
            return 503, {"error": str(e)}
        except TransientFault as e:
            self._m_injected.inc()
            self._m_errors.inc()
            if rt is not None:
                rt.finish(rid, "error", status=503,
                          error=f"injected fault: {e}")
            return 503, {"error": f"injected fault: {e}"}
        except TimeoutError as e:
            self._m_errors.inc()
            if rt is not None:
                rt.finish(rid, "error", status=503, error=str(e))
            return 503, {"error": str(e)}
        except Exception as e:
            logger.exception("/%s failed", ep)
            self._m_errors.inc()
            if rt is not None:
                rt.finish(rid, "error", status=500,
                          error=f"{type(e).__name__}: {e}")
            return 500, {"error": f"{type(e).__name__}: {e}"}
        if status == 200:
            self._m_requests[ep].inc()
            self._m_latency[ep].observe((time.perf_counter() - t0) * 1000.0)
            if rt is not None:
                # decode-path records already finished inside the
                # engine (honest t_finish); this is a no-op there and
                # terminalizes the predict path
                rt.finish(rid, "finished", status=200)
        else:
            self._m_errors.inc()
            if rt is not None:
                rt.finish(rid,
                          "bad_request" if status == 400 else "error",
                          status=status,
                          error=str(body.get("error", "")) or None)
        return status, body

    def close(self) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.batcher is not None:
            self.batcher.close()
        if self.decoder is not None:
            self.decoder.close()
        if self.replicas is not None:
            self.replicas.close()
        rt = _reqtrace.get()
        if rt is not None:
            rt.close()  # flush the access log


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    @property
    def app(self) -> ServingApp:
        return self.server.app  # type: ignore[attr-defined]

    def _rid(self) -> str:
        """The request id echoed on EVERY response (ISSUE 15): a valid
        client-supplied ``x-request-id`` wins (so the caller can join
        server records to its own logs), else one is minted — with or
        without tracing enabled."""
        return (_reqtrace.sanitize_rid(self.headers.get("x-request-id"))
                or _reqtrace.mint_rid())

    def _send_json(self, status: int, body: dict,
                   rid: Optional[str] = None) -> None:
        data = json.dumps(body).encode()
        self.send_response(status)
        if status == 429:
            self.send_header("Retry-After", "1")
        if rid is not None:
            self.send_header("x-request-id", rid)
        version = getattr(self.app, "model_version", None)
        if version:
            self.send_header("x-model-version", str(version))
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 (stdlib naming)
        rid = self._rid()
        if self.path == "/healthz":
            self._send_json(*self.app.handle_healthz(), rid=rid)
        elif self.path == "/readyz":
            self._send_json(*self.app.handle_readyz(), rid=rid)
        elif self.path == "/debug/requests":
            self._send_json(*self.app.handle_debug_requests(), rid=rid)
        elif self.path == "/debug/slots":
            self._send_json(*self.app.handle_debug_slots(), rid=rid)
        elif self.path == "/metrics":
            data = self.app.handle_metrics().encode()
            self.send_response(200)
            self.send_header("x-request-id", rid)
            version = getattr(self.app, "model_version", None)
            if version:
                self.send_header("x-model-version", str(version))
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        elif ("GET", self.path) in getattr(self.app, "extra_routes", {}):
            handler = self.app.extra_routes[("GET", self.path)]
            self._send_json(*handler(None), rid=rid)
        else:
            self._send_json(404, {"error": f"unknown path {self.path}"},
                            rid=rid)

    def do_POST(self):  # noqa: N802
        rid = self._rid()
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            length = 0
        if length <= 0 or length > _MAX_BODY:
            self._send_json(400, {"error": "missing or oversized body"},
                            rid=rid)
            return
        try:
            payload = json.loads(self.rfile.read(length))
            if not isinstance(payload, dict):
                raise ValueError("body must be a JSON object")
        except (ValueError, json.JSONDecodeError) as e:
            self._send_json(400, {"error": f"bad JSON: {e}"}, rid=rid)
            return
        if ("POST", self.path) in getattr(self.app, "extra_routes", {}):
            handler = self.app.extra_routes[("POST", self.path)]
            self._send_json(*handler(payload), rid=rid)
            return
        if self.path.strip("/") == "generate" and payload.get("stream"):
            self._stream_generate(payload, rid)
            return
        status, body = self.app.dispatch_post(self.path, payload,
                                              rid=rid)
        self._send_json(status, body, rid=rid)

    # ------------------------------------------------------------- streaming
    def _write_chunk(self, data: bytes) -> None:
        """One HTTP/1.1 chunked-transfer frame (``b""`` terminates)."""
        self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
        self.wfile.flush()

    @staticmethod
    def _sse(obj: dict) -> bytes:
        return b"data: " + json.dumps(obj).encode() + b"\n\n"

    def _stream_generate(self, payload: dict, rid: str) -> None:
        """Streamed /generate (ISSUE 18): chunked-transfer SSE frames,
        one per emitting decode round (only ACCEPTED tokens under
        ``--speculate``, so concatenating the frames is bit-identical to
        the buffered response), a final ``{"done": true}`` frame, and
        client-disconnect detection — a failed write cancels the slot
        mid-decode, releasing its paged-KV pages back to the
        allocator."""
        app = self.app
        t0 = time.perf_counter()
        status, obj = app.start_generate_stream(payload, rid=rid)
        if status != 200:
            self._send_json(status, obj, rid=rid)
            return
        stream: _GenerateStream = obj
        rt = _reqtrace.get()
        ok = False
        first = True
        n_out = 0
        deadline = time.monotonic() + app.request_timeout_s
        try:
            self.send_response(200)
            self.send_header("x-request-id", rid)
            version = getattr(app, "model_version", None)
            if version:
                self.send_header("x-model-version", str(version))
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Transfer-Encoding", "chunked")
            self.send_header("Connection", "close")
            self.end_headers()
            self.close_connection = True
            while True:
                try:
                    toks, done = stream.queue.get(timeout=0.05)
                except _queue_mod.Empty:
                    if stream.future.done() and stream.queue.empty():
                        # terminated without a final emit: deadline
                        # expiry, cancel, or shutdown — surface the
                        # error as the last frame
                        try:
                            stream.future.result(0)
                            err = "stream ended without tokens"
                        except Exception as e:
                            err = str(e)
                        self._write_chunk(self._sse({"error": err}))
                        break
                    if time.monotonic() > deadline:
                        stream.decoder.cancel(
                            rid, reason="server stream timeout")
                        self._write_chunk(
                            self._sse({"error": "stream timeout"}))
                        break
                    continue
                if first and rt is not None:
                    # first byte is about to hit the wire: THIS is the
                    # TTFT the client feels, and what --slo judges
                    rt.note_first_byte(rid)
                self._write_chunk(self._sse({"tokens": toks}))
                first = False
                n_out += len(toks)
                if done:
                    self._write_chunk(self._sse(
                        {"done": True, "prompt_len": stream.prompt_len,
                         "tokens_out": n_out}))
                    ok = True
                    break
            self._write_chunk(b"")  # terminating chunk
        except (BrokenPipeError, ConnectionResetError, OSError):
            # the client went away mid-stream: free the slot and its KV
            # page reservation NOW instead of decoding into a dead pipe
            stream.decoder.cancel(rid)
        finally:
            app.finish_generate_stream(rid, ok, t0)

    def log_message(self, fmt, *args):  # route access logs to logging
        logger.debug("%s - %s", self.address_string(), fmt % args)


def make_server(app: ServingApp, host: str = "127.0.0.1",
                port: int = 8000) -> ThreadingHTTPServer:
    """Bind (port 0 = ephemeral, for tests) and attach the app; the
    caller runs ``serve_forever`` (or a thread does)."""
    srv = ThreadingHTTPServer((host, port), _Handler)
    srv.daemon_threads = True
    srv.app = app  # type: ignore[attr-defined]
    return srv


def run_server(app: ServingApp, host: str = "127.0.0.1",
               port: int = 8000,
               ready_event: Optional[threading.Event] = None) -> int:
    """Foreground serve loop with clean SIGINT/SIGTERM shutdown (the CI
    smoke asserts exit code 0 after SIGTERM). Returns 0."""
    import signal

    srv = make_server(app, host, port)
    actual = srv.server_address[1]
    logger.info("serving %s on http://%s:%d (/predict /generate /healthz "
                "/readyz /metrics)", app.name, host, actual)
    print(f"serving {app.name} on http://{host}:{actual}", flush=True)

    def _stop(signum, frame):
        # shutdown() must come from another thread than serve_forever's
        threading.Thread(target=srv.shutdown, daemon=True).start()

    prev = {}
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            prev[sig] = signal.signal(sig, _stop)
        except ValueError:  # non-main thread (tests drive make_server)
            pass
    if ready_event is not None:
        ready_event.set()
    try:
        srv.serve_forever(poll_interval=0.2)
    finally:
        for sig, h in prev.items():
            signal.signal(sig, h)
        srv.server_close()
        app.close()
        print("serving shutdown clean", flush=True)
    return 0
