"""Stdlib HTTP serving surface: JSON in, JSON out, no new dependencies.

BigDL 2.0's Cluster Serving put a full streaming stack (Redis + Flink)
in front of the model; the TPU-native equivalent starts smaller and
honest: a ``ThreadingHTTPServer`` (one thread per connection, fine at
micro-batcher concurrency levels) exposing

* ``POST /predict``  — ``{"inputs": [...]}`` -> argmax predictions
  (scores on request), routed through the dynamic micro-batcher so
  concurrent callers share bucketed forwards;
* ``POST /generate`` — ``{"tokens": [...], "max_new_tokens": N}`` ->
  generated token ids from the continuous-batching KV-cache decoder
  (LM models only);
* ``GET /healthz``   — liveness;
* ``GET /metrics``   — plaintext counters/histograms with the serving
  config provenance stamped into every scrape.

Error contract: malformed JSON/fields -> 400, admission rejection
(queue full) -> 429 with ``Retry-After``, engine failure -> 500; every
error body is ``{"error": ...}``.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from bigdl_tpu.serving.batcher import AdmissionError

logger = logging.getLogger(__name__)

__all__ = ["ServingApp", "make_server", "run_server"]

_MAX_BODY = 64 * 1024 * 1024  # refuse absurd payloads before np.asarray


class ServingApp:
    """The wiring between HTTP handlers and the serving stack: engine
    (+ optional batcher) for /predict, decoder for /generate, one
    metrics registry for everything. Endpoint handlers return
    ``(status, payload_dict)`` so they are unit-testable without
    sockets."""

    def __init__(self, *, name: str, metrics, engine=None, batcher=None,
                 decoder=None, request_timeout_s: float = 120.0):
        self.name = name
        self.metrics = metrics
        self.engine = engine
        self.batcher = batcher
        self.decoder = decoder
        self.request_timeout_s = float(request_timeout_s)
        self._m_requests = {
            ep: metrics.counter(f"requests_{ep}_total",
                                f"completed /{ep} requests")
            for ep in ("predict", "generate")}
        self._m_errors = metrics.counter(
            "request_errors_total", "requests answered 4xx/5xx")
        self._m_latency = {
            ep: metrics.histogram(f"latency_{ep}_ms",
                                  f"/{ep} request latency (receipt to "
                                  f"response ready)")
            for ep in ("predict", "generate")}

    # ------------------------------------------------------------ endpoints
    def handle_healthz(self):
        return 200, {"status": "ok", "model": self.name}

    def handle_predict(self, payload: dict):
        if self.engine is None:
            return 400, {"error": "no /predict engine for this model"}
        inputs = payload.get("inputs")
        if inputs is None:
            return 400, {"error": "missing 'inputs'"}
        try:
            x = np.asarray(inputs)
            if x.dtype == object:
                raise ValueError("ragged inputs")
            if np.issubdtype(x.dtype, np.floating):
                x = x.astype(np.float32)
            elif np.issubdtype(x.dtype, np.integer):
                x = x.astype(np.int32)
            else:
                raise ValueError(f"unsupported dtype {x.dtype}")
        except ValueError as e:
            return 400, {"error": f"bad inputs: {e}"}
        if x.ndim < 2:
            return 400, {"error": "inputs must be a batch (rows on "
                                  "axis 0)"}
        if self.batcher is not None:
            futs = [self.batcher.submit(row) for row in x]
            scores = np.stack([f.result(self.request_timeout_s)
                               for f in futs])
        else:
            scores = self.engine.predict_scores(x)
        preds = np.argmax(scores, axis=-1)
        out = {"predictions": preds.tolist()}
        if payload.get("return_scores"):
            out["scores"] = np.asarray(scores, np.float64).tolist()
        return 200, out

    def handle_generate(self, payload: dict):
        if self.decoder is None:
            return 400, {"error": "no /generate decoder for this model "
                                  "(serve a transformer_lm* model)"}
        tokens = payload.get("tokens")
        if (not isinstance(tokens, (list, tuple)) or not tokens
                or not all(isinstance(t, int) for t in tokens)):
            return 400, {"error": "'tokens' must be a non-empty list of "
                                  "ints"}
        max_new = payload.get("max_new_tokens", 16)
        temperature = payload.get("temperature", 0.0)
        stop = payload.get("stop_token")
        try:
            fut = self.decoder.submit(tokens, max_new, temperature, stop)
        except ValueError as e:
            return 400, {"error": str(e)}
        out_tokens = fut.result(self.request_timeout_s)
        return 200, {"tokens": out_tokens,
                     "prompt_len": len(tokens)}

    def handle_metrics(self) -> str:
        return self.metrics.render()

    # ------------------------------------------------------------- dispatch
    def dispatch_post(self, path: str, payload: dict):
        ep = path.strip("/")
        handler = {"predict": self.handle_predict,
                   "generate": self.handle_generate}.get(ep)
        if handler is None:
            return 404, {"error": f"unknown endpoint {path}"}
        import time
        t0 = time.perf_counter()
        try:
            status, body = handler(payload)
        except AdmissionError as e:
            self._m_errors.inc()
            return 429, {"error": str(e)}
        except TimeoutError as e:
            self._m_errors.inc()
            return 503, {"error": str(e)}
        except Exception as e:
            logger.exception("/%s failed", ep)
            self._m_errors.inc()
            return 500, {"error": f"{type(e).__name__}: {e}"}
        if status == 200:
            self._m_requests[ep].inc()
            self._m_latency[ep].observe((time.perf_counter() - t0) * 1000.0)
        else:
            self._m_errors.inc()
        return status, body

    def close(self) -> None:
        if self.batcher is not None:
            self.batcher.close()
        if self.decoder is not None:
            self.decoder.close()


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    @property
    def app(self) -> ServingApp:
        return self.server.app  # type: ignore[attr-defined]

    def _send_json(self, status: int, body: dict) -> None:
        data = json.dumps(body).encode()
        self.send_response(status)
        if status == 429:
            self.send_header("Retry-After", "1")
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 (stdlib naming)
        if self.path == "/healthz":
            self._send_json(*self.app.handle_healthz())
        elif self.path == "/metrics":
            data = self.app.handle_metrics().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        else:
            self._send_json(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):  # noqa: N802
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            length = 0
        if length <= 0 or length > _MAX_BODY:
            self._send_json(400, {"error": "missing or oversized body"})
            return
        try:
            payload = json.loads(self.rfile.read(length))
            if not isinstance(payload, dict):
                raise ValueError("body must be a JSON object")
        except (ValueError, json.JSONDecodeError) as e:
            self._send_json(400, {"error": f"bad JSON: {e}"})
            return
        self._send_json(*self.app.dispatch_post(self.path, payload))

    def log_message(self, fmt, *args):  # route access logs to logging
        logger.debug("%s - %s", self.address_string(), fmt % args)


def make_server(app: ServingApp, host: str = "127.0.0.1",
                port: int = 8000) -> ThreadingHTTPServer:
    """Bind (port 0 = ephemeral, for tests) and attach the app; the
    caller runs ``serve_forever`` (or a thread does)."""
    srv = ThreadingHTTPServer((host, port), _Handler)
    srv.daemon_threads = True
    srv.app = app  # type: ignore[attr-defined]
    return srv


def run_server(app: ServingApp, host: str = "127.0.0.1",
               port: int = 8000,
               ready_event: Optional[threading.Event] = None) -> int:
    """Foreground serve loop with clean SIGINT/SIGTERM shutdown (the CI
    smoke asserts exit code 0 after SIGTERM). Returns 0."""
    import signal

    srv = make_server(app, host, port)
    actual = srv.server_address[1]
    logger.info("serving %s on http://%s:%d (/predict /generate /healthz "
                "/metrics)", app.name, host, actual)
    print(f"serving {app.name} on http://{host}:{actual}", flush=True)

    def _stop(signum, frame):
        # shutdown() must come from another thread than serve_forever's
        threading.Thread(target=srv.shutdown, daemon=True).start()

    prev = {}
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            prev[sig] = signal.signal(sig, _stop)
        except ValueError:  # non-main thread (tests drive make_server)
            pass
    if ready_event is not None:
        ready_event.set()
    try:
        srv.serve_forever(poll_interval=0.2)
    finally:
        for sig, h in prev.items():
            signal.signal(sig, h)
        srv.server_close()
        app.close()
        print("serving shutdown clean", flush=True)
    return 0
