"""Speculative decoding primitives for the serving decoder (ISSUE 14).

A small DRAFT ``transformer_lm`` proposes K tokens with K cheap
single-token steps; the TARGET model then scores all K (plus the bonus
position) in ONE chunked dispatch (``TransformerLM.verify_logits``) and
an exact acceptance rule decides how many proposals stand. Per emitted
token the target runs ``1/(accepted+1)`` dispatches instead of 1 — the
whole win; nothing about the output distribution changes:

* **greedy** (temperature 0): proposal j is accepted iff it equals the
  target argmax at its position, the first rejection is replaced by that
  argmax, and a full acceptance appends the bonus argmax — token for
  token the sequence the non-speculative greedy loop emits (acceptance
  criterion; pinned bit-identical in tests/test_spec_decode.py);
* **sampled**: classic speculative rejection sampling (Leviathan et al.
  / Chen et al.): accept proposal ``d ~ q`` with prob ``min(1, p(d) /
  q(d))``, on rejection resample from ``normalize(max(p - q, 0))``, on
  full acceptance sample the bonus from ``p`` — the emitted tokens are
  distributed EXACTLY as if sampled from the target alone (distribution
  check under fixed seeds in tests).

All randomness is counter-based off the per-request seed:
``fold_in(fold_in(PRNGKey(seed), position), stream_tag)`` — replayable,
order-independent, and disjoint between the draft-proposal, acceptance
and residual streams. The same ``warp_logits`` implements the plain
path's temperature/top-k/top-p (satellite: finish sampling modes), so
speculative-off sampling uses byte-identical warping.

Everything here is pure and trace-safe (top-k/top-p arrive as traced
per-slot scalars; sentinels ``top_k=0`` / ``top_p>=1`` disable exactly —
the keep-mask is all-True, so disabled warping is bitwise a no-op).
"""

from __future__ import annotations

__all__ = ["warp_logits", "sample_token", "request_key", "draft_propose",
           "accept_chunk", "parse_draft_dims", "STREAM_STEP",
           "STREAM_DRAFT", "STREAM_ACCEPT", "STREAM_RESIDUAL"]

# stream tags folded into per-request keys so the four consumers of
# randomness never share a counter
STREAM_STEP = 0        # plain-path / bonus sampling at a position
STREAM_DRAFT = 1       # draft proposal sampling
STREAM_ACCEPT = 2      # acceptance uniforms
STREAM_RESIDUAL = 3    # rejection-residual resampling


def request_key(seed, pos, stream=STREAM_STEP):
    """Per-(request, position, stream) PRNG key. Deterministic given the
    request seed — the satellite contract: same seed, same output."""
    import jax

    base = jax.random.PRNGKey(seed)
    return jax.random.fold_in(jax.random.fold_in(base, pos), stream)


def warp_logits(logits, temp, top_k, top_p):
    """Temperature + top-k + top-p warp of a (vocab,) logit vector with
    TRACED knobs (one compiled program serves every request mix).

    ``top_k == 0`` and ``top_p >= 1`` disable their filters exactly
    (all-True keep mask -> output is bitwise ``logits / temp``). Both
    filters share one descending sort; thresholds replace
    ``lax.top_k`` because k is traced. ``temp <= 0`` is passed through
    un-scaled (greedy callers argmax raw logits anyway)."""
    import jax
    import jax.numpy as jnp

    v = logits.shape[-1]
    safe_t = jnp.where(temp > 0, temp, 1.0)
    lg = logits / safe_t
    srt = jnp.sort(lg)[::-1]                              # descending
    # top-k: keep logits >= k-th largest (k traced; 0 -> vocab)
    k = jnp.clip(jnp.where(top_k > 0, top_k, v), 1, v).astype(jnp.int32)
    kth = srt[k - 1]
    keep = lg >= kth
    # top-p: smallest prefix of descending probs whose mass reaches p
    prob = jax.nn.softmax(srt)
    csum = jnp.cumsum(prob)
    p = jnp.clip(top_p, 0.0, 1.0)
    nucleus = (csum - prob) < p                           # head always in
    n_keep = jnp.maximum(jnp.sum(nucleus.astype(jnp.int32)), 1)
    pth = srt[n_keep - 1]
    keep &= jnp.where(top_p >= 1.0, True, lg >= pth)
    return jnp.where(keep, lg, -1e30)


def sample_token(logits, temp, top_k, top_p, key):
    """One token from a (vocab,) logit vector: argmax when ``temp <= 0``
    (raw logits — the greedy contract predates warping and stays
    bit-identical), else categorical over the warped logits."""
    import jax
    import jax.numpy as jnp

    greedy = jnp.argmax(logits).astype(jnp.int32)
    warped = warp_logits(logits, temp, top_k, top_p)
    sampled = jax.random.categorical(key, warped).astype(jnp.int32)
    return jnp.where(temp > 0, sampled, greedy)


def draft_propose(logits, temp, top_k, top_p, seed, pos):
    """Draft-side proposal at ``pos``: (token, q) where q is the warped
    draft distribution the acceptance test needs. Greedy slots propose
    the draft argmax (q unused there)."""
    import jax
    import jax.numpy as jnp

    warped = warp_logits(logits, temp, top_k, top_p)
    q = jax.nn.softmax(warped)
    key = request_key(seed, pos, STREAM_DRAFT)
    sampled = jax.random.categorical(key, warped).astype(jnp.int32)
    greedy = jnp.argmax(logits).astype(jnp.int32)
    return jnp.where(temp > 0, sampled, greedy), q


def accept_chunk(target_logits, draft_q, proposals, temp, top_k, top_p,
                 seed, pos):
    """Exact acceptance for ONE slot's verified chunk.

    ``target_logits``: (m, vocab) f32 — row j is the target's
    distribution after the first j+1 chunk feeds (feed 0 is the pending
    token, feeds 1..m-1 are the proposals). ``draft_q``: (m-1, vocab)
    warped draft distributions each proposal was drawn from.
    ``proposals``: (m-1,) int32. Returns ``(emitted, n_emit, n_accept)``
    — ``emitted[:n_emit]`` is the token stream this round appends
    (accepted proposals + one correction/bonus), ``n_accept`` the
    accepted-proposal count feeding the ``spec_accept_rate`` gauge.
    Designed for use under ``jax.vmap`` over slots."""
    import jax
    import jax.numpy as jnp

    m = target_logits.shape[0]
    greedy = jnp.argmax(target_logits, axis=-1).astype(jnp.int32)  # (m,)
    warped = jax.vmap(warp_logits, in_axes=(0, None, None, None))(
        target_logits, temp, top_k, top_p)
    p = jax.nn.softmax(warped, axis=-1)                           # (m, v)
    j = jnp.arange(m - 1)
    p_d = p[j, proposals]
    q_d = draft_q[j, proposals]
    u = jax.random.uniform(request_key(seed, pos, STREAM_ACCEPT), (m - 1,))
    ok_sampled = u * jnp.maximum(q_d, 1e-30) < p_d
    ok_greedy = proposals == greedy[: m - 1]
    ok = jnp.where(temp > 0, ok_sampled, ok_greedy)
    # accepted prefix length: first rejection stops everything after it
    n_accept = jnp.sum(jnp.cumprod(ok.astype(jnp.int32))).astype(jnp.int32)
    # correction (first rejection) / bonus (full acceptance) token: the
    # residual distribution is max(p - q, 0) renormalized; on full
    # acceptance the "draft row" is all-zero so the residual IS p — one
    # formula covers both
    q_pad = jnp.concatenate(
        [draft_q, jnp.zeros_like(draft_q[:1])], axis=0)            # (m, v)
    p_a = p[n_accept]
    resid = jnp.maximum(p_a - q_pad[n_accept], 0.0)
    rs = jnp.sum(resid)
    resid = jnp.where(rs > 0, resid / rs, p_a)
    r_key = jax.random.fold_in(
        request_key(seed, pos, STREAM_RESIDUAL), n_accept)
    extra_sampled = jax.random.categorical(
        r_key, jnp.log(jnp.maximum(resid, 1e-38))).astype(jnp.int32)
    extra = jnp.where(temp > 0, extra_sampled, greedy[n_accept])
    # emitted stream: proposals[:n_accept] then the correction/bonus
    prop_pad = jnp.concatenate(
        [proposals, jnp.zeros((1,), jnp.int32)], axis=0)           # (m,)
    idx = jnp.arange(m)
    emitted = jnp.where(idx < n_accept, prop_pad,
                        jnp.where(idx == n_accept, extra, 0))
    return emitted, n_accept + 1, n_accept


def parse_draft_dims(spec: str):
    """``--draftDims d_model,num_layers,num_heads`` -> dict of
    transformer_lm kwargs for the draft model."""
    parts = [int(x) for x in str(spec).split(",")]
    if len(parts) != 3:
        raise ValueError(
            f"--draftDims wants d_model,num_layers,num_heads; got {spec!r}")
    d_model, num_layers, num_heads = parts
    if d_model % num_heads:
        raise ValueError(f"draft d_model {d_model} must be divisible by "
                         f"num_heads {num_heads}")
    return {"d_model": d_model, "num_layers": num_layers,
            "num_heads": num_heads}
