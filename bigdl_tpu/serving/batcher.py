"""Dynamic micro-batching with backpressure-based admission control.

Online traffic arrives one row at a time; the accelerator wants bucketed
batches (serving/engine.py). The micro-batcher sits between: requests
queue, a worker thread flushes a batch when either ``max_batch`` rows
are waiting (throughput trigger) or the OLDEST row has waited
``max_wait_ms`` (latency trigger), and the engine's bucket padding turns
whatever was gathered into a compiled shape. This is the standard
dynamic-batching contract (TF-Serving/Triton); the BigDL lineage analog
is the DLClassifier's per-partition batching, which had Spark to do the
gathering — here a queue + worker thread replace the RDD machinery.

Admission control is backpressure by queue depth: when ``max_queue``
rows are already pending, ``submit`` raises :class:`AdmissionError`
IMMEDIATELY (fast-reject) instead of letting latency grow without bound
— the caller (server.py) maps it to HTTP 429 so load sheds at the edge.

Determinism for tests: the flush decision is a pure function of the
injected ``clock`` (``_flush_ready``/``pump``), so the trigger semantics
are testable without threads or real time.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Optional

import numpy as np

__all__ = ["AdmissionError", "MicroBatcher"]


class AdmissionError(RuntimeError):
    """Queue at capacity — request rejected at admission (HTTP 429)."""


class _Future:
    """Minimal thread-safe future (no concurrent.futures executor to
    own it — the batcher resolves it from its worker thread)."""

    __slots__ = ("_event", "_value", "_exc")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._exc = None

    def set_result(self, v) -> None:
        self._value = v
        self._event.set()

    def set_exception(self, e: BaseException) -> None:
        self._exc = e
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("batched request did not complete in time")
        if self._exc is not None:
            raise self._exc
        return self._value


class _Pending:
    __slots__ = ("row", "future", "t_enqueue")

    def __init__(self, row, future, t):
        self.row, self.future, self.t_enqueue = row, future, t


class MicroBatcher:
    """Gather single-row requests into engine batches.

    ``predict_fn(batch_rows) -> scores`` is typically
    ``engine.predict_scores``; rows of one flush are stacked along axis
    0 and results are split back per request.

    ``clock`` is injectable (monotonic seconds) for deterministic tests;
    with ``start=False`` no worker thread runs and the test drives
    :meth:`pump` manually.
    """

    def __init__(self, predict_fn: Callable, *, max_batch: int = 32,
                 max_wait_ms: float = 5.0, max_queue: int = 256,
                 clock: Callable[[], float] = time.monotonic,
                 metrics=None, start: bool = True):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.predict_fn = predict_fn
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1000.0
        self.max_queue = int(max_queue)
        self.clock = clock
        self._pending: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._wakeup = threading.Condition(self._lock)
        self._closed = False
        self._thread = None

        if metrics is not None:
            self._m_submitted = metrics.counter(
                "batcher_rows_submitted_total", "rows accepted by submit")
            self._m_rejected = metrics.counter(
                "batcher_rows_rejected_total",
                "rows fast-rejected at admission (queue full)")
            self._m_flushes = metrics.counter(
                "batcher_flushes_total", "micro-batches dispatched")
            self._m_wait = metrics.histogram(
                "batcher_queue_wait_ms", "enqueue -> flush wait per row")
            metrics.gauge("batcher_queue_depth", "rows currently queued",
                          fn=lambda: len(self._pending))
        else:
            self._m_submitted = self._m_rejected = self._m_flushes = None
            self._m_wait = None

        if start:
            self._thread = threading.Thread(target=self._worker,
                                            name="micro-batcher",
                                            daemon=True)
            self._thread.start()

    # --------------------------------------------------------------- submit
    def submit(self, row) -> _Future:
        """Queue one input row; returns a future resolving to its score
        row. Raises :class:`AdmissionError` without blocking when the
        queue is at ``max_queue`` (backpressure fast-reject)."""
        fut = _Future()
        with self._lock:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if len(self._pending) >= self.max_queue:
                if self._m_rejected is not None:
                    self._m_rejected.inc()
                raise AdmissionError(
                    f"queue at capacity ({self.max_queue} rows pending)")
            self._pending.append(_Pending(row, fut, self.clock()))
            if self._m_submitted is not None:
                self._m_submitted.inc()
            self._wakeup.notify()
        return fut

    @property
    def queue_depth(self) -> int:
        return len(self._pending)

    # ---------------------------------------------------------- flush logic
    def _flush_ready(self, now: float) -> bool:
        """Pure trigger decision: full batch waiting, or the oldest row
        has aged past max_wait."""
        if not self._pending:
            return False
        if len(self._pending) >= self.max_batch:
            return True
        return (now - self._pending[0].t_enqueue) >= self.max_wait_s

    def _drain(self) -> list:
        batch = []
        while self._pending and len(batch) < self.max_batch:
            batch.append(self._pending.popleft())
        return batch

    def _flush(self, batch: list, now: float) -> None:
        if self._m_wait is not None:
            for p in batch:
                self._m_wait.observe((now - p.t_enqueue) * 1000.0)
        try:
            scores = self.predict_fn(
                np.stack([np.asarray(p.row) for p in batch]))
        except BaseException as e:  # resolve every waiter, never hang them
            for p in batch:
                p.future.set_exception(e)
            return
        if self._m_flushes is not None:
            self._m_flushes.inc()
        for p, s in zip(batch, np.asarray(scores)):
            p.future.set_result(s)

    def pump(self, now: Optional[float] = None) -> int:
        """Flush at most one micro-batch if a trigger fired; returns the
        number of rows flushed. The worker thread calls this in a loop;
        tests call it directly with an injected ``now``."""
        now = self.clock() if now is None else now
        with self._lock:
            if not self._flush_ready(now):
                return 0
            batch = self._drain()
        # engine call happens OUTSIDE the lock: submits stay wait-free
        # while the forward runs
        self._flush(batch, now)
        return len(batch)

    # --------------------------------------------------------------- worker
    def _worker(self) -> None:
        while True:
            with self._lock:
                while not self._pending and not self._closed:
                    self._wakeup.wait()
                if self._closed and not self._pending:
                    return
                now = self.clock()
                if not self._flush_ready(now):
                    # sleep until the oldest row's deadline (or an earlier
                    # submit fills the batch and notifies)
                    deadline = self._pending[0].t_enqueue + self.max_wait_s
                    self._wakeup.wait(timeout=max(deadline - now, 0.0))
                    continue
                batch = self._drain()
            self._flush(batch, self.clock())

    def close(self, timeout: float = 10.0) -> None:
        """Stop accepting work, flush what is queued, join the worker."""
        with self._lock:
            self._closed = True
            self._wakeup.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        # no worker (tests / start=False): drain synchronously
        while self._pending:
            with self._lock:
                batch = self._drain()
            if batch:
                self._flush(batch, self.clock())
