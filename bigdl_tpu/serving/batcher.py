"""Dynamic micro-batching with backpressure-based admission control,
per-row deadlines, and dead-worker fast-fail.

Online traffic arrives one row at a time; the accelerator wants bucketed
batches (serving/engine.py). The micro-batcher sits between: requests
queue, a worker thread flushes a batch when either ``max_batch`` rows
are waiting (throughput trigger) or the OLDEST row has waited
``max_wait_ms`` (latency trigger), and the engine's bucket padding turns
whatever was gathered into a compiled shape. This is the standard
dynamic-batching contract (TF-Serving/Triton); the BigDL lineage analog
is the DLClassifier's per-partition batching, which had Spark to do the
gathering — here a queue + worker thread replace the RDD machinery.

Admission control is backpressure by queue depth: when ``max_queue``
rows are already pending, ``submit`` raises :class:`AdmissionError`
IMMEDIATELY (fast-reject) instead of letting latency grow without bound
— the caller (server.py) maps it to HTTP 429 so load sheds at the edge.

Robustness (ISSUE 6):

* **deadlines** — ``submit(row, deadline=t)`` marks the row with an
  absolute expiry on the batcher's clock; expired rows are dropped at
  drain time BEFORE any compute is spent on them (and a row already
  past its deadline is rejected at submit), resolving their futures
  with :class:`DeadlineExceeded` — server.py maps it to HTTP 504;
* **dead-worker fast-fail** — if the worker thread dies (a
  ``worker_fatal`` exception out of the engine, or any bug in the loop
  itself), every pending future is failed with :class:`WorkerDied` and
  subsequent ``submit`` calls raise it immediately, instead of
  enqueueing into a queue nobody drains until the caller's own timeout;
* **deterministic close** — ``close()`` either flushes every pending
  row (live worker / no worker) or fails them all with
  :class:`WorkerDied` (dead or wedged worker); nothing is left hanging;
* **watchdog surface** — ``alive()``/``busy()``/``heartbeat_age()``/
  ``declare_dead()`` let serving/watchdog.py detect a wedged (alive but
  stuck) worker and fail it fast.

Determinism for tests: the flush decision is a pure function of the
injected ``clock`` (``_flush_ready``/``pump``), so trigger, deadline,
and expiry semantics are testable without threads or real time.
"""

from __future__ import annotations

import collections
import inspect
import threading
import time
from typing import Callable, Optional

import numpy as np

from bigdl_tpu.obs.spans import (get_tracer as _get_tracer,
                                 span as _obs_span)
from bigdl_tpu.serving.reqtrace import get as _get_reqtracer

__all__ = ["AdmissionError", "DeadlineExceeded", "WorkerDied",
           "MicroBatcher"]


class AdmissionError(RuntimeError):
    """Queue at capacity — request rejected at admission (HTTP 429)."""


class DeadlineExceeded(RuntimeError):
    """Request deadline expired before compute (HTTP 504) — the row was
    dropped unprocessed, so the caller may safely retry elsewhere."""


class WorkerDied(RuntimeError):
    """The serving worker thread is dead or wedged; the request failed
    fast instead of waiting out its timeout (HTTP 503)."""


class _Future:
    """Minimal thread-safe future (no concurrent.futures executor to
    own it — the batcher resolves it from its worker thread)."""

    __slots__ = ("_event", "_value", "_exc")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._exc = None

    def set_result(self, v) -> None:
        self._value = v
        self._event.set()

    def set_exception(self, e: BaseException) -> None:
        self._exc = e
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("batched request did not complete in time")
        if self._exc is not None:
            raise self._exc
        return self._value


class _Pending:
    __slots__ = ("row", "future", "t_enqueue", "deadline", "rid")

    def __init__(self, row, future, t, deadline=None, rid=None):
        self.row, self.future, self.t_enqueue = row, future, t
        self.deadline = deadline
        self.rid = rid


class MicroBatcher:
    """Gather single-row requests into engine batches.

    ``predict_fn(batch_rows) -> scores`` is typically
    ``engine.predict_scores``; rows of one flush are stacked along axis
    0 and results are split back per request.

    ``clock`` is injectable (monotonic seconds) for deterministic tests;
    with ``start=False`` no worker thread runs and the test drives
    :meth:`pump` manually.
    """

    def __init__(self, predict_fn: Callable, *, max_batch: int = 32,
                 max_wait_ms: float = 5.0, max_queue: int = 256,
                 clock: Callable[[], float] = time.monotonic,
                 metrics=None, start: bool = True):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.predict_fn = predict_fn
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1000.0
        self.max_queue = int(max_queue)
        self.clock = clock
        self._pending: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._wakeup = threading.Condition(self._lock)
        self._closed = False
        self._thread = None
        self._worker_error: Optional[BaseException] = None
        self._last_beat = clock()
        self._in_flush = False
        # ISSUE 15: when the engine forward can attribute compute back
        # to request ids (engine.predict_scores grew a ``rids`` kwarg),
        # forward them; a plain fn gets a coarse whole-flush window
        try:
            self._fn_takes_rids = "rids" in inspect.signature(
                predict_fn).parameters
        except (TypeError, ValueError):
            self._fn_takes_rids = False

        if metrics is not None:
            self._m_submitted = metrics.counter(
                "batcher_rows_submitted_total", "rows accepted by submit")
            self._m_rejected = metrics.counter(
                "batcher_rows_rejected_total",
                "rows fast-rejected at admission (queue full)")
            self._m_expired = metrics.counter(
                "batcher_rows_expired_total",
                "rows dropped before compute (deadline exceeded)")
            self._m_dead = metrics.counter(
                "batcher_dead_submit_total",
                "submits fast-failed because the worker is dead")
            self._m_flushes = metrics.counter(
                "batcher_flushes_total", "micro-batches dispatched")
            self._m_wait = metrics.histogram(
                "batcher_queue_wait_ms", "enqueue -> flush wait per row")
            metrics.gauge("batcher_queue_depth", "rows currently queued",
                          fn=lambda: len(self._pending))
            metrics.gauge("batcher_worker_up",
                          "1 while the flush worker is healthy",
                          fn=lambda: 0.0 if self._worker_error else 1.0)
        else:
            self._m_submitted = self._m_rejected = self._m_flushes = None
            self._m_expired = self._m_dead = self._m_wait = None

        if start:
            self._thread = threading.Thread(target=self._worker,
                                            name="micro-batcher",
                                            daemon=True)
            self._thread.start()

    # --------------------------------------------------------------- submit
    def submit(self, row, deadline: Optional[float] = None,
               rid: Optional[str] = None) -> _Future:
        """Queue one input row; returns a future resolving to its score
        row. ``deadline`` is an absolute time on the batcher's clock —
        rows past it are dropped before compute (future raises
        :class:`DeadlineExceeded`). ``rid`` tags the row with its
        request id for lifecycle tracing (ISSUE 15); None when tracing
        is off. Raises :class:`AdmissionError` without blocking when
        the queue is at ``max_queue`` (backpressure fast-reject) and
        :class:`WorkerDied` when the worker thread is gone (nothing
        would ever drain the queue)."""
        fut = _Future()
        now = self.clock()
        with self._lock:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if self._worker_error is not None or (
                    self._thread is not None
                    and not self._thread.is_alive()):
                if self._m_dead is not None:
                    self._m_dead.inc()
                raise WorkerDied(
                    "micro-batcher worker is dead: "
                    f"{self._worker_error or 'thread exited'}")
            if deadline is not None and now >= deadline:
                if self._m_expired is not None:
                    self._m_expired.inc()
                raise DeadlineExceeded(
                    f"deadline expired {now - deadline:.3f}s before "
                    f"submit")
            if len(self._pending) >= self.max_queue:
                if self._m_rejected is not None:
                    self._m_rejected.inc()
                raise AdmissionError(
                    f"queue at capacity ({self.max_queue} rows pending)")
            self._pending.append(_Pending(row, fut, now, deadline, rid))
            if self._m_submitted is not None:
                self._m_submitted.inc()
            self._wakeup.notify()
        if rid is not None:
            rt = _get_reqtracer()
            if rt is not None:
                rt.note_queued(rid)
        return fut

    @property
    def queue_depth(self) -> int:
        return len(self._pending)

    # ------------------------------------------------------ watchdog surface
    def alive(self) -> bool:
        """False once the worker thread has died or been declared dead
        (threadless test mode counts as alive — pump() is the worker)."""
        if self._worker_error is not None:
            return False
        return self._thread is None or self._thread.is_alive()

    def busy(self) -> bool:
        """True while there is work a healthy worker should be making
        progress on (queued rows or an in-flight flush)."""
        return bool(self._pending) or self._in_flush

    def heartbeat_age(self, now: Optional[float] = None) -> float:
        """Seconds since the worker last proved liveness."""
        return (self.clock() if now is None else now) - self._last_beat

    @property
    def worker_error(self) -> Optional[BaseException]:
        return self._worker_error

    def declare_dead(self, exc: BaseException) -> None:
        """Mark the worker dead (watchdog verdict on a wedged thread, or
        the worker's own epitaph): every pending future fails with
        ``exc`` and subsequent submits raise :class:`WorkerDied` fast."""
        with self._lock:
            if self._worker_error is None:
                self._worker_error = exc
            dead = list(self._pending)
            self._pending.clear()
            self._wakeup.notify_all()
        for p in dead:
            p.future.set_exception(
                exc if isinstance(exc, WorkerDied)
                else WorkerDied(f"micro-batcher worker died: {exc}"))

    # ---------------------------------------------------------- flush logic
    def _flush_ready(self, now: float) -> bool:
        """Pure trigger decision: full batch waiting, the oldest row has
        aged past max_wait, or expired rows need dropping."""
        if not self._pending:
            return False
        if len(self._pending) >= self.max_batch:
            return True
        head = self._pending[0]
        if head.deadline is not None and now >= head.deadline:
            return True
        return (now - head.t_enqueue) >= self.max_wait_s

    def _drain(self, now: float) -> list:
        """Pop up to max_batch live rows, expiring dead-on-arrival ones
        (deadline passed) BEFORE any compute is spent on them."""
        batch = []
        while self._pending and len(batch) < self.max_batch:
            p = self._pending.popleft()
            if p.deadline is not None and now >= p.deadline:
                if self._m_expired is not None:
                    self._m_expired.inc()
                p.future.set_exception(DeadlineExceeded(
                    f"deadline expired {now - p.deadline:.3f}s before "
                    f"compute (queued {now - p.t_enqueue:.3f}s)"))
                continue
            batch.append(p)
        return batch

    def _flush(self, batch: list, now: float) -> None:
        if not batch:
            return
        if self._m_wait is not None:
            for p in batch:
                self._m_wait.observe((now - p.t_enqueue) * 1000.0)
        tr = _get_tracer()
        if tr is not None:
            # queue wait is retrospective (enqueue happened on another
            # thread): back-date one span PER ROW so every request's
            # wait — not just the oldest's — lands on the timeline, and
            # the request-path reads queue_wait -> batch_assembly ->
            # compute (per-row accounting: ISSUE 15 satellite)
            t1 = tr.clock()
            for p in batch:
                args = {"rows": len(batch)}
                if p.rid is not None:
                    args["rid"] = p.rid
                tr.record("queue_wait",
                          t1 - max(now - p.t_enqueue, 0.0), t1,
                          depth=0, args=args)
        rt = _get_reqtracer()
        if rt is not None:
            for p in batch:
                if p.rid is not None:
                    rt.note_dequeued(p.rid)
        rids = None
        if rt is not None and self._fn_takes_rids:
            rids = [p.rid for p in batch]
        try:
            # queue_wait ended at drain; assembly (stack) and compute
            # (engine forward) are the next spans on the request path
            with _obs_span("batch_assembly", rows=len(batch)):
                stacked = np.stack([np.asarray(p.row) for p in batch])
            with _obs_span("compute", rows=len(batch)):
                if rids is not None:
                    scores = self.predict_fn(stacked, rids=rids)
                else:
                    t0c = rt.clock() if rt is not None else 0.0
                    scores = self.predict_fn(stacked)
                    if rt is not None:
                        t1c = rt.clock()
                        for p in batch:
                            if p.rid is not None:
                                rt.note_compute(p.rid, t0c, t1c)
        except BaseException as e:  # resolve every waiter, never hang them
            for p in batch:
                p.future.set_exception(e)
            if getattr(e, "worker_fatal", False):
                raise  # fatal to the WORKER: die so submits fast-fail
            return
        if self._m_flushes is not None:
            self._m_flushes.inc()
        for p, s in zip(batch, np.asarray(scores)):
            p.future.set_result(s)

    def pump(self, now: Optional[float] = None) -> int:
        """Flush at most one micro-batch if a trigger fired; returns the
        number of rows flushed (expired rows count — they were resolved).
        The worker thread calls this in a loop; tests call it directly
        with an injected ``now``."""
        now = self.clock() if now is None else now
        with self._lock:
            if not self._flush_ready(now):
                return 0
            depth0 = len(self._pending)
            batch = self._drain(now)
            settled = depth0 - len(self._pending)  # flushed + expired
        # engine call happens OUTSIDE the lock: submits stay wait-free
        # while the forward runs
        self._flush(batch, now)
        return settled

    # --------------------------------------------------------------- worker
    def _worker(self) -> None:
        try:
            while True:
                with self._lock:
                    self._last_beat = self.clock()
                    while not self._pending and not self._closed:
                        self._wakeup.wait()
                        self._last_beat = self.clock()
                    if self._closed and not self._pending:
                        return
                    now = self.clock()
                    if not self._flush_ready(now):
                        # sleep until the oldest row's deadline (or an
                        # earlier submit fills the batch and notifies)
                        head = self._pending[0]
                        wake = head.t_enqueue + self.max_wait_s
                        if head.deadline is not None:
                            wake = min(wake, head.deadline)
                        self._wakeup.wait(timeout=max(wake - now, 0.0))
                        continue
                    batch = self._drain(now)
                    self._in_flush = True
                try:
                    self._flush(batch, self.clock())
                finally:
                    self._in_flush = False
        except BaseException as e:
            # the worker is the only drain: record the cause, fail every
            # waiter, and let submit() fast-fail from here on
            self.declare_dead(e)

    def close(self, timeout: float = 10.0) -> None:
        """Stop accepting work, then deterministically settle every
        pending row: flush it (live worker, or no worker at all) or fail
        it with :class:`WorkerDied` (dead/wedged worker). Nothing is
        left for callers to time out on."""
        with self._lock:
            self._closed = True
            self._wakeup.notify_all()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                # wedged mid-flush: its waiters cannot be flushed twice,
                # but everything still queued gets a deterministic error
                self.declare_dead(WorkerDied(
                    f"worker did not drain within {timeout}s at close"))
                return
        if self._worker_error is not None:
            self.declare_dead(self._worker_error)
            return
        # no worker (tests / start=False) or clean worker exit that left
        # rows (closed while flushing): drain synchronously
        while self._pending:
            with self._lock:
                batch = self._drain(self.clock())
            self._flush(batch, self.clock())
