"""Quantized serving weights: per-channel int8/fp8 with fused dequant
(ISSUE 17).

Every serving byte was f32/bf16 until now, so slot count and max model
size per chip were half of what the hardware admits. This module
quantizes the 2-D projection weights (Linear / attention projections /
the tied embedding) to 8 bits at engine-construction time and swaps a
**dequant-fused matmul** into the exact code paths the engines already
trace — without editing a single module forward:

* :class:`QuantizedWeight` is a registered pytree node holding the int8
  (or fp8) tensor plus one f32 scale per output channel (per-channel
  symmetric, axis 1). It flows through ``jax.jit`` / ``tree_map`` /
  ``device_put`` like any other params leaf.
* Module code reads weights as ``x @ params["weight"].astype(x.dtype)``
  (and the tied head as ``h @ w.astype(h.dtype).T``). ``astype`` on a
  :class:`QuantizedWeight` returns a :class:`_QView` — an ephemeral,
  non-pytree handle WITHOUT ``__jax_array__``, so jax's binary ops defer
  to ``_QView.__rmatmul__`` and the dequant lands fused into the matmul
  epilogue: ``(x @ q.astype(dt)) * scale`` (scale on the output dim is
  exact — it commutes with the contraction). The transposed tied-head
  orientation folds into the prologue instead: ``(x * scale) @ q.T``
  (scale is on the contraction dim there, equally exact).
* Embedding gathers go through :meth:`QuantizedWeight.take_rows`
  (``nn.linear.LookupTable`` guards on the attribute): gather the int8
  rows, then scale — 8-bit HBM traffic on the gather.
* Where the backend multiplies int8 natively, the ``quant`` autotune
  namespace (:func:`bigdl_tpu.tuning.quant_matmul_kind`) can pick a
  **native-int8** kernel per shape instead: dynamic per-row activation
  quant + ``lax.dot_general`` with i32 accumulation, both scales folded
  into the output epilogue.

fp8 uses ``jnp.float8_e4m3fn`` where this jax build has it and falls
back to int8 (with a log line) where it doesn't — capability, not
version, is what's probed.

Quality is measured, not assumed: :func:`quant_report` runs a greedy
teacher-forced decode on the f32 path and the quantized path and
reports the argmax agreement rate plus the max logit error —
``cli/serve`` stamps both into provenance, tests pin them.

The KV-cache half of ISSUE 17 (8-bit paged pools) lives in
``serving/kv_pages`` — this module owns only the weight side and the
shared report.
"""

from __future__ import annotations

import logging
from typing import Optional, Tuple

logger = logging.getLogger(__name__)

__all__ = ["QuantizedWeight", "quantize_weight", "quantize_params",
           "is_quantized", "parse_quantize", "fp8_supported",
           "quant_report", "QUANTIZE_CHOICES"]

QUANTIZE_CHOICES = ("off", "int8", "fp8", "kv8", "int8+kv8", "fp8+kv8")

# dict keys that hold 2-D projection weights across the model zoo:
# nn.Linear / LookupTable ("weight"), nn.attention's qkv/out projections
# and the transformer block's MLP pair. Biases, norms scales and conv
# kernels stay in full precision — they are a rounding error of the
# footprint and the quality risk is all theirs.
_QUANT_KEYS = frozenset(
    {"weight", "wq", "wk", "wv", "wo", "w1", "w2"})

_FP8_MAX = 448.0  # float8_e4m3fn finite max
_EPS = 1e-8


def fp8_supported() -> bool:
    """True when this jax build ships ``float8_e4m3fn`` (capability
    probe — the fallback is per-build, not per-version)."""
    import jax.numpy as jnp
    return hasattr(jnp, "float8_e4m3fn")


def parse_quantize(mode: Optional[str]) -> Tuple[Optional[str], bool]:
    """``--quantize`` value -> ``(weight_fmt, kv8)`` where weight_fmt is
    ``"int8"``/``"fp8"``/None. ``fp8`` degrades to ``int8`` when the
    dtype is absent from this jax build (logged once per call site)."""
    if mode is None:
        return None, False
    mode = str(mode)
    if mode not in QUANTIZE_CHOICES:
        raise ValueError(
            f"--quantize must be one of {'/'.join(QUANTIZE_CHOICES)}, "
            f"got {mode!r}")
    if mode == "off":
        return None, False
    parts = mode.split("+")
    kv8 = "kv8" in parts
    wfmt = next((p for p in parts if p in ("int8", "fp8")), None)
    if wfmt == "fp8" and not fp8_supported():
        logger.warning("quantize: this jax build has no float8_e4m3fn; "
                       "falling back to int8 weights")
        wfmt = "int8"
    return wfmt, kv8


class QuantizedWeight:
    """A 2-D weight stored 8-bit with per-output-channel f32 scales.

    Registered as a pytree node (children ``q``/``scale``, static
    ``fmt``), so placement, jit tracing and ShapeDtypeStruct shadowing
    all flow through it. The module-facing protocol is duck-typed:
    ``.astype(dt)`` hands back a :class:`_QView` whose matmul overloads
    fold the dequant into the contraction; ``.take_rows(idx)`` is the
    embedding gather. ``shape``/``ndim``/``dtype`` report the LOGICAL
    f32 weight, which is what spec builders inspect.
    """

    __slots__ = ("q", "scale", "fmt")

    def __init__(self, q, scale, fmt: str):
        self.q = q
        self.scale = scale
        self.fmt = fmt

    # pytree protocol ----------------------------------------------------
    def tree_flatten(self):
        return (self.q, self.scale), (self.fmt,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0])

    # array-ish surface (what spec builders / accounting touch) ----------
    @property
    def shape(self):
        return tuple(self.q.shape)

    @property
    def ndim(self):
        return len(self.q.shape)

    @property
    def dtype(self):
        import jax.numpy as jnp
        return jnp.dtype(jnp.float32)

    @property
    def nbytes(self) -> int:
        import numpy as np
        q_b = int(np.prod(self.q.shape)) * np.dtype(self.q.dtype).itemsize
        s_b = (int(np.prod(self.scale.shape))
               * np.dtype(self.scale.dtype).itemsize)
        return q_b + s_b

    def __repr__(self):
        return (f"QuantizedWeight({self.fmt}, shape={self.shape}, "
                f"q={self.q.dtype})")

    # module-facing protocol ---------------------------------------------
    def astype(self, dt):
        return _QView(self, dt, transposed=False)

    @property
    def T(self):
        return _QView(self, None, transposed=True)

    def take_rows(self, idx):
        """Embedding gather: 8-bit rows out of HBM, scaled after —
        returns f32 rows exactly like ``jnp.take`` on the dense f32
        weight would (the caller casts to compute dtype downstream)."""
        import jax.numpy as jnp
        rows = jnp.take(self.q, idx, axis=0)
        return rows.astype(self.scale.dtype) * self.scale

    def dequantize(self):
        """The full-precision tensor back (tests / reporting — the hot
        path never materializes this)."""
        return self.q.astype(self.scale.dtype) * self.scale[None, :]


class _QView:
    """Ephemeral dequant handle: what ``QuantizedWeight.astype`` returns
    into module code. Deliberately NOT a pytree and WITHOUT
    ``__jax_array__`` — jax's binary ops then return NotImplemented on
    it and Python dispatches to our ``__rmatmul__``, which is where the
    dequant fuses into the matmul."""

    __slots__ = ("_w", "_dt", "_transposed")

    def __init__(self, w: QuantizedWeight, dt, transposed: bool):
        self._w = w
        self._dt = dt
        self._transposed = transposed

    def astype(self, dt):
        return _QView(self._w, dt, self._transposed)

    @property
    def T(self):
        return _QView(self._w, self._dt, not self._transposed)

    @property
    def shape(self):
        s = tuple(self._w.q.shape)
        return s[::-1] if self._transposed else s

    @property
    def ndim(self):
        return len(self._w.q.shape)

    @property
    def dtype(self):
        import jax.numpy as jnp
        return jnp.dtype(self._dt) if self._dt is not None \
            else jnp.dtype(jnp.float32)

    def __rmatmul__(self, x):
        import jax
        import jax.numpy as jnp

        w = self._w
        dt = self._dt if self._dt is not None else x.dtype
        scale = w.scale.astype(dt)
        if self._transposed:
            # w is (n, k) with scale on k (the contraction dim here):
            # x @ (q * s).T == (x * s) @ q.T — prologue fold, exact.
            return (x * scale) @ w.q.astype(dt).T
        if w.fmt == "int8" and _matmul_kind(x, w, dt) == "native-int8":
            # dynamic per-row activation quant + i32-accumulated int8
            # dot; both scales fold into the output epilogue
            xf = x.astype(jnp.float32)
            xs = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1, keepdims=True),
                             _EPS) / 127.0
            xq = jnp.clip(jnp.round(xf / xs), -127, 127).astype(jnp.int8)
            acc = jax.lax.dot_general(
                xq, w.q, (((xq.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            return acc.astype(dt) * xs.astype(dt) * scale
        # dequant fused into the epilogue: scale sits on the output
        # channels, so it commutes with the contraction — exact.
        return (x @ w.q.astype(dt)) * scale


def _matmul_kind(x, w: QuantizedWeight, dt) -> str:
    """Consult the ``quant`` autotune namespace for this shape (static
    at trace time). Off mode -> the dequant-fused default."""
    from bigdl_tpu import tuning
    m = int(x.shape[-2]) if getattr(x, "ndim", 1) >= 2 else 1
    k, n = int(w.q.shape[0]), int(w.q.shape[1])
    return tuning.quant_matmul_kind(m, k, n, dt)


def is_quantized(x) -> bool:
    return isinstance(x, QuantizedWeight)


def quantize_weight(w, fmt: str = "int8") -> QuantizedWeight:
    """Per-channel symmetric quantization of a 2-D weight, axis 1 (one
    scale per output channel — and, for the tied embedding's transposed
    read, per contraction channel, which folds just as exactly)."""
    import jax.numpy as jnp

    if is_quantized(w):
        return w
    if getattr(w, "ndim", None) != 2:
        raise ValueError(f"quantize_weight wants a 2-D weight, got shape "
                         f"{getattr(w, 'shape', None)}")
    if fmt == "fp8" and not fp8_supported():
        fmt = "int8"
    wf = w.astype(jnp.float32)
    amax = jnp.maximum(jnp.max(jnp.abs(wf), axis=0), _EPS)
    if fmt == "int8":
        scale = amax / 127.0
        q = jnp.clip(jnp.round(wf / scale[None, :]),
                     -127, 127).astype(jnp.int8)
    elif fmt == "fp8":
        scale = amax / _FP8_MAX
        q = jnp.clip(wf / scale[None, :],
                     -_FP8_MAX, _FP8_MAX).astype(jnp.float8_e4m3fn)
    else:
        raise ValueError(f"unknown quantize format {fmt!r}")
    return QuantizedWeight(q, scale.astype(jnp.float32), fmt)


def quantize_params(params, fmt: Optional[str]):
    """Quantize every eligible 2-D projection leaf in a params tree
    (dict keys in ``_QUANT_KEYS``, floating, ndim 2). Idempotent —
    already-quantized leaves pass through, so engines can re-apply it
    on trees ``cli/serve`` quantized up front."""
    import jax.numpy as jnp

    if fmt is None:
        return params

    def _eligible(v):
        return (not is_quantized(v)
                and getattr(v, "ndim", None) == 2
                and hasattr(v, "dtype")
                and jnp.issubdtype(v.dtype, jnp.floating))

    def rec(node):
        if isinstance(node, dict):
            return {k: (quantize_weight(v, fmt)
                        if k in _QUANT_KEYS and _eligible(v)
                        else rec(v))
                    for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(rec(v) for v in node)
        return node

    return rec(params)


# ------------------------------------------------------------- reporting
def kv_fake_quant(vals):
    """Round-trip ``vals`` (…, head_dim) through the kv8 storage format:
    one symmetric int8 scale per (…,) row over head_dim — the SAME math
    ``serving.kv_pages`` applies on scatter, computed with the same op
    order, so a dense cache fake-quantized with this is bit-identical
    to a quantized pool gathered back (pinned in tests/test_quant.py)."""
    import jax.numpy as jnp

    v = vals.astype(jnp.float32)
    amax = jnp.max(jnp.abs(v), axis=-1)
    s = jnp.maximum(amax, _EPS) / 127.0
    q = jnp.clip(jnp.round(v / s[..., None]), -127, 127).astype(jnp.int8)
    return (q.astype(jnp.float32) * s[..., None]).astype(vals.dtype)


def quant_report(model, params, qparams, *, prompt,
                 max_new_tokens: int = 16, kv8: bool = False,
                 cache_dtype=None) -> dict:
    """Greedy-decode quality report: f32 reference vs the quantized
    path, teacher-forced on the reference's tokens so every step's
    logits compare like-for-like. Returns::

        {"agreement": float,       # argmax match rate over decode steps
         "logit_max_err": float,   # max |logits_q - logits_f32|
         "steps": int}

    ``kv8`` additionally round-trips the quantized path's cache rows
    through the 8-bit storage format after every write (prefill rows
    once, each decoded token's row as it lands) — exactly the pool
    semantics, on a dense cache.
    """
    import jax
    import jax.numpy as jnp

    toks = jnp.asarray(prompt, jnp.int32)[None, :]
    p = int(toks.shape[1])
    max_len = p + int(max_new_tokens)
    dt = cache_dtype if cache_dtype is not None else jnp.float32

    prefill = jax.jit(model.prefill_logits)
    decode = jax.jit(model.decode_logits)

    @jax.jit
    def _fq_row(cache, pos):
        # fake-quant the single cache row at ``pos`` on every leaf —
        # the decode-step quantize-on-write
        def f(leaf):
            row = jax.lax.dynamic_slice_in_dim(leaf, pos, 1, axis=2)
            return jax.lax.dynamic_update_slice_in_dim(
                leaf, kv_fake_quant(row), pos, axis=2)
        return jax.tree_util.tree_map(f, cache)

    def run(ps, fake, forced):
        cache = model.encoder.init_cache(1, max_len, dt)
        logits, cache = prefill(ps, toks, cache)
        if fake:
            cache = jax.tree_util.tree_map(
                lambda leaf: leaf.at[:, :, :p, :].set(
                    kv_fake_quant(leaf[:, :, :p, :])), cache)
        outs = [logits]
        for i in range(int(max_new_tokens) - 1):
            tok = (forced[i] if forced is not None
                   else jnp.argmax(outs[-1], -1).astype(jnp.int32))[:, None]
            pos = p + i
            logits, cache = decode(ps, tok, cache, jnp.int32(pos))
            if fake:
                cache = _fq_row(cache, jnp.int32(pos))
            outs.append(logits)
        return jnp.stack(outs, 0)  # (steps, 1, vocab)

    import numpy as np
    ref = np.asarray(run(params, False, None))
    forced = [jnp.asarray(t) for t in
              np.argmax(ref, -1).astype(np.int32)]
    got = np.asarray(run(qparams, kv8, forced))
    agree = float(np.mean(np.argmax(ref, -1) == np.argmax(got, -1)))
    err = float(np.max(np.abs(ref.astype(np.float64)
                              - got.astype(np.float64))))
    return {"agreement": agree, "logit_max_err": err,
            "steps": int(ref.shape[0])}


def _register():
    import jax
    jax.tree_util.register_pytree_node_class(QuantizedWeight)


_register()
