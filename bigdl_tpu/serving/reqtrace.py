"""Per-request serving observability (ISSUE 15 tentpole): lifecycle
tracing, server-side TTFT/TPOT, SLO accounting, and a decode flight
recorder.

The obs stack (PRs 7/8/11) answers "where do the milliseconds go" in
aggregate — phase spans and global histograms. Serving debugging needs
the other axis: ONE request's path through the machine. BigDL's
production story leans on per-task Spark UI metrics to autopsy
stragglers (arxiv 1804.05839; BigDL 2.0 extends this to end-to-end
serving pipelines, arxiv 2204.01715); the TPU-native equivalent is a
request ID minted at admission and threaded through the micro-batcher,
the bucketed engine, and the continuous-batching decoder, accumulating
a lifecycle record::

    admitted -> queued -> prefill -> decode round* -> finished
                                                   |  expired
                                                   |  shed / rejected
                                                   |  worker_dead ...

Each decode round notes the tokens emitted, speculative tokens
accepted, KV pages held, and sequence position; prefill notes the
prefix-cache hit length and slot. Completed records land in a bounded
ring (the flight recorder) with drop counting; derived latencies —
TTFT, TPOT, per-token ITL, queue wait, prefill, decode — publish into
the shared metrics registry as histograms with p50/p95/p99, and each
record can be joined back onto the ``obs.spans`` Chrome-trace timeline
as back-dated ``req:*`` phase spans (category ``request``) so one slow
request renders next to the batcher/engine spans that served it.

Optional policy hooks:

* :class:`SloPolicy` — ``--slo ttft=200,tpot=30``: per-request SLO
  evaluation into goodput / ``slo_violations_total`` counters plus a
  windowed burn rate the tiered shedder (PR 6) consults;
* :class:`AccessLog` — ``--accessLog`` / ``--logSample``: a sampled
  structured JSONL access log, one line per completed request, with
  DETERMINISTIC sampling (hash of the request id, not a coin flip) so
  reruns and multi-replica merges select the same requests.

Disabled-path contract (same as ``obs.spans``): with no tracer
installed, every hook in the hot loop is one module-global load and one
``None`` check — ``--reqTrace off`` keeps the decode loop
byte-identical.

Thread model: records are mutated from HTTP handler threads, the
batcher worker, and the decode loop; one lock guards the live table and
the ring. Hooks touch a few scalars under it — never an engine call.
The clock is injectable for deterministic tests; when an ``obs`` tracer
is installed the default clock is the tracer's, so joined spans share
its timebase.
"""

from __future__ import annotations

import collections
import hashlib
import itertools
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from bigdl_tpu.obs import spans as _spans
from bigdl_tpu.obs.metrics import ITL_BUCKETS_MS

__all__ = ["RequestRecord", "RequestTracer", "SloPolicy", "AccessLog",
           "mint_rid", "sanitize_rid", "get_request_tracer",
           "set_request_tracer", "get"]

# terminal lifecycle states and the HTTP status each implies when the
# server layer never got to annotate one (decode-side terminations)
TERMINAL_STATES: Dict[str, int] = {
    "finished": 200,      # all tokens emitted / scores returned
    "expired": 504,       # deadline passed (queue or mid-decode)
    "shed": 429,          # tiered overload shed (PR 6) or SLO burn
    "rejected": 429,      # admission fast-reject (queue at capacity)
    "worker_dead": 503,   # batcher/decode worker died under the request
    "bad_request": 400,   # malformed payload
    "error": 500,         # engine raised
    "closed": 503,        # engine shut down with the request in flight
}

LIVE_STATES = ("admitted", "queued", "prefill", "decode")


# ------------------------------------------------------------- request ids
_RID_SEQ = itertools.count(1)
# pid-stamped prefix: ids stay unique across server restarts sharing an
# access log, without any randomness in the hot path
_RID_PREFIX = f"r{os.getpid() & 0xffff:04x}"


def mint_rid() -> str:
    """Mint a fresh request id (``r<pid16><seq>``); works with no tracer
    installed so ``x-request-id`` is echoed even with ``--reqTrace off``."""
    return f"{_RID_PREFIX}-{next(_RID_SEQ):06d}"


def sanitize_rid(raw) -> Optional[str]:
    """Validate a client-supplied ``x-request-id``: printable ASCII, no
    whitespace, at most 64 chars — anything else is discarded (a minted
    id replaces it) so ids are safe in headers, JSONL, and trace args."""
    if not isinstance(raw, str):
        return None
    rid = raw.strip()
    if not rid or len(rid) > 64:
        return None
    if any(c <= " " or c > "~" for c in rid):
        return None
    return rid


class RequestRecord:
    """One request's lifecycle: timestamps (seconds on the tracer's
    clock), decode-round ring, and terminal state.

    ``t_prefill0``/``t_prefill1`` bound the compute window — prefill for
    ``/generate``, the (possibly multi-flush) engine forward for
    ``/predict``."""

    __slots__ = ("rid", "endpoint", "state", "status",
                 "t_admit", "t_queue", "t_dequeue",
                 "t_prefill0", "t_prefill1",
                 "t_first_token", "t_first_byte", "t_last_token",
                 "t_finish",
                 "prompt_tokens", "max_new", "tokens_out",
                 "rounds", "round_count", "accepted_total",
                 "prefix_hit_tokens", "pages_held", "slot", "replica",
                 "error")

    def __init__(self, rid: str, endpoint: str, t_admit: float,
                 max_rounds: int = 64):
        self.rid = rid
        self.endpoint = endpoint
        self.state = "admitted"
        self.status: Optional[int] = None
        self.t_admit = t_admit
        self.t_queue: Optional[float] = None
        self.t_dequeue: Optional[float] = None
        self.t_prefill0: Optional[float] = None
        self.t_prefill1: Optional[float] = None
        self.t_first_token: Optional[float] = None
        self.t_first_byte: Optional[float] = None
        self.t_last_token: Optional[float] = None
        self.t_finish: Optional[float] = None
        self.prompt_tokens: Optional[int] = None
        self.max_new: Optional[int] = None
        self.tokens_out = 0
        # last max_rounds decode rounds: (t, emitted, accepted, pages, pos)
        self.rounds: collections.deque = collections.deque(
            maxlen=max_rounds)
        self.round_count = 0
        self.accepted_total = 0
        self.prefix_hit_tokens = 0
        self.pages_held: Optional[int] = None
        self.slot: Optional[int] = None
        self.replica: Optional[int] = None
        self.error: Optional[str] = None

    # ------------------------------------------------- derived latencies
    def queue_wait_ms(self) -> Optional[float]:
        t0 = self.t_queue if self.t_queue is not None else self.t_admit
        t1 = self.t_dequeue
        if t1 is None:
            return None
        return max(t1 - t0, 0.0) * 1000.0

    def prefill_ms(self) -> Optional[float]:
        if self.t_prefill0 is None or self.t_prefill1 is None:
            return None
        return max(self.t_prefill1 - self.t_prefill0, 0.0) * 1000.0

    def decode_ms(self) -> Optional[float]:
        """Prefill end -> last token (0 for single-token / predict)."""
        if self.t_prefill1 is None or self.t_last_token is None:
            return None
        return max(self.t_last_token - self.t_prefill1, 0.0) * 1000.0

    def ttft_ms(self) -> Optional[float]:
        """Admission -> first token as FELT by the client: when the
        streaming handler stamped a first-byte-out time (``--stream``)
        that wins over the engine-side first-emit time, so SLO judgment
        covers the wire, not just the decode loop. For ``/predict``
        (scores, not tokens) the response-ready time stands in for
        token one."""
        t1 = self.t_first_byte
        if t1 is None:
            t1 = self.t_first_token
        if t1 is None and self.endpoint == "predict" \
                and self.state == "finished":
            t1 = self.t_finish
        if t1 is None:
            return None
        return max(t1 - self.t_admit, 0.0) * 1000.0

    def tpot_ms(self) -> Optional[float]:
        """Mean time per output token AFTER the first:
        ``(t_last - t_first) / (n - 1)``. None below two tokens."""
        if (self.t_first_token is None or self.t_last_token is None
                or self.tokens_out < 2):
            return None
        return max(self.t_last_token - self.t_first_token, 0.0) \
            * 1000.0 / (self.tokens_out - 1)

    def total_ms(self) -> Optional[float]:
        if self.t_finish is None:
            return None
        return max(self.t_finish - self.t_admit, 0.0) * 1000.0

    def to_dict(self, now: Optional[float] = None) -> dict:
        """JSON-safe rendering for /debug/requests and the access log."""
        d = {"rid": self.rid, "endpoint": self.endpoint,
             "state": self.state, "status": self.status,
             "prompt_tokens": self.prompt_tokens, "max_new": self.max_new,
             "tokens_out": self.tokens_out,
             "rounds": self.round_count,
             "accepted_tokens": self.accepted_total,
             "prefix_hit_tokens": self.prefix_hit_tokens,
             "pages_held": self.pages_held, "slot": self.slot,
             "queue_wait_ms": self.queue_wait_ms(),
             "prefill_ms": self.prefill_ms(),
             "decode_ms": self.decode_ms(),
             "ttft_ms": self.ttft_ms(), "tpot_ms": self.tpot_ms(),
             "total_ms": self.total_ms()}
        if self.replica is not None:
            d["replica"] = self.replica
        if self.error:
            d["error"] = self.error
        if now is not None and self.t_finish is None:
            d["age_ms"] = max(now - self.t_admit, 0.0) * 1000.0
        for k, v in list(d.items()):
            if isinstance(v, float):
                d[k] = round(v, 3)
        return d


class SloPolicy:
    """Server-side SLO targets and burn accounting.

    Spec grammar (``--slo``): comma-separated ``dim=value`` with latency
    dims in ms (``ttft``, ``tpot``) plus two policy knobs —
    ``burn=<frac>`` (windowed violation fraction above which the tiered
    shedder treats the server as overloaded; default 0.9) and
    ``window=<n>`` (requests in the burn window, default 32). A request
    is GOOD when every configured dim it exposes meets its target;
    requests that never produced a dim (e.g. a one-token generate has no
    TPOT) are judged on the dims they have."""

    DIMS = ("ttft", "tpot")
    MIN_BURN_SAMPLES = 8

    def __init__(self, targets: Dict[str, float], burn: float = 0.9,
                 window: int = 32):
        for k in targets:
            if k not in self.DIMS:
                raise ValueError(
                    f"unknown SLO dim {k!r} (have {self.DIMS})")
        if not targets:
            raise ValueError("SLO spec configured no dims")
        if not 0.0 < burn <= 1.0:
            raise ValueError(f"burn must be in (0, 1], got {burn}")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.targets = dict(targets)
        self.burn = float(burn)
        self.window = int(window)
        self._lock = threading.Lock()
        self._recent: collections.deque = collections.deque(maxlen=window)
        self._evaluated = 0
        self._good = 0

    @classmethod
    def parse(cls, spec: str) -> "SloPolicy":
        targets: Dict[str, float] = {}
        burn, window = 0.9, 32
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(
                    f"bad SLO term {part!r} (want dim=value)")
            k, v = part.split("=", 1)
            k = k.strip().lower()
            if k == "burn":
                burn = float(v)
            elif k == "window":
                window = int(v)
            else:
                ms = float(v)
                if ms <= 0:
                    raise ValueError(f"SLO target must be > 0: {part!r}")
                targets[k] = ms
        return cls(targets, burn=burn, window=window)

    def evaluate(self, rec: RequestRecord) -> List[str]:
        """Violated dims for one completed record (empty = good)."""
        violated = []
        for dim, target in self.targets.items():
            v = rec.ttft_ms() if dim == "ttft" else rec.tpot_ms()
            if v is not None and v > target:
                violated.append(dim)
        return violated

    def account(self, good: bool) -> None:
        with self._lock:
            self._recent.append(bool(good))
            self._evaluated += 1
            if good:
                self._good += 1

    def burn_rate(self) -> float:
        """Violation fraction over the sliding window (0 when empty)."""
        with self._lock:
            if not self._recent:
                return 0.0
            return 1.0 - sum(self._recent) / len(self._recent)

    def goodput_frac(self) -> float:
        with self._lock:
            return self._good / self._evaluated if self._evaluated else 1.0

    def should_shed(self) -> bool:
        """True when the windowed burn rate says the server is missing
        its SLOs badly enough that admitting more work only makes every
        in-flight request later — the tiered shedder (server.py)
        consults this alongside queue depth."""
        with self._lock:
            if len(self._recent) < self.MIN_BURN_SAMPLES:
                return False
            rate = 1.0 - sum(self._recent) / len(self._recent)
        return rate >= self.burn

    def describe(self) -> dict:
        return {"targets": dict(self.targets), "burn": self.burn,
                "window": self.window}


class AccessLog:
    """Sampled structured JSONL access log, one line per completed
    request.

    Sampling is DETERMINISTIC in the request id: a request is logged iff
    ``sha256(rid) / 2^64 < sample`` — reruns pick the same subset, and
    N replicas sharing id space log disjoint-free consistent samples
    (the Spark-lineage analog: event-log sampling keyed by task id, not
    by a per-executor RNG)."""

    def __init__(self, path: str, sample: float = 1.0):
        if not 0.0 <= sample <= 1.0:
            raise ValueError(f"sample must be in [0, 1], got {sample}")
        self.path = path
        self.sample = float(sample)
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", buffering=1)
        self.lines = 0
        self.sampled_out = 0

    def sampled(self, rid: str) -> bool:
        if self.sample >= 1.0:
            return True
        if self.sample <= 0.0:
            return False
        h = hashlib.sha256(rid.encode()).digest()
        return int.from_bytes(h[:8], "big") / 2.0 ** 64 < self.sample

    def write(self, rec_dict: dict) -> bool:
        rid = rec_dict.get("rid", "")
        if not self.sampled(rid):
            with self._lock:
                self.sampled_out += 1
            return False
        line = json.dumps(rec_dict, sort_keys=True)
        with self._lock:
            self._f.write(line + "\n")
            self.lines += 1
        return True

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except Exception:
                pass


class RequestTracer:
    """The flight recorder: live in-flight table + bounded ring of
    completed :class:`RequestRecord`, metric derivation on completion,
    optional SLO/access-log policies, and Chrome-trace join.

    Hot-loop hooks (``note_*``) tolerate unknown rids (a request
    admitted before the tracer was installed, or a None rid threaded
    through) by doing nothing — instrumentation must never fail a
    request."""

    def __init__(self, capacity: int = 1024,
                 clock: Optional[Callable[[], float]] = None,
                 metrics=None, slo: Optional[SloPolicy] = None,
                 access_log: Optional[AccessLog] = None,
                 max_rounds: int = 64):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if clock is None:
            obs = _spans.get_tracer()
            clock = obs.clock if obs is not None else time.perf_counter
        self.clock = clock
        self.capacity = int(capacity)
        self.max_rounds = int(max_rounds)
        self.slo = slo
        self.access_log = access_log
        self._lock = threading.Lock()
        self._live: Dict[str, RequestRecord] = {}
        # completed records, oldest first; _done_index mirrors it so a
        # late status annotation (server thread, after the decode loop
        # already finished the record) still finds its record
        self._done: collections.deque = collections.deque()
        self._done_index: Dict[str, RequestRecord] = {}
        self.dropped = 0

        if metrics is not None:
            self._h_ttft = metrics.histogram(
                "ttft_ms", "server-side time to first token",
                bounds=ITL_BUCKETS_MS)
            self._h_tpot = metrics.histogram(
                "tpot_ms", "server-side mean time per output token",
                bounds=ITL_BUCKETS_MS)
            self._h_itl = metrics.histogram(
                "itl_ms", "server-side inter-token latency",
                bounds=ITL_BUCKETS_MS)
            self._h_queue = metrics.histogram(
                "request_queue_wait_ms", "per-request queue wait")
            self._h_prefill = metrics.histogram(
                "request_prefill_ms", "per-request prefill/compute time")
            self._h_decode = metrics.histogram(
                "request_decode_ms", "per-request decode time")
            self._h_total = metrics.histogram(
                "request_total_ms", "per-request admission -> terminal")
            # "requests_state_*" (not "requests_*"): the server already
            # owns requests_expired_total / requests_shed_total /
            # requests_worker_dead_total and the registry dedups by
            # name, so reusing those names would double-count
            self._c_finished = {
                st: metrics.counter(
                    f"requests_state_{st}_total",
                    f"requests that terminated {st} (lifecycle tracer)")
                for st in TERMINAL_STATES}
            self._c_dropped = metrics.counter(
                "reqtrace_records_dropped_total",
                "completed lifecycle records evicted from the ring")
            metrics.gauge("reqtrace_in_flight",
                          "requests currently holding a live record",
                          fn=lambda: len(self._live))
            if slo is not None:
                self._c_slo_req = metrics.counter(
                    "slo_requests_total", "requests evaluated against SLO")
                self._c_slo_good = metrics.counter(
                    "slo_good_total", "requests that met every SLO dim")
                self._c_slo_viol = metrics.counter(
                    "slo_violations_total",
                    "requests that missed at least one SLO dim")
                self._c_slo_dim = {
                    dim: metrics.counter(
                        f"slo_{dim}_violations_total",
                        f"requests that missed the {dim} target")
                    for dim in slo.targets}
                metrics.gauge("slo_goodput_frac",
                              "lifetime fraction of requests meeting SLO",
                              fn=slo.goodput_frac)
                metrics.gauge("slo_burn_rate",
                              "windowed SLO violation fraction",
                              fn=slo.burn_rate)
            if access_log is not None:
                metrics.gauge("access_log_lines",
                              "access-log lines written",
                              fn=lambda: self.access_log.lines)
                metrics.gauge("access_log_sampled_out",
                              "completed requests the sampler skipped",
                              fn=lambda: self.access_log.sampled_out)
        else:
            self._h_ttft = self._h_tpot = self._h_itl = None
            self._h_queue = self._h_prefill = self._h_decode = None
            self._h_total = None
            self._c_finished = {}
            self._c_dropped = None
        if slo is None or metrics is None:
            self._c_slo_req = self._c_slo_good = self._c_slo_viol = None
            self._c_slo_dim = {}

    # -------------------------------------------------------- lifecycle
    def admit(self, endpoint: str, rid: Optional[str] = None,
              prompt_tokens: Optional[int] = None,
              max_new: Optional[int] = None) -> str:
        """Open a lifecycle record; returns the (possibly minted) rid."""
        if rid is None:
            rid = mint_rid()
        rec = RequestRecord(rid, endpoint, self.clock(),
                            max_rounds=self.max_rounds)
        rec.prompt_tokens = prompt_tokens
        rec.max_new = max_new
        with self._lock:
            self._live[rid] = rec
        return rid

    def _rec(self, rid: Optional[str]) -> Optional[RequestRecord]:
        if rid is None:
            return None
        return self._live.get(rid)

    def note_replica(self, rid: Optional[str], replica: int) -> None:
        """dp routing decision (ISSUE 16): which engine replica serves
        this request — stamped by the router before submit."""
        with self._lock:
            rec = self._rec(rid)
            if rec is not None:
                rec.replica = int(replica)

    def note_queued(self, rid: Optional[str]) -> None:
        """Request entered a queue (batcher pending / decode waiting).
        First call wins: a /predict fanned out over N rows queues once."""
        with self._lock:
            rec = self._rec(rid)
            if rec is not None and rec.t_queue is None:
                rec.t_queue = self.clock()
                if rec.state == "admitted":
                    rec.state = "queued"

    def note_dequeued(self, rid: Optional[str]) -> None:
        """Request left the queue toward compute (batch drain / slot
        install). Last call wins: queue wait covers the slowest row."""
        with self._lock:
            rec = self._rec(rid)
            if rec is not None:
                rec.t_dequeue = self.clock()

    def note_compute(self, rid: Optional[str], t0: float,
                     t1: float) -> None:
        """An engine forward covered this request (possibly one of
        several chunks): widen the compute window."""
        with self._lock:
            rec = self._rec(rid)
            if rec is None:
                return
            if rec.t_prefill0 is None or t0 < rec.t_prefill0:
                rec.t_prefill0 = t0
            if rec.t_prefill1 is None or t1 > rec.t_prefill1:
                rec.t_prefill1 = t1
            if rec.state in ("admitted", "queued"):
                rec.state = "prefill"

    def note_prefill(self, rid: Optional[str], t0: float, t1: float,
                     slot: Optional[int] = None,
                     prefix_hit_tokens: int = 0,
                     pages: Optional[int] = None) -> None:
        """Decode-path prefill finished: the request owns a slot."""
        with self._lock:
            rec = self._rec(rid)
            if rec is None:
                return
            if rec.t_dequeue is None:
                rec.t_dequeue = t0
            rec.t_prefill0, rec.t_prefill1 = t0, t1
            rec.slot = slot
            rec.prefix_hit_tokens = int(prefix_hit_tokens)
            if pages is not None:
                rec.pages_held = int(pages)
            rec.state = "decode"

    def note_first_byte(self, rid: Optional[str]) -> None:
        """Streaming handler wrote the first response byte for this
        request (chunked ``/generate``). First call wins; the derived
        TTFT prefers this over the engine-emit time so ``--slo`` judges
        streamed traffic on felt latency."""
        with self._lock:
            rec = self._rec(rid)
            if rec is not None and rec.t_first_byte is None:
                rec.t_first_byte = self.clock()

    def note_round(self, rid: Optional[str], emitted: int,
                   accepted: Optional[int] = None,
                   pages: Optional[int] = None,
                   pos: Optional[int] = None) -> None:
        """One decode round emitted ``emitted`` tokens for this request
        (1 on the plain path; up to k+1 speculative). ``accepted`` is
        the draft tokens the target kept this round."""
        if emitted <= 0:
            return
        itl_obs = None
        with self._lock:
            rec = self._rec(rid)
            if rec is None:
                return
            t = self.clock()
            prev = rec.t_last_token
            if rec.t_first_token is None:
                rec.t_first_token = t
            rec.t_last_token = t
            rec.tokens_out += emitted
            rec.round_count += 1
            if accepted is not None:
                rec.accepted_total += accepted
            if pages is not None:
                rec.pages_held = int(pages)
            rec.rounds.append((t, int(emitted), accepted, pages, pos))
            rec.state = "decode"
            if prev is not None and self._h_itl is not None:
                # a k-token round contributes k samples of the mean
                # inter-token gap it realized — per-token ITL, not
                # per-round latency
                itl_obs = ((t - prev) * 1000.0 / emitted, emitted)
        if itl_obs is not None:
            gap, n = itl_obs
            for _ in range(n):
                self._h_itl.observe(gap)

    # -------------------------------------------------------- completion
    def finish(self, rid: Optional[str], state: str,
               status: Optional[int] = None,
               error: Optional[str] = None) -> None:
        """Terminalize the record: stamp ``t_finish``, publish derived
        histograms, evaluate SLO, write the access log, join the obs
        timeline, and move the record into the ring. Idempotent — a
        second finish (server annotating HTTP status after the decode
        loop already finished the record) only fills in ``status``."""
        if rid is None or state not in TERMINAL_STATES:
            return
        with self._lock:
            rec = self._live.pop(rid, None)
            if rec is None:
                done = self._done_index.get(rid)
                if done is not None and status is not None \
                        and done.status is None:
                    done.status = int(status)
                return
            rec.state = state
            rec.status = int(status) if status is not None \
                else TERMINAL_STATES[state]
            rec.error = error
            rec.t_finish = self.clock()
            self._done.append(rec)
            self._done_index[rid] = rec
            while len(self._done) > self.capacity:
                old = self._done.popleft()
                self._done_index.pop(old.rid, None)
                self.dropped += 1
                if self._c_dropped is not None:
                    self._c_dropped.inc()
        self._publish(rec)

    def _publish(self, rec: RequestRecord) -> None:
        c = self._c_finished.get(rec.state)
        if c is not None:
            c.inc()
        if self._h_total is not None:
            for h, v in ((self._h_ttft, rec.ttft_ms()),
                         (self._h_tpot, rec.tpot_ms()),
                         (self._h_queue, rec.queue_wait_ms()),
                         (self._h_prefill, rec.prefill_ms()),
                         (self._h_decode, rec.decode_ms()),
                         (self._h_total, rec.total_ms())):
                if v is not None:
                    h.observe(v)
        if self.slo is not None and rec.state == "finished":
            violated = self.slo.evaluate(rec)
            self.slo.account(not violated)
            if self._c_slo_req is not None:
                self._c_slo_req.inc()
                if violated:
                    self._c_slo_viol.inc()
                    for dim in violated:
                        d = self._c_slo_dim.get(dim)
                        if d is not None:
                            d.inc()
                else:
                    self._c_slo_good.inc()
        if self.access_log is not None:
            self.access_log.write(rec.to_dict())
        self._join_obs(rec)

    def _join_obs(self, rec: RequestRecord) -> None:
        """Back-date the record's phases onto the obs.spans timeline as
        ``req:*`` spans (category ``request``) keyed by rid — one slow
        request renders against the batcher/engine spans that served
        it. Skipped when the obs tracer runs a different clock (the
        timebases would not line up)."""
        tr = _spans.get_tracer()
        if tr is None or tr.clock is not self.clock:
            return
        args = {"rid": rec.rid, "state": rec.state}
        t_q0 = rec.t_queue if rec.t_queue is not None else rec.t_admit
        phases = (("req:queue_wait", t_q0, rec.t_dequeue),
                  ("req:prefill", rec.t_prefill0, rec.t_prefill1),
                  ("req:decode", rec.t_prefill1, rec.t_last_token))
        tr.record(f"req:{rec.endpoint}", rec.t_admit,
                  rec.t_finish, depth=0,
                  args={**args, "tokens_out": rec.tokens_out},
                  cat="request")
        for name, t0, t1 in phases:
            if t0 is not None and t1 is not None and t1 > t0:
                tr.record(name, t0, t1, depth=1, args=args,
                          cat="request")

    # --------------------------------------------------------- inspection
    def in_flight(self) -> List[RequestRecord]:
        with self._lock:
            return list(self._live.values())

    def recent(self, n: Optional[int] = None) -> List[RequestRecord]:
        """Most-recent-last completed records (up to ``n``)."""
        with self._lock:
            recs = list(self._done)
        return recs if n is None else recs[-n:]

    def snapshot(self, recent: int = 32) -> dict:
        """The /debug/requests JSON."""
        now = self.clock()
        with self._lock:
            live = [r.to_dict(now) for r in self._live.values()]
            done = [r.to_dict() for r in
                    list(self._done)[-max(recent, 0):]]
            dropped = self.dropped
        live.sort(key=lambda d: d["rid"])
        out = {"enabled": True, "now": round(now, 6),
               "in_flight": live, "recent": done,
               "completed_retained": len(done), "dropped": dropped,
               "capacity": self.capacity}
        if self.slo is not None:
            out["slo"] = {**self.slo.describe(),
                          "burn_rate": round(self.slo.burn_rate(), 4),
                          "goodput_frac":
                              round(self.slo.goodput_frac(), 4),
                          "shedding": self.slo.should_shed()}
        return out

    def close(self) -> None:
        if self.access_log is not None:
            self.access_log.close()


# ------------------------------------------------------------ module global
_TRACER: Optional[RequestTracer] = None


def get() -> Optional[RequestTracer]:
    """The hot-path hook: one global load. ``None`` means ``--reqTrace
    off`` — callers do their single ``None`` check and touch nothing."""
    return _TRACER


get_request_tracer = get


def set_request_tracer(tracer: Optional[RequestTracer]) -> None:
    """Install (or clear, with None) the process-global request
    tracer."""
    global _TRACER
    _TRACER = tracer
