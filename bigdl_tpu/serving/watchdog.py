"""Serving watchdog: detect dead or wedged worker threads and fail
fast (ISSUE 6 serving hardening).

Two failure shapes escape the per-component guards:

* a worker thread that DIED outside its own try/except (component
  ``alive()`` goes false — submits already fast-fail, but readiness
  must flip and pending futures must be settled);
* a worker that is alive but WEDGED — stuck inside a single engine call
  (a hung device transfer, an injected stall) while work queues behind
  it. No exception ever fires; only the combination "busy, but the
  heartbeat hasn't moved in ``stall_timeout_s``" reveals it.

The watchdog polls each registered component (anything exposing
``alive()``, ``busy()``, ``heartbeat_age(now)``, ``declare_dead(exc)``
— MicroBatcher and DecodeEngine both do) and on either verdict calls
``declare_dead``: pending futures resolve with
:class:`~bigdl_tpu.serving.batcher.WorkerDied`, later submits fail
immediately, and :meth:`ready` goes false so ``/readyz`` returns 503
and the load balancer drains this replica while ``/healthz`` (liveness)
keeps answering 200 — degraded, not dead.

``check(now)`` is a pure function of the injected clock so the verdict
logic is unit-testable without threads; ``start()`` runs it on a
daemon-thread interval for the real server.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional

from bigdl_tpu.serving.batcher import WorkerDied

logger = logging.getLogger(__name__)

__all__ = ["Watchdog"]


class Watchdog:
    def __init__(self, *, interval_s: float = 0.5,
                 stall_timeout_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic,
                 metrics=None):
        if stall_timeout_s <= 0:
            raise ValueError(
                f"stall_timeout_s must be > 0, got {stall_timeout_s}")
        self.interval_s = float(interval_s)
        self.stall_timeout_s = float(stall_timeout_s)
        self.clock = clock
        self._targets: Dict[str, object] = {}
        self._failed: Dict[str, str] = {}  # name -> verdict
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        if metrics is not None:
            self._m_failures = metrics.counter(
                "watchdog_failures_total",
                "workers declared dead or wedged by the watchdog")
            metrics.gauge("watchdog_ready",
                          "1 while every watched worker is healthy",
                          fn=lambda: 1.0 if self.ready() else 0.0)
        else:
            self._m_failures = None

    def watch(self, name: str, target) -> "Watchdog":
        """Register a component exposing ``alive/busy/heartbeat_age/
        declare_dead`` (MicroBatcher, DecodeEngine)."""
        for attr in ("alive", "busy", "heartbeat_age", "declare_dead"):
            if not callable(getattr(target, attr, None)):
                raise TypeError(f"{name}: watch target lacks {attr}()")
        self._targets[name] = target
        return self

    # --------------------------------------------------------------- verdict
    def check(self, now: Optional[float] = None) -> Dict[str, str]:
        """One poll: returns ``{name: "ok" | "dead" | "wedged"}`` and
        acts on new failures (declare_dead + counter). Pure in its
        verdict given ``now``; safe to call from tests without start()."""
        now = self.clock() if now is None else now
        out: Dict[str, str] = {}
        for name, t in self._targets.items():
            prior = self._failed.get(name)
            if prior:
                out[name] = prior
                continue
            if not t.alive():
                verdict = "dead"
                exc = WorkerDied(
                    f"{name}: worker thread died "
                    f"({getattr(t, 'worker_error', None) or 'unknown'})")
            elif t.busy() and t.heartbeat_age(now) > self.stall_timeout_s:
                verdict = "wedged"
                exc = WorkerDied(
                    f"{name}: worker wedged — busy with no heartbeat "
                    f"for {t.heartbeat_age(now):.1f}s "
                    f"(> {self.stall_timeout_s}s)")
            else:
                out[name] = "ok"
                continue
            with self._lock:
                self._failed[name] = verdict
            logger.error("watchdog: %s", exc)
            if self._m_failures is not None:
                self._m_failures.inc()
            t.declare_dead(exc)
            out[name] = verdict
        return out

    def ready(self) -> bool:
        """Readiness verdict for ``/readyz``: no watched worker has
        failed."""
        return not self._failed

    @property
    def failures(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._failed)

    # ---------------------------------------------------------------- thread
    def start(self) -> "Watchdog":
        if self._thread is not None:
            return self

        def _loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.check()
                except Exception:  # the watchdog must not die of a bug
                    logger.exception("watchdog poll failed")

        self._thread = threading.Thread(target=_loop, name="watchdog",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(5.0)
