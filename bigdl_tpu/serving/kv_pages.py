"""Paged KV allocation for the continuous-batching decoder (ISSUE 14).

The dense layout pays ``slots x max_len`` HBM per request no matter how
short the request is — PR 11's ``kv_cache_bytes`` gauges made that the
single biggest resident cost of a serving process. This module replaces
it with the vLLM-style fix: each layer's K/V lives in a pool of
fixed-size PAGES of ``page_tokens`` tokens, and every slot owns just the
pages its token budget needs, recorded in a per-slot page table.

* **Allocation is a host-side free list** (:class:`PageAllocator`): page
  ids are plain ints, page 0 is reserved as the NULL page — unused page-
  table entries point at it, and writes that fall past a slot's
  reservation land in it. Its contents are garbage by design; the decode
  live-mask guarantees garbage positions are never attended before being
  overwritten (the same argument that makes bucketed prefill exact).
* **Admission is reservation-based**: a slot reserves
  ``ceil((prompt + max_new) / page_tokens)`` pages up front, so a request
  that starts decoding can always finish — no mid-decode OOM deadlock,
  requests that don't fit simply wait in the queue.
* **The device side is pure functions** used inside the engine's jitted
  steps: :func:`gather_cache` rebuilds a slot's contiguous (kv_heads,
  max_len, head_dim) view from its pages (a transient — freed when the
  step ends; *residency* is what pages cut), :func:`scatter_tokens`
  writes per-token K/V back into the pools, :func:`scatter_pages`
  repacks a whole contiguous cache into a slot's pages after prefill.

``page_tokens`` must divide ``max_len`` (keeps the gathered view exactly
max_len, so decode/verify graphs and the positional tables are shared
bit-for-bit with the dense path) — `bigdl_tpu.tuning.kv_page_tokens`
picks it, `bigdl_tpu.analysis` lints it against the flash block plan.

Tensor parallel (ISSUE 16): every device helper below indexes pools only
on the PAGE dim (axis 0) and writes whole head rows, so a pool sharded
on its kv_heads dim (axis 1 — the layout GSPMD propagates from
column-split wk/wv) passes through gather/scatter/copy without a
resharding collective. ``PagedKvCache(sharding=...)`` commits the pools
to that layout at construction and keeps the matching sharding pytree
(``pool_shardings``) for engines to pin as ``out_shardings``; page
tables and the :class:`PageAllocator` free list stay host-side and
replicated — allocation is a host decision, only where the KV bytes
live changes.
"""

from __future__ import annotations

import collections
from typing import List, Optional

__all__ = ["PageAllocator", "PagedKvCache", "QuantPool", "gather_cache",
           "scatter_tokens", "scatter_pages", "copy_pages",
           "pages_needed", "kv_quant_rows"]


def pages_needed(tokens: int, page_tokens: int) -> int:
    return -(-int(tokens) // int(page_tokens))


# --------------------------------------------------------- quantized pools
class QuantPool:
    """One layer's K (or V) pool stored 8-bit (ISSUE 17): ``q`` is the
    int8 pool ``(pool_pages, kv_heads, page_tokens, head_dim)`` and
    ``s`` the f32 scale plane ``(pool_pages, kv_heads, page_tokens)`` —
    one symmetric scale per stored token row, computed over head_dim at
    write time. Registered as a pytree node so it sits AT the pools'
    leaf positions: the decode/verify/prefill programs, their
    ShapeDtypeStruct shadows, ``out_shardings`` pytrees and
    ``device_put`` all flow through unchanged, and the device helpers
    below dispatch on ``isinstance`` — quantize on scatter, dequantize
    on gather. ``view_dtype`` is the dtype the gathered contiguous view
    dequantizes to (the engine's cache dtype, so the decode graph
    downstream of the gather is the same program as the dense path).

    HBM per page drops from ``kh*pt*hd*itemsize(cache_dtype)`` to
    ``kh*pt*(hd + 4)`` bytes — ~0.27x at head_dim 64 vs f32, so
    reservation-based admission grants ~2x the slots even after adding
    the weight savings' headroom elsewhere.
    """

    __slots__ = ("q", "s", "view_dtype")

    def __init__(self, q, s, view_dtype):
        import numpy as np
        self.q = q
        self.s = s
        self.view_dtype = np.dtype(view_dtype)

    def tree_flatten(self):
        return (self.q, self.s), (self.view_dtype,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0])

    def __repr__(self):
        return (f"QuantPool(q={getattr(self.q, 'shape', None)}, "
                f"view={self.view_dtype})")


def _is_qp(x) -> bool:
    return isinstance(x, QuantPool)


def kv_quant_rows(vals):
    """Quantize K/V rows ``(..., head_dim)`` to the kv8 storage format:
    per-row symmetric int8 over head_dim. Returns ``(q int8, s f32)``
    with ``s`` shaped ``vals.shape[:-1]``. The op order here is the
    contract ``serving.quant.kv_fake_quant`` mirrors — keep them in
    lockstep or the paged==dense parity pin breaks."""
    import jax.numpy as jnp

    v = vals.astype(jnp.float32)
    amax = jnp.max(jnp.abs(v), axis=-1)
    s = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(v / s[..., None]), -127, 127).astype(jnp.int8)
    return q, s


def _zip_map(fn, pools, vals):
    """Map ``fn(pool_leaf, val_leaf)`` over a pools tree whose leaves may
    be :class:`QuantPool` nodes and a vals tree of plain arrays at the
    same positions — explicit flatten/zip/unflatten, because two-tree
    ``tree_map`` would descend INTO the QuantPool children on one side
    only."""
    import jax
    pl, treedef = jax.tree_util.tree_flatten(pools, is_leaf=_is_qp)
    vl = jax.tree_util.tree_leaves(vals)
    if len(pl) != len(vl):
        raise ValueError(f"pools/vals leaf mismatch: {len(pl)} vs {len(vl)}")
    return jax.tree_util.tree_unflatten(
        treedef, [fn(p, v) for p, v in zip(pl, vl)])


# --------------------------------------------------------- device helpers
def gather_cache(pools, pages):
    """Rebuild a slot's contiguous cache view from its page table row.

    ``pools``: pytree with leaves (pool_pages, kv_heads, page_tokens,
    head_dim); ``pages``: (max_pages,) int32 page ids (0 = null). Returns
    the same pytree with leaves (kv_heads, max_pages*page_tokens,
    head_dim) — the exact shape ``model.decode_logits`` expects, so the
    decode graph is unchanged; only where the bytes live changed.
    """
    import jax
    import jax.numpy as jnp

    def g(leaf):
        if _is_qp(leaf):
            # kv8 (ISSUE 17): 8-bit gather out of HBM, dequantize into
            # the transient view — q * s row-wise, the exact inverse of
            # the scatter-side kv_quant_rows
            x = jnp.take(leaf.q, pages, axis=0)   # (mp, kh, pt, hd) i8
            s = jnp.take(leaf.s, pages, axis=0)   # (mp, kh, pt) f32
            v = (x.astype(jnp.float32) * s[..., None]).astype(
                leaf.view_dtype)
            mp, kh, pt, hd = v.shape
            return v.transpose(1, 0, 2, 3).reshape(kh, mp * pt, hd)
        x = jnp.take(leaf, pages, axis=0)      # (mp, kh, pt, hd)
        mp, kh, pt, hd = x.shape
        return x.transpose(1, 0, 2, 3).reshape(kh, mp * pt, hd)

    return jax.tree_util.tree_map(g, pools, is_leaf=_is_qp)


def scatter_tokens(pools, tok_kv, page_ids, offsets):
    """Write per-token K/V back into the pools.

    ``tok_kv``: pytree with leaves (n, kv_heads, head_dim) — n writes;
    ``page_ids``/``offsets``: (n,) int32. Slots own disjoint pages so
    real writes never collide; junk writes all land in null page 0.
    """
    def s(pool, vals):
        if _is_qp(pool):
            # quantize-on-write: the row's scale lands in the scale
            # plane at the same (page, head, offset) address
            q, sc = kv_quant_rows(vals)      # (n, kh, hd) i8 / (n, kh)
            return QuantPool(
                pool.q.at[page_ids, :, offsets, :].set(q),
                pool.s.at[page_ids, :, offsets].set(sc),
                pool.view_dtype)
        return pool.at[page_ids, :, offsets, :].set(
            vals.astype(pool.dtype))

    return _zip_map(s, pools, tok_kv)


def scatter_pages(pools, cache, pages):
    """Repack a contiguous (1, kv_heads, max_pages*pt, head_dim) cache
    into pool pages ``pages`` ((max_pages,) int32) — the post-prefill
    write. Tail entries past the reservation are 0: those page-sized
    chunks of pad K/V pile into the null page, harmlessly."""
    def s(pool, c):
        kh, length, hd = c.shape[1], c.shape[2], c.shape[3]
        mp = pages.shape[0]
        pt = length // mp
        x = c[0].reshape(kh, mp, pt, hd).transpose(1, 0, 2, 3)
        if _is_qp(pool):
            q, sc = kv_quant_rows(x)  # (mp, kh, pt, hd) i8 / (mp, kh, pt)
            return QuantPool(pool.q.at[pages].set(q),
                             pool.s.at[pages].set(sc),
                             pool.view_dtype)
        return pool.at[pages].set(x.astype(pool.dtype))

    return _zip_map(s, pools, cache)


def copy_pages(pools, src, dst):
    """Device-copy pages ``src`` -> ``dst`` ((n,) int32 each) across
    every layer pool — the shared-prefix-cache hit/insert primitive."""
    import jax
    import jax.numpy as jnp

    def c(pool):
        if _is_qp(pool):
            # already 8-bit at rest: copy q and scale rows verbatim, no
            # re-quantization loss on prefix-cache hits
            return QuantPool(
                pool.q.at[dst].set(jnp.take(pool.q, src, axis=0)),
                pool.s.at[dst].set(jnp.take(pool.s, src, axis=0)),
                pool.view_dtype)
        return pool.at[dst].set(jnp.take(pool, src, axis=0))

    return jax.tree_util.tree_map(c, pools, is_leaf=_is_qp)


# ------------------------------------------------------------- allocation
class PageAllocator:
    """Host-side free list over page ids 1..pool_pages-1 (0 = null)."""

    def __init__(self, pool_pages: int):
        if pool_pages < 2:
            raise ValueError(f"pool needs >= 2 pages (1 null + 1 real), "
                             f"got {pool_pages}")
        self.pool_pages = int(pool_pages)
        self._free: collections.deque = collections.deque(
            range(1, self.pool_pages))

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return (self.pool_pages - 1) - len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """n pages, or None if the pool can't serve them (caller queues)."""
        if n > len(self._free):
            return None
        return [self._free.popleft() for _ in range(n)]

    def free(self, pages) -> None:
        for p in pages:
            if not 1 <= p < self.pool_pages:
                raise ValueError(f"freeing invalid page id {p}")
            self._free.append(int(p))


class PagedKvCache:
    """Pools + per-slot page tables + the allocator, owned by
    :class:`bigdl_tpu.serving.decode.DecodeEngine` when
    ``kv_page_tokens`` is set.

    ``pool_pages`` defaults to ``1 + slots * max_pages_per_slot`` — the
    dense footprint, so default behaviour is never worse; raise ``slots``
    or add prefix-cache headroom without growing it to see the paging
    win, or shrink it to run more slots in fixed HBM.
    """

    def __init__(self, encoder, *, slots: int, max_len: int,
                 page_tokens: int, dtype, pool_pages: Optional[int] = None,
                 extra_pages: int = 0, sharding=None,
                 quantized: bool = False):
        import numpy as np

        page_tokens = int(page_tokens)
        if page_tokens < 1:
            raise ValueError(f"page_tokens must be >= 1, got {page_tokens}")
        if max_len % page_tokens:
            raise ValueError(
                f"kv page_tokens ({page_tokens}) must divide max_len "
                f"({max_len}) so the gathered view is exactly max_len")
        self.page_tokens = page_tokens
        self.max_len = int(max_len)
        self.slots = int(slots)
        self.max_pages = max_len // page_tokens
        if pool_pages is None:
            pool_pages = 1 + self.slots * self.max_pages + int(extra_pages)
        self.pool_pages = int(pool_pages)
        self.alloc = PageAllocator(self.pool_pages)
        # pools: template one-page cache broadcast to pool_pages
        import jax
        import jax.numpy as jnp
        self.quantized = bool(quantized)
        tmpl = encoder.init_cache(1, page_tokens, dtype)
        if self.quantized:
            # kv8 (ISSUE 17): int8 pools + f32 per-row scale planes at
            # the same leaf positions — the device helpers dispatch on
            # the QuantPool node, every program shape stays put
            def mk(a):
                kh, pt, hd = a.shape[1], a.shape[2], a.shape[3]
                return QuantPool(
                    jnp.zeros((self.pool_pages, kh, pt, hd), jnp.int8),
                    jnp.zeros((self.pool_pages, kh, pt), jnp.float32),
                    dtype)
            self.pools = jax.tree_util.tree_map(mk, tmpl)
        else:
            self.pools = jax.tree_util.tree_map(
                lambda a: jnp.zeros((self.pool_pages,) + a.shape[1:],
                                    a.dtype),
                tmpl)
        # tp (ISSUE 16): commit the pools to the caller's layout (a
        # per-leaf callable, e.g. ServingSharding.kv_sharding — kv_heads
        # dim split over the model axis) and keep the sharding pytree so
        # the engine pins it on every pool-returning program
        if sharding is not None:
            self.pool_shardings = jax.tree_util.tree_map(
                lambda a: sharding(a), self.pools)
            self.pools = jax.device_put(self.pools, self.pool_shardings)
        else:
            self.pool_shardings = None
        self._bytes_per_page = sum(
            int(np.prod(a.shape[1:])) * a.dtype.itemsize
            for a in jax.tree_util.tree_leaves(self.pools))
        # host page table mirrors what the device jits are handed
        self.page_table = np.zeros((self.slots, self.max_pages), np.int32)
        self.slot_pages: List[List[int]] = [[] for _ in range(self.slots)]

    # ------------------------------------------------------------- slots
    def reserve(self, slot: int, n_tokens: int) -> bool:
        """Reserve pages covering ``n_tokens`` for ``slot``; False if the
        pool can't serve it right now (request stays queued)."""
        need = pages_needed(n_tokens, self.page_tokens)
        got = self.alloc.alloc(need)
        if got is None:
            return False
        self.release(slot)
        self.slot_pages[slot] = got
        self.page_table[slot, :] = 0
        self.page_table[slot, :need] = got
        return True

    def release(self, slot: int) -> None:
        if self.slot_pages[slot]:
            self.alloc.free(self.slot_pages[slot])
            self.slot_pages[slot] = []
        self.page_table[slot, :] = 0

    # ----------------------------------------------------------- metrics
    @property
    def bytes_per_page(self) -> int:
        return self._bytes_per_page

    def allocated_bytes(self) -> int:
        """Bytes backing pages actually handed out — what
        ``kv_cache_bytes`` reports in paged mode (vs the dense max-len
        bound it used to report; ISSUE 14 satellite)."""
        return self.alloc.pages_in_use * self._bytes_per_page

    def pool_bytes(self) -> int:
        return self.pool_pages * self._bytes_per_page


def _register():
    import jax
    jax.tree_util.register_pytree_node_class(QuantPool)


_register()
