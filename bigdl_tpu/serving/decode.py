"""Incremental generation for ``transformer_lm`` with a preallocated
KV cache and continuous-batching slots.

``TransformerLM.generate`` is the offline shape of decoding: one request,
one fori_loop, prompt and token budget baked into the compile. An online
server cannot afford that — every (prompt_len, max_new) pair would be a
fresh XLA program, and concurrent requests would each run their own
batch-1 decode at ~1/slots of the achievable throughput. This module
splits decoding the way serving systems do (Orca-style continuous
batching):

* **prefill** — one compiled program per PROMPT-LENGTH BUCKET
  (``ops.attention_kernel.serving_prefill_buckets`` keeps the ladder on
  the flash kernel's zero-padding block plans): the prompt, right-padded
  to its bucket, runs once through ``model.prefill_logits`` building a
  batch-1 K/V cache, exact because causal attention never reads past the
  true last position and decode overwrites pad K/V before attending it;

* **decode** — ONE compiled per-token step over all ``slots``
  (``jax.vmap`` of ``model.decode_logits`` with per-slot positions), so
  requests of different lengths and arrival times share the batch. A
  finishing request frees its slot; the next waiting request prefills
  into it while the others keep decoding.

ISSUE 14 rebuilt the hot path around three composable optimisations:

* **sampling modes** — temperature / top-k / top-p with PER-REQUEST
  seeds (``spec_decode.warp_logits``; randomness is counter-based off
  the seed, so outputs are deterministic and replayable). The sort-free
  program still serves requests that only use temperature.
* **speculative decoding** (``speculate=K``) — a draft LM proposes K
  tokens per round, the target scores all K+1 positions in ONE chunked
  ``verify_logits`` dispatch, and exact acceptance keeps greedy output
  bit-identical / sampled output distribution-correct
  (:mod:`bigdl_tpu.serving.spec_decode`). Target dispatches per emitted
  token drop from 1 to 1/(accepted+1).
* **paged KV** (``kv_page_tokens=N``) — the dense ``slots x max_len``
  cache becomes pools of N-token pages with per-slot page tables
  (:mod:`bigdl_tpu.serving.kv_pages`); short requests stop paying
  max-length HBM (``kv_cache_bytes`` now reports ALLOCATED pages) and
  admission reserves a request's full page budget up front so decode
  never deadlocks mid-flight.
* **shared-prefix cache** (``prefix_cache=True``, needs paging) —
  prefills whose page-aligned token prefix hashes to a cached entry
  copy resident pages and chunk-prefill only the suffix
  (:mod:`bigdl_tpu.serving.prefix_cache`).

Greedy decoding (temperature 0) is bit-exact with the offline
full-sequence argmax decode (the acceptance contract; see
tests/test_serving.py) because both run the same ``prefill_logits`` /
``decode_logits`` graph per token — and speculative greedy is pinned
bit-identical to that in tests/test_spec_decode.py.
"""

from __future__ import annotations

import collections
import logging
import threading
from typing import Optional, Sequence

import numpy as np

from bigdl_tpu.obs.spans import span as _obs_span
from bigdl_tpu.serving import kv_pages as _kvp
from bigdl_tpu.serving import spec_decode as _spec
from bigdl_tpu.serving.batcher import (AdmissionError, DeadlineExceeded,
                                       WorkerDied, _Future)
from bigdl_tpu.serving.prefix_cache import PrefixCache
from bigdl_tpu.serving.reqtrace import get as _get_reqtracer

logger = logging.getLogger(__name__)

__all__ = ["DecodeEngine", "DecodeRequest"]


class DecodeRequest:
    __slots__ = ("tokens", "max_new_tokens", "temperature", "stop_token",
                 "top_k", "top_p", "seed", "future", "out", "deadline",
                 "rid", "emit")

    def __init__(self, tokens, max_new_tokens, temperature=0.0,
                 stop_token=None, deadline=None, top_k=0, top_p=1.0,
                 seed=0, rid=None, emit=None):
        self.tokens = [int(t) for t in tokens]
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.stop_token = stop_token
        self.deadline = deadline
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = int(seed) & 0xFFFFFFFF
        self.future = _Future()
        self.out: list = []
        self.rid = rid  # lifecycle-trace request id (ISSUE 15)
        # streaming sink (ISSUE 18): called as emit(new_tokens, done)
        # after every round that appended tokens — only ACCEPTED tokens
        # reach it on the speculative path, so streamed output is
        # structurally identical to the buffered future result
        self.emit = emit


class DecodeEngine:
    """Continuous-batching KV-cache decoder over a fixed slot count.

    ``slots`` bounds the decode batch (and, dense, the cache HBM
    footprint: slots x layers x kv_heads x max_len x head_dim x 2;
    paged, the page-table width — HBM then follows ALLOCATED pages).
    ``submit`` assigns a free slot (prefill) or queues up to
    ``max_waiting`` requests, rejecting beyond that
    (:class:`AdmissionError` -> 429). ``step`` advances every active
    slot — one token each plain, up to ``speculate+1`` each
    speculative. Without a worker thread the caller drives ``step``
    (tests, ``generate``); ``start()`` launches the decode loop for the
    HTTP server.

    * ``kv_page_tokens`` — page size in tokens; None keeps the dense
      layout. Must divide ``max_len``. ``pool_pages`` overrides the
      pool size (default = the dense footprint + ``prefix_cache``
      headroom).
    * ``speculate`` — draft chunk length K; 0 disables. ``draft_model``
      / ``draft_params`` supply the proposer (default: the target
      itself — "self-draft", 100% greedy acceptance, useful for
      dispatch-count wins and CI determinism).
    * ``prefix_cache`` — share page-aligned prompt-prefix K/V across
      requests (requires paging).
    * ``quantize`` — ``--quantize`` mode (ISSUE 17): int8/fp8 weights
      via ``serving.quant``, ``kv8`` stores the page pools 8-bit
      (requires paging). ``off``/None is byte-identical to the
      unquantized path — no quant code runs.
    """

    def __init__(self, model, params, *, slots: int = 4,
                 max_len: Optional[int] = None, cache_dtype=None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 max_waiting: int = 64, metrics=None,
                 clock=None, kv_page_tokens: Optional[int] = None,
                 pool_pages: Optional[int] = None, speculate: int = 0,
                 draft_model=None, draft_params=None,
                 prefix_cache: bool = False,
                 prefix_cache_pages: Optional[int] = None,
                 mesh=None, model_axis: str = "model",
                 quantize: Optional[str] = None):
        import jax
        import jax.numpy as jnp
        import time as _time

        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if speculate < 0:
            raise ValueError(f"speculate must be >= 0, got {speculate}")
        self.clock = clock or _time.monotonic
        self._worker_error: Optional[BaseException] = None
        self._last_beat = self.clock()
        self.model = model
        # ---- quantized serving (ISSUE 17): weights go 8-bit BEFORE tp
        # placement so each scale vector ships to the mesh alongside its
        # weight (column-split weight -> split scale). Idempotent: trees
        # cli/serve already quantized pass through untouched.
        from bigdl_tpu.serving import quant as _q
        self.quantize = quantize if quantize else "off"
        self._wfmt, self._kv8 = _q.parse_quantize(quantize)
        if self._wfmt is not None:
            params = _q.quantize_params(params, self._wfmt)
            if draft_model is not None and draft_params is not None:
                draft_params = _q.quantize_params(draft_params, self._wfmt)
        # ---- tp placement (ISSUE 16): params go to the mesh under the
        # Megatron layout, KV leaves split on the kv_heads dim, logits /
        # host scalars stay replicated. mesh=None keeps the single-chip
        # path byte-for-byte (a 1-device mesh = a pinned dp replica).
        self.mesh = mesh
        if mesh is not None:
            from bigdl_tpu.serving.sharding import ServingSharding
            self._shard = ServingSharding(mesh, axis=model_axis)
            params = self._shard.place_params(model, params)
        else:
            self._shard = None
        self.params = params
        self.slots = int(slots)
        self.max_len = int(max_len or model.max_len)
        self.cache_dtype = cache_dtype or model.compute_dtype or jnp.float32
        self.max_waiting = int(max_waiting)
        self.speculate = int(speculate)
        self._jax, self._jnp = jax, jnp

        if prompt_buckets is None:
            from bigdl_tpu.ops.attention_kernel import serving_prefill_buckets
            head_dim = getattr(
                model.encoder._modules[0].mha, "head_dim",
                model.d_model // 4)
            prompt_buckets = serving_prefill_buckets(
                self.max_len, head_dim, True, self.cache_dtype)
        self.prompt_buckets = tuple(sorted(set(int(b)
                                               for b in prompt_buckets)))

        self._lock = threading.RLock()
        self._work = threading.Condition(self._lock)
        self._reqs: list = [None] * self.slots
        self._waiting: collections.deque = collections.deque()

        # ---- KV backend: dense slab or page pools (ISSUE 14) -------------
        self.page_tokens = int(kv_page_tokens) if kv_page_tokens else None
        self.paged = self.page_tokens is not None
        if prefix_cache and not self.paged:
            raise ValueError("prefix_cache requires kv_page_tokens "
                             "(prefix sharing is a page copy)")
        if self._kv8 and not self.paged:
            raise ValueError("--quantize kv8 requires kv_page_tokens "
                             "(8-bit KV is a page-pool layout)")
        if self.paged:
            extra = 0
            if prefix_cache and pool_pages is None:
                # headroom so a warm prefix cache never starves decode
                extra = prefix_cache_pages or (
                    self.max_len // self.page_tokens)
            self._kv = _kvp.PagedKvCache(
                model.encoder, slots=self.slots, max_len=self.max_len,
                page_tokens=self.page_tokens, dtype=self.cache_dtype,
                pool_pages=pool_pages, extra_pages=extra,
                sharding=(self._shard.kv_sharding
                          if self._shard is not None else None),
                quantized=self._kv8)
            self._cache = None
        else:
            self._kv = None
            self._cache = model.encoder.init_cache(
                self.slots, self.max_len, self.cache_dtype)
            if self._shard is not None:
                self._cache = self._shard.place_kv(self._cache)
        self._pfx = (PrefixCache(self._kv, max_pages=prefix_cache_pages,
                                 metrics=metrics)
                     if prefix_cache else None)

        self._logits = jnp.zeros((self.slots, model.vocab), jnp.float32)
        if self._shard is not None:
            self._logits = jax.device_put(self._logits,
                                          self._shard.replicated)
        self._pos = np.zeros(self.slots, np.int32)
        self._temp = np.zeros(self.slots, np.float32)
        self._topk = np.zeros(self.slots, np.int32)
        self._topp = np.ones(self.slots, np.float32)
        self._seed = np.zeros(self.slots, np.uint32)
        self._pending = np.zeros(self.slots, np.int32)  # speculative only
        self._thread = None
        self._closed = False

        # ---- draft model (speculative) -----------------------------------
        if self.speculate > 0:
            self.draft_model = draft_model or model
            self.draft_params = (draft_params if draft_model is not None
                                 else params)
            if draft_model is not None and draft_params is None:
                raise ValueError("draft_model without draft_params")
            self._draft_dtype = (self.draft_model.compute_dtype
                                 or jnp.float32)
            self._draft_cache = self.draft_model.encoder.init_cache(
                self.slots, self.max_len, self._draft_dtype)
            if self._shard is not None:
                # a distinct draft model gets its own Megatron layout
                # (the self-draft default already shares the placed
                # target params)
                if draft_model is not None:
                    self.draft_params = self._shard.place_params(
                        self.draft_model, self.draft_params)
                self._draft_cache = self._shard.place_kv(self._draft_cache)
        else:
            self.draft_model = self.draft_params = None
            self._draft_cache = None

        self._init_metrics(metrics)
        self._build_programs()

    # -------------------------------------------------------------- metrics
    def _init_metrics(self, metrics) -> None:
        self.metrics = metrics
        if metrics is None:
            self._m_tokens = self._m_steps = self._m_prefills = None
            self._m_prompt_tokens = self._m_rejected = None
            self._m_expired = self._m_dead = self._m_cancelled = None
            self._m_spec_prop = self._m_spec_acc = None
            self._m_draft_steps = None
            return
        self._m_tokens = metrics.counter(
            "generated_tokens_total", "decode tokens emitted")
        self._m_steps = metrics.counter(
            "decode_steps_total",
            "batched TARGET-model decode/verify steps executed")
        self._m_prefills = metrics.counter(
            "prefills_total", "prompt prefills executed")
        self._m_prompt_tokens = metrics.counter(
            "prompt_tokens_total", "prompt tokens prefilled")
        self._m_rejected = metrics.counter(
            "decode_rejected_total",
            "generate requests fast-rejected (waiting queue full)")
        self._m_expired = metrics.counter(
            "decode_expired_total",
            "generate requests dropped on deadline expiry")
        self._m_dead = metrics.counter(
            "decode_dead_submit_total",
            "generate submits fast-failed (decode worker dead)")
        self._m_cancelled = metrics.counter(
            "decode_cancelled_total",
            "generate requests cancelled mid-flight (client disconnect)")
        metrics.gauge("decode_worker_up",
                      "1 while the decode loop is healthy",
                      fn=lambda: 0.0 if self._worker_error else 1.0)
        metrics.gauge("decode_slots_active", "occupied decode slots",
                      fn=lambda: sum(r is not None for r in self._reqs))
        metrics.gauge(
            "decode_tokens_per_second",
            "lifetime generated_tokens_total / uptime",
            fn=lambda: (self._m_tokens.value
                        / max(metrics.uptime_s(), 1e-9)))
        # KV-cache byte accounting (ISSUE 12, corrected by ISSUE 14):
        # paged mode reports ALLOCATED pages — the real resident cost —
        # not the dense max-len bound the gauges used to assume
        from bigdl_tpu.obs.memory import tree_bytes as _kv_bytes
        if self.paged:
            metrics.gauge("kv_cache_bytes",
                          "allocated KV page bytes (all slots + prefix "
                          "cache)",
                          fn=lambda: self._kv.allocated_bytes())
            metrics.gauge("kv_cache_bytes_per_slot",
                          "allocated KV page bytes / slots",
                          fn=lambda: (self._kv.allocated_bytes()
                                      / max(1, self.slots)))
            metrics.gauge("kv_pages_in_use", "KV pool pages handed out",
                          fn=lambda: self._kv.alloc.pages_in_use)
            metrics.gauge("kv_page_occupancy_frac",
                          "live tokens / (pages_in_use x page_tokens)",
                          fn=self._page_occupancy)
            logger.info(
                "decode KV pages: %d-token pages, pool %d pages "
                "(%d bytes; dense bound was %d bytes)",
                self.page_tokens, self._kv.pool_pages,
                self._kv.pool_bytes(),
                self.slots * self._kv.max_pages * self._kv.bytes_per_page)
        else:
            kv_total = _kv_bytes(self._cache)
            metrics.gauge("kv_cache_bytes",
                          "resident KV cache bytes (all slots, max_len)",
                          fn=lambda: _kv_bytes(self._cache))
            metrics.gauge("kv_cache_bytes_per_slot",
                          "resident KV cache bytes per decode slot",
                          fn=lambda: (_kv_bytes(self._cache)
                                      / max(1, self.slots)))
            logger.info("decode KV cache: %d bytes (%d slots x max_len "
                        "%d, %s)", kv_total, self.slots, self.max_len,
                        self.cache_dtype)
        if self.speculate > 0:
            self._m_spec_prop = metrics.counter(
                "spec_proposed_total", "draft tokens proposed")
            self._m_spec_acc = metrics.counter(
                "spec_accepted_total", "draft tokens accepted by verify")
            self._m_draft_steps = metrics.counter(
                "spec_draft_steps_total", "draft-model decode steps")
            metrics.gauge(
                "spec_accept_rate",
                "accepted / proposed draft tokens",
                fn=lambda: (self._m_spec_acc.value
                            / max(self._m_spec_prop.value, 1)))
            metrics.gauge(
                "spec_accepted_tokens_per_step",
                "tokens emitted per target verify step",
                fn=lambda: (self._m_tokens.value
                            / max(self._m_steps.value, 1)))
        else:
            self._m_spec_prop = self._m_spec_acc = None
            self._m_draft_steps = None

    def kv_bytes(self) -> int:
        """Resident KV bytes — allocated pages when paged, the dense
        slab otherwise. Per-replica truth; the dp fleet aggregate sums
        this across replicas (ISSUE 16 satellite)."""
        if self.paged:
            return self._kv.allocated_bytes()
        from bigdl_tpu.obs.memory import tree_bytes
        return tree_bytes(self._cache)

    def kv_pages_in_use(self) -> int:
        return self._kv.alloc.pages_in_use if self.paged else 0

    def queue_load(self) -> int:
        """Routing signal for dp replica selection: active slots plus
        waiting requests (approximate read — no lock; routing only needs
        a consistent ordering, not an exact census)."""
        return (sum(r is not None for r in self._reqs)
                + len(self._waiting))

    def _page_occupancy(self) -> float:
        live = int(sum(int(self._pos[i])
                       for i, r in enumerate(self._reqs) if r is not None))
        if self._pfx is not None:
            live += self._pfx.cached_tokens()
        cap = self._kv.alloc.pages_in_use * self.page_tokens
        return live / cap if cap else 0.0

    # ---------------------------------------------------- compiled programs
    def _build_programs(self) -> None:
        jax, jnp = self._jax, self._jnp
        model = self.model
        # donation keeps the big cache in place on device backends; CPU
        # can't honor it and warns on every compile
        self._don = jax.default_backend() != "cpu"

        # tp (ISSUE 16): precompute the sharding pytrees pinned as
        # out_shardings on every program whose output feeds persistent
        # state (_logits / _cache / pools / draft cache) — the layout is
        # decided once here, never re-derived per compile, so sharded
        # state cannot ping-pong between layouts across the lazily-keyed
        # program caches
        shard = self._shard
        if shard is not None:
            cache1_abs = jax.eval_shape(
                lambda: model.encoder.init_cache(1, self.max_len,
                                                 self.cache_dtype))
            self._cache1_sh = shard.kv_shardings(cache1_abs)
            self._state_sh = (self._kv.pool_shardings if self.paged
                              else shard.kv_shardings(self._cache))
            self._repl_sh = shard.replicated
            self._draft_sh = (shard.kv_shardings(self._draft_cache)
                              if self._draft_cache is not None else None)
        else:
            self._cache1_sh = self._state_sh = self._repl_sh = None
            self._draft_sh = None

        def _prefill(params, tokens, last):
            # tokens (1, bucket) int32; last = true_len - 1 (traced)
            cache = model.encoder.init_cache(1, self.max_len,
                                             self.cache_dtype)
            logits, cache = model.prefill_logits(params, tokens, cache,
                                                 last)
            return logits[0].astype(jnp.float32), cache

        self._prefill_jit = jax.jit(  # one compile per bucket
            _prefill, **self._pin(self._repl_sh, self._cache1_sh))

        def _write_slot(cache_full, cache_one, slot):
            return jax.tree_util.tree_map(
                lambda f, o: jax.lax.dynamic_update_index_in_dim(
                    f, o[0].astype(f.dtype), slot, 0),
                cache_full, cache_one)

        self._write_slot = jax.jit(
            _write_slot, donate_argnums=(0,) if self._don else ())
        if self.paged:
            self._scatter_prefill = jax.jit(
                _kvp.scatter_pages,
                donate_argnums=(0,) if self._don else (),
                **self._pin(self._state_sh))
            self._copy_pages_jit = jax.jit(
                _kvp.copy_pages,
                donate_argnums=(0,) if self._don else (),
                **self._pin(self._state_sh))
        # single-vector sampler: install-time first token (speculative)
        self._sample1_jit = jax.jit(
            lambda lg, t, k, p, seed, pos: _spec.sample_token(
                lg, t, k, p, _spec.request_key(seed, pos)))
        # lazily-built program caches, keyed by shape/variant
        self._step_programs: dict = {}
        self._verify_programs: dict = {}
        self._accept_programs: dict = {}
        self._suffix_programs: dict = {}
        self._draft_step_jit = None

    def _pin(self, *out_sh):
        """``out_shardings=`` kwarg for a jit whose outputs must land in
        the tp layout (``{}`` when unsharded — the single-chip programs
        are untouched). Positional order mirrors the program's outputs;
        a single entry pins a single-output program."""
        if self._shard is None:
            return {}
        return {"out_shardings": (out_sh if len(out_sh) > 1
                                  else out_sh[0])}

    def _sample_fn(self, warp: bool):
        jax, jnp = self._jax, self._jnp

        def fn(logits, pos, temp, topk, topp, seed):
            key = _spec.request_key(seed, pos)
            if warp:
                return _spec.sample_token(logits, temp, topk, topp, key)
            greedy = jnp.argmax(logits).astype(jnp.int32)
            safe_t = jnp.where(temp > 0, temp, 1.0)
            sampled = jax.random.categorical(
                key, logits / safe_t).astype(jnp.int32)
            return jnp.where(temp > 0, sampled, greedy)

        return fn

    def _get_step(self, warp: bool):
        """The plain per-token step. ``warp=False`` is the sort-free
        program (greedy/temperature-only traffic); ``warp=True`` adds
        the top-k/top-p filters. Both sample identically when the
        filters are disabled, so program choice never changes output."""
        key = ("paged" if self.paged else "dense", warp)
        prog = self._step_programs.get(key)
        if prog is not None:
            return prog
        jax, jnp = self._jax, self._jnp
        model, sample = self.model, self._sample_fn(warp)

        if not self.paged:
            def _one(params, logits, cache1, pos, temp, topk, topp, seed):
                tok = sample(logits, pos, temp, topk, topp, seed)
                cache_b = jax.tree_util.tree_map(lambda a: a[None], cache1)
                lg, cache_b = model.decode_logits(params, tok[None, None],
                                                  cache_b, pos)
                return (tok, lg[0].astype(jnp.float32),
                        jax.tree_util.tree_map(lambda a: a[0], cache_b))

            prog = jax.jit(
                jax.vmap(_one, in_axes=(None, 0, 0, 0, 0, 0, 0, 0)),
                donate_argnums=(1, 2) if self._don else (),
                **self._pin(self._repl_sh, self._repl_sh,
                            self._state_sh))
        else:
            pt = self.page_tokens

            def _paged_step(params, logits, pools, table, pos, temp,
                            topk, topp, seed):
                def _one(logits, pages, pos, temp, topk, topp, seed):
                    tok = sample(logits, pos, temp, topk, topp, seed)
                    cache1 = _kvp.gather_cache(pools, pages)
                    cache_b = jax.tree_util.tree_map(
                        lambda a: a[None], cache1)
                    lg, cache_b = model.decode_logits(
                        params, tok[None, None], cache_b, pos)
                    tok_kv = jax.tree_util.tree_map(
                        lambda c: jax.lax.dynamic_slice_in_dim(
                            c[0], pos, 1, axis=1)[:, 0, :], cache_b)
                    return tok, lg[0].astype(jnp.float32), tok_kv

                toks, lgs, tok_kv = jax.vmap(_one)(
                    logits, table, pos, temp, topk, topp, seed)
                page_ids = jnp.take_along_axis(
                    table, (pos // pt)[:, None], axis=1)[:, 0]
                pools2 = _kvp.scatter_tokens(pools, tok_kv, page_ids,
                                             pos % pt)
                return toks, lgs, pools2

            prog = jax.jit(
                _paged_step,
                donate_argnums=(1, 2) if self._don else (),
                **self._pin(self._repl_sh, self._repl_sh,
                            self._state_sh))
        self._step_programs[key] = prog
        return prog

    def _get_draft_step(self):
        if self._draft_step_jit is not None:
            return self._draft_step_jit
        jax, jnp = self._jax, self._jnp
        dmodel = self.draft_model

        def _one(dparams, tok, cache1, pos, temp, topk, topp, seed):
            cache_b = jax.tree_util.tree_map(lambda a: a[None], cache1)
            lg, cache_b = dmodel.decode_logits(dparams, tok[None, None],
                                               cache_b, pos)
            prop, q = _spec.draft_propose(lg[0].astype(jnp.float32),
                                          temp, topk, topp, seed, pos)
            return (prop, q,
                    jax.tree_util.tree_map(lambda a: a[0], cache_b))

        self._draft_step_jit = jax.jit(
            jax.vmap(_one, in_axes=(None, 0, 0, 0, 0, 0, 0, 0)),
            donate_argnums=(2,) if self._don else (),
            **self._pin(self._repl_sh, self._repl_sh, self._draft_sh))
        return self._draft_step_jit

    def _get_verify(self, m: int):
        prog = self._verify_programs.get(m)
        if prog is not None:
            return prog
        jax, jnp = self._jax, self._jnp
        model = self.model

        if not self.paged:
            def _verify(params, toks, cache, pos):
                def _one(toks1, cache1, pos):
                    cache_b = jax.tree_util.tree_map(
                        lambda a: a[None], cache1)
                    lg, cache_b = model.verify_logits(
                        params, toks1[None], cache_b, pos)
                    return (lg[0].astype(jnp.float32),
                            jax.tree_util.tree_map(lambda a: a[0],
                                                   cache_b))

                return jax.vmap(_one, in_axes=(0, 0, 0))(toks, cache, pos)

            prog = jax.jit(_verify,
                           donate_argnums=(2,) if self._don else (),
                           **self._pin(self._repl_sh, self._state_sh))
        else:
            pt = self.page_tokens

            def _verify(params, toks, pools, table, pos):
                def _one(toks1, pages, pos):
                    cache1 = _kvp.gather_cache(pools, pages)
                    cache_b = jax.tree_util.tree_map(
                        lambda a: a[None], cache1)
                    lg, cache_b = model.verify_logits(
                        params, toks1[None], cache_b, pos)
                    tok_kv = jax.tree_util.tree_map(
                        lambda c: jax.lax.dynamic_slice_in_dim(
                            c[0], pos, m, axis=1), cache_b)  # (kh, m, hd)
                    return lg[0].astype(jnp.float32), tok_kv

                lgs, tok_kv = jax.vmap(_one)(toks, table, pos)
                abspos = pos[:, None] + jnp.arange(m)[None, :]  # (S, m)
                page_ids = jnp.take_along_axis(table, abspos // pt,
                                               axis=1).reshape(-1)
                offs = (abspos % pt).reshape(-1)
                flat = jax.tree_util.tree_map(
                    lambda c: c.transpose(0, 2, 1, 3).reshape(
                        (-1,) + c.shape[1:2] + c.shape[3:]), tok_kv)
                pools2 = _kvp.scatter_tokens(pools, flat, page_ids, offs)
                return lgs, pools2

            prog = jax.jit(_verify,
                           donate_argnums=(2,) if self._don else (),
                           **self._pin(self._repl_sh, self._state_sh))
        self._verify_programs[m] = prog
        return prog

    def _get_accept(self, m: int):
        prog = self._accept_programs.get(m)
        if prog is None:
            jax = self._jax
            prog = jax.jit(jax.vmap(_spec.accept_chunk,
                                    in_axes=(0, 0, 0, 0, 0, 0, 0, 0)))
            self._accept_programs[m] = prog
        return prog

    def _get_suffix(self, mb: int):
        """Chunked suffix prefill at a page-aligned offset — the
        prefix-cache HIT path (paged only)."""
        prog = self._suffix_programs.get(mb)
        if prog is not None:
            return prog
        jax, jnp = self._jax, self._jnp
        model = self.model

        def _suffix(params, toks, pages, pos0, last, pools):
            cache1 = _kvp.gather_cache(pools, pages)
            cache_b = jax.tree_util.tree_map(lambda a: a[None], cache1)
            lgs, cache_b = model.verify_logits(params, toks, cache_b,
                                               pos0)
            lg = jax.lax.dynamic_slice_in_dim(
                lgs[0], last, 1, axis=0)[0].astype(jnp.float32)
            pools2 = _kvp.scatter_pages(pools, cache_b, pages)
            return lg, pools2

        prog = jax.jit(_suffix, donate_argnums=(5,) if self._don else (),
                       **self._pin(self._repl_sh, self._state_sh))
        self._suffix_programs[mb] = prog
        return prog

    def trace_step_jaxpr(self):
        """Jaxpr of the full-sampling decode step — what the tpulint
        decode rules inspect (``bigdl_tpu.analysis.run_decode_rules``)."""
        jax, jnp = self._jax, self._jnp
        S, V = self.slots, self.model.vocab
        f32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.float32)
        i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)
        sds = lambda a: jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), a)
        args = [sds(self.params), f32(S, V)]
        jnp_u32 = jax.ShapeDtypeStruct((S,), jnp.uint32)
        jax_fn = self._get_step(warp=True)
        if self.paged:
            args += [sds(self._kv.pools),
                     i32(S, self._kv.max_pages), i32(S), f32(S),
                     i32(S), f32(S), jnp_u32]
        else:
            args += [sds(self._cache), i32(S), f32(S), i32(S), f32(S),
                     jnp_u32]
        return jax.make_jaxpr(jax_fn)(*args)

    # ------------------------------------------------------------ admission
    def prompt_bucket_for(self, n: int) -> int:
        for b in self.prompt_buckets:
            if b >= n:
                return b
        return self.prompt_buckets[-1]

    def submit(self, tokens, max_new_tokens: int,
               temperature: float = 0.0, stop_token=None,
               deadline: Optional[float] = None, top_k: int = 0,
               top_p: float = 1.0, seed: int = 0,
               rid: Optional[str] = None, emit=None) -> _Future:
        """Queue one generation request; the future resolves to the list
        of generated token ids. Validates the length budget, fast-rejects
        when the waiting queue is full, when the decode worker is dead
        (:class:`WorkerDied` — nothing would ever drain the queue), or
        when ``deadline`` (absolute, on the engine's clock) has already
        passed (:class:`DeadlineExceeded`). ``top_k=0`` / ``top_p=1``
        disable those filters; ``seed`` makes sampled output
        deterministic per request; ``rid`` tags the request for
        lifecycle tracing (ISSUE 15); ``emit`` is an optional streaming
        sink called as ``emit(new_tokens, done)`` per emitting round
        (ISSUE 18) — called under the engine lock, so it must only hand
        tokens off (e.g. queue.put), never block."""
        tokens = list(tokens)
        if not tokens:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, "
                             f"got {max_new_tokens}")
        if len(tokens) + int(max_new_tokens) > self.max_len:
            raise ValueError(
                f"prompt ({len(tokens)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_len {self.max_len}")
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        req = DecodeRequest(tokens, max_new_tokens, temperature,
                            stop_token, deadline, top_k, top_p, seed,
                            rid=rid, emit=emit)
        with self._lock:
            if self._closed:
                raise RuntimeError("decode engine is closed")
            if self._worker_error is not None or (
                    self._thread is not None
                    and not self._thread.is_alive()):
                if self._m_dead is not None:
                    self._m_dead.inc()
                raise WorkerDied(
                    "decode worker is dead: "
                    f"{self._worker_error or 'thread exited'}")
            if deadline is not None and self.clock() >= deadline:
                if self._m_expired is not None:
                    self._m_expired.inc()
                raise DeadlineExceeded("deadline expired before submit")
            slot = self._free_slot()
            if slot is not None and self._install(req, slot):
                pass
            elif len(self._waiting) >= self.max_waiting:
                if self._m_rejected is not None:
                    self._m_rejected.inc()
                raise AdmissionError(
                    f"decode queue at capacity ({self.max_waiting} waiting)")
            else:
                self._waiting.append(req)
                if rid is not None:
                    rt = _get_reqtracer()
                    if rt is not None:
                        rt.note_queued(rid)
            self._work.notify()
        return req.future

    def _free_slot(self) -> Optional[int]:
        for i, r in enumerate(self._reqs):
            if r is None:
                return i
        return None

    def _release_slot(self, slot: int) -> None:
        self._reqs[slot] = None
        self._pos[slot] = 0
        if self.paged:
            self._kv.release(slot)

    def _handoff(self, slot: int) -> None:
        """Install the next waiting request into a freed slot. A paged
        reservation failure (pool still too full) puts the request back
        at the queue head — FIFO order is preserved and the request is
        retried as soon as more pages free up."""
        while self._waiting:
            req = self._waiting.popleft()
            if self._install(req, slot):
                return
            self._waiting.appendleft(req)
            return

    # -------------------------------------------------------------- prefill
    def _install(self, req: DecodeRequest, slot: int) -> bool:
        """Prefill ``req``'s prompt into ``slot`` (lock held). False iff
        the paged pool cannot serve the request's page reservation yet —
        the caller keeps it queued; nothing was spent."""
        jnp = self._jnp
        s = len(req.tokens)
        if self.paged and not self._kv.reserve(slot,
                                               s + req.max_new_tokens):
            return False
        rt = _get_reqtracer() if req.rid is not None else None
        t0_pf = rt.clock() if rt is not None else 0.0
        with _obs_span("decode_prefill", prompt=s):
            n_pfx, src_pages = (self._pfx.match(req.tokens)
                                if self._pfx is not None else (0, []))
            if n_pfx:
                logits_vec = self._prefill_from_prefix(
                    req, slot, n_pfx, src_pages)
            else:
                bucket = self.prompt_bucket_for(s)
                padded = np.zeros((1, bucket), np.int32)
                padded[0, :s] = req.tokens
                logits_vec, cache1 = self._prefill_jit(
                    self.params, jnp.asarray(padded), jnp.int32(s - 1))
                if self.paged:
                    self._kv.pools = self._scatter_prefill(
                        self._kv.pools, cache1,
                        jnp.asarray(self._kv.page_table[slot]))
                else:
                    self._cache = self._write_slot(self._cache, cache1,
                                                   jnp.int32(slot))
            if self._pfx is not None:
                self._maybe_insert_prefix(req, slot)
        self._logits = self._logits.at[slot].set(logits_vec)
        self._pos[slot] = s
        self._temp[slot] = req.temperature
        self._topk[slot] = req.top_k
        self._topp[slot] = req.top_p
        self._seed[slot] = req.seed
        self._reqs[slot] = req
        if self._m_prefills is not None:
            self._m_prefills.inc()
            self._m_prompt_tokens.inc(s - n_pfx)
        if rt is not None:
            rt.note_prefill(
                req.rid, t0_pf, rt.clock(), slot=slot,
                prefix_hit_tokens=n_pfx,
                pages=(len(self._kv.slot_pages[slot])
                       if self.paged else None))
        if self.speculate > 0:
            self._install_draft(req, slot)
            # speculative mode emits the first token NOW (it becomes the
            # round's pending feed) — same sample the plain step's first
            # iteration would draw (same key: fold_in(seed, pos=s))
            tok0 = int(self._sample1_jit(
                logits_vec, jnp.float32(req.temperature),
                jnp.int32(req.top_k), jnp.float32(req.top_p),
                jnp.uint32(req.seed), jnp.int32(s)))
            self._pending[slot] = tok0
            self._emit(req, slot, [tok0])
        return True

    def _prefill_from_prefix(self, req, slot: int, n_pfx: int, src_pages):
        """Prefix-cache HIT: device-copy the entry's pages into the
        slot, then chunk-prefill only the suffix at offset ``n_pfx`` —
        bit-identical to the full prefill (the copied K/V came from the
        identical graph; suffix rows compute the same per-row math)."""
        jnp = self._jnp
        s = len(req.tokens)
        pt = self.page_tokens
        dst = self._kv.page_table[slot, :n_pfx // pt]
        with _obs_span("prefix_copy", pages=len(src_pages)):
            self._kv.pools = self._copy_pages_jit(
                self._kv.pools, jnp.asarray(src_pages, jnp.int32),
                jnp.asarray(dst))
        suffix = req.tokens[n_pfx:]
        mb = min(self.prompt_bucket_for(len(suffix)),
                 self.max_len - n_pfx)
        padded = np.zeros((1, mb), np.int32)
        padded[0, :len(suffix)] = suffix
        logits_vec, self._kv.pools = self._get_suffix(mb)(
            self.params, jnp.asarray(padded),
            jnp.asarray(self._kv.page_table[slot]), jnp.int32(n_pfx),
            jnp.int32(len(suffix) - 1), self._kv.pools)
        return logits_vec

    def _maybe_insert_prefix(self, req, slot: int) -> None:
        ins = self._pfx.prepare_insert(req.tokens)
        if ins is None:
            return
        key, dst_pages = ins
        need = len(dst_pages)
        src = self._kv.page_table[slot, :need]
        jnp = self._jnp
        self._kv.pools = self._copy_pages_jit(
            self._kv.pools, jnp.asarray(src),
            jnp.asarray(dst_pages, jnp.int32))
        self._pfx.commit_insert(key, dst_pages, need * self.page_tokens)

    def _install_draft(self, req, slot: int) -> None:
        """Prefill the draft model's own (dense) cache for this slot."""
        jax, jnp = self._jax, self._jnp
        s = len(req.tokens)
        bucket = self.prompt_bucket_for(s)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :s] = req.tokens
        if not hasattr(self, "_draft_prefill_jit"):
            dmodel, ddtype = self.draft_model, self._draft_dtype

            def _dprefill(dparams, tokens, last):
                cache = dmodel.encoder.init_cache(1, self.max_len, ddtype)
                _, cache = dmodel.prefill_logits(dparams, tokens, cache,
                                                 last)
                return cache

            pin = {}
            if self._shard is not None:
                dcache1_abs = jax.eval_shape(
                    lambda: dmodel.encoder.init_cache(1, self.max_len,
                                                      ddtype))
                pin = self._pin(self._shard.kv_shardings(dcache1_abs))
            self._draft_prefill_jit = jax.jit(_dprefill, **pin)
        cache1 = self._draft_prefill_jit(
            self.draft_params, jnp.asarray(padded), jnp.int32(s - 1))
        self._draft_cache = self._write_slot(self._draft_cache, cache1,
                                             jnp.int32(slot))

    # ------------------------------------------------------------- emission
    def _emit(self, req, slot: int, toks, accepted=None) -> bool:
        """Append generated tokens to ``req`` (respecting stop token and
        max_new budget), resolve + hand off if finished. ``accepted`` is
        the speculative draft tokens the verify kept this round (None on
        the plain path). Returns True if the request completed. Lock
        held."""
        done = False
        emitted = 0
        for tok in toks:
            req.out.append(int(tok))
            emitted += 1
            if (len(req.out) >= req.max_new_tokens
                    or (req.stop_token is not None
                        and int(tok) == req.stop_token)):
                done = True
                break
        if self._m_tokens is not None and emitted:
            self._m_tokens.inc(emitted)
        rt = _get_reqtracer() if req.rid is not None else None
        if rt is not None and emitted:
            rt.note_round(
                req.rid, emitted, accepted=accepted,
                pages=(len(self._kv.slot_pages[slot])
                       if self.paged else None),
                pos=int(self._pos[slot]))
        if req.emit is not None and emitted:
            # streaming sink (ISSUE 18): hand the round's accepted
            # tokens to the HTTP handler's queue. A broken sink must
            # never take the decode loop (and every other slot) down —
            # the disconnect path is decoder.cancel(), not an exception
            # propagated from here.
            try:
                req.emit(req.out[-emitted:], done)
            except Exception:
                logger.exception("streaming emit sink failed (rid=%s)",
                                 req.rid)
        if done:
            self._release_slot(slot)
            req.future.set_result(list(req.out))
            if rt is not None:
                rt.finish(req.rid, "finished")
            self._handoff(slot)
        return done

    # ------------------------------------------------------------- deadlines
    def _expire(self, now: float) -> None:
        """Drop expired requests BEFORE compute is spent on them (lock
        held): waiting-queue entries simply resolve with
        :class:`DeadlineExceeded`; active slots free up and hand off to
        the next (still-live) waiting request."""
        rt = _get_reqtracer()
        if self._waiting:
            live = collections.deque()
            for req in self._waiting:
                if req.deadline is not None and now >= req.deadline:
                    if self._m_expired is not None:
                        self._m_expired.inc()
                    req.future.set_exception(DeadlineExceeded(
                        "deadline expired while waiting for a decode "
                        "slot"))
                    if rt is not None and req.rid is not None:
                        rt.finish(req.rid, "expired",
                                  error="expired in decode queue")
                else:
                    live.append(req)
            self._waiting = live
        for i, req in enumerate(self._reqs):
            if (req is not None and req.deadline is not None
                    and now >= req.deadline):
                self._release_slot(i)
                if self._m_expired is not None:
                    self._m_expired.inc()
                req.future.set_exception(DeadlineExceeded(
                    f"deadline expired after {len(req.out)} of "
                    f"{req.max_new_tokens} tokens"))
                if rt is not None and req.rid is not None:
                    rt.finish(req.rid, "expired",
                              error=f"expired mid-decode after "
                                    f"{len(req.out)} tokens")
                self._handoff(i)

    # --------------------------------------------------------- cancellation
    def cancel(self, rid: str, reason: str = "client disconnected") -> bool:
        """First-class mid-decode cancellation (ISSUE 18): drop the
        request identified by ``rid`` wherever it is — waiting queue or
        active slot — releasing the slot AND its paged-KV page
        reservation atomically under the engine lock, then hand the slot
        to the next waiting request. This is the primitive the streaming
        disconnect path uses (previously only deadline expiry and
        shutdown freed slots early).

        Returns True iff a request was found and cancelled. Safe against
        the speculative verify/accept race: ``step()`` holds the engine
        lock for the ENTIRE round (draft feeds, the chunked verify
        dispatch, acceptance, and emission), so a cancel landing between
        a verify dispatch and its accept simply waits for the round to
        retire — it can never free pages the in-flight verify is still
        writing, and a stale ``_pending`` feed is reset by the next
        ``_install`` into that slot."""
        if rid is None:
            return False
        err = RuntimeError(f"request {rid} cancelled: {reason}")
        rt = _get_reqtracer()
        with self._lock:
            for req in self._waiting:
                if req.rid == rid:
                    self._waiting.remove(req)
                    break
            else:
                req = None
            if req is None:
                for i, r in enumerate(self._reqs):
                    if r is not None and r.rid == rid:
                        req = r
                        self._release_slot(i)
                        self._handoff(i)
                        break
            if req is None:
                return False
            if self._m_cancelled is not None:
                self._m_cancelled.inc()
            self._work.notify()
        req.future.set_exception(err)
        if rt is not None:
            rt.finish(rid, "closed", error=reason)
        return True

    # ---------------------------------------------------------------- step
    def step(self) -> int:
        """One batched decode step: every active slot emits one token
        (plain) or up to ``speculate+1`` tokens (speculative round).
        Returns the number of active slots advanced (0 = idle). Finished
        requests resolve their futures and hand their slot to the next
        waiting request; expired ones are dropped before compute."""
        with self._lock:
            self._last_beat = self.clock()
            self._expire(self.clock())
            active = [i for i, r in enumerate(self._reqs)
                      if r is not None]
            if not active:
                return 0
            if self.speculate > 0:
                return self._step_spec(active)
            return self._step_plain(active)

    def _sampling_args(self):
        jnp = self._jnp
        return (jnp.asarray(self._pos), jnp.asarray(self._temp),
                jnp.asarray(self._topk), jnp.asarray(self._topp),
                jnp.asarray(self._seed))

    def _needs_warp(self, active) -> bool:
        return any(self._topk[i] > 0 or self._topp[i] < 1.0
                   for i in active)

    def _step_plain(self, active) -> int:
        jnp = self._jnp
        prog = self._get_step(self._needs_warp(active))
        pos, temp, topk, topp, seed = self._sampling_args()
        with _obs_span("decode_step", active=len(active)):
            try:
                if self.paged:
                    toks, self._logits, self._kv.pools = prog(
                        self.params, self._logits, self._kv.pools,
                        jnp.asarray(self._kv.page_table), pos, temp,
                        topk, topp, seed)
                else:
                    toks, self._logits, self._cache = prog(
                        self.params, self._logits, self._cache, pos,
                        temp, topk, topp, seed)
            except Exception as e:
                # RESOURCE_EXHAUSTED autopsy (ISSUE 12): the KV cache is
                # usually the culprit — report to --traceDir + fault
                # log, then die as before
                from bigdl_tpu.obs import memory as _obs_mem
                _obs_mem.handle_oom(e, "decode_step")
                raise
            toks_host = np.asarray(toks)
        if self._m_steps is not None:
            self._m_steps.inc()
        for i in active:
            req = self._reqs[i]
            self._pos[i] += 1
            self._emit(req, i, [int(toks_host[i])])
        return len(active)

    def _step_spec(self, active) -> int:
        """One speculative round: m-1 draft proposals + the sync feed,
        ONE chunked target verify, exact acceptance, emit 1..m tokens
        per slot (m = speculate+1 clamped to the cache tail)."""
        jax, jnp = self._jax, self._jnp
        # the chunk writes K/V at pos..pos+m-1 for every active slot;
        # clamping m keeps writes inside max_len (dynamic_update_slice
        # would silently SHIFT an out-of-range window). pos <= max_len-2
        # always (prompt+max_new <= max_len and the final token is never
        # fed), so m >= 2 — at least one proposal per round.
        m = min(self.speculate + 1,
                self.max_len - max(int(self._pos[i]) for i in active))
        pos, temp, topk, topp, seed = self._sampling_args()
        feed = jnp.asarray(self._pending)
        draft_step = self._get_draft_step()
        props, qrows = [], []
        with _obs_span("spec_draft", active=len(active), feeds=m):
            for j in range(m):
                prop_j, q_j, self._draft_cache = draft_step(
                    self.draft_params, feed, self._draft_cache,
                    pos + j, temp, topk, topp, seed)
                if j < m - 1:
                    props.append(prop_j)
                    qrows.append(q_j)
                    feed = prop_j
        if self._m_draft_steps is not None:
            self._m_draft_steps.inc(m * len(active))
        chunk = jnp.stack([jnp.asarray(self._pending)] + props, axis=1)
        if props:
            pstack = jnp.stack(props, axis=1)
            qstack = jnp.stack(qrows, axis=1)
        else:
            # m == 1 (a slot is one token from max_len): pure verify of
            # the pending feed, zero proposals — accept_chunk handles
            # the degenerate (m-1)=0 shapes
            pstack = jnp.zeros((self.slots, 0), jnp.int32)
            qstack = jnp.zeros((self.slots, 0, self.model.vocab),
                               jnp.float32)
        with _obs_span("spec_verify", active=len(active), chunk=m):
            try:
                if self.paged:
                    T, self._kv.pools = self._get_verify(m)(
                        self.params, chunk, self._kv.pools,
                        jnp.asarray(self._kv.page_table), pos)
                else:
                    T, self._cache = self._get_verify(m)(
                        self.params, chunk, self._cache, pos)
            except Exception as e:
                from bigdl_tpu.obs import memory as _obs_mem
                _obs_mem.handle_oom(e, "decode_step")
                raise
        emitted, n_emit, n_acc = self._get_accept(m)(
            T, qstack, pstack, temp, topk, topp, seed, pos)
        emitted = np.asarray(emitted)
        n_emit = np.asarray(n_emit)
        n_acc = np.asarray(n_acc)
        if self._m_steps is not None:
            self._m_steps.inc()
        if self._m_spec_prop is not None:
            self._m_spec_prop.inc((m - 1) * len(active))
            self._m_spec_acc.inc(int(sum(int(n_acc[i]) for i in active)))
        for i in active:
            req = self._reqs[i]
            k = int(n_emit[i])
            stream = [int(t) for t in emitted[i, :k]]
            self._pos[i] += k
            if not self._emit(req, i, stream, accepted=int(n_acc[i])):
                self._pending[i] = stream[-1]
        return len(active)

    def generate(self, tokens, max_new_tokens: int,
                 temperature: float = 0.0, stop_token=None, top_k: int = 0,
                 top_p: float = 1.0, seed: int = 0) -> list:
        """Synchronous single-request convenience: submit + drive the
        decode loop until this request resolves (other queued requests
        keep advancing alongside — continuous batching has no 'exclusive'
        mode)."""
        fut = self.submit(tokens, max_new_tokens, temperature, stop_token,
                          top_k=top_k, top_p=top_p, seed=seed)
        if self._thread is None:
            while not fut.done():
                if self.step() == 0 and not fut.done():
                    raise RuntimeError(
                        "decode engine idle with unresolved request")
        return fut.result()

    # ----------------------------------------------------- debug inspection
    def debug_snapshot(self) -> dict:
        """The /debug/slots JSON (ISSUE 15): the slot table, waiting
        queue depth, and — paged — the KV page-pool occupancy. Holds the
        engine lock only to copy a few scalars."""
        with self._lock:
            slots = []
            for i, req in enumerate(self._reqs):
                if req is None:
                    slots.append({"slot": i, "state": "free"})
                    continue
                slots.append({
                    "slot": i, "state": "active",
                    "rid": req.rid,
                    "pos": int(self._pos[i]),
                    "prompt_tokens": len(req.tokens),
                    "tokens_out": len(req.out),
                    "max_new": req.max_new_tokens,
                    "pages": (len(self._kv.slot_pages[i])
                              if self.paged else None)})
            out = {"slots": slots,
                   "slots_total": self.slots,
                   "slots_active": sum(1 for r in self._reqs
                                       if r is not None),
                   "waiting": len(self._waiting),
                   "max_waiting": self.max_waiting,
                   "speculate": self.speculate,
                   "worker_up": self._worker_error is None,
                   "tp": self._shard.n_shard if self._shard else 1,
                   "kv": {"paged": self.paged}}
            if self.paged:
                out["kv"].update({
                    "page_tokens": self.page_tokens,
                    "pool_pages": self._kv.pool_pages,
                    "pages_in_use": self._kv.alloc.pages_in_use,
                    "free_pages": self._kv.alloc.free_pages,
                    "occupancy_frac": round(self._page_occupancy(), 4),
                    "allocated_bytes": self._kv.allocated_bytes(),
                    "bytes_per_page": self._kv.bytes_per_page})
        return out

    # ------------------------------------------------------ watchdog surface
    def alive(self) -> bool:
        """False once the decode loop has died or been declared dead
        (threadless caller-driven mode counts as alive)."""
        if self._worker_error is not None:
            return False
        return self._thread is None or self._thread.is_alive()

    def busy(self) -> bool:
        """True while there is work a healthy decode loop should be
        advancing (active slots or waiting requests)."""
        return (any(r is not None for r in self._reqs)
                or bool(self._waiting))

    def heartbeat_age(self, now: Optional[float] = None) -> float:
        return (self.clock() if now is None else now) - self._last_beat

    @property
    def worker_error(self) -> Optional[BaseException]:
        return self._worker_error

    def declare_dead(self, exc: BaseException) -> None:
        """Fail every in-flight and waiting request with
        :class:`WorkerDied` and make subsequent submits fast-fail —
        the watchdog's verdict on a wedged loop, or the loop's own."""
        with self._lock:
            if self._worker_error is None:
                self._worker_error = exc
            dead = list(self._waiting)
            self._waiting.clear()
            for i, req in enumerate(self._reqs):
                if req is not None:
                    self._release_slot(i)
                    dead.append(req)
            self._work.notify_all()
        err = (exc if isinstance(exc, WorkerDied)
               else WorkerDied(f"decode worker died: {exc}"))
        rt = _get_reqtracer()
        for req in dead:
            req.future.set_exception(err)
            if rt is not None and req.rid is not None:
                rt.finish(req.rid, "worker_dead", error=str(err))

    # --------------------------------------------------------------- worker
    def start(self) -> None:
        """Launch the decode loop thread (server mode)."""
        if self._thread is not None:
            return

        def _loop():
            try:
                while True:
                    with self._lock:
                        self._last_beat = self.clock()
                        while (not self._closed
                               and not any(r is not None
                                           for r in self._reqs)):
                            self._work.wait()
                            self._last_beat = self.clock()
                        if self._closed:
                            return
                    self.step()
            except BaseException as e:
                # the loop is the only thing advancing decode: record
                # the cause, fail every waiter, fast-fail future submits
                self.declare_dead(e)

        self._thread = threading.Thread(target=_loop, name="decode-loop",
                                        daemon=True)
        self._thread.start()

    def close(self, timeout: float = 10.0) -> None:
        rt = _get_reqtracer()
        with self._lock:
            self._closed = True
            for req in list(self._waiting):
                req.future.set_exception(
                    RuntimeError("decode engine closed"))
                if rt is not None and req.rid is not None:
                    rt.finish(req.rid, "closed")
            self._waiting.clear()
            for i, req in enumerate(self._reqs):
                if req is not None:
                    self._release_slot(i)
                    req.future.set_exception(
                        RuntimeError("decode engine closed mid-request"))
                    if rt is not None and req.rid is not None:
                        rt.finish(req.rid, "closed")
            self._work.notify_all()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout)


# ------------------------------------------------- shardlint (ISSUE 19)
class _AbstractPagedKv:
    """Just enough of :class:`~bigdl_tpu.serving.kv_pages.PagedKvCache`
    for :meth:`DecodeEngine.trace_step_jaxpr`: abstract pools (the same
    leaf geometry the real pool allocates, as ShapeDtypeStructs) plus
    the page-table bound — no allocator, no device memory."""

    def __init__(self, pools, max_pages: int, pool_pages: int,
                 page_tokens: int):
        self.pools = pools
        self.max_pages = int(max_pages)
        self.pool_pages = int(pool_pages)
        self.page_tokens = int(page_tokens)
        self.pool_shardings = None


def abstract_decode_engine(model, *, slots: int = 4,
                           max_len: Optional[int] = None,
                           cache_dtype=None,
                           kv_page_tokens: Optional[int] = None,
                           pool_pages: Optional[int] = None,
                           speculate: int = 0, tp: int = 1,
                           model_axis: str = "model",
                           quantize: Optional[str] = None):
    """A lintable :class:`DecodeEngine` shell: every field
    ``trace_step_jaxpr`` (and the ``_get_step`` program builder under
    it) reads, built fully abstractly — params/KV from ``eval_shape``,
    the tp mesh an :class:`jax.sharding.AbstractMesh`, nothing placed,
    nothing compiled, zero devices required (ISSUE 19: the serving
    surfaces shardlint analyzes without standing up an engine).

    Returns the engine shell; call ``trace_step_jaxpr()`` on it. Do NOT
    ``start()``/``submit()`` it — there is no worker, no allocator, and
    no real state behind it."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.serving import quant as _q

    eng = DecodeEngine.__new__(DecodeEngine)
    eng.model = model
    eng._jax, eng._jnp = jax, jnp
    eng.quantize = quantize if quantize else "off"
    eng._wfmt, eng._kv8 = _q.parse_quantize(quantize)
    eng.slots = int(slots)
    eng.max_len = int(max_len or model.max_len)
    eng.cache_dtype = cache_dtype or model.compute_dtype or jnp.float32
    eng.speculate = int(speculate)
    eng.page_tokens = int(kv_page_tokens) if kv_page_tokens else None
    eng.paged = eng.page_tokens is not None
    if eng._kv8 and not eng.paged:
        raise ValueError("--quantize kv8 needs paged KV "
                         "(--kvPageTokens); the dense cache path has no "
                         "quantized pools")
    params = jax.eval_shape(model.init, jax.random.PRNGKey(0))
    if eng._wfmt is not None:
        params = jax.eval_shape(
            lambda p: _q.quantize_params(p, eng._wfmt), params)
    eng.params = params

    if int(tp) > 1:
        from jax.sharding import AbstractMesh

        from bigdl_tpu.serving.sharding import ServingSharding
        eng.mesh = AbstractMesh(((model_axis, int(tp)),))
        eng._shard = ServingSharding(eng.mesh, axis=model_axis)
    else:
        eng.mesh = None
        eng._shard = None

    if eng.paged:
        if eng.max_len % eng.page_tokens:
            raise ValueError(
                f"kv page_tokens ({eng.page_tokens}) must divide "
                f"max_len ({eng.max_len})")
        max_pages = eng.max_len // eng.page_tokens
        pp = int(pool_pages or (1 + eng.slots * max_pages))
        tmpl = jax.eval_shape(
            lambda: model.encoder.init_cache(1, eng.page_tokens,
                                             eng.cache_dtype))
        if eng._kv8:
            def mk(a):
                kh, pt, hd = a.shape[1], a.shape[2], a.shape[3]
                return _kvp.QuantPool(
                    jax.ShapeDtypeStruct((pp, kh, pt, hd), jnp.int8),
                    jax.ShapeDtypeStruct((pp, kh, pt), jnp.float32),
                    eng.cache_dtype)
            pools = jax.tree_util.tree_map(mk, tmpl)
        else:
            pools = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct((pp,) + a.shape[1:],
                                               a.dtype), tmpl)
        eng._kv = _AbstractPagedKv(pools, max_pages, pp, eng.page_tokens)
        eng._cache = None
    else:
        eng._kv = None
        eng._cache = jax.eval_shape(
            lambda: model.encoder.init_cache(eng.slots, eng.max_len,
                                             eng.cache_dtype))

    shard = eng._shard
    if shard is not None:
        eng._repl_sh = shard.replicated
        eng._state_sh = shard.kv_shardings(
            eng._kv.pools if eng.paged else eng._cache)
    else:
        eng._repl_sh = eng._state_sh = None
    eng._cache1_sh = eng._draft_sh = None
    eng._don = False           # nothing real to donate; CPU-safe
    eng._step_programs = {}
    eng._verify_programs = {}
    eng._accept_programs = {}
    eng._suffix_programs = {}
    eng._draft_step_jit = None
    return eng
