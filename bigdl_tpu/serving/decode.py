"""Incremental generation for ``transformer_lm`` with a preallocated
KV cache and continuous-batching slots.

``TransformerLM.generate`` is the offline shape of decoding: one request,
one fori_loop, prompt and token budget baked into the compile. An online
server cannot afford that — every (prompt_len, max_new) pair would be a
fresh XLA program, and concurrent requests would each run their own
batch-1 decode at ~1/slots of the achievable throughput. This module
splits decoding the way serving systems do (Orca-style continuous
batching):

* **prefill** — one compiled program per PROMPT-LENGTH BUCKET
  (``ops.attention_kernel.serving_prefill_buckets`` keeps the ladder on
  the flash kernel's zero-padding block plans): the prompt, right-padded
  to its bucket, runs once through ``model.prefill_logits`` building a
  batch-1 K/V cache, exact because causal attention never reads past the
  true last position and decode overwrites pad K/V before attending it;

* **decode** — ONE compiled per-token step over all ``slots``
  (``jax.vmap`` of ``model.decode_logits`` with per-slot positions), so
  requests of different lengths and arrival times share the batch. A
  finishing request frees its slot; the next waiting request prefills
  into it while the others keep decoding. The whole-cache slot write is
  a donated jitted update — no per-request cache reallocation.

Greedy decoding (temperature 0) is bit-exact with the offline
full-sequence argmax decode (the acceptance contract; see
tests/test_serving.py) because both run the same ``prefill_logits`` /
``decode_logits`` graph per token.
"""

from __future__ import annotations

import collections
import logging
import threading
from typing import Optional, Sequence

import numpy as np

from bigdl_tpu.obs.spans import span as _obs_span
from bigdl_tpu.serving.batcher import (AdmissionError, DeadlineExceeded,
                                       WorkerDied, _Future)

logger = logging.getLogger(__name__)

__all__ = ["DecodeEngine", "DecodeRequest"]


class DecodeRequest:
    __slots__ = ("tokens", "max_new_tokens", "temperature", "stop_token",
                 "future", "out", "deadline")

    def __init__(self, tokens, max_new_tokens, temperature=0.0,
                 stop_token=None, deadline=None):
        self.tokens = [int(t) for t in tokens]
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.stop_token = stop_token
        self.deadline = deadline
        self.future = _Future()
        self.out: list = []


class DecodeEngine:
    """Continuous-batching KV-cache decoder over a fixed slot count.

    ``slots`` bounds the decode batch (and the cache HBM footprint:
    slots x layers x kv_heads x max_len x head_dim x 2). ``submit``
    assigns a free slot (prefill) or queues up to ``max_waiting``
    requests, rejecting beyond that (:class:`AdmissionError` -> 429).
    ``step`` advances every active slot one token. Without a worker
    thread the caller drives ``step`` (tests, ``generate``); ``start()``
    launches the decode loop for the HTTP server.
    """

    def __init__(self, model, params, *, slots: int = 4,
                 max_len: Optional[int] = None, cache_dtype=None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 max_waiting: int = 64, metrics=None,
                 clock=None):
        import jax
        import jax.numpy as jnp
        import time as _time

        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.clock = clock or _time.monotonic
        self._worker_error: Optional[BaseException] = None
        self._last_beat = self.clock()
        self.model = model
        self.params = params
        self.slots = int(slots)
        self.max_len = int(max_len or model.max_len)
        self.cache_dtype = cache_dtype or model.compute_dtype or jnp.float32
        self.max_waiting = int(max_waiting)
        self._jax, self._jnp = jax, jnp

        if prompt_buckets is None:
            from bigdl_tpu.ops.attention_kernel import serving_prefill_buckets
            head_dim = getattr(
                model.encoder._modules[0].mha, "head_dim",
                model.d_model // 4)
            prompt_buckets = serving_prefill_buckets(
                self.max_len, head_dim, True, self.cache_dtype)
        self.prompt_buckets = tuple(sorted(set(int(b)
                                               for b in prompt_buckets)))

        self._lock = threading.RLock()
        self._work = threading.Condition(self._lock)
        self._reqs: list = [None] * self.slots
        self._waiting: collections.deque = collections.deque()
        self._cache = model.encoder.init_cache(self.slots, self.max_len,
                                               self.cache_dtype)
        self._logits = jnp.zeros((self.slots, model.vocab), jnp.float32)
        self._pos = np.zeros(self.slots, np.int32)
        self._temp = np.zeros(self.slots, np.float32)
        self._key = jax.random.PRNGKey(0)
        self._thread = None
        self._closed = False

        if metrics is not None:
            self._m_tokens = metrics.counter(
                "generated_tokens_total", "decode tokens emitted")
            self._m_steps = metrics.counter(
                "decode_steps_total", "batched decode steps executed")
            self._m_prefills = metrics.counter(
                "prefills_total", "prompt prefills executed")
            self._m_prompt_tokens = metrics.counter(
                "prompt_tokens_total", "prompt tokens prefilled")
            self._m_rejected = metrics.counter(
                "decode_rejected_total",
                "generate requests fast-rejected (waiting queue full)")
            self._m_expired = metrics.counter(
                "decode_expired_total",
                "generate requests dropped on deadline expiry")
            self._m_dead = metrics.counter(
                "decode_dead_submit_total",
                "generate submits fast-failed (decode worker dead)")
            metrics.gauge("decode_worker_up",
                          "1 while the decode loop is healthy",
                          fn=lambda: 0.0 if self._worker_error else 1.0)
            metrics.gauge("decode_slots_active", "occupied decode slots",
                          fn=lambda: sum(r is not None
                                         for r in self._reqs))
            metrics.gauge(
                "decode_tokens_per_second",
                "lifetime generated_tokens_total / uptime",
                fn=lambda: (self._m_tokens.value
                            / max(metrics.uptime_s(), 1e-9)))
            # KV-cache byte accounting (ISSUE 12): the resident cost of
            # max_len x slots — the evidence base for paged KV (ROADMAP
            # item 2: short requests pay the full max-length HBM today)
            from bigdl_tpu.obs.memory import tree_bytes as _kv_bytes
            kv_total = _kv_bytes(self._cache)
            metrics.gauge("kv_cache_bytes",
                          "resident KV cache bytes (all slots, max_len)",
                          fn=lambda: _kv_bytes(self._cache))
            metrics.gauge("kv_cache_bytes_per_slot",
                          "resident KV cache bytes per decode slot",
                          fn=lambda: (_kv_bytes(self._cache)
                                      / max(1, self.slots)))
            logger.info("decode KV cache: %d bytes (%d slots x max_len "
                        "%d, %s)", kv_total, self.slots, self.max_len,
                        self.cache_dtype)
        else:
            self._m_tokens = self._m_steps = self._m_prefills = None
            self._m_prompt_tokens = self._m_rejected = None
            self._m_expired = self._m_dead = None

        # ---- compiled programs -------------------------------------------
        def _prefill(params, tokens, last):
            # tokens (1, bucket) int32; last = true_len - 1 (traced)
            cache = model.encoder.init_cache(1, self.max_len,
                                             self.cache_dtype)
            logits, cache = model.prefill_logits(params, tokens, cache,
                                                 last)
            return logits[0].astype(jnp.float32), cache

        self._prefill_jit = jax.jit(_prefill)  # one compile per bucket
        # donation keeps the big cache in place on device backends; CPU
        # can't honor it and warns on every compile
        _don = jax.default_backend() != "cpu"

        def _write_slot(cache_full, cache_one, slot):
            return jax.tree_util.tree_map(
                lambda f, o: jax.lax.dynamic_update_index_in_dim(
                    f, o[0].astype(f.dtype), slot, 0),
                cache_full, cache_one)

        self._write_slot = jax.jit(_write_slot,
                                   donate_argnums=(0,) if _don else ())

        def _one(params, logits, cache1, pos, temp, key):
            greedy = jnp.argmax(logits).astype(jnp.int32)
            safe_t = jnp.where(temp > 0, temp, 1.0)
            sampled = jax.random.categorical(
                key, logits / safe_t).astype(jnp.int32)
            tok = jnp.where(temp > 0, sampled, greedy)
            cache_b = jax.tree_util.tree_map(lambda a: a[None], cache1)
            lg, cache_b = model.decode_logits(params, tok[None, None],
                                              cache_b, pos)
            return (tok, lg[0].astype(jnp.float32),
                    jax.tree_util.tree_map(lambda a: a[0], cache_b))

        self._step_jit = jax.jit(
            jax.vmap(_one, in_axes=(None, 0, 0, 0, 0, 0)),
            donate_argnums=(1, 2) if _don else ())

    # ------------------------------------------------------------ admission
    def prompt_bucket_for(self, n: int) -> int:
        for b in self.prompt_buckets:
            if b >= n:
                return b
        return self.prompt_buckets[-1]

    def submit(self, tokens, max_new_tokens: int,
               temperature: float = 0.0, stop_token=None,
               deadline: Optional[float] = None) -> _Future:
        """Queue one generation request; the future resolves to the list
        of generated token ids. Validates the length budget, fast-rejects
        when the waiting queue is full, when the decode worker is dead
        (:class:`WorkerDied` — nothing would ever drain the queue), or
        when ``deadline`` (absolute, on the engine's clock) has already
        passed (:class:`DeadlineExceeded`)."""
        tokens = list(tokens)
        if not tokens:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, "
                             f"got {max_new_tokens}")
        if len(tokens) + int(max_new_tokens) > self.max_len:
            raise ValueError(
                f"prompt ({len(tokens)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_len {self.max_len}")
        req = DecodeRequest(tokens, max_new_tokens, temperature,
                            stop_token, deadline)
        with self._lock:
            if self._closed:
                raise RuntimeError("decode engine is closed")
            if self._worker_error is not None or (
                    self._thread is not None
                    and not self._thread.is_alive()):
                if self._m_dead is not None:
                    self._m_dead.inc()
                raise WorkerDied(
                    "decode worker is dead: "
                    f"{self._worker_error or 'thread exited'}")
            if deadline is not None and self.clock() >= deadline:
                if self._m_expired is not None:
                    self._m_expired.inc()
                raise DeadlineExceeded("deadline expired before submit")
            slot = self._free_slot()
            if slot is not None:
                self._install(req, slot)
            elif len(self._waiting) >= self.max_waiting:
                if self._m_rejected is not None:
                    self._m_rejected.inc()
                raise AdmissionError(
                    f"decode queue at capacity ({self.max_waiting} waiting)")
            else:
                self._waiting.append(req)
            self._work.notify()
        return req.future

    def _free_slot(self) -> Optional[int]:
        for i, r in enumerate(self._reqs):
            if r is None:
                return i
        return None

    # -------------------------------------------------------------- prefill
    def _install(self, req: DecodeRequest, slot: int) -> None:
        """Prefill ``req``'s prompt into ``slot`` (lock held)."""
        jnp = self._jnp
        s = len(req.tokens)
        bucket = self.prompt_bucket_for(s)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :s] = req.tokens
        logits_vec, cache1 = self._prefill_jit(
            self.params, jnp.asarray(padded), jnp.int32(s - 1))
        self._cache = self._write_slot(self._cache, cache1,
                                       jnp.int32(slot))
        self._logits = self._logits.at[slot].set(logits_vec)
        self._pos[slot] = s
        self._temp[slot] = req.temperature
        self._reqs[slot] = req
        if self._m_prefills is not None:
            self._m_prefills.inc()
            self._m_prompt_tokens.inc(s)

    # ------------------------------------------------------------- deadlines
    def _expire(self, now: float) -> None:
        """Drop expired requests BEFORE compute is spent on them (lock
        held): waiting-queue entries simply resolve with
        :class:`DeadlineExceeded`; active slots free up and hand off to
        the next (still-live) waiting request."""
        if self._waiting:
            live = collections.deque()
            for req in self._waiting:
                if req.deadline is not None and now >= req.deadline:
                    if self._m_expired is not None:
                        self._m_expired.inc()
                    req.future.set_exception(DeadlineExceeded(
                        "deadline expired while waiting for a decode "
                        "slot"))
                else:
                    live.append(req)
            self._waiting = live
        for i, req in enumerate(self._reqs):
            if (req is not None and req.deadline is not None
                    and now >= req.deadline):
                self._reqs[i] = None
                if self._m_expired is not None:
                    self._m_expired.inc()
                req.future.set_exception(DeadlineExceeded(
                    f"deadline expired after {len(req.out)} of "
                    f"{req.max_new_tokens} tokens"))
                if self._waiting:
                    self._install(self._waiting.popleft(), i)

    # ---------------------------------------------------------------- step
    def step(self) -> int:
        """One batched decode step: every active slot emits one token.
        Returns the number of active slots advanced (0 = idle). Finished
        requests resolve their futures and hand their slot to the next
        waiting request; expired ones are dropped before compute."""
        jax, jnp = self._jax, self._jnp
        with self._lock:
            self._last_beat = self.clock()
            self._expire(self.clock())
            active = [i for i, r in enumerate(self._reqs) if r is not None]
            if not active:
                return 0
            self._key, sub = jax.random.split(self._key)
            keys = jax.random.split(sub, self.slots)
            with _obs_span("decode_step", active=len(active)):
                try:
                    toks, self._logits, self._cache = self._step_jit(
                        self.params, self._logits, self._cache,
                        jnp.asarray(self._pos), jnp.asarray(self._temp),
                        keys)
                except Exception as e:
                    # RESOURCE_EXHAUSTED autopsy (ISSUE 12): the KV
                    # cache is usually the culprit — report to
                    # --traceDir + fault log, then die as before
                    from bigdl_tpu.obs import memory as _obs_mem
                    _obs_mem.handle_oom(e, "decode_step")
                    raise
                toks_host = np.asarray(toks)
            if self._m_steps is not None:
                self._m_steps.inc()
                self._m_tokens.inc(len(active))
            for i in active:
                req = self._reqs[i]
                tok = int(toks_host[i])
                req.out.append(tok)
                self._pos[i] += 1
                done = (len(req.out) >= req.max_new_tokens
                        or (req.stop_token is not None
                            and tok == req.stop_token))
                if done:
                    self._reqs[i] = None
                    req.future.set_result(list(req.out))
                    if self._waiting:
                        self._install(self._waiting.popleft(), i)
            return len(active)

    def generate(self, tokens, max_new_tokens: int,
                 temperature: float = 0.0, stop_token=None) -> list:
        """Synchronous single-request convenience: submit + drive the
        decode loop until this request resolves (other queued requests
        keep advancing alongside — continuous batching has no 'exclusive'
        mode)."""
        fut = self.submit(tokens, max_new_tokens, temperature, stop_token)
        if self._thread is None:
            while not fut.done():
                if self.step() == 0 and not fut.done():
                    raise RuntimeError(
                        "decode engine idle with unresolved request")
        return fut.result()

    # ------------------------------------------------------ watchdog surface
    def alive(self) -> bool:
        """False once the decode loop has died or been declared dead
        (threadless caller-driven mode counts as alive)."""
        if self._worker_error is not None:
            return False
        return self._thread is None or self._thread.is_alive()

    def busy(self) -> bool:
        """True while there is work a healthy decode loop should be
        advancing (active slots or waiting requests)."""
        return (any(r is not None for r in self._reqs)
                or bool(self._waiting))

    def heartbeat_age(self, now: Optional[float] = None) -> float:
        return (self.clock() if now is None else now) - self._last_beat

    @property
    def worker_error(self) -> Optional[BaseException]:
        return self._worker_error

    def declare_dead(self, exc: BaseException) -> None:
        """Fail every in-flight and waiting request with
        :class:`WorkerDied` and make subsequent submits fast-fail —
        the watchdog's verdict on a wedged loop, or the loop's own."""
        with self._lock:
            if self._worker_error is None:
                self._worker_error = exc
            dead = list(self._waiting)
            self._waiting.clear()
            for i, req in enumerate(self._reqs):
                if req is not None:
                    self._reqs[i] = None
                    dead.append(req)
            self._work.notify_all()
        err = (exc if isinstance(exc, WorkerDied)
               else WorkerDied(f"decode worker died: {exc}"))
        for req in dead:
            req.future.set_exception(err)

    # --------------------------------------------------------------- worker
    def start(self) -> None:
        """Launch the decode loop thread (server mode)."""
        if self._thread is not None:
            return

        def _loop():
            try:
                while True:
                    with self._lock:
                        self._last_beat = self.clock()
                        while (not self._closed
                               and not any(r is not None
                                           for r in self._reqs)):
                            self._work.wait()
                            self._last_beat = self.clock()
                        if self._closed:
                            return
                    self.step()
            except BaseException as e:
                # the loop is the only thing advancing decode: record
                # the cause, fail every waiter, fast-fail future submits
                self.declare_dead(e)

        self._thread = threading.Thread(target=_loop, name="decode-loop",
                                        daemon=True)
        self._thread.start()

    def close(self, timeout: float = 10.0) -> None:
        with self._lock:
            self._closed = True
            for req in list(self._waiting):
                req.future.set_exception(
                    RuntimeError("decode engine closed"))
            self._waiting.clear()
            for i, req in enumerate(self._reqs):
                if req is not None:
                    self._reqs[i] = None
                    req.future.set_exception(
                        RuntimeError("decode engine closed mid-request"))
            self._work.notify_all()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout)
