"""Tensor-parallel placement for the serving stack (ISSUE 16).

Training already shards this exact model over a ``model`` mesh axis
(``parallel/tensor_parallel.py`` — Megatron column/row pairing); serving
reuses the SAME spec builder so a checkpoint trained under any topology
decodes under any other. What serving adds is the KV side: the cache
(dense slab or page pools) carries one leaf per layer shaped
``(slots|pages, kv_heads, tokens, head_dim)``, and the natural
tensor-parallel layout splits the **kv_heads** dim — exactly the
sharding GSPMD propagates out of column-split wk/wv, so gather/scatter
page ops never introduce a resharding collective.

Division of labour:

* :class:`ServingSharding` — one replica's mesh + the placement rules:
  params via ``megatron_specs``, KV leaves on the head dim (replicated
  when ``kv_heads % tp`` != 0 — correct over clever), scalars/logits
  replicated. Engines pin these as ``out_shardings`` on every program
  whose output feeds persistent state, so the layout is decided here
  once instead of re-derived per compile.
* :func:`replica_device_groups` — partitions the visible devices into N
  disjoint K-chip groups for dp replicas (replica r owns devices
  ``[r*K, (r+1)*K)``; deterministic, so routing and traces are
  reproducible).
* :func:`restore_for_serving` — checkpoint -> mesh placement using PR
  10's ``restore_resharded`` for blob checkpoints (any training topology
  loads into any serving topology), with the same clean-SystemExit
  contract as ``restore_for_inference``.

Host-side structures (page tables, the :class:`PageAllocator` free
list, slot bookkeeping) are **not** sharded — the ISSUE 16 contract:
allocation stays a host decision, only where the KV bytes live changes.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Sequence

logger = logging.getLogger(__name__)

__all__ = ["ServingSharding", "serving_mesh", "replica_device_groups",
           "restore_for_serving"]


def replica_device_groups(n_replicas: int, tp_k: int = 1,
                          devices: Optional[Sequence] = None) -> List[list]:
    """Split the visible devices into ``n_replicas`` disjoint groups of
    ``tp_k`` chips each (contiguous slices of ``jax.devices()`` order —
    on a real slice that keeps each replica's tp ring on neighbouring
    chips). Leftover devices stay idle by design: capacity comes from
    adding replicas, not from ragged groups."""
    import jax

    devices = list(devices if devices is not None else jax.devices())
    need = int(n_replicas) * int(tp_k)
    if need > len(devices):
        raise ValueError(
            f"{n_replicas} replicas x {tp_k}-way tp needs {need} devices, "
            f"have {len(devices)}")
    return [devices[r * tp_k:(r + 1) * tp_k] for r in range(n_replicas)]


def serving_mesh(devices: Sequence, axis: str = "model"):
    """A 1-D mesh over one replica's devices, all on the model axis
    (serving has no data axis inside a replica — the batch dim is slots,
    which stays replicated so host sampling sees full logits)."""
    import numpy as np
    from jax.sharding import Mesh

    arr = np.asarray(list(devices), dtype=object).reshape(len(devices))
    return Mesh(arr, (axis,))


class ServingSharding:
    """Placement rules for one tensor-parallel serving replica.

    ``n_shard == 1`` (a dp replica's single chip, or no strategy) makes
    every spec ``P()`` — placement then just pins work to the replica's
    device(s), and the compiled programs are the single-chip ones.
    """

    def __init__(self, mesh, axis: str = "model"):
        from jax.sharding import NamedSharding, PartitionSpec as P

        if axis not in mesh.axis_names:
            raise ValueError(
                f"mesh {dict(mesh.shape)} has no {axis!r} axis")
        self.mesh = mesh
        self.axis = axis
        self.n_shard = int(mesh.shape[axis])
        self._P = P
        self.replicated = NamedSharding(mesh, P())

    # ------------------------------------------------------------- params
    def param_specs(self, module, params):
        """PartitionSpec pytree for ``params`` — the training-side
        Megatron layout (column/row pairing, head-divisibility gates)
        whenever tp > 1, fully replicated otherwise.

        Quantized trees (ISSUE 17): spec building runs over a SHADOW
        tree with each :class:`~bigdl_tpu.serving.quant.QuantizedWeight`
        replaced by a logical-f32 ShapeDtypeStruct — the spec builders'
        bare tree_maps would otherwise descend into the node and
        reconstruct QuantizedWeights holding PartitionSpecs. The
        returned tree carries ONE spec at each quantized position (the
        weight's); :meth:`place_params` derives the scale's from it."""
        import jax

        from bigdl_tpu.serving.quant import is_quantized

        shadow = jax.tree_util.tree_map(
            lambda p: (jax.ShapeDtypeStruct(p.shape, p.dtype)
                       if is_quantized(p) else p),
            params, is_leaf=is_quantized)
        if self.n_shard <= 1:
            return jax.tree_util.tree_map(lambda _: self._P(), shadow)
        from bigdl_tpu.parallel.tensor_parallel import megatron_specs
        return megatron_specs(module, shadow, self.axis, self.n_shard)

    def scale_spec(self, weight_spec):
        """Placement of a quantized weight's per-output-channel scale
        vector: split exactly when the weight's axis 1 is split (the
        scale indexes output channels), replicated otherwise (row-split
        weights contract over their axis 0 — every shard needs every
        output scale)."""
        ws = tuple(weight_spec)
        if len(ws) >= 2 and ws[1] is not None:
            return self._P(ws[1])
        return self._P()

    def place_params(self, module, params):
        """Commit ``params`` to the mesh under the Megatron layout.
        Quantized leaves place their int8 tensor under the weight's
        spec and the scale under :meth:`scale_spec`."""
        import jax
        from jax.sharding import NamedSharding

        from bigdl_tpu.serving.quant import QuantizedWeight, is_quantized

        specs = self.param_specs(module, params)

        def put(p, s):
            if is_quantized(p):
                return QuantizedWeight(
                    jax.device_put(p.q, NamedSharding(self.mesh, s)),
                    jax.device_put(p.scale, NamedSharding(
                        self.mesh, self.scale_spec(s))),
                    p.fmt)
            return jax.device_put(p, NamedSharding(self.mesh, s))

        return jax.tree_util.tree_map(put, params, specs,
                                      is_leaf=is_quantized)

    # ----------------------------------------------------------------- kv
    def kv_spec(self, leaf):
        """KV leaves are ``(slots|pages, kv_heads, tokens, head_dim)``;
        split the head dim when tp divides it, else replicate (GQA with
        kv_heads < tp would otherwise need head-splitting math the
        decode graph doesn't have)."""
        shape = tuple(getattr(leaf, "shape", ()))
        if (self.n_shard > 1 and len(shape) == 4
                and shape[1] % self.n_shard == 0):
            return self._P(None, self.axis, None, None)
        return self._P()

    def kv_sharding(self, leaf):
        from jax.sharding import NamedSharding
        return NamedSharding(self.mesh, self.kv_spec(leaf))

    def kv_shardings(self, cache):
        """Sharding pytree for a cache/pool pytree — what the engines
        pin as ``out_shardings`` on prefill/step/verify/scatter programs
        so the layout never ping-pongs between compiles."""
        import jax
        return jax.tree_util.tree_map(self.kv_sharding, cache)

    def place_kv(self, cache):
        import jax
        return jax.device_put(cache, self.kv_shardings(cache))

    # ---------------------------------------------------------- provenance
    def describe(self) -> dict:
        return {"tp": self.n_shard,
                "mesh": ",".join(f"{k}:{v}"
                                 for k, v in dict(self.mesh.shape).items()),
                "mesh_devices": int(self.mesh.size)}


def restore_for_serving(path: str, mesh) -> tuple:
    """``(params, mod_state)`` from a training checkpoint, placed
    replicated onto ``mesh`` (the engine re-shards params to the
    Megatron layout at construction — placement, not a data transform,
    because blobs hold logical host arrays).

    Resolution mirrors ``restore_for_inference`` (directory -> newest
    ``model.<n>``; clean SystemExit on missing/corrupt); single-blob
    checkpoints go through PR 10's :func:`restore_resharded` so the
    manifest shape validation runs, orbax snapshot dirs restore to host
    first and are then committed to the mesh."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from bigdl_tpu.utils.file import (ChecksumError, exists, isdir,
                                      latest_checkpoint, restore_resharded)

    if not exists(path):
        raise SystemExit(f"checkpoint {path}: does not exist")
    target = path
    if isdir(path):
        newest = latest_checkpoint(path, "model.")
        if newest is not None:
            target = newest
    if isdir(target):
        # orbax snapshot: restore to host, then commit replicated
        from bigdl_tpu.utils.orbax_ckpt import restore_for_inference
        params, mod_state = restore_for_inference(target)
        repl = NamedSharding(mesh, P())
        place = lambda t: jax.tree_util.tree_map(
            lambda x: jax.device_put(x, repl), t)
        return place(params), (place(mod_state)
                               if mod_state is not None else None)
    try:
        tree = restore_resharded(target, mesh, zero1=False)
    except SystemExit:
        raise
    except ChecksumError as e:
        raise SystemExit(f"checkpoint {target}: {e}")
    except Exception as e:
        raise SystemExit(
            f"checkpoint {target}: failed to load "
            f"({type(e).__name__}: {e})")
    if not isinstance(tree, dict) or "params" not in tree:
        raise SystemExit(
            f"checkpoint {target}: not a model checkpoint (no 'params' "
            f"entry — did you point at a state.<n> optimizer blob?)")
    return tree["params"], tree.get("mod_state")
