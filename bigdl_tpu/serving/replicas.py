"""Data-parallel engine replicas behind one front door (ISSUE 16).

Tensor parallel (``serving/sharding.py``) makes ONE request faster by
spreading its matmuls over K chips; this module makes MANY requests
faster by running N independent engine stacks — each a full
engine/batcher/decoder pinned to its own device group — and routing
every request to the least-loaded live replica. The two compose:
``dp:N+tp:K`` runs N replicas of K-chip tensor-parallel engines.

Design points, in the order they bit during bring-up:

* **Routing is deterministic**: least queue depth, lowest replica index
  on ties. Tests inject a clock and replay exact routing decisions; the
  chosen replica index is stamped into the request's lifecycle record
  (``reqtrace.note_replica``) so every trace names its server.
* **Readiness is fleet-level**: ``/readyz`` stays 200 while at least
  one replica can serve (a dead replica is ROUTED AROUND, not a reason
  to drain the whole process) — but the detail body names every dead
  replica so operators see the capacity loss immediately.
* **Shedding is fleet-level**: /generate sheds only when EVERY live
  replica is past the saturation fraction — one hot replica must not
  turn away work the idle ones could take.
* **Metrics are two-layered**: each replica's components register their
  usual series against a ``LabelledRegistry`` view (``replica="0"``),
  and this module adds unlabelled fleet aggregates of the same gauges
  (``kv_cache_bytes``, ``kv_pages_in_use``) plus ``replicas`` /
  ``replicas_live`` / ``fleet_generated_tokens_total`` — so existing
  dashboards keep reading totals while new ones can break out replicas.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from bigdl_tpu.serving.batcher import WorkerDied

logger = logging.getLogger(__name__)

__all__ = ["Replica", "ReplicaSet"]


class Replica:
    """One dp replica: a full serving stack pinned to its own device
    group. Pure container + health/load accessors — construction (and
    the choice of which components exist) belongs to the caller."""

    def __init__(self, index: int, *, devices=None, mesh=None,
                 engine=None, batcher=None, decoder=None, watchdog=None,
                 metrics=None):
        self.index = int(index)
        self.name = f"r{self.index}"
        self.devices = list(devices) if devices is not None else []
        self.mesh = mesh
        self.engine = engine
        self.batcher = batcher
        self.decoder = decoder
        self.watchdog = watchdog
        self.metrics = metrics

    # ------------------------------------------------------------- health
    def alive(self) -> bool:
        """Every component this replica has is healthy. A replica with a
        dead batcher OR decoder is out of rotation entirely — half-alive
        replicas would make routing verdicts endpoint-dependent."""
        if self.watchdog is not None and not self.watchdog.ready():
            return False
        for comp in (self.batcher, self.decoder):
            if comp is not None and not comp.alive():
                return False
        return True

    def dead_components(self) -> List[str]:
        out = []
        if self.watchdog is not None and not self.watchdog.ready():
            out.extend(sorted(self.watchdog.failures))
        for nm, comp in (("batcher", self.batcher),
                         ("decoder", self.decoder)):
            if comp is not None and not comp.alive():
                out.append(nm)
        return out

    # --------------------------------------------------------------- load
    def predict_depth(self) -> int:
        return self.batcher.queue_depth if self.batcher is not None else 0

    def generate_load(self) -> int:
        return self.decoder.queue_load() if self.decoder is not None else 0

    def generate_saturated(self, frac: float) -> bool:
        """This replica's own tier-1 shed verdict — same predicate the
        single-replica server applies globally."""
        if (self.batcher is not None
                and self.batcher.queue_depth
                >= frac * self.batcher.max_queue):
            return True
        if (self.decoder is not None
                and len(self.decoder._waiting)
                >= frac * self.decoder.max_waiting):
            return True
        return False

    def kv_bytes(self) -> int:
        return self.decoder.kv_bytes() if self.decoder is not None else 0

    def kv_pages_in_use(self) -> int:
        return (self.decoder.kv_pages_in_use()
                if self.decoder is not None else 0)

    def generated_tokens(self) -> int:
        d = self.decoder
        if d is None or d._m_tokens is None:
            return 0
        return int(d._m_tokens.value)

    def describe(self) -> dict:
        out = {"replica": self.index, "alive": self.alive(),
               "devices": len(self.devices),
               "predict_depth": self.predict_depth(),
               "generate_load": self.generate_load()}
        dead = self.dead_components()
        if dead:
            out["dead"] = dead
        return out

    def close(self) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.batcher is not None:
            self.batcher.close()
        if self.decoder is not None:
            self.decoder.close()


class ReplicaSet:
    """N replicas + the routing/readiness/aggregation policy over them.

    ``metrics`` (the FLEET registry, not a labelled view) receives the
    unlabelled aggregates; per-replica series are registered by each
    replica's own components against their labelled views."""

    def __init__(self, replicas: List[Replica], metrics=None):
        if not replicas:
            raise ValueError("ReplicaSet needs at least one replica")
        self.replicas = list(replicas)
        if metrics is not None:
            metrics.gauge("replicas", "configured dp engine replicas",
                          fn=lambda: len(self.replicas))
            metrics.gauge("replicas_live",
                          "replicas currently passing health checks",
                          fn=lambda: sum(r.alive()
                                         for r in self.replicas))
            # fleet aggregates of the per-replica gauges — SAME names
            # the single-replica decoder registers, so dashboards and
            # `explain --mem` keep reading totals under dp
            metrics.gauge("kv_cache_bytes",
                          "KV cache bytes, summed over replicas",
                          fn=lambda: sum(r.kv_bytes()
                                         for r in self.replicas))
            metrics.gauge("kv_pages_in_use",
                          "KV pool pages handed out, summed over "
                          "replicas",
                          fn=lambda: sum(r.kv_pages_in_use()
                                         for r in self.replicas))
            # counters can't be fn-backed sums of counters without
            # double-counting scrapes, so the fleet total is a gauge
            # under a fleet_ name (per-replica counters keep the
            # canonical name, labelled)
            metrics.gauge("fleet_generated_tokens_total",
                          "decode tokens emitted, summed over replicas",
                          fn=lambda: sum(r.generated_tokens()
                                         for r in self.replicas))

    def __len__(self) -> int:
        return len(self.replicas)

    # -------------------------------------------------------------- routing
    def live(self) -> List[Replica]:
        return [r for r in self.replicas if r.alive()]

    def _pick(self, load_fn) -> Replica:
        live = self.live()
        if not live:
            raise WorkerDied("all engine replicas are dead")
        # min() keeps the FIRST minimal element, and self.replicas is in
        # index order — so ties break to the lowest index, always
        return min(live, key=load_fn)

    def pick_predict(self) -> Replica:
        """Least batcher queue depth among live replicas; lowest index
        wins ties. Raises WorkerDied (-> 503) when none are live."""
        return self._pick(lambda r: r.predict_depth())

    def pick_generate(self) -> Replica:
        """Least decode load (active slots + waiting queue) among live
        replicas; lowest index wins ties."""
        return self._pick(lambda r: r.generate_load())

    # ------------------------------------------------------------ readiness
    def ready_detail(self) -> tuple:
        """(ok, detail): ok while >= 1 replica is live — dead replicas
        are routed around, not a reason to drain the fleet — but every
        replica's verdict is in the detail body."""
        states = [r.describe() for r in self.replicas]
        n_live = sum(1 for s in states if s["alive"])
        detail = {"replicas": len(self.replicas),
                  "replicas_live": n_live,
                  "replica_states": states}
        dead = [s["replica"] for s in states if not s["alive"]]
        if dead:
            detail["replicas_dead"] = dead
        return n_live > 0, detail

    def shed_generate(self, frac: float) -> bool:
        """Fleet tier-1 shed: only when EVERY live replica is past its
        saturation fraction (idle replicas must keep taking work)."""
        live = self.live()
        if not live:
            return False  # dead-fleet requests 503 via routing, not 429
        return all(r.generate_saturated(frac) for r in live)

    # ------------------------------------------------------------ lifecycle
    def debug_snapshot(self) -> dict:
        out = {"replicas": []}
        for r in self.replicas:
            snap = (r.decoder.debug_snapshot()
                    if r.decoder is not None else {})
            snap["replica"] = r.index
            snap["alive"] = r.alive()
            if r.batcher is not None:
                snap["batcher"] = {
                    "queue_depth": r.batcher.queue_depth,
                    "max_queue": r.batcher.max_queue,
                    "worker_up": r.batcher.alive()}
            out["replicas"].append(snap)
        return out

    def describe(self) -> dict:
        return {"replicas": len(self.replicas),
                "replica_devices": [len(r.devices)
                                    for r in self.replicas]}

    def close(self) -> None:
        for r in self.replicas:
            try:
                r.close()
            except Exception:  # one bad replica must not block the rest
                logger.exception("closing replica %d failed", r.index)
