"""Fleet router (ISSUE 20): the front-door process that spawns,
monitors, and proxies for K engine worker processes.

The single-process server multiplexes replicas inside one interpreter
(PR 15's ``ReplicaSet``); one wedged interpreter or one weight reload
still takes down every replica at once. The fleet tier moves that
boundary to the OS: each worker is today's ``serve`` stack in its own
process on its own port, and this router is the only thing clients see:

* ``POST /predict`` / ``POST /generate`` — proxied to the live worker
  with the lowest SLO-burn-weighted queue depth (``(1 + depth) *
  (1 + w * burn)``: at equal depth traffic drifts away from replicas
  already missing their TTFT/TPOT targets). Streamed ``/generate``
  passes SSE frames through chunk-for-chunk. Connect failures fail
  over to the next worker; the dead one is routed around immediately.
* worker lifecycle — a worker that exits is restarted under the
  resilience retry policy (exponential backoff, deterministic jitter,
  bounded budget) and rejoins rotation on its first ``ready``
  heartbeat. ``/readyz`` stays 200 while >= 1 worker is routable.
* ``GET /metrics`` — the router's own counters plus every worker's
  page re-exported with a ``worker="i"`` label and summed fleet
  aggregates (:mod:`bigdl_tpu.obs.aggregate`).
* ``GET /debug/fleet`` — the routing table: per-worker state, queue
  depth, burn, version, restart count.
* ``POST /admin/reload`` — rolling zero-downtime weight swap
  (:mod:`fleet.swap`), one worker at a time.

Every response — proxied or router-originated, including the 503 when
no worker lives — echoes ``x-request-id``; proxied responses carry the
worker's ``x-model-version`` through untouched.
"""

from __future__ import annotations

import json
import logging
import os
import re
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional

from bigdl_tpu.resilience.supervisor import RetryPolicy
from bigdl_tpu.serving import reqtrace as _reqtrace
from bigdl_tpu.serving.fleet import control, swap

logger = logging.getLogger(__name__)

__all__ = ["FleetRouter", "NoLiveWorker", "WorkerHandle", "run_fleet",
           "worker_base_argv"]

_MAX_BODY = 64 * 1024 * 1024
_PORT_RE = re.compile(r"serving .+ on http://[^:]+:(\d+)")

# serve/fleet flags the ROUTER owns — stripped from the argv forwarded
# to workers (each entry: flag -> number of value tokens that follow)
_ROUTER_FLAGS = {"--fleet": 1, "--port": 1, "-p": 1, "--host": 1,
                 "--model": 1, "--modelVersion": 1,
                 "--fleetHeartbeatS": 1, "--fleetRestartBudget": 1}
_ROUTER_SWITCHES = {"--randomInit"}


class NoLiveWorker(RuntimeError):
    """Every worker is dead, unreachable, or draining."""


def worker_base_argv(argv: List[str]) -> List[str]:
    """The serve argv minus everything the router owns (fleet shape,
    bind address, weights source + version — re-attached per spawn so a
    worker restarted AFTER a rolling swap boots with the swapped
    checkpoint, not the original one)."""
    out: List[str] = []
    i = 0
    while i < len(argv):
        a = argv[i]
        key = a.split("=", 1)[0]
        if key in _ROUTER_SWITCHES:
            i += 1
            continue
        if key in _ROUTER_FLAGS:
            i += 1 + (0 if "=" in a else _ROUTER_FLAGS[key])
            continue
        out.append(a)
        i += 1
    return out


class WorkerHandle:
    """Router-side view of one worker process: the Popen, the parsed
    port, the last heartbeat, and the restart bookkeeping."""

    def __init__(self, index: int):
        self.index = int(index)
        self.proc: Optional[subprocess.Popen] = None
        self.port: Optional[int] = None
        self.state = "starting"          # router-side lifecycle verdict
        self.status: Optional[control.WorkerStatus] = None
        self.draining = False            # router-side (rolling swap)
        self.restarts = 0
        self.restart_at: Optional[float] = None
        self.gave_up = False
        self.missed = 0
        self.last_seen = 0.0
        self.last_rc: Optional[int] = None

    def process_alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def routable(self) -> bool:
        return (self.process_alive() and self.port is not None
                and self.state == "ready" and not self.draining
                and not self.gave_up)

    def score(self, burn_weight: float) -> float:
        st = self.status
        depth = (st.queue_depth + st.decode_active) if st else 0
        burn = st.slo_burn if st else 0.0
        return (1.0 + depth) * (1.0 + burn_weight * burn)

    def describe(self) -> dict:
        out = {"worker": self.index, "port": self.port,
               "state": ("dead" if not self.process_alive()
                         else self.state),
               "pid": self.proc.pid if self.proc is not None else None,
               "alive": self.process_alive(),
               "routable": self.routable(),
               "draining": self.draining,
               "restarts": self.restarts, "gave_up": self.gave_up}
        if self.last_rc is not None:
            out["last_rc"] = self.last_rc
        if self.status is not None:
            out.update(queue_depth=self.status.queue_depth,
                       decode_active=self.status.decode_active,
                       slo_burn=self.status.slo_burn,
                       goodput=self.status.goodput,
                       model_version=self.status.model_version)
        return out


class FleetRouter:
    """Spawns and supervises K workers and owns the routing table. The
    HTTP proxying lives in :class:`_RouterHandler`; everything here is
    socket-free and unit-testable."""

    def __init__(self, name: str, n_workers: int, *,
                 make_argv: Optional[Callable[[int], List[str]]] = None,
                 base_argv: Optional[List[str]] = None,
                 checkpoint: Optional[str] = None,
                 random_init: bool = False, version: str = "v0",
                 host: str = "127.0.0.1", heartbeat_s: float = 0.5,
                 burn_weight: float = 4.0,
                 restart_policy: Optional[RetryPolicy] = None,
                 proxy_timeout_s: float = 150.0,
                 start_timeout_s: float = 300.0,
                 miss_limit: int = 6, env: Optional[dict] = None,
                 provenance: Optional[dict] = None):
        if n_workers < 1:
            raise ValueError(f"fleet needs >= 1 worker, got {n_workers}")
        self.name = name
        self.host = host
        self.heartbeat_s = float(heartbeat_s)
        self.burn_weight = float(burn_weight)
        self.restart_policy = restart_policy or RetryPolicy(
            budget=8, base_s=0.25, multiplier=2.0, max_s=10.0,
            jitter=0.5)
        self.proxy_timeout_s = float(proxy_timeout_s)
        self.start_timeout_s = float(start_timeout_s)
        self.miss_limit = int(miss_limit)
        self.checkpoint = checkpoint
        self.random_init = bool(random_init)
        self.version = str(version)
        self._make_argv = make_argv
        self.base_argv = list(base_argv or [])
        self._env = env
        self._handles = [WorkerHandle(i) for i in range(n_workers)]
        self._lock = threading.RLock()
        self._reload_lock = threading.Lock()
        self._monitor: Optional[threading.Thread] = None
        self._stop = threading.Event()

        from bigdl_tpu.obs.metrics import MetricsRegistry
        self.metrics = MetricsRegistry(namespace="bigdl_fleet")
        self._m_requests = {
            ep: self.metrics.counter(f"requests_{ep}_total",
                                     f"/{ep} requests proxied")
            for ep in ("predict", "generate")}
        self._m_reroutes = self.metrics.counter(
            "proxy_reroutes_total",
            "requests failed over to another worker after a connect "
            "failure")
        self._m_5xx = self.metrics.counter(
            "responses_5xx_total",
            "5xx responses the ROUTER originated (no live worker, "
            "upstream died mid-request)")
        self._m_restarts = self.metrics.counter(
            "worker_restarts_total",
            "worker processes restarted by the supervisor policy")
        self._m_reloads = self.metrics.counter(
            "reloads_total", "rolling weight swaps completed")
        self.metrics.gauge("workers", "fleet size",
                           fn=lambda: len(self._handles))
        self.metrics.gauge("workers_routable",
                           "workers currently in rotation",
                           fn=lambda: sum(h.routable()
                                          for h in self._handles))
        prov = {"model": name, "fleet_workers": n_workers,
                "model_version": lambda: self.version,
                "checkpoint": checkpoint or "randomInit"}
        if provenance:
            prov.update(provenance)
        self.metrics.set_provenance(prov)

    # ------------------------------------------------------------ lifecycle
    def worker_argv(self, index: int) -> List[str]:
        if self._make_argv is not None:
            return list(self._make_argv(index))
        av = [sys.executable, "-m", "bigdl_tpu.serving.fleet.worker"]
        av += self.base_argv
        if self.checkpoint:
            av += ["--model", self.checkpoint]
        elif self.random_init:
            av += ["--randomInit"]
        av += ["--modelVersion", self.version, "--host", self.host,
               "--port", "0", "--workerIndex", str(index)]
        return av

    def _spawn(self, h: WorkerHandle) -> None:
        env = dict(self._env if self._env is not None else os.environ)
        env["BIGDL_TPU_WORKER_RESTARTS"] = str(h.restarts)
        argv = self.worker_argv(h.index)
        h.port = None
        h.status = None
        h.state = "starting"
        h.missed = 0
        h.proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                                  stderr=subprocess.STDOUT, text=True,
                                  bufsize=1, env=env)
        logger.info("fleet: worker %d spawned pid=%d", h.index,
                    h.proc.pid)
        threading.Thread(target=self._pump, args=(h, h.proc),
                         daemon=True,
                         name=f"fleet-w{h.index}-log").start()

    def _pump(self, h: WorkerHandle, proc: subprocess.Popen) -> None:
        """Forward one worker's stdout (prefixed) and parse the serve
        banner for the ephemeral port."""
        try:
            for line in proc.stdout:
                line = line.rstrip("\n")
                m = _PORT_RE.search(line)
                if m and proc is h.proc:
                    h.port = int(m.group(1))
                print(f"[worker {h.index}] {line}", flush=True)
        except (ValueError, OSError):
            pass  # stream closed during shutdown

    def start(self) -> None:
        """Spawn the fleet and the monitor; block until every worker
        heartbeats ready (or the start timeout passes with >= 1 ready —
        stragglers keep booting under the monitor's eye)."""
        for h in self._handles:
            self._spawn(h)
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True,
                                         name="fleet-monitor")
        self._monitor.start()
        deadline = time.monotonic() + self.start_timeout_s
        while time.monotonic() < deadline:
            if all(h.routable() or h.gave_up for h in self._handles):
                break
            time.sleep(0.1)
        live = sum(h.routable() for h in self._handles)
        if live == 0:
            self.close()
            raise SystemExit(
                f"fleet: no worker became ready within "
                f"{self.start_timeout_s:.0f}s — see [worker N] output "
                f"above")
        logger.info("fleet: %d/%d workers ready", live,
                    len(self._handles))

    def _monitor_loop(self) -> None:
        while not self._stop.is_set():
            for h in self._handles:
                try:
                    self._check_worker(h)
                except Exception:
                    logger.exception("fleet: monitor check for worker "
                                     "%d failed", h.index)
            self._stop.wait(self.heartbeat_s)

    def _check_worker(self, h: WorkerHandle) -> None:
        now = time.monotonic()
        if h.proc is None:
            return
        rc = h.proc.poll()
        if rc is not None:
            if h.state != "dead":
                # fresh death: record it and schedule the supervised
                # restart (the fleet keeps serving on the survivors;
                # /readyz stays 200 while >= 1 worker is routable)
                h.state = "dead"
                h.status = None
                h.last_rc = rc
                if h.restarts >= self.restart_policy.budget:
                    h.gave_up = True
                    logger.error(
                        "fleet: worker %d exited rc=%d — restart "
                        "budget (%d) exhausted, leaving it down",
                        h.index, rc, self.restart_policy.budget)
                    return
                h.restarts += 1
                d = self.restart_policy.delay(h.restarts)
                h.restart_at = now + d
                logger.warning(
                    "fleet: worker %d exited rc=%d — restart %d/%d "
                    "in %.2fs", h.index, rc, h.restarts,
                    self.restart_policy.budget, d)
            elif (not h.gave_up and h.restart_at is not None
                    and now >= h.restart_at):
                h.restart_at = None
                self._m_restarts.inc()
                self._spawn(h)
            return
        if h.port is None:
            return  # still booting: no banner yet
        st = control.fetch_status(self.host, h.port,
                                  timeout=max(self.heartbeat_s, 2.0))
        if st is None:
            h.missed += 1
            if h.missed >= self.miss_limit and h.state == "ready":
                # alive but unresponsive (wedged interpreter): route
                # around it; the first heartbeat that lands rejoins it
                h.state = "unreachable"
                logger.warning("fleet: worker %d missed %d heartbeats "
                               "— out of rotation", h.index, h.missed)
            return
        h.missed = 0
        h.last_seen = now
        h.status = st
        h.state = st.state if st.state in control.WORKER_STATES \
            else "ready"

    def close(self) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(self.heartbeat_s + 2.0)
        for h in self._handles:
            if h.proc is not None and h.proc.poll() is None:
                h.proc.terminate()
        deadline = time.monotonic() + 10.0
        for h in self._handles:
            if h.proc is None:
                continue
            try:
                h.proc.wait(max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                h.proc.kill()
                h.proc.wait(5.0)

    # -------------------------------------------------------------- routing
    def worker_handles(self) -> List[WorkerHandle]:
        return list(self._handles)

    def set_draining(self, h: WorkerHandle, flag: bool) -> None:
        h.draining = bool(flag)

    def note_reloaded(self, checkpoint: str, version: str) -> None:
        """A rolling swap finished: restarts from here on boot with the
        NEW checkpoint/version (a worker killed after the swap rejoins
        at the swapped weights, not the originals)."""
        self.checkpoint = checkpoint
        self.random_init = False
        self.version = str(version)
        self._m_reloads.inc()

    def pick(self, exclude=()) -> WorkerHandle:
        cands = [h for h in self._handles
                 if h.routable() and h.index not in exclude]
        if not cands:
            raise NoLiveWorker("no live fleet worker")
        return min(cands, key=lambda h: (h.score(self.burn_weight),
                                         h.index))

    # ------------------------------------------------------------ endpoints
    def handle_healthz(self):
        return 200, {"status": "ok", "model": self.name,
                     "role": "fleet-router"}

    def handle_readyz(self):
        detail = {"model": self.name, "role": "fleet-router",
                  "workers": len(self._handles),
                  "workers_routable": sum(h.routable()
                                          for h in self._handles),
                  "worker_states": {
                      str(h.index): ("dead" if not h.process_alive()
                                     else h.state)
                      for h in self._handles}}
        ok = detail["workers_routable"] >= 1
        detail["status"] = "ready" if ok else "unready"
        return (200 if ok else 503), detail

    def handle_debug_fleet(self):
        return 200, {"model": self.name, "version": self.version,
                     "checkpoint": self.checkpoint or "randomInit",
                     "workers": [h.describe() for h in self._handles]}

    def handle_admin_reload(self, payload):
        payload = payload or {}
        ckpt = payload.get("checkpoint")
        version = payload.get("version")
        if not ckpt or not version:
            return 400, {"error": "reload needs 'checkpoint' and "
                                  "'version'"}
        if not self._reload_lock.acquire(blocking=False):
            return 409, {"error": "a rolling reload is already in "
                                  "progress"}
        try:
            results = swap.rolling_reload(
                self, str(ckpt), str(version),
                drain_timeout_s=float(payload.get("drain_timeout_s",
                                                  60.0)))
        finally:
            self._reload_lock.release()
        failed = [r for r in results if r.get("status") == "error"]
        status = 500 if failed else 200
        return status, {"status": "error" if failed else "reloaded",
                        "version": str(version), "workers": results}

    def handle_metrics(self) -> str:
        """The router's own page plus every worker's page, re-exported
        with a ``worker`` label and summed into fleet series."""
        from bigdl_tpu.obs.aggregate import aggregate_pages
        pages = {}
        for h in self._handles:
            if not h.process_alive() or h.port is None:
                continue
            try:
                status, text = _http_get_text(self.host, h.port,
                                              "/metrics", timeout=3.0)
            except OSError:
                continue
            if status == 200:
                pages[str(h.index)] = text
        out = self.metrics.render()
        if pages:
            out += "\n" + aggregate_pages(pages, label="worker")
        return out

    # --------------------------------------------------------------- serve
    def serve(self, port: int = 8000) -> int:
        """Foreground router loop, mirroring ``run_server``'s banner and
        clean-shutdown contract (SIGTERM -> rc 0 + shutdown marker)."""
        import signal

        self.start()
        srv = ThreadingHTTPServer((self.host, port), _RouterHandler)
        srv.daemon_threads = True
        srv.router = self  # type: ignore[attr-defined]
        actual = srv.server_address[1]
        logger.info("serving fleet %s on http://%s:%d (%d workers)",
                    self.name, self.host, actual, len(self._handles))
        print(f"serving {self.name} fleet on http://{self.host}:{actual}",
              flush=True)

        def _sig(signum, frame):
            threading.Thread(target=srv.shutdown, daemon=True).start()

        prev = {}
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                prev[sig] = signal.signal(sig, _sig)
            except ValueError:
                pass  # non-main thread (tests)
        try:
            srv.serve_forever(poll_interval=0.2)
        finally:
            for sig, handler in prev.items():
                signal.signal(sig, handler)
            srv.server_close()
            self.close()
            print("serving shutdown clean", flush=True)
        return 0


def _http_get_text(host, port, path, timeout=5.0):
    import http.client
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read().decode("utf-8", "replace")
    finally:
        conn.close()


class _RouterHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    @property
    def router(self) -> FleetRouter:
        return self.server.router  # type: ignore[attr-defined]

    def _rid(self) -> str:
        return (_reqtrace.sanitize_rid(self.headers.get("x-request-id"))
                or _reqtrace.mint_rid())

    def _send_json(self, status: int, body: dict, rid: str,
                   version: Optional[str] = None) -> None:
        data = json.dumps(body).encode()
        self.send_response(status)
        if status == 429:
            self.send_header("Retry-After", "1")
        self.send_header("x-request-id", rid)
        if version:
            self.send_header("x-model-version", version)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)
        if status >= 500:
            self.router._m_5xx.inc()

    # ------------------------------------------------------------------ GET
    def do_GET(self):  # noqa: N802
        rid = self._rid()
        r = self.router
        if self.path == "/healthz":
            self._send_json(*r.handle_healthz(), rid=rid)
        elif self.path == "/readyz":
            self._send_json(*r.handle_readyz(), rid=rid)
        elif self.path == "/debug/fleet":
            self._send_json(*r.handle_debug_fleet(), rid=rid)
        elif self.path == "/metrics":
            data = r.handle_metrics().encode()
            self.send_response(200)
            self.send_header("x-request-id", rid)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        elif self.path.startswith("/debug/"):
            self._proxy("GET", self.path, None, rid, stream=False)
        else:
            self._send_json(404,
                            {"error": f"unknown path {self.path}"},
                            rid=rid)

    # ----------------------------------------------------------------- POST
    def do_POST(self):  # noqa: N802
        rid = self._rid()
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            length = 0
        if length <= 0 or length > _MAX_BODY:
            self._send_json(400, {"error": "missing or oversized body"},
                            rid=rid)
            return
        body = self.rfile.read(length)
        if self.path == control.RELOAD_PATH:
            try:
                payload = json.loads(body)
            except ValueError as e:
                self._send_json(400, {"error": f"bad JSON: {e}"},
                                rid=rid)
                return
            status, out = self.router.handle_admin_reload(payload)
            self._send_json(status, out, rid=rid,
                            version=self.router.version)
            return
        ep = self.path.strip("/")
        if ep not in ("predict", "generate"):
            self._send_json(404,
                            {"error": f"unknown endpoint {self.path}"},
                            rid=rid)
            return
        stream = False
        if ep == "generate":
            try:  # routing only needs the stream bit; workers validate
                stream = bool(json.loads(body).get("stream"))
            except (ValueError, AttributeError):
                pass
        self.router._m_requests[ep].inc()
        self._proxy("POST", self.path, body, rid, stream=stream)

    # ------------------------------------------------------------- proxying
    def _proxy(self, method: str, path: str, body: Optional[bytes],
               rid: str, stream: bool) -> None:
        """Forward to the best worker; connect failures fail over (the
        request never reached an engine), failures AFTER the request was
        sent answer 503/504 without a blind retry."""
        import http.client
        import socket

        r = self.router
        tried: set = set()
        while True:
            try:
                h = r.pick(exclude=tried)
            except NoLiveWorker:
                self._send_json(
                    503, {"error": "no live fleet worker"}, rid=rid,
                    version=r.version)
                return
            conn = http.client.HTTPConnection(r.host, h.port,
                                              timeout=5.0)
            try:
                conn.connect()
            except OSError:
                conn.close()
                tried.add(h.index)
                r._m_reroutes.inc()
                logger.warning("fleet: worker %d connect failed — "
                               "failing over", h.index)
                continue
            conn.sock.settimeout(r.proxy_timeout_s)
            headers = {"x-request-id": rid}
            if body is not None:
                headers["Content-Type"] = "application/json"
            try:
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
            except socket.timeout:
                conn.close()
                self._send_json(
                    504, {"error": f"fleet worker {h.index} timed out "
                                   f"after {r.proxy_timeout_s:.0f}s"},
                    rid=rid, version=r.version)
                return
            except OSError as e:
                conn.close()
                self._send_json(
                    503, {"error": f"fleet worker {h.index} died "
                                   f"mid-request: {e}"},
                    rid=rid, version=r.version)
                return
            try:
                if stream and resp.status == 200:
                    self._relay_stream(resp, rid)
                else:
                    self._relay(resp, rid)
            finally:
                conn.close()
            return

    def _relay(self, resp, rid: str) -> None:
        data = resp.read()
        self.send_response(resp.status)
        self.send_header("x-request-id", rid)
        for name in ("x-model-version", "Retry-After"):
            v = resp.getheader(name)
            if v:
                self.send_header(name, v)
        self.send_header("Content-Type",
                         resp.getheader("Content-Type",
                                        "application/json"))
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _write_chunk(self, data: bytes) -> None:
        self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
        self.wfile.flush()

    def _relay_stream(self, resp, rid: str) -> None:
        """SSE passthrough: http.client de-chunks the worker's frames;
        re-chunk them to the client byte-for-byte. A worker death
        mid-stream surfaces as a final SSE error frame (the stream
        already committed a 200); a client disconnect just drops the
        upstream connection, which cancels the worker-side slot."""
        self.send_response(200)
        self.send_header("x-request-id", rid)
        v = resp.getheader("x-model-version")
        if v:
            self.send_header("x-model-version", v)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("Connection", "close")
        self.end_headers()
        self.close_connection = True
        try:
            while True:
                try:
                    chunk = resp.read(4096)
                except OSError:
                    self._write_chunk(
                        b'data: {"error": "fleet worker died '
                        b'mid-stream"}\n\n')
                    break
                if not chunk:
                    break
                self._write_chunk(chunk)
            self._write_chunk(b"")
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # client went away; dropping upstream cancels the slot

    def log_message(self, fmt, *args):
        logger.debug("%s - %s", self.address_string(), fmt % args)


def run_fleet(args, argv: List[str]) -> int:
    """``serve --fleet K`` / ``bigdl-tpu fleet`` entry: resolve the
    shared config spine once (validates strategy/quantize/speculate
    BEFORE any worker pays a boot), build the router, serve."""
    from bigdl_tpu.cli import common

    k = int(args.fleet)
    if k < 1:
        raise SystemExit(f"--fleet {k}: a fleet needs >= 1 worker")
    if not args.checkpoint and not args.randomInit:
        raise SystemExit(
            "fleet needs weights: pass --model CKPT (a training "
            "checkpoint dir or file) or --randomInit for smoke/bench "
            "runs")
    cfg = common.resolve_serve_config(args)
    router = FleetRouter(
        name=args.model, n_workers=k,
        base_argv=worker_base_argv(argv),
        checkpoint=args.checkpoint, random_init=args.randomInit,
        version=getattr(args, "modelVersion", None) or "v0",
        host=args.host,
        heartbeat_s=getattr(args, "fleetHeartbeatS", 0.5),
        restart_policy=RetryPolicy(
            budget=int(getattr(args, "fleetRestartBudget", 8)),
            base_s=0.25, multiplier=2.0, max_s=10.0, jitter=0.5),
        proxy_timeout_s=float(args.timeout) + 30.0,
        provenance={"strategy": args.strategy or "none",
                    "serving_replicas": cfg.serving_replicas,
                    "serving_tp": cfg.serving_tp,
                    "quantize": cfg.quantize or "off",
                    "speculate": cfg.speculate})
    return router.serve(port=args.port)
