"""Serving fleet tier (ISSUE 20): router process + K engine workers.

``control`` is the message schema and transport (stdlib-only, jax-free
— safe to import in the router process). ``router`` spawns/monitors
workers and proxies traffic by SLO-burn-weighted queue depth. ``worker``
is the engine process (the full ``serve`` stack + control surface).
``swap`` is the zero-downtime rolling weight reload.

Deliberately lazy: importing :mod:`bigdl_tpu.serving.fleet` pulls in
none of the submodules, and the router process never CALLS a jax API —
backends init lazily, so the front door holds no accelerator client and
the K workers own the chips.
"""

from __future__ import annotations

__all__ = ["control", "router", "swap", "worker"]


def __getattr__(name):
    if name in __all__:
        import importlib
        return importlib.import_module(f"{__name__}.{name}")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
