"""Zero-downtime weight swap (ISSUE 20): drain, restore, switch trees.

Two halves, one invariant. The WORKER half (:func:`swap_app_weights`)
waits until its serving stack is idle — every in-flight decode finishes
on the OLD weights, so no response is ever computed from a
mixed-version batch — then restores the checkpoint (through PR 10's
topology-independent path when the engine is meshed) and swaps the
param trees under the decoder lock. The ROUTER half
(:func:`rolling_reload`) walks the fleet one worker at a time: pull the
worker out of rotation, wait for its queues to hit zero, POST its
``/admin/reload``, wait until it heartbeats ``ready`` at the new
version, put it back. At every instant K-1 workers serve, so the fleet
answers with zero 5xx responses across the whole swap — the old and
new ``x-model-version`` are both observed during the window, never
within one response.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from bigdl_tpu.serving.fleet import control

logger = logging.getLogger(__name__)

__all__ = ["WeightSwapError", "rolling_reload", "swap_app_weights"]


class WeightSwapError(RuntimeError):
    """A reload that could not complete safely (drain timeout, restore
    failure). The worker keeps serving the OLD weights — a failed swap
    never leaves a half-swapped tree."""


def _stacks(app):
    """(engine, batcher, decoder) per replica — the single-stack app is
    a one-element fleet of itself."""
    if app.replicas is not None:
        return [(r.engine, r.batcher, r.decoder)
                for r in app.replicas.replicas]
    return [(app.engine, app.batcher, app.decoder)]


def _in_flight(app) -> int:
    n = 0
    for _, batcher, decoder in _stacks(app):
        if batcher is not None:
            n += int(batcher.queue_depth)
        if decoder is not None:
            n += int(decoder.queue_load())
    return n


def _swap_stack(engine, decoder, params, mod_state) -> None:
    """Point one replica's engines at the new trees. The decoder swap
    happens under its slot lock: ``submit``/``step`` serialize on the
    same lock, so a decode batch reads either the old tree or the new
    one — never a mix."""
    from bigdl_tpu.serving import quant as _q

    wfmt, _ = _q.parse_quantize(engine.quantize)
    if wfmt is not None:
        params = _q.quantize_params(params, wfmt)
    eng_params = params
    if engine._shard is not None:
        eng_params = engine._shard.place_params(engine.module, params)
        if mod_state is not None:
            import jax
            mod_state = jax.device_put(mod_state, engine._shard.replicated)
    engine.params = eng_params
    if mod_state is not None:
        engine.mod_state = mod_state
    if decoder is None:
        return
    dec_params = params
    if decoder._shard is not None:
        dec_params = decoder._shard.place_params(decoder.model, params)
    with decoder._lock:
        decoder.params = dec_params
        if decoder.speculate > 0 and decoder.draft_model is decoder.model:
            # self-draft shares the target tree; a distinct draft model
            # keeps its own (randomly initialized) proposer weights
            decoder.draft_params = dec_params


def swap_app_weights(app, checkpoint: str, version: str, *,
                     drain_timeout_s: float = 60.0,
                     poll_s: float = 0.02,
                     clock=time.monotonic) -> None:
    """Drain-then-swap on one worker. Blocks until every in-flight
    request has FINISHED ON THE OLD WEIGHTS (the rolling-swap atomicity
    contract), then restores ``checkpoint`` and swaps every replica's
    trees. Raises :class:`WeightSwapError` without touching the served
    weights when the drain times out or the restore fails."""
    deadline = clock() + float(drain_timeout_s)
    while _in_flight(app):
        if clock() > deadline:
            raise WeightSwapError(
                f"drain timeout after {drain_timeout_s}s with "
                f"{_in_flight(app)} request(s) still in flight — "
                f"weights NOT swapped")
        time.sleep(poll_s)

    for engine, _, decoder in _stacks(app):
        try:
            if engine.mesh is not None:
                from bigdl_tpu.serving.sharding import restore_for_serving
                params, mod_state = restore_for_serving(checkpoint,
                                                        engine.mesh)
            else:
                from bigdl_tpu.utils.orbax_ckpt import restore_for_inference
                params, mod_state = restore_for_inference(checkpoint)
        except SystemExit as e:
            # restore_* exits clean on missing/corrupt checkpoints at
            # startup; mid-serve that must be a refusable error instead
            raise WeightSwapError(
                f"restore failed for {checkpoint!r}: {e} — "
                f"weights NOT swapped")
        _swap_stack(engine, decoder, params, mod_state)

    app.model_version = str(version)
    logger.info("weight swap complete: %s -> version %s", checkpoint,
                version)


def rolling_reload(router, checkpoint: str, version: str, *,
                   drain_timeout_s: float = 60.0,
                   reload_timeout_s: float = 600.0,
                   rejoin_timeout_s: float = 60.0,
                   poll_s: float = 0.05) -> list:
    """Walk the fleet one worker at a time: drain (out of rotation; the
    worker finishes in-flight work on the old weights), reload, wait for
    a ``ready`` heartbeat at the new version, rejoin. Aborts on the
    first failure — the already-swapped workers keep the new version,
    the untouched ones keep the old, and the result rows say which is
    which."""
    results = []
    host = router.host
    for h in router.worker_handles():
        row = {"worker": h.index, "port": h.port}
        if not h.process_alive():
            row.update(status="skipped", reason="process not running")
            results.append(row)
            continue
        router.set_draining(h, True)
        try:
            t_end = time.monotonic() + drain_timeout_s
            while True:
                st = control.fetch_status(host, h.port, timeout=2.0)
                if (st is not None and st.queue_depth == 0
                        and st.decode_active == 0):
                    break
                if time.monotonic() > t_end:
                    row.update(status="error",
                               error=f"drain timeout after "
                                     f"{drain_timeout_s}s")
                    results.append(row)
                    return results
                time.sleep(poll_s)
            try:
                code, body = control.request_json(
                    "POST", host, h.port, control.RELOAD_PATH,
                    {"checkpoint": checkpoint, "version": version,
                     "drain_timeout_s": drain_timeout_s},
                    timeout=reload_timeout_s)
            except OSError as e:
                row.update(status="error", error=f"reload transport: {e}")
                results.append(row)
                return results
            if code != 200:
                row.update(status="error",
                           error=str(body.get("error", f"HTTP {code}")))
                results.append(row)
                return results
            t_end = time.monotonic() + rejoin_timeout_s
            while True:
                st = control.fetch_status(host, h.port, timeout=2.0)
                if (st is not None and st.state == "ready"
                        and st.model_version == str(version)):
                    break
                if time.monotonic() > t_end:
                    row.update(status="error",
                               error="worker never reported ready at "
                                     f"version {version}")
                    results.append(row)
                    return results
                time.sleep(poll_s)
            row.update(status="reloaded", version=str(version))
            results.append(row)
        finally:
            router.set_draining(h, False)
    router.note_reloaded(checkpoint, str(version))
    return results
