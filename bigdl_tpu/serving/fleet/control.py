"""Fleet control plane (ISSUE 20): the message schema and transport
between the router process and its engine workers.

The control channel is deliberately boring — plain HTTP on the worker's
own serving port, so there is exactly one socket per worker to keep
alive and the control surface inherits the serving stack's threading
model. Two endpoints make up the whole protocol:

* ``GET /control/state``  — the worker heartbeat: one
  :class:`WorkerStatus` JSON object per poll (state, queue depth, SLO
  burn, model version). The router polls it every ``heartbeat_s``;
  a worker that stops answering is routed around, a worker whose
  PROCESS died is restarted by the supervisor machinery.
* ``POST /admin/reload``  — ``{"checkpoint": ..., "version": ...}``:
  drain in-flight work, restore the checkpoint through the
  topology-independent PR 10 path, swap the weight trees, bump the
  version stamped into provenance and the ``x-model-version`` response
  header.

Everything here is stdlib-only — no jax API is ever called, so the
router process never initializes an accelerator client (jax backends
init lazily on first use; the router gives them no first use).
"""

from __future__ import annotations

import dataclasses
import http.client
import json
from typing import Optional, Tuple

__all__ = ["CONTROL_PATH", "RELOAD_PATH", "WORKER_STATES", "WorkerStatus",
           "fetch_status", "request_json"]

CONTROL_PATH = "/control/state"
RELOAD_PATH = "/admin/reload"

# the worker lifecycle the router's routing table understands:
#   starting  — process up, engines still compiling / warming
#   ready     — in rotation
#   draining  — finishing in-flight work, no NEW requests routed
#   reloading — weight swap in progress (implies drained)
#   dead      — process exited (router-side verdict; a worker never
#               reports it about itself)
WORKER_STATES = ("starting", "ready", "draining", "reloading", "dead")


@dataclasses.dataclass
class WorkerStatus:
    """One heartbeat: everything the router's balancer needs to score a
    worker — queue depth for least-loaded, SLO burn for the weighting,
    model version for the rolling-swap bookkeeping."""

    index: int
    pid: int = 0
    port: int = 0
    state: str = "starting"
    queue_depth: int = 0
    decode_active: int = 0
    slo_burn: float = 0.0
    goodput: float = 1.0
    model_version: str = "v0"
    restarts: int = 0
    uptime_s: float = 0.0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "WorkerStatus":
        names = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in dict(d).items() if k in names}
        if "index" not in kw:
            raise ValueError("worker status missing 'index'")
        st = cls(**kw)
        if st.state not in WORKER_STATES:
            raise ValueError(f"unknown worker state {st.state!r} "
                             f"(states: {', '.join(WORKER_STATES)})")
        return st


def request_json(method: str, host: str, port: int, path: str,
                 payload: Optional[dict] = None, timeout: float = 5.0,
                 headers: Optional[dict] = None) -> Tuple[int, dict]:
    """One JSON request/response over a fresh connection. Raises OSError
    (incl. ConnectionRefusedError / socket.timeout) on transport
    failure — callers decide whether that means retry, reroute, or
    restart."""
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        body = (json.dumps(payload).encode()
                if payload is not None else None)
        hdrs = dict(headers or {})
        if body is not None:
            hdrs.setdefault("Content-Type", "application/json")
        conn.request(method, path, body=body, headers=hdrs)
        resp = conn.getresponse()
        data = resp.read()
        try:
            obj = json.loads(data) if data else {}
            if not isinstance(obj, dict):
                obj = {"value": obj}
        except ValueError:
            obj = {"raw": data.decode("utf-8", "replace")}
        return resp.status, obj
    finally:
        conn.close()


def fetch_status(host: str, port: int,
                 timeout: float = 2.0) -> Optional[WorkerStatus]:
    """Poll one worker heartbeat; ``None`` on any transport or schema
    failure (a missed heartbeat is data, not an exception — the monitor
    loop counts them)."""
    try:
        status, obj = request_json("GET", host, port, CONTROL_PATH,
                                   timeout=timeout)
    except OSError:
        return None
    if status != 200:
        return None
    try:
        return WorkerStatus.from_dict(obj)
    except (TypeError, ValueError):
        return None
