"""Fleet engine worker (ISSUE 20): today's ``serve`` stack plus the
control surface the router talks to.

A worker IS the single-process server — same flags, same engines, same
endpoints — started by the router on an ephemeral port with two extra
routes installed:

* ``GET /control/state``  — the heartbeat (:mod:`fleet.control`):
  lifecycle state, queue depth (batcher rows + decode load), active
  decode slots, SLO burn/goodput from the request tracer, the model
  version currently served, and the restart count the supervisor
  stamped into the environment.
* ``POST /admin/reload``  — the worker half of the rolling weight swap
  (:func:`fleet.swap.swap_app_weights`): drain, restore, swap, bump the
  version echoed as ``x-model-version`` on every response.

``python -m bigdl_tpu.serving.fleet.worker transformer_lm ...`` also
runs standalone — a fleet worker of one, useful for poking the control
surface by hand.
"""

from __future__ import annotations

import os
import threading
import time

from bigdl_tpu.cli import common
from bigdl_tpu.serving.fleet import control, swap

__all__ = ["WorkerControl", "build_parser", "main"]


class WorkerControl:
    """The worker-side control plane: owns the lifecycle state machine
    (ready -> reloading -> ready), renders heartbeats, and serializes
    reloads (one swap at a time; concurrent reload requests queue on
    the lock rather than interleave)."""

    def __init__(self, app, *, index: int = 0, version: str = "v0",
                 port: int = 0, clock=time.monotonic):
        self.app = app
        self.index = int(index)
        self.port = int(port)
        self.clock = clock
        self._t0 = clock()
        self._state = "ready"
        self._lock = threading.Lock()
        self.restarts = int(os.environ.get("BIGDL_TPU_WORKER_RESTARTS",
                                           "0") or 0)
        app.model_version = str(version)
        app.extra_routes[("GET", control.CONTROL_PATH)] = self.handle_state
        app.extra_routes[("POST", control.RELOAD_PATH)] = self.handle_reload

    # ------------------------------------------------------------- signals
    def _components(self):
        if self.app.replicas is not None:
            return [(r.batcher, r.decoder)
                    for r in self.app.replicas.replicas]
        return [(self.app.batcher, self.app.decoder)]

    def queue_depth(self) -> int:
        n = 0
        for batcher, decoder in self._components():
            if batcher is not None:
                n += int(batcher.queue_depth)
            if decoder is not None:
                n += int(decoder.queue_load())
        return n

    def decode_active(self) -> int:
        n = 0
        for _, decoder in self._components():
            if decoder is not None:
                n += sum(r is not None for r in decoder._reqs)
        return n

    @staticmethod
    def _slo():
        from bigdl_tpu.serving import reqtrace as _reqtrace
        rt = _reqtrace.get()
        return rt.slo if rt is not None else None

    def status(self) -> control.WorkerStatus:
        slo = self._slo()
        return control.WorkerStatus(
            index=self.index, pid=os.getpid(), port=self.port,
            state=self._state,
            queue_depth=self.queue_depth(),
            decode_active=self.decode_active(),
            slo_burn=(round(slo.burn_rate(), 4) if slo is not None
                      else 0.0),
            goodput=(round(slo.goodput_frac(), 4) if slo is not None
                     else 1.0),
            model_version=str(self.app.model_version or "v0"),
            restarts=self.restarts,
            uptime_s=round(self.clock() - self._t0, 3))

    # ------------------------------------------------------------ handlers
    def handle_state(self, _payload=None):
        return 200, self.status().to_dict()

    def handle_reload(self, payload):
        payload = payload or {}
        ckpt = payload.get("checkpoint")
        version = payload.get("version")
        if not ckpt or not version:
            return 400, {"error": "reload needs 'checkpoint' and "
                                  "'version'"}
        try:
            drain_s = float(payload.get("drain_timeout_s", 60.0))
        except (TypeError, ValueError):
            return 400, {"error": "'drain_timeout_s' must be a number"}
        with self._lock:
            self._state = "reloading"
            try:
                swap.swap_app_weights(self.app, str(ckpt), str(version),
                                      drain_timeout_s=drain_s)
            except swap.WeightSwapError as e:
                return 503, {"error": str(e)}
            except Exception as e:  # restore/placement bug: old weights
                return 500, {"error": f"{type(e).__name__}: {e}"}
            finally:
                # a failed swap leaves the old tree serving — the worker
                # goes straight back into rotation either way
                self._state = "ready"
        return 200, {"status": "reloaded",
                     "version": str(self.app.model_version)}


def build_parser():
    from bigdl_tpu.cli import serve as serve_cli
    p = serve_cli.build_parser()
    p.prog = "bigdl_tpu.serving.fleet.worker"
    p.add_argument("--workerIndex", type=int, default=0,
                   help="this worker's slot in the fleet (router-"
                        "assigned; labels heartbeats and metrics)")
    return p


def main(argv=None) -> int:
    common.setup_logging()
    args = build_parser().parse_args(argv)
    if getattr(args, "fleet", 0):
        raise SystemExit("--fleet belongs to the router process; a "
                         "worker serves exactly one engine stack")
    common.apply_platform(args)

    # fleet chaos drill site: a --faultPlan 'worker_kill@worker_boot:N'
    # kills the Nth boot of this PROCESS — the supervisor-restart path
    # the fleet CI smoke exercises (no-op without a plan)
    from bigdl_tpu.resilience.faults import hook as _fault_hook
    _fault_hook("worker_boot")

    from bigdl_tpu.cli import serve as serve_cli
    from bigdl_tpu.serving import run_server

    app, engine, in_shape, in_dtype = serve_cli.build_app(args)
    WorkerControl(app, index=args.workerIndex,
                  version=getattr(args, "modelVersion", None) or "v0",
                  port=args.port)
    if not args.no_warmup:
        engines = ([r.engine for r in app.replicas.replicas]
                   if app.replicas is not None else [engine])
        for e in engines:
            e.warmup(in_shape, in_dtype)
    return run_server(app, args.host, args.port)


if __name__ == "__main__":
    raise SystemExit(main())
