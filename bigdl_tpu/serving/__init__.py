"""Online inference subsystem (ISSUE 5) — the production-serving half of
the roadmap the first four PRs left open.

BigDL's pitch was one stack for training AND serving (arxiv 1804.05839;
BigDL 2.0 made seamless serving pipelines the headline, 2204.01715).
Here the serving path deliberately reuses everything the training side
tuned: the same modules and checkpoints, the same ``--fusedBN`` /
``--convLayout`` / ``--convGeom`` / ``--autotune`` program configuration
the perf harness measured, and the tpulint pre-flight before first
compile.

Modules:

* :mod:`engine`  — bucketed pre-compiled eval forwards with donated
  inputs (bounded compile cache, metered padding waste);
* :mod:`batcher` — dynamic micro-batching (max_batch / max_wait_ms
  triggers) with backpressure fast-reject admission control;
* :mod:`decode`  — KV-cache prefill/decode split with
  continuous-batching slots for ``transformer_lm``;
* :mod:`metrics` — lock-cheap counters + latency histograms with a
  plaintext exposition format and config-provenance stamping (now a
  re-export of :mod:`bigdl_tpu.obs.metrics` — ISSUE 7 promoted the
  registry process-global so training and resilience share it);
* :mod:`watchdog` — dead/wedged-worker detection: pending futures fail
  fast, ``/readyz`` flips, ``/healthz`` stays live (ISSUE 6);
* :mod:`reqtrace` — per-request lifecycle flight recorder (ISSUE 15):
  request IDs threaded admission -> terminal state, server-side
  TTFT/TPOT/ITL histograms, SLO goodput/burn accounting, sampled JSONL
  access log, ``/debug/requests`` + ``/debug/slots``;
* :mod:`server`  — stdlib ThreadingHTTPServer JSON endpoints
  (``/predict`` ``/generate`` ``/healthz`` ``/readyz`` ``/metrics``)
  with per-request deadlines (504), tiered overload shedding (429 on
  ``/generate`` first), wired to the ``bigdl-tpu serve`` CLI;
* :mod:`sharding` — tensor-parallel placement for serving (ISSUE 16):
  reuses the training Megatron specs for params, shards KV on the
  kv_heads dim, restores checkpoints onto any serving mesh;
* :mod:`quant` — quantized serving (ISSUE 17): per-channel int8/fp8
  weights dequantized in the matmul epilogue (or native int8 dot),
  8-bit paged KV pools, and the ``quant_report`` quality guardrail —
  ``--quantize off`` is byte-identical to not having the module;
* :mod:`replicas` — data-parallel engine replicas behind one front
  door (ISSUE 16): least-loaded deterministic routing, fleet-level
  readiness/shedding, per-replica labelled metrics + fleet aggregates;
* :mod:`bulk` — offline bulk scoring (ISSUE 18): the executor-fed,
  cursor-checkpointed sharded batch job behind ``bigdl-tpu
  batch-predict`` — kill+resume byte-identical output.
"""

from bigdl_tpu.serving.batcher import (AdmissionError, DeadlineExceeded,
                                       MicroBatcher, WorkerDied)
from bigdl_tpu.serving.bulk import (ShardSink, load_cursor, merge_shards,
                                    run_bulk, save_cursor, shard_paths)
from bigdl_tpu.serving.decode import DecodeEngine, DecodeRequest
from bigdl_tpu.serving.engine import InferenceEngine, power_of_two_buckets
from bigdl_tpu.serving.kv_pages import (PageAllocator, PagedKvCache,
                                        QuantPool, kv_quant_rows,
                                        pages_needed)
from bigdl_tpu.serving.metrics import (Counter, Gauge, Histogram,
                                       MetricsRegistry)
from bigdl_tpu.serving.prefix_cache import PrefixCache
from bigdl_tpu.serving.quant import (QUANTIZE_CHOICES, QuantizedWeight,
                                     parse_quantize, quant_report,
                                     quantize_params)
from bigdl_tpu.serving.replicas import Replica, ReplicaSet
from bigdl_tpu.serving.reqtrace import (AccessLog, RequestRecord,
                                        RequestTracer, SloPolicy,
                                        get_request_tracer, mint_rid,
                                        sanitize_rid, set_request_tracer)
from bigdl_tpu.serving.server import ServingApp, make_server, run_server
from bigdl_tpu.serving.sharding import (ServingSharding,
                                        replica_device_groups,
                                        restore_for_serving, serving_mesh)
from bigdl_tpu.serving.spec_decode import (accept_chunk, parse_draft_dims,
                                           request_key, sample_token,
                                           warp_logits)
from bigdl_tpu.serving.watchdog import Watchdog

__all__ = ["AdmissionError", "DeadlineExceeded", "MicroBatcher",
           "WorkerDied", "DecodeEngine", "DecodeRequest",
           "InferenceEngine", "power_of_two_buckets",
           "PageAllocator", "PagedKvCache", "pages_needed", "PrefixCache",
           "QUANTIZE_CHOICES", "QuantizedWeight", "QuantPool",
           "kv_quant_rows", "parse_quantize", "quant_report",
           "quantize_params",
           "accept_chunk", "parse_draft_dims", "request_key",
           "sample_token", "warp_logits",
           "Counter", "Gauge", "Histogram", "MetricsRegistry",
           "AccessLog", "RequestRecord", "RequestTracer", "SloPolicy",
           "get_request_tracer", "mint_rid", "sanitize_rid",
           "set_request_tracer",
           "ServingApp", "make_server", "run_server", "Watchdog",
           "Replica", "ReplicaSet", "ServingSharding",
           "replica_device_groups", "restore_for_serving",
           "serving_mesh",
           "ShardSink", "load_cursor", "merge_shards", "run_bulk",
           "save_cursor", "shard_paths"]
