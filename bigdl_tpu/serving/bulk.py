"""Offline bulk inference (ISSUE 18 tentpole a): the checkpointable,
sharded batch-scoring job behind ``bigdl-tpu batch-predict``.

Batch scoring was BigDL's bread-and-butter workload — RDD-fed model
evaluation fanned across executors (arxiv 1804.05839; the "seamless
pipeline" framing of BigDL 2.0, 2204.01715). The TPU-native analog is
pure composition of layers this repo already has: the
``dataset/pipeline`` executor (N workers, deterministic
:class:`EpochPlan`, optional double-buffered device staging) feeds the
bucketed :class:`~bigdl_tpu.serving.engine.InferenceEngine` forwards,
``--strategy dp[:N]`` fans batches round-robin across engines built on
disjoint device groups, and outputs append to a sharded,
order-reconstructible JSONL sink.

Determinism + resume contract (the PR 10 manifest discipline):

* the record order is owned by the ``EpochPlan`` (``shuffle=False``
  here): batch ordinal ``s`` covers ``plan.batch_indices(0)[s]``, and
  ordinal ``s`` always lands in output shard ``s % n_groups`` — the
  global order is reconstructible by sorting merged lines on ``"i"``;
* a cursor checkpoint (``cursor.json``, written atomically via
  tmp+rename at drain barriers every ``checkpoint_every`` batches)
  records the plan signature, the first unscored batch ordinal, and the
  byte offset of every shard;
* resume VALIDATES the signature (a changed feed is an error, not a
  silent rescore), truncates each shard to its checkpointed offset
  (discarding lines written after the last barrier), and skips ordinals
  below the watermark — kill+resume output is byte-identical to an
  uninterrupted run, with no re-scored and no dropped records.

Phase attribution mirrors the training perf loop (``cli/perf.py``):
``data_wait`` is time blocked on the feed, ``dispatch`` time blocked
handing a batch to a scoring worker, ``device`` the summed engine
forward wall — so the batch-predict report carries the same
``stall_frac`` column the PR 12 executor work is graded on.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

__all__ = ["ShardSink", "load_cursor", "save_cursor", "run_bulk",
           "shard_paths", "merge_shards"]

CURSOR_FILE = "cursor.json"


def shard_paths(out_dir: str, n_groups: int) -> List[str]:
    return [os.path.join(out_dir,
                         f"scores-{g:05d}-of-{n_groups:05d}.jsonl")
            for g in range(n_groups)]


class ShardSink:
    """One append-mode JSONL output shard with byte-offset resume.

    Lines are rendered deterministically (``sort_keys``, plain ``repr``
    floats) so byte-identity across kill+resume reduces to scoring
    determinism. ``resume_offset`` truncates the file to the last
    checkpointed byte before appending — lines written after the final
    barrier of a killed run are discarded, never duplicated."""

    def __init__(self, path: str, resume_offset: Optional[int] = None):
        self.path = path
        if resume_offset is not None and os.path.exists(path):
            with open(path, "r+b") as f:
                f.truncate(int(resume_offset))
        else:
            open(path, "wb").close()
        self._f = open(path, "ab")
        self.offset = os.path.getsize(path)
        self.lines = 0

    def write_batch(self, indices, preds,
                    scores: Optional[np.ndarray] = None) -> int:
        rows = []
        for j, i in enumerate(indices):
            d: dict = {"i": int(i), "pred": int(preds[j])}
            if scores is not None:
                d["scores"] = [float(v) for v in
                               np.asarray(scores[j], np.float64)]
            rows.append(json.dumps(d, sort_keys=True))
        data = ("\n".join(rows) + "\n").encode()
        self._f.write(data)
        self.offset += len(data)
        self.lines += len(rows)
        return len(rows)

    def flush(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:
            pass


# ------------------------------------------------------------------ cursor
def load_cursor(out_dir: str) -> Optional[dict]:
    path = os.path.join(out_dir, CURSOR_FILE)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def save_cursor(out_dir: str, signature: dict, next_batch: int,
                offsets: Sequence[int], records_done: int) -> None:
    """Atomic (tmp+rename) cursor write — a kill mid-write leaves the
    previous cursor intact, never a torn one."""
    path = os.path.join(out_dir, CURSOR_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"signature": signature,
                   "next_batch": int(next_batch),
                   "offsets": [int(o) for o in offsets],
                   "records_done": int(records_done)}, f,
                  sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def merge_shards(out_dir: str) -> List[dict]:
    """All shard lines merged back into plan-record order (sorted on
    ``"i"``) — the order-reconstruction half of the sink contract."""
    rows: List[dict] = []
    for name in sorted(os.listdir(out_dir)):
        if name.startswith("scores-") and name.endswith(".jsonl"):
            with open(os.path.join(out_dir, name)) as f:
                rows.extend(json.loads(ln) for ln in f if ln.strip())
    rows.sort(key=lambda d: d["i"])
    return rows


# ------------------------------------------------------------------ runner
class _Group:
    """One scoring group: an engine, its output shard, and the worker
    thread that drains this group's batch queue."""

    def __init__(self, index: int, engine, sink: ShardSink):
        self.index = index
        self.engine = engine
        self.sink = sink
        self.queue: queue.Queue = queue.Queue(maxsize=2)
        self.device_s = 0.0
        self.records = 0
        self.error: Optional[BaseException] = None
        self.thread: Optional[threading.Thread] = None

    def start(self, scores: bool) -> None:
        def _work():
            while True:
                item = self.queue.get()
                try:
                    if item is None:
                        return
                    if self.error is None:  # after a failure keep
                        indices, x = item   # draining (task_done) so
                        t0 = time.perf_counter()  # barriers never hang
                        y = np.asarray(self.engine.predict_scores(x))
                        self.device_s += time.perf_counter() - t0
                        preds = np.argmax(y, axis=-1)
                        self.records += self.sink.write_batch(
                            indices, preds, y if scores else None)
                except BaseException as e:  # surfaced by the dispatcher
                    self.error = e
                finally:
                    self.queue.task_done()

        self.thread = threading.Thread(
            target=_work, name=f"bulk-score-{self.index}", daemon=True)
        self.thread.start()

    def join(self) -> None:
        self.queue.put(None)
        if self.thread is not None:
            self.thread.join()


def run_bulk(engines: Sequence, feed, signature: dict, out_dir: str, *,
             scores: bool = False, checkpoint_every: int = 32,
             phase: Optional[Dict[str, float]] = None,
             on_batch: Optional[Callable[[int], None]] = None) -> dict:
    """Drive ``feed`` through ``engines`` into the sharded sink.

    ``feed`` yields ``(ordinal, indices, x)`` — the global batch
    ordinal, the plan's record indices for that batch, and the input
    rows (host or device array). Batch ``ordinal`` is scored by engine
    ``ordinal % len(engines)`` and written to that group's shard.
    ``signature`` is the deterministic feed identity (plan signature +
    scoring config) the resume path validates. ``phase`` is an optional
    perf-style accumulator dict (``data_wait``/``dispatch``/``device``
    keys are added); ``on_batch`` is a per-dispatch hook (capture
    windows, progress).

    Returns the report dict: record/batch counts, resume watermark, and
    shard paths."""
    os.makedirs(out_dir, exist_ok=True)
    n_groups = len(engines)
    paths = shard_paths(out_dir, n_groups)

    cursor = load_cursor(out_dir)
    next_batch = 0
    records_done = 0
    if cursor is not None:
        if cursor.get("signature") != signature:
            raise ValueError(
                f"resume refused: {out_dir}/{CURSOR_FILE} was written "
                f"for a different feed\n  cursor:  "
                f"{json.dumps(cursor.get('signature'), sort_keys=True)}"
                f"\n  current: {json.dumps(signature, sort_keys=True)}")
        if len(cursor.get("offsets", [])) != n_groups:
            raise ValueError(
                f"resume refused: cursor has "
                f"{len(cursor.get('offsets', []))} shards, run has "
                f"{n_groups} (changed --strategy?)")
        next_batch = int(cursor["next_batch"])
        records_done = int(cursor.get("records_done", 0))
        logger.info("resuming batch-predict at batch %d (%d records "
                    "already scored)", next_batch, records_done)
    resumed_from = next_batch

    groups = [_Group(g, engines[g],
                     ShardSink(paths[g],
                               resume_offset=(cursor["offsets"][g]
                                              if cursor else None)))
              for g in range(n_groups)]
    for grp in groups:
        grp.start(scores)

    def _barrier() -> None:
        for grp in groups:
            grp.queue.join()
            if grp.error is not None:
                raise grp.error
            grp.sink.flush()

    pc = time.perf_counter
    ph = phase if phase is not None else {}
    for k in ("data_wait", "dispatch", "device"):
        ph.setdefault(k, 0.0)
    dispatched = 0
    total_batches = 0
    try:
        it = iter(feed)
        while True:
            t = pc()
            try:
                ordinal, indices, x = next(it)
            except StopIteration:
                break
            ph["data_wait"] += pc() - t
            total_batches = max(total_batches, ordinal + 1)
            if ordinal < next_batch:
                continue  # already scored before the kill
            if on_batch is not None:
                on_batch(ordinal)
            t = pc()
            grp = groups[ordinal % n_groups]
            while True:
                if grp.error is not None:  # dead worker: fail fast,
                    raise grp.error        # never block on a full queue
                try:
                    grp.queue.put((np.asarray(indices), x), timeout=1.0)
                    break
                except queue.Full:
                    continue
            ph["dispatch"] += pc() - t
            dispatched += 1
            records_done += len(indices)
            if dispatched % max(1, checkpoint_every) == 0:
                _barrier()
                save_cursor(out_dir, signature, ordinal + 1,
                            [grp.sink.offset for grp in groups],
                            records_done)
        _barrier()
        save_cursor(out_dir, signature, total_batches,
                    [grp.sink.offset for grp in groups], records_done)
    finally:
        for grp in groups:
            grp.join()
            grp.sink.close()
    for grp in groups:
        if grp.error is not None:
            raise grp.error
    ph["device"] += sum(grp.device_s for grp in groups)
    return {"records": records_done,
            "batches": total_batches,
            "batches_scored_this_run": dispatched,
            "resumed_from_batch": resumed_from,
            "groups": n_groups,
            "shards": paths,
            "shard_lines": [grp.sink.lines for grp in groups]}
