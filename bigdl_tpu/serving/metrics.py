"""Back-compat shim (ISSUE 7 satellite): the serving metrics registry
was promoted to :mod:`bigdl_tpu.obs.metrics` so training, resilience,
and serving share one instrument set and one exposition format.

Everything that imported from here keeps working unchanged — same
classes, same default ``bigdl_serving`` namespace, same bucket ladder,
same ``# provenance`` stamping. New code should import from
``bigdl_tpu.obs`` directly.
"""

from __future__ import annotations

from bigdl_tpu.obs.metrics import (Counter, DEFAULT_LATENCY_BUCKETS_MS,
                                   Gauge, Histogram, MetricsRegistry,
                                   _fmt, _label_escape)

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "DEFAULT_LATENCY_BUCKETS_MS"]
