"""Shared-prefix KV cache for the serving decoder (ISSUE 14).

Production prompt traffic is heavily prefix-shared — the same system
prompt, few-shot block, or conversation head fronts thousands of
requests. Recomputing that prefill per request is pure waste: the K/V a
causal model produces for a prefix depends on the prefix alone. With the
KV cache already paged (:mod:`bigdl_tpu.serving.kv_pages`), reuse is a
device-side page copy:

* on a prefill MISS the engine runs the normal bucketed prefill, then
  donates a copy of the slot's leading page-aligned pages to the cache
  under a hash of the token prefix they encode;
* on a HIT the engine copies the entry's pages into the new slot's page
  table and runs a CHUNKED suffix prefill (``TransformerLM.
  verify_logits`` at the page-aligned offset) for the remaining tokens
  only — bit-identical to the full prefill because the copied K/V was
  produced by the identical prefill graph and every suffix row computes
  the same per-row math at the same positions (pinned in
  tests/test_spec_decode.py).

Entries are page-granular: a prompt of ``s`` tokens caches
``floor(min(s - 1, aligned) / page_tokens)`` pages — at least one suffix
token always recomputes, because the engine needs the next-token logits
at position ``s-1`` and cached pages carry K/V, not logits. Matching
walks aligned prefix lengths longest-first, so a hit is always the
deepest cached ancestor. Eviction is LRU under a page budget served by
the SAME allocator the slots use — cache pressure and decode pressure
meet in one accounting (``kv_pages_in_use`` counts both).

Quantized pools (ISSUE 17 ``--quantize kv8``) compose for free: the
cache holds page IDs, never tensors, and ``copy_pages`` moves the int8
rows AND their scales verbatim — a hit replays the exact stored
quantization, so there is no re-quantization loss on reuse, and each
cached page costs ~4x fewer HBM bytes under the same page budget.
"""

from __future__ import annotations

import collections
import hashlib
from typing import List, Optional, Tuple

__all__ = ["PrefixCache"]


def _digest(tokens) -> bytes:
    import numpy as np

    return hashlib.sha1(
        np.asarray(tokens, np.int64).tobytes()).digest()


class _Entry:
    __slots__ = ("pages", "n_tokens")

    def __init__(self, pages: List[int], n_tokens: int):
        self.pages = pages
        self.n_tokens = n_tokens


class PrefixCache:
    """LRU page-granular prefix store over a :class:`PageAllocator`.

    ``max_pages`` bounds the pages the cache may hold at once (default:
    half the pool) — inserts that cannot fit evict LRU entries first and
    are skipped (never block decode) if eviction cannot make room.
    """

    def __init__(self, kv, *, max_pages: Optional[int] = None,
                 metrics=None):
        self.kv = kv
        self.page_tokens = kv.page_tokens
        if max_pages is None:
            max_pages = max(1, (kv.pool_pages - 1) // 2)
        self.max_pages = int(max_pages)
        self._entries: "collections.OrderedDict[bytes, _Entry]" = \
            collections.OrderedDict()
        self.hits = 0
        self.misses = 0
        self.inserts = 0
        self.evictions = 0
        if metrics is not None:
            self._m_hits = metrics.counter(
                "prefix_cache_hits_total",
                "prefills served from the shared-prefix KV cache")
            self._m_miss = metrics.counter(
                "prefix_cache_misses_total",
                "prefills with no usable cached prefix")
        else:
            self._m_hits = self._m_miss = None

    # ------------------------------------------------------------ lookup
    def cached_pages(self) -> int:
        return sum(len(e.pages) for e in self._entries.values())

    def cached_tokens(self) -> int:
        return sum(e.n_tokens for e in self._entries.values())

    def _usable_prefix(self, n_prompt: int) -> int:
        """Longest cacheable prefix of an n-token prompt: page-aligned
        and strictly shorter than the prompt (the last position must
        recompute to produce the next-token logits)."""
        return ((n_prompt - 1) // self.page_tokens) * self.page_tokens

    def match(self, tokens) -> Tuple[int, List[int]]:
        """Longest cached prefix of ``tokens`` -> (n_tokens, pages).
        (0, []) on miss. Counts the hit/miss."""
        n = self._usable_prefix(len(tokens))
        while n >= self.page_tokens:
            key = _digest(tokens[:n])
            ent = self._entries.get(key)
            if ent is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                if self._m_hits is not None:
                    self._m_hits.inc()
                return ent.n_tokens, list(ent.pages)
            n -= self.page_tokens
        self.misses += 1
        if self._m_miss is not None:
            self._m_miss.inc()
        return 0, []

    # ------------------------------------------------------------ insert
    def insertable_prefix(self, tokens) -> int:
        """How many leading tokens of ``tokens`` an insert would cache
        (0 = nothing new to cache)."""
        n = self._usable_prefix(len(tokens))
        if n < self.page_tokens:
            return 0
        if _digest(tokens[:n]) in self._entries:
            return 0
        return n

    def prepare_insert(self, tokens) -> Optional[Tuple[bytes, List[int]]]:
        """Reserve pages for caching ``tokens``' usable prefix, evicting
        LRU entries as needed. Returns (key, dst_pages) — the caller
        device-copies the slot's leading pages into ``dst_pages`` then
        calls :meth:`commit_insert` — or None when nothing should be
        cached (too short, already cached, or no room even after
        eviction)."""
        n = self.insertable_prefix(tokens)
        if n == 0:
            return None
        need = n // self.page_tokens
        if need > self.max_pages:
            return None
        while (self.cached_pages() + need > self.max_pages
               or self.kv.alloc.free_pages < need):
            if not self._entries:
                break
            self._evict_one()
        pages = self.kv.alloc.alloc(need)
        if pages is None:
            return None
        return _digest(tokens[:n]), pages

    def commit_insert(self, key: bytes, pages: List[int],
                      n_tokens: int) -> None:
        self._entries[key] = _Entry(pages, n_tokens)
        self.inserts += 1

    def abort_insert(self, pages: List[int]) -> None:
        self.kv.alloc.free(pages)

    # ----------------------------------------------------------- eviction
    def _evict_one(self) -> None:
        key, ent = self._entries.popitem(last=False)
        self.kv.alloc.free(ent.pages)
        self.evictions += 1

    def clear(self) -> None:
        while self._entries:
            self._evict_one()
