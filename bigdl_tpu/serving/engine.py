"""Online inference engine: bucketed pre-compiled forwards over a
restored eval-mode module.

The training half of the stack compiles ONE step shape and reuses it for
hours; serving sees a new batch geometry on every request. Left to
``jax.jit`` alone that means a fresh XLA compile per distinct request
count — tens of seconds of p99 on a TPU for a shape the compile cache
has never seen. The engine therefore admits only a fixed, declared set
of batch **buckets**: a request of n rows pads up to the smallest bucket
>= n (chunking through the largest bucket first when n exceeds it), so
the compile cache is bounded by ``len(buckets)`` programs per input
geometry and the steady state recompiles nothing. Padding waste is
metered (``padded_rows_total`` vs ``rows_total``) so the bucket ladder
can be re-fit to observed traffic.

The same tuned program the perf harness measured is what serves: the
caller installs ``--fusedBN``/``--convLayout``/``--convGeom``/
``--autotune`` before construction (cli/serve.py mirrors the perf
flags), inputs are donated into the jitted forward, activations
optionally run bf16, and the tpulint pre-flight (`bigdl_tpu.analysis`)
runs over the exact serving graph BEFORE the first compile — strict mode
refuses to serve a graph with error-severity findings.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional, Sequence

import numpy as np

from bigdl_tpu.obs.spans import span as _obs_span
from bigdl_tpu.resilience.faults import hook as _fault_hook
from bigdl_tpu.serving.reqtrace import get as _get_reqtracer

logger = logging.getLogger(__name__)

__all__ = ["InferenceEngine", "power_of_two_buckets"]


def power_of_two_buckets(max_batch: int, min_bucket: int = 1) -> tuple:
    """The default bucket ladder: powers of two from ``min_bucket`` up to
    and including ``max_batch`` (which is always a member, power of two
    or not) — log2(max_batch) compiles bound the cache, and tail batches
    waste at most half a bucket."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    out = []
    b = max(1, min_bucket)
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return tuple(sorted(set(out)))


class InferenceEngine:
    """Eval-mode forward over fixed batch buckets.

    ``predict_scores(x)`` accepts any row count, pads each chunk to a
    bucket, runs the compiled forward, and strips the padding — output
    is row-for-row what an unpadded forward would produce (padding rows
    never influence real rows: eval-mode modules are row-independent;
    BN runs on frozen stats).

    ``compute_dtype`` (e.g. bf16) casts floating inputs before the
    module — int inputs (LM tokens) pass through and the module's own
    ``compute_dtype`` handles the post-embedding cast.
    """

    def __init__(self, module, params, mod_state=None, *,
                 buckets: Sequence[int] = (1, 2, 4, 8, 16, 32),
                 compute_dtype=None, donate_inputs: bool = True,
                 lint: Optional[str] = None, metrics=None,
                 mesh=None, model_axis: str = "model",
                 quantize: Optional[str] = None):
        import jax

        self.module = module
        # quantized weights (ISSUE 17) go 8-bit BEFORE mesh placement so
        # scales ride their weight's layout; "off"/None never touches
        # the tree (byte-identical serving path, CI-enforced)
        from bigdl_tpu.serving import quant as _q
        self.quantize = quantize if quantize else "off"
        wfmt, _ = _q.parse_quantize(quantize)
        if wfmt is not None:
            params = _q.quantize_params(params, wfmt)
        # tp placement (ISSUE 16): params committed to the mesh under
        # the training-side Megatron layout; GSPMD partitions the
        # bucketed forwards from there. A 1-device mesh just pins the
        # engine to a dp replica's chip; mesh=None is the single-chip
        # path unchanged.
        self.mesh = mesh
        if mesh is not None:
            from bigdl_tpu.serving.sharding import ServingSharding
            self._shard = ServingSharding(mesh, axis=model_axis)
            params = self._shard.place_params(module, params)
            if mod_state is not None:
                mod_state = jax.device_put(mod_state,
                                           self._shard.replicated)
        else:
            self._shard = None
        self.params = params
        self.mod_state = (mod_state if mod_state is not None
                          else module.init_state())
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"buckets must be positive ints, got {buckets}")
        self.compute_dtype = compute_dtype
        self.donate_inputs = donate_inputs
        self.lint_mode = lint if lint in ("on", "strict") else None
        self.lint_annotation = None
        self._linted = False
        self._compiled = {}  # (bucket, feat_shape, dtype_str) -> jitted fn
        self._compile_lock = threading.Lock()
        # ISSUE 12: compile-time memory per bucket (memory_analysis of
        # the exact AOT-compiled program), stamped into provenance so a
        # bucket ladder's HBM cost is visible before traffic arrives
        self._bucket_mem: dict = {}

        if metrics is not None:
            self._m_rows = metrics.counter(
                "rows_total", "input rows submitted to the engine")
            self._m_pad = metrics.counter(
                "padded_rows_total",
                "bucket-padding rows (waste) run alongside real rows")
            self._m_compiles = metrics.counter(
                "compiles_total", "distinct (bucket, geometry) compiles")
            metrics.gauge(
                "padding_waste_fraction",
                "padded_rows_total / (rows_total + padded_rows_total)",
                fn=self._padding_waste)
        else:
            self._m_rows = self._m_pad = self._m_compiles = None

        def fwd(params, mod_state, x):
            import jax.numpy as jnp
            if (self.compute_dtype is not None
                    and jnp.issubdtype(x.dtype, jnp.floating)):
                x = x.astype(self.compute_dtype)
            y, _ = module.apply(params, mod_state, x, training=False)
            return y

        self._fwd = fwd
        self._jax = jax

    # -------------------------------------------------------- construction
    @classmethod
    def from_checkpoint(cls, module, path: str, mesh=None,
                        **kw) -> "InferenceEngine":
        """Restore an inference-only view of a training checkpoint
        (params + mod_state, no optimizer state — single-blob model.<n>
        or sharded orbax; clean SystemExit on missing/corrupt).

        With ``mesh`` (ISSUE 16) the blob loads through PR 10's
        ``restore_resharded`` — checkpoints written under ANY training
        topology place onto ANY serving topology, manifest-validated —
        and the engine re-shards params to the serving tp layout."""
        if mesh is not None:
            from bigdl_tpu.serving.sharding import restore_for_serving
            params, mod_state = restore_for_serving(path, mesh)
            return cls(module, params, mod_state, mesh=mesh, **kw)
        from bigdl_tpu.utils.orbax_ckpt import restore_for_inference
        params, mod_state = restore_for_inference(path)
        return cls(module, params, mod_state, **kw)

    def _padding_waste(self) -> float:
        if self._m_rows is None:
            return 0.0
        real, pad = self._m_rows.value, self._m_pad.value
        total = real + pad
        return (pad / total) if total else 0.0

    # --------------------------------------------------------------- lint
    def preflight_lint(self, feat_shape, dtype) -> int:
        """tpulint over the exact serving forward (largest bucket) before
        anything compiles. Returns the report's exit code (0 = serve;
        nonzero = strict mode found error-severity findings). The
        summary annotation is kept for provenance stamping either way."""
        if self.lint_mode is None or self._linted:
            return 0
        self._linted = True
        import jax

        from bigdl_tpu.analysis import lint_fn
        from bigdl_tpu.cli.common import run_preflight_lint

        x = jax.ShapeDtypeStruct((self.buckets[-1],) + tuple(feat_shape),
                                 dtype)
        jitted = jax.jit(self._fwd)
        report = lint_fn(jitted, self.params, self.mod_state, x)
        rc, ann = run_preflight_lint(report,
                                     strict=(self.lint_mode == "strict"))
        self.lint_annotation = ann if rc == 0 else report.annotation()
        return rc

    # ------------------------------------------------------------- compile
    def _get_compiled(self, bucket: int, feat_shape: tuple, dtype):
        key = (bucket, feat_shape, str(dtype))
        fn = self._compiled.get(key)
        if fn is not None:
            return fn
        with self._compile_lock:
            fn = self._compiled.get(key)
            if fn is None:
                if self.lint_mode is not None and not self._linted:
                    rc = self.preflight_lint(feat_shape, dtype)
                    if rc:
                        raise SystemExit(rc)
                # CPU can't donate (XLA copies + warns every compile);
                # the buffer-reuse win only exists on device backends
                donate = ((2,) if self.donate_inputs
                          and self._jax.default_backend() != "cpu" else ())
                fn = self._jax.jit(self._fwd, donate_argnums=donate)
                try:
                    # AOT-compile so the program's memory footprint is
                    # known NOW (and served as-is); lazy-jit fallback if
                    # the AOT path misbehaves on this backend
                    x_abs = self._jax.ShapeDtypeStruct(
                        (bucket,) + tuple(feat_shape), dtype)
                    compiled = fn.lower(self.params, self.mod_state,
                                        x_abs).compile()
                    ma = compiled.memory_analysis()
                    arg = int(getattr(ma, "argument_size_in_bytes", 0))
                    out_b = int(getattr(ma, "output_size_in_bytes", 0))
                    tmp = int(getattr(ma, "temp_size_in_bytes", 0))
                    alias = int(getattr(ma, "alias_size_in_bytes", 0))
                    self._bucket_mem[bucket] = {
                        "argument_bytes": arg, "output_bytes": out_b,
                        "temp_bytes": tmp,
                        "total_bytes": arg + tmp + max(0, out_b - alias)}
                    fn = compiled
                except Exception:
                    pass  # serve through the lazy jit; memory unknown
                self._compiled[key] = fn
                if self._m_compiles is not None:
                    self._m_compiles.inc()
                logger.info("serving compile: bucket=%d feat=%s dtype=%s "
                            "(%d cached)", bucket, feat_shape, dtype,
                            len(self._compiled))
        return fn

    def warmup(self, feat_shape, dtype=np.float32,
               buckets: Optional[Sequence[int]] = None) -> None:
        """Pre-compile (and execute once, so XLA autotuning settles)
        every bucket at the given input geometry — pays the compile cost
        at startup instead of on the first unlucky request."""
        for b in (buckets or self.buckets):
            x = np.zeros((b,) + tuple(feat_shape), dtype)
            fn = self._get_compiled(b, tuple(feat_shape), np.dtype(dtype))
            np.asarray(fn(self.params, self.mod_state,
                          self._jax.numpy.asarray(x)))

    # ------------------------------------------------------------- predict
    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= n, or the largest bucket (callers chunk)."""
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def predict_scores(self, x, rids=None) -> np.ndarray:
        """Raw model outputs for every row of ``x`` (any row count).

        ``rids`` (ISSUE 15) is an optional per-row sequence of request
        ids aligned with ``x``: each compiled-chunk forward attributes
        its compute window back to exactly the requests whose rows it
        carried, so a request split across chunks gets the union."""
        # fault-injection site for the serving forward (no-op unless a
        # --faultPlan is installed): a `worker_kill` here is fatal to
        # the batcher worker — the dead-worker/watchdog drill
        _fault_hook("infer")
        x = np.asarray(x)
        n = len(x)
        if n == 0:
            return np.zeros((0,), np.float32)
        rt = _get_reqtracer() if rids is not None else None
        feat_shape = tuple(x.shape[1:])
        dtype = x.dtype
        outs = []
        i = 0
        while i < n:
            take = min(n - i, self.buckets[-1])
            bucket = self.bucket_for(take)
            chunk = x[i:i + take]
            pad = bucket - take
            if pad > 0:
                # repeat the last real row (a benign, in-distribution
                # filler — all-zeros can NaN under log/normalization)
                chunk = np.concatenate(
                    [chunk, np.repeat(chunk[-1:], pad, axis=0)])
            fn = self._get_compiled(bucket, feat_shape, dtype)
            with _obs_span("infer", bucket=bucket, rows=take):
                t0c = rt.clock() if rt is not None else 0.0
                try:
                    y = fn(self.params, self.mod_state,
                           self._jax.numpy.asarray(chunk))
                except Exception as e:
                    # RESOURCE_EXHAUSTED autopsy (ISSUE 12): report to
                    # --traceDir + fault log, then fail the request
                    # exactly as before
                    from bigdl_tpu.obs import memory as _obs_mem
                    _obs_mem.handle_oom(e, "serving_predict")
                    raise
                outs.append(np.asarray(y)[:take])
                if rt is not None:
                    t1c = rt.clock()
                    for rid in rids[i:i + take]:
                        if rid is not None:
                            rt.note_compute(rid, t0c, t1c)
            if self._m_rows is not None:
                self._m_rows.inc(take)
                self._m_pad.inc(pad)
            i += take
        return np.concatenate(outs)

    def predict(self, x) -> np.ndarray:
        """Argmax class ids (the Classifier-compatible surface)."""
        scores = self.predict_scores(x)
        if len(scores) == 0:
            return np.zeros((0,), np.int64)
        return np.argmax(scores, axis=-1)

    # ---------------------------------------------------------- provenance
    def provenance(self) -> dict:
        """Serving config provenance for /metrics scrapes and bench JSON
        lines — the same fields the perf harness stamps (bn_fused, conv
        layout source, autotune mode, lint summary) plus the bucket set,
        so every latency number is attributable to an exact program."""
        from bigdl_tpu.cli.provenance import provenance_dict
        out = {
            "buckets": ",".join(str(b) for b in self.buckets),
            **(self._shard.describe() if self._shard is not None else {}),
            "compute_dtype": (np.dtype(self.compute_dtype).name
                              if self.compute_dtype is not None
                              else "float32"),
            # shared assembly (ISSUE 18 satellite): same code path as
            # the perf JSON line and batch-predict reports
            **provenance_dict(self.module, flat=True),
            "quantize": self.quantize,
        }
        for b, m in sorted(self._bucket_mem.items()):
            # per-bucket compile-time memory (ISSUE 12): the HBM cost of
            # each program in the ladder, scrape-visible
            out[f"bucket_{b}_hbm_bytes"] = m["total_bytes"]
        ann = self.lint_annotation
        if isinstance(ann, dict):
            out["lint"] = (f"{ann.get('errors', 0)}e/"
                           f"{ann.get('warnings', 0)}w/"
                           f"{ann.get('infos', 0)}i")
        elif ann is not None:
            out["lint"] = str(ann)
        return out
