"""Jaxpr traversal for the tpulint pass — provenance-preserving iteration
over a ClosedJaxpr including every nested sub-jaxpr (``pjit`` bodies,
``custom_vjp``/``custom_jvp`` rules, scan/while/cond branches, and
``pallas_call`` kernel bodies).

Unlike ``utils/flops.py`` (which only needs a FLOP sum), rules need to
know *where* an equation lives — so each visited jaxpr level carries a
path string like ``pjit:train_step/custom_vjp_call_jaxpr/pallas_call:
_fba_fwd_kernel`` — and *who consumes* each value, so the dtype rules can
tell a stats-reduction upcast from an fp32-softmax one. Everything here
is read-only over trace-time metadata: no compilation, no execution, no
device needed.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np
from jax.extend import core as jex_core

__all__ = ["JaxprLevel", "iter_levels", "eqn_label", "consumers_map",
           "pallas_block_views", "pallas_scratch_avals",
           "pallas_kernel_name", "aval_bytes"]


def aval_bytes(aval) -> int:
    """Abstract byte size of one value (0 when shape/dtype are absent,
    e.g. tokens of an opaque effect)."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        try:
            n *= int(d)
        except TypeError:  # symbolic/polymorphic dim
            return 0
    return n * np.dtype(dtype).itemsize


def eqn_label(eqn) -> str:
    """Short label for one equation: primitive plus its best name hint
    (pjit ``name``, pallas kernel name) when one exists."""
    name = eqn.params.get("name") if eqn.params else None
    if name is None and eqn.primitive.name == "pallas_call":
        name = pallas_kernel_name(eqn)
    return (f"{eqn.primitive.name}:{name}" if name
            else eqn.primitive.name)


def pallas_kernel_name(eqn) -> Optional[str]:
    """Kernel function name of a ``pallas_call`` eqn (from
    ``name_and_src_info``), or None."""
    nsi = eqn.params.get("name_and_src_info")
    name = getattr(nsi, "name", None)
    if name:
        return str(name)
    if nsi is not None:  # str form is "name at file:line"
        return str(nsi).split(" ")[0] or None
    return None


def _sub_jaxprs(eqn) -> Iterator[Tuple[object, str]]:
    """(jaxpr, label) pairs for every sub-jaxpr carried in one eqn's
    params — the recursion edge of the walk."""
    label = eqn_label(eqn)
    for v in eqn.params.values():
        vs = v if isinstance(v, (tuple, list)) else [v]
        for w in vs:
            if isinstance(w, jex_core.ClosedJaxpr):
                yield w.jaxpr, label
            elif isinstance(w, jex_core.Jaxpr):
                yield w, label


@dataclass
class JaxprLevel:
    """One jaxpr in the nesting tree: the jaxpr itself, the ``/``-joined
    path of enclosing eqn labels (empty for the top level), and depth."""
    jaxpr: object
    path: str
    depth: int

    def where(self, i: int, eqn) -> str:
        """Provenance string for eqn ``i`` of this level."""
        base = f"{self.path}/" if self.path else ""
        return f"{base}{eqn_label(eqn)}#{i}"


def iter_levels(jaxpr, path: str = "", depth: int = 0,
                max_depth: int = 24) -> Iterator[JaxprLevel]:
    """Yield every jaxpr level (pre-order), starting at ``jaxpr`` itself.
    Accepts a ClosedJaxpr or Jaxpr. ``max_depth`` guards against
    pathological nesting."""
    if isinstance(jaxpr, jex_core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    yield JaxprLevel(jaxpr, path, depth)
    if depth >= max_depth:
        return
    for i, eqn in enumerate(jaxpr.eqns):
        for sub, label in _sub_jaxprs(eqn):
            sub_path = f"{path}/{label}#{i}" if path else f"{label}#{i}"
            yield from iter_levels(sub, sub_path, depth + 1, max_depth)


def consumers_map(jaxpr) -> Dict[object, List[object]]:
    """var -> [consumer eqns] within ONE jaxpr level (no recursion —
    cross-level dataflow goes through sub-jaxpr invars, which the nested
    level's own map sees)."""
    out: Dict[object, List[object]] = {}
    for eqn in jaxpr.eqns:
        for v in eqn.invars:
            if isinstance(v, jex_core.Literal):
                continue
            out.setdefault(v, []).append(eqn)
    return out


# ------------------------------------------------------------------ pallas
def pallas_block_views(eqn) -> List[Tuple[Tuple, Tuple, object, bool]]:
    """(block_shape, array_shape, dtype, is_output) for every block
    mapping of a ``pallas_call`` eqn — the raw material of the tiling,
    padding and VMEM rules. Best-effort across jax versions: mappings
    without the expected fields are skipped rather than crashed on."""
    gm = eqn.params.get("grid_mapping")
    bms = getattr(gm, "block_mappings", None) or ()
    n_in = getattr(gm, "num_inputs", None)
    views = []
    for idx, bm in enumerate(bms):
        bs = getattr(bm, "block_shape", None)
        sds = getattr(bm, "array_shape_dtype", None)
        if bs is None or sds is None:
            continue
        is_out = n_in is not None and idx >= n_in
        views.append((tuple(bs), tuple(sds.shape),
                      np.dtype(sds.dtype), is_out))
    return views


def pallas_scratch_avals(eqn) -> List[object]:
    """Avals of the kernel's scratch operands (the VMEM accumulators) —
    the tail invars of the kernel jaxpr, per ``num_scratch_operands``."""
    gm = eqn.params.get("grid_mapping")
    n = int(getattr(gm, "num_scratch_operands", 0) or 0)
    if n <= 0:
        return []
    kj = eqn.params.get("jaxpr")
    if isinstance(kj, jex_core.ClosedJaxpr):
        kj = kj.jaxpr
    invars = getattr(kj, "invars", None)
    if not invars:
        return []
    out = []
    for v in invars[-n:]:
        aval = getattr(v, "aval", None)
        inner = getattr(aval, "inner_aval", aval)  # Ref wraps the array
        if inner is not None:
            out.append(inner)
    return out
