"""Jaxpr traversal for the tpulint pass — provenance-preserving iteration
over a ClosedJaxpr including every nested sub-jaxpr (``pjit`` bodies,
``custom_vjp``/``custom_jvp`` rules, scan/while/cond branches, and
``pallas_call`` kernel bodies).

Unlike ``utils/flops.py`` (which only needs a FLOP sum), rules need to
know *where* an equation lives — so each visited jaxpr level carries a
path string like ``pjit:train_step/custom_vjp_call_jaxpr/pallas_call:
_fba_fwd_kernel`` — and *who consumes* each value, so the dtype rules can
tell a stats-reduction upcast from an fp32-softmax one. Everything here
is read-only over trace-time metadata: no compilation, no execution, no
device needed.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np
from jax.extend import core as jex_core

__all__ = ["JaxprLevel", "iter_levels", "eqn_label", "consumers_map",
           "pallas_block_views", "pallas_scratch_avals",
           "pallas_kernel_name", "aval_bytes",
           "ShardedLevel", "sharded_levels", "named_sharding",
           "spec_axes", "observed_mesh_axes", "collect_constraints",
           "collect_collectives", "COLLECTIVE_PRIMS"]


def aval_bytes(aval) -> int:
    """Abstract byte size of one value (0 when shape/dtype are absent,
    e.g. tokens of an opaque effect)."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        try:
            n *= int(d)
        except TypeError:  # symbolic/polymorphic dim
            return 0
    return n * np.dtype(dtype).itemsize


def eqn_label(eqn) -> str:
    """Short label for one equation: primitive plus its best name hint
    (pjit ``name``, pallas kernel name) when one exists."""
    name = eqn.params.get("name") if eqn.params else None
    if name is None and eqn.primitive.name == "pallas_call":
        name = pallas_kernel_name(eqn)
    return (f"{eqn.primitive.name}:{name}" if name
            else eqn.primitive.name)


def pallas_kernel_name(eqn) -> Optional[str]:
    """Kernel function name of a ``pallas_call`` eqn (from
    ``name_and_src_info``), or None."""
    nsi = eqn.params.get("name_and_src_info")
    name = getattr(nsi, "name", None)
    if name:
        return str(name)
    if nsi is not None:  # str form is "name at file:line"
        return str(nsi).split(" ")[0] or None
    return None


def _sub_jaxprs(eqn) -> Iterator[Tuple[object, str]]:
    """(jaxpr, label) pairs for every sub-jaxpr carried in one eqn's
    params — the recursion edge of the walk."""
    label = eqn_label(eqn)
    for v in eqn.params.values():
        vs = v if isinstance(v, (tuple, list)) else [v]
        for w in vs:
            if isinstance(w, jex_core.ClosedJaxpr):
                yield w.jaxpr, label
            elif isinstance(w, jex_core.Jaxpr):
                yield w, label


@dataclass
class JaxprLevel:
    """One jaxpr in the nesting tree: the jaxpr itself, the ``/``-joined
    path of enclosing eqn labels (empty for the top level), and depth."""
    jaxpr: object
    path: str
    depth: int

    def where(self, i: int, eqn) -> str:
        """Provenance string for eqn ``i`` of this level."""
        base = f"{self.path}/" if self.path else ""
        return f"{base}{eqn_label(eqn)}#{i}"


def iter_levels(jaxpr, path: str = "", depth: int = 0,
                max_depth: int = 24) -> Iterator[JaxprLevel]:
    """Yield every jaxpr level (pre-order), starting at ``jaxpr`` itself.
    Accepts a ClosedJaxpr or Jaxpr. ``max_depth`` guards against
    pathological nesting."""
    if isinstance(jaxpr, jex_core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    yield JaxprLevel(jaxpr, path, depth)
    if depth >= max_depth:
        return
    for i, eqn in enumerate(jaxpr.eqns):
        for sub, label in _sub_jaxprs(eqn):
            sub_path = f"{path}/{label}#{i}" if path else f"{label}#{i}"
            yield from iter_levels(sub, sub_path, depth + 1, max_depth)


def consumers_map(jaxpr) -> Dict[object, List[object]]:
    """var -> [consumer eqns] within ONE jaxpr level (no recursion —
    cross-level dataflow goes through sub-jaxpr invars, which the nested
    level's own map sees)."""
    out: Dict[object, List[object]] = {}
    for eqn in jaxpr.eqns:
        for v in eqn.invars:
            if isinstance(v, jex_core.Literal):
                continue
            out.setdefault(v, []).append(eqn)
    return out


# --------------------------------------------------------------- sharding
# The shardlint walk (ISSUE 19). jit-SPMD traces carry no collective
# eqns — the partitioner inserts them after tracing — so everything a
# static pass can know about the multichip plan lives in ANNOTATIONS:
# ``pjit`` eqn params (``in_shardings``/``out_shardings`` zip with the
# body's invars/outvars), ``sharding_constraint`` eqns (the
# ``with_sharding_constraint`` steering points, e.g. grad_comm's
# compressed buckets), and — in shard_map/pmap graphs only — explicit
# collective primitives. ``sharded_levels`` threads those annotations
# through every nesting level so the sharding_rules module reads a
# var -> NamedSharding environment instead of re-deriving placement.

# explicit collective primitives (shard_map/pmap graphs only; jit-SPMD
# traces never contain these — mirrored by rules._COLLECTIVE_PRIMS).
# psum2 is what shard_map's check_rep rewrite lowers psum to.
COLLECTIVE_PRIMS = ("psum", "psum2", "ppermute", "all_gather",
                    "all_to_all", "reduce_scatter", "psum_scatter",
                    "pmax", "pmin")

# single-input primitives that neither reshape nor re-lay-out their
# operand: a sharding known for the input holds for the output (the
# edge the wire-dtype and churn rules follow through casts)
_SHARDING_TRANSPARENT = ("convert_element_type", "copy", "device_put",
                         "stop_gradient", "neg", "exp", "log", "tanh",
                         "integer_pow", "sqrt", "rsqrt", "abs")


def named_sharding(s) -> Optional[object]:
    """``s`` if it is a usable NamedSharding-like annotation (has a spec
    and a mesh), else None — filters pjit's UnspecifiedValue entries."""
    if s is None:
        return None
    if getattr(s, "spec", None) is None or getattr(s, "mesh", None) is None:
        return None
    return s


def spec_axes(spec) -> List[str]:
    """Mesh axis names referenced by one PartitionSpec, in dim order
    (entries may be axis tuples — flattened here)."""
    out: List[str] = []
    for entry in tuple(spec or ()):
        if entry is None:
            continue
        for ax in (entry if isinstance(entry, (tuple, list)) else (entry,)):
            if ax is not None:
                out.append(str(ax))
    return out


@dataclass
class ShardedLevel:
    """One jaxpr level plus its sharding environment: ``shardings`` maps
    this level's vars to the NamedSharding annotations that reach them
    (pjit boundary zips, constraint eqns, transparent-op propagation)."""
    jaxpr: object
    path: str
    depth: int
    shardings: Dict[object, object]

    def where(self, i: int, eqn) -> str:
        base = f"{self.path}/" if self.path else ""
        return f"{base}{eqn_label(eqn)}#{i}"


def _bind(env: Dict[object, object], var, sharding) -> None:
    if sharding is not None and not isinstance(var, jex_core.Literal):
        env[var] = sharding


def _lookup(env: Dict[object, object], var):
    if isinstance(var, jex_core.Literal):
        return None
    return env.get(var)


def _walk_sharded(jaxpr, path: str, depth: int,
                  env: Dict[object, object], out: List[ShardedLevel],
                  max_depth: int = 24) -> None:
    level = ShardedLevel(jaxpr, path, depth, env)
    out.append(level)
    for i, eqn in enumerate(jaxpr.eqns):
        name = eqn.primitive.name
        sub_path = (f"{path}/{eqn_label(eqn)}#{i}" if path
                    else f"{eqn_label(eqn)}#{i}")
        if name == "sharding_constraint":
            _bind(env, eqn.outvars[0],
                  named_sharding(eqn.params.get("sharding")))
        elif name == "pjit" and depth < max_depth:
            closed = eqn.params.get("jaxpr")
            sub = closed.jaxpr if isinstance(
                closed, jex_core.ClosedJaxpr) else closed
            sub_env: Dict[object, object] = {}
            in_sh = eqn.params.get("in_shardings") or ()
            for v, s in zip(sub.invars, in_sh):
                _bind(sub_env, v, named_sharding(s))
            # caller knowledge flows in where the boundary left the
            # sharding unspecified (nested pjit under an annotated one)
            for v_sub, v_call in zip(sub.invars, eqn.invars):
                if v_sub not in sub_env:
                    _bind(sub_env, v_sub, _lookup(env, v_call))
            _walk_sharded(sub, sub_path, depth + 1, sub_env, out,
                          max_depth)
            out_sh = eqn.params.get("out_shardings") or ()
            for v, s in zip(eqn.outvars, out_sh):
                _bind(env, v, named_sharding(s))
            # body-constrained outputs bubble up through unspecified
            # out_shardings (e.g. a constrained bucket returned as-is)
            for v_call, v_body in zip(eqn.outvars, sub.outvars):
                if v_call not in env:
                    _bind(env, v_call, _lookup(sub_env, v_body))
        elif name in _SHARDING_TRANSPARENT and len(eqn.outvars) == 1 \
                and eqn.invars:
            _bind(env, eqn.outvars[0], _lookup(env, eqn.invars[0]))
        elif depth < max_depth:
            # custom_vjp/scan/while/pallas etc.: recurse with positional
            # invar propagation when the sub signature lines up
            for sub, _label in _sub_jaxprs(eqn):
                sub_env = {}
                if len(getattr(sub, "invars", ())) == len(eqn.invars):
                    for v_sub, v_call in zip(sub.invars, eqn.invars):
                        _bind(sub_env, v_sub, _lookup(env, v_call))
                _walk_sharded(sub, sub_path, depth + 1, sub_env, out,
                              max_depth)


def sharded_levels(jaxpr, max_depth: int = 24) -> List[ShardedLevel]:
    """Every jaxpr level (pre-order) with its sharding environment fully
    populated — the shardlint analogue of :func:`iter_levels`. Accepts a
    ClosedJaxpr or Jaxpr; read-only trace-time metadata, no devices."""
    if isinstance(jaxpr, jex_core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    out: List[ShardedLevel] = []
    _walk_sharded(jaxpr, "", 0, {}, out, max_depth)
    return out


def observed_mesh_axes(levels: List[ShardedLevel]) -> Dict[str, int]:
    """Merged axis -> size of every mesh named by any annotation in the
    walk (constraint shardings, pjit boundary shardings)."""
    axes: Dict[str, int] = {}
    for lv in levels:
        for s in lv.shardings.values():
            mesh = getattr(s, "mesh", None)
            shape = getattr(mesh, "shape", None)
            if shape:
                for k, v in dict(shape).items():
                    axes[str(k)] = int(v)
        for eqn in lv.jaxpr.eqns:
            if eqn.primitive.name != "sharding_constraint":
                continue
            s = named_sharding(eqn.params.get("sharding"))
            shape = getattr(getattr(s, "mesh", None), "shape", None)
            if shape:
                for k, v in dict(shape).items():
                    axes[str(k)] = int(v)
    return axes


def collect_constraints(levels: List[ShardedLevel]) -> List[tuple]:
    """Every ``sharding_constraint`` eqn in the walk as
    ``(level, eqn_index, eqn, sharding, prev_sharding)`` — ``sharding``
    the constraint applied, ``prev_sharding`` what the walk knew about
    the operand BEFORE the constraint (None when unannotated); the raw
    material of the wire-dtype and reshard-churn rules."""
    out = []
    for lv in levels:
        for i, eqn in enumerate(lv.jaxpr.eqns):
            if eqn.primitive.name != "sharding_constraint":
                continue
            s = named_sharding(eqn.params.get("sharding"))
            if s is None:
                continue
            prev = _lookup(lv.shardings, eqn.invars[0])
            out.append((lv, i, eqn, s, prev))
    return out


def collect_collectives(levels: List[ShardedLevel]) -> List[tuple]:
    """Every explicit collective eqn (shard_map/pmap graphs only) as
    ``(level, eqn_index, eqn, axis_names)``."""
    out = []
    for lv in levels:
        for i, eqn in enumerate(lv.jaxpr.eqns):
            if eqn.primitive.name not in COLLECTIVE_PRIMS:
                continue
            axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
            if not isinstance(axes, (tuple, list)):
                axes = (axes,)
            names = tuple(str(a) for a in axes if isinstance(a, str))
            out.append((lv, i, eqn, names))
    return out


# ------------------------------------------------------------------ pallas
def pallas_block_views(eqn) -> List[Tuple[Tuple, Tuple, object, bool]]:
    """(block_shape, array_shape, dtype, is_output) for every block
    mapping of a ``pallas_call`` eqn — the raw material of the tiling,
    padding and VMEM rules. Best-effort across jax versions: mappings
    without the expected fields are skipped rather than crashed on."""
    gm = eqn.params.get("grid_mapping")
    bms = getattr(gm, "block_mappings", None) or ()
    n_in = getattr(gm, "num_inputs", None)
    views = []
    for idx, bm in enumerate(bms):
        bs = getattr(bm, "block_shape", None)
        sds = getattr(bm, "array_shape_dtype", None)
        if bs is None or sds is None:
            continue
        is_out = n_in is not None and idx >= n_in
        views.append((tuple(bs), tuple(sds.shape),
                      np.dtype(sds.dtype), is_out))
    return views


def pallas_scratch_avals(eqn) -> List[object]:
    """Avals of the kernel's scratch operands (the VMEM accumulators) —
    the tail invars of the kernel jaxpr, per ``num_scratch_operands``."""
    gm = eqn.params.get("grid_mapping")
    n = int(getattr(gm, "num_scratch_operands", 0) or 0)
    if n <= 0:
        return []
    kj = eqn.params.get("jaxpr")
    if isinstance(kj, jex_core.ClosedJaxpr):
        kj = kj.jaxpr
    invars = getattr(kj, "invars", None)
    if not invars:
        return []
    out = []
    for v in invars[-n:]:
        aval = getattr(v, "aval", None)
        inner = getattr(aval, "inner_aval", aval)  # Ref wraps the array
        if inner is not None:
            out.append(inner)
    return out
