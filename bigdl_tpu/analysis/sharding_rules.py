"""shardlint — sharding- and collective-aware rules over the real
multichip graphs (ISSUE 19 tentpole).

The single-device tpulint rules (``rules.py``) read equations; these
rules read the SPMD *plan*: the ``NamedSharding``/``PartitionSpec``
annotations that :mod:`bigdl_tpu.analysis.jaxpr_walk.sharded_levels`
threads through nested pjit levels, plus the abstract param/KV spec
trees the strategies expose. jit-SPMD traces carry no collective eqns
(the partitioner inserts them after tracing), so what a static pass can
check is exactly what the annotations promise — and that is enough for
the five failure classes that dominate multichip step time:

1. **strategy/collective consistency** — the declared ``--strategy``
   mesh implies an expected signature (dp ⇒ a steered grad reduction
   per bucket, tp ⇒ a row-split layout that creates the partial-sum
   reduce, ep ⇒ expert-axis routing); a mesh axis nothing shards over,
   an annotation naming an undeclared axis, or an explicit collective
   the strategy never asked for are all errors.
2. **replicated-large-operand** — a ≥ 1 MiB operand left fully
   replicated under a model-ish mesh axis (the mesh-aware
   generalization of PR 15's serving-only ``serving-unsharded-matmul``,
   which stays as an alias).
3. **wire-dtype** — a ≥ 1 MiB replication point still crossing in f32
   while ``--gradCompress`` is active, or an 8-bit weight
   rematerialized dense right before a sharding boundary.
4. **reshard churn** — conflicting consecutive sharding constraints
   (all-gather → re-partition ping-pong) with an estimated wasted-bytes
   figure.
5. **KV-pool sharding misfit** — a paged/dense KV layout whose
   ``kv_heads`` dim the tp degree cannot split, breaking the
   ``P(None, "model", None, None)`` head split the serving engines pin.

Everything runs fully abstractly: AbstractMesh + ``eval_shape`` traces,
no devices, no compiles — seconds on CPU (PERF.md §26).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from bigdl_tpu.analysis.jaxpr_walk import (aval_bytes, collect_collectives,
                                           collect_constraints,
                                           observed_mesh_axes,
                                           sharded_levels, spec_axes)
from bigdl_tpu.analysis.report import Finding, Report

__all__ = ["SHARD_CATALOG", "SHARD_MIN_BYTES", "expected_collective_axes",
           "run_sharding_rules", "run_replicated_operand_rules",
           "run_kv_sharding_rules"]

# operands below this are latency-bound anyway — same bar as the
# serving tp rule and the comm f32 rule (1 MiB)
SHARD_MIN_BYTES = 1 * 2 ** 20

# mesh axes that replicate params BY DESIGN (dp batch axes): the
# replicated-operand rule only fires for the model-ish axes
_DATA_AXES = ("data", "batch")

# the collective signature each strategy is allowed to produce: an
# explicit collective (shard_map graphs) over any other axis is "extra"
_EXPECTED_AXES = {
    "dp": ("data",),
    "tp": ("data", "model"),
    "sp": ("data", "seq"),
    "pp": ("data", "pipe"),
    "ep": ("data", "expert"),
}

SHARD_CATALOG = {
    "shard-collective-axis": (
        "sharding", "error",
        "a sharding annotation or explicit collective references a mesh "
        "axis the declared --strategy mesh does not define — the "
        "partitioner would reject or silently replicate it"),
    "shard-collective-missing": (
        "sharding", "error",
        "the declared strategy implies a collective signature the traced "
        "step does not carry (a mesh axis nothing shards over, a "
        "gradCompress run with no 16-bit steered bucket, a tp layout "
        "with no split weight) — the strategy is a silent no-op"),
    "shard-collective-extra": (
        "sharding", "error",
        "an explicit collective over an axis the declared strategy never "
        "asked for — an unplanned reduction in the hot path"),
    "shard-replicated-operand": (
        "sharding", "error",
        "a >=1 MiB operand fully replicated under a model-ish mesh axis "
        "(mesh-aware generalization of serving-unsharded-matmul): every "
        "shard computes/stores it whole"),
    "shard-wire-dtype": (
        "sharding", "error",
        "a >=1 MiB replication point crossing the wire in f32 while "
        "--gradCompress is active — the compressed path did not engage "
        "for this value"),
    "shard-quant-remat-wire": (
        "sharding", "warning",
        "an 8-bit tensor dequantized dense immediately before a sharding "
        "boundary — the wire/HBM carries the dense value, forfeiting the "
        "quantization win"),
    "shard-reshard-churn": (
        "sharding", "warning",
        "conflicting consecutive sharding constraints on one value: "
        "all-gather then re-partition ping-pong the partitioner must "
        "materialize, with estimated wasted wire bytes"),
    "kv-shard-misfit": (
        "sharding", "error",
        "KV pool/cache layout whose kv_heads dim the tp degree cannot "
        "split — pages replicate on every chip, breaking the "
        "P(None,'model',None,None) head split the engines pin"),
}


def _shard_finding(rule: str, message: str, where: str = "",
                   hint: str = "", detail: Optional[dict] = None,
                   severity: Optional[str] = None) -> Finding:
    fam, sev, _ = SHARD_CATALOG[rule]
    return Finding(rule=rule, family=fam, severity=severity or sev,
                   message=message, where=where, hint=hint,
                   detail=detail or {})


def expected_collective_axes(strategy: Optional[str]) -> Tuple[str, ...]:
    """Mesh axes the declared strategy may legitimately reduce over
    (``None``/unknown strategy allows any declared axis)."""
    if not strategy:
        return ()
    return _EXPECTED_AXES.get(str(strategy), ())


def _spec_is_replicated(spec) -> bool:
    return not any(a is not None for a in tuple(spec or ()))


def _eqn_out_aval(eqn):
    return getattr(eqn.outvars[0], "aval", None) if eqn.outvars else None


# ================================================ group 1: consistency
def _rule_axis_membership(constraints, collectives, declared, report):
    for lv, i, eqn, s, _prev in constraints:
        bad = [a for a in spec_axes(s.spec) if a not in declared]
        if bad:
            report.add(_shard_finding(
                "shard-collective-axis",
                f"sharding constraint over undeclared mesh axis(es) "
                f"{bad} — declared mesh is "
                f"{{{', '.join(f'{k}:{v}' for k, v in declared.items())}}}",
                where=lv.where(i, eqn),
                hint="align with_sharding_constraint specs with the "
                     "--strategy mesh (bigdl_tpu.cli.common."
                     "strategy_mesh_axes)",
                detail={"axes": bad, "mesh": dict(declared)}))
    for lv, i, eqn, names in collectives:
        bad = [a for a in names if a not in declared]
        if bad:
            report.add(_shard_finding(
                "shard-collective-axis",
                f"{eqn.primitive.name} over undeclared mesh axis(es) "
                f"{bad}",
                where=lv.where(i, eqn),
                hint="the collective's axis_name must be a declared "
                     "mesh axis",
                detail={"axes": bad, "mesh": dict(declared)}))


def _rule_extra_collectives(collectives, declared, strategy, context,
                            report):
    allowed = set(expected_collective_axes(strategy)) or set(declared)
    if context == "serving":
        # the decode/verify hot path plans NO explicit collectives —
        # tp resolution is the partitioner's (annotation-driven)
        allowed = set()
    for lv, i, eqn, names in collectives:
        extra = [a for a in names if a in declared and a not in allowed]
        if extra:
            report.add(_shard_finding(
                "shard-collective-extra",
                f"{eqn.primitive.name} over axis(es) {extra} — the "
                f"declared strategy "
                f"({strategy or context}) plans no collective there",
                where=lv.where(i, eqn),
                hint="drop the collective or declare the strategy that "
                     "owns it",
                detail={"axes": extra, "strategy": strategy,
                        "context": context}))


def _rule_signature(levels, constraints, collectives, declared, strategy,
                    grad_comm, param_specs, report):
    # (a) every declared >1 axis must be referenced by SOME annotation
    referenced = set()
    for lv in levels:
        for s in lv.shardings.values():
            referenced.update(spec_axes(getattr(s, "spec", ())))
    for _lv, _i, _eqn, s, _prev in constraints:
        referenced.update(spec_axes(s.spec))
    for _lv, _i, _eqn, names in collectives:
        referenced.update(names)
    if param_specs is not None:
        import jax
        from jax.sharding import PartitionSpec as P
        for sp in jax.tree_util.tree_leaves(
                param_specs, is_leaf=lambda x: isinstance(x, P)):
            if isinstance(sp, P):
                referenced.update(spec_axes(sp))
    for axis, size in declared.items():
        if size > 1 and axis not in referenced:
            report.add(_shard_finding(
                "shard-collective-missing",
                f"mesh axis {axis!r}:{size} is declared but no "
                "annotation in the traced step shards anything over it "
                "— the strategy is a silent no-op on that axis",
                where="mesh",
                hint="check the strategy's spec builder (megatron_specs "
                     "divisibility, batch sharding) against the model "
                     "geometry",
                detail={"axis": axis, "size": int(size),
                        "referenced": sorted(referenced)}))

    # (b) gradCompress declared ⇒ ≥1 steered 16-bit bucket (the
    # apply_grad_comm replication point) must exist in the traced step
    if grad_comm is not None and getattr(grad_comm, "active", False) \
            and any(s > 1 for s in declared.values()):
        wire16 = 0
        for _lv, _i, eqn, s, _prev in constraints:
            aval = _eqn_out_aval(eqn)
            dt = getattr(aval, "dtype", None)
            if dt is None or not _spec_is_replicated(s.spec):
                continue
            if np.dtype(dt).itemsize == 2:
                wire16 += 1
        if wire16 == 0:
            report.add(_shard_finding(
                "shard-collective-missing",
                f"--gradCompress {grad_comm.compress} is active but the "
                "traced step carries no 16-bit steered bucket "
                "(with_sharding_constraint on a compressed value) — "
                "the grad all-reduce would ride f32",
                where="grad_comm",
                hint="route reduce_grads through parallel.grad_comm."
                     "apply_grad_comm (the DataParallel path does)",
                detail={"compress": grad_comm.compress}))

    # (c) tp ⇒ the layout must actually split weights (the row-split
    # partial-sum reduce is what the strategy buys)
    if strategy == "tp" and param_specs is not None \
            and int(declared.get("model", 1)) > 1:
        import jax
        from jax.sharding import PartitionSpec as P
        leaves = [sp for sp in jax.tree_util.tree_leaves(
            param_specs, is_leaf=lambda x: isinstance(x, P))
            if isinstance(sp, P)]
        n_split = sum(1 for sp in leaves if not _spec_is_replicated(sp))
        if leaves and n_split == 0:
            report.add(_shard_finding(
                "shard-collective-missing",
                f"tp mesh (model:{declared['model']}) declared but the "
                "Megatron layout split zero parameter leaves — no "
                "row-split reduce exists; every chip runs the full "
                "model",
                where="megatron_specs",
                hint="pick a tp degree that divides d_model/heads, or "
                     "drop --strategy tp for this model",
                detail={"tp": int(declared["model"]),
                        "param_leaves": len(leaves)}))


# ===================================== group 3: wire dtype / quant remat
def _rule_wire_dtype(constraints, grad_comm, report):
    if grad_comm is None or not getattr(grad_comm, "active", False):
        return
    for lv, i, eqn, s, _prev in constraints:
        aval = _eqn_out_aval(eqn)
        nbytes = aval_bytes(aval)
        dt = getattr(aval, "dtype", None)
        if dt is None or nbytes < SHARD_MIN_BYTES:
            continue
        if _spec_is_replicated(s.spec) and np.dtype(dt) == np.float32:
            report.add(_shard_finding(
                "shard-wire-dtype",
                f"{nbytes / 2**20:.1f} MiB replication point crosses "
                f"the wire in f32 while --gradCompress "
                f"{grad_comm.compress} is active — this value bypassed "
                "the compressed bucket path",
                where=lv.where(i, eqn),
                hint="compress before the constraint "
                     "(grad_comm.compress_bucket) or exclude the value "
                     "from the steered set deliberately",
                detail={"bytes": nbytes,
                        "compress": grad_comm.compress}))


_8BIT_NAMES = ("int8", "uint8", "float8_e4m3fn", "float8_e5m2")


def _rule_quant_remat(levels, report):
    """8-bit → wide convert whose (≥1 MiB) result feeds a sharding
    boundary within a few transparent hops: the dense rematerialization
    crosses the wire, not the 8-bit value (composes with the
    quant-dequant-upcast chain rule)."""
    for lv in levels:
        dense_from_8bit = {}  # id(outvar) -> src dtype name (Vars only;
        for eqn in lv.jaxpr.eqns:  # Literal operands are unhashable)
            if eqn.primitive.name != "convert_element_type":
                continue
            src = getattr(getattr(eqn.invars[0], "aval", None), "dtype",
                          None)
            if src is None or str(np.dtype(src)) not in _8BIT_NAMES:
                continue
            out = eqn.outvars[0]
            if aval_bytes(getattr(out, "aval", None)) >= SHARD_MIN_BYTES:
                dense_from_8bit[id(out)] = str(np.dtype(src))
        if not dense_from_8bit:
            continue
        # follow ≤4 pointwise hops to a sharding_constraint consumer
        hops = dict(dense_from_8bit)
        for _ in range(4):
            grew = {}
            for eqn in lv.jaxpr.eqns:
                srcs = [v for v in eqn.invars if id(v) in hops]
                if not srcs or not eqn.outvars:
                    continue
                if eqn.primitive.name == "sharding_constraint":
                    continue
                if len(eqn.outvars) == 1 and aval_bytes(getattr(
                        eqn.outvars[0], "aval", None)) >= SHARD_MIN_BYTES:
                    grew[id(eqn.outvars[0])] = hops[id(srcs[0])]
            before = len(hops)
            hops.update(grew)
            if len(hops) == before:
                break
        for i, eqn in enumerate(lv.jaxpr.eqns):
            if eqn.primitive.name != "sharding_constraint":
                continue
            v = eqn.invars[0]
            if id(v) in hops:
                nbytes = aval_bytes(getattr(v, "aval", None))
                report.add(_shard_finding(
                    "shard-quant-remat-wire",
                    f"{hops[id(v)]} tensor rematerialized dense "
                    f"({nbytes / 2**20:.1f} MiB) before a sharding "
                    "boundary — the wire carries the dense value",
                    where=lv.where(i, eqn),
                    hint="keep the 8-bit value across the boundary and "
                         "dequantize per shard (QuantizedWeight keeps "
                         "the scale alongside)",
                    detail={"bytes": nbytes, "src_dtype": hops[id(v)]}))


# ============================================== group 4: reshard churn
def _churn_factor(axes: List[str], declared: Dict[str, int]) -> float:
    n = 1
    for a in axes:
        n *= int(declared.get(a, 1))
    return (n - 1) / n if n > 1 else 0.0


def _rule_reshard_churn(constraints, declared, report):
    for lv, i, eqn, s, prev in constraints:
        if prev is None:
            continue
        s_spec, p_spec = tuple(s.spec or ()), tuple(
            getattr(prev, "spec", ()) or ())
        if _spec_is_replicated(s_spec) or _spec_is_replicated(p_spec):
            continue  # first placement / deliberate full gather
        if s_spec == p_spec:
            continue
        nbytes = aval_bytes(_eqn_out_aval(eqn))
        if nbytes < SHARD_MIN_BYTES:
            continue
        gathered = int(nbytes * _churn_factor(spec_axes(p_spec), declared))
        rescattered = int(nbytes * _churn_factor(spec_axes(s_spec),
                                                 declared))
        report.add(_shard_finding(
            "shard-reshard-churn",
            f"consecutive conflicting sharding constraints "
            f"{p_spec} -> {s_spec} on a {nbytes / 2**20:.1f} MiB value: "
            "the partitioner must all-gather then re-partition "
            f"(~{(gathered + rescattered) / 2**20:.1f} MiB wasted wire)",
            where=lv.where(i, eqn),
            hint="pick ONE layout for the value's lifetime, or reshape "
                 "under a single constraint",
            detail={"bytes": nbytes, "from": [str(a) for a in p_spec],
                    "to": [str(a) for a in s_spec],
                    "wasted_bytes": gathered + rescattered}))


# ======================================= group 2: replicated operands
def _leaf_layout(leaf, spec):
    """(shape, nbytes, replicated) for one param leaf — reads the
    committed ``.sharding`` when the leaf is a placed array, the spec
    tree entry when linting abstractly; ``None`` (unknown placement —
    abstract leaf with no spec) never fires the rule."""
    shape = tuple(getattr(leaf, "shape", ()))
    dtype = getattr(leaf, "dtype", None)
    if not shape or dtype is None:
        return shape, 0, None
    nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize
    sharding = getattr(leaf, "sharding", None)
    if sharding is not None and hasattr(sharding, "is_fully_replicated"):
        return shape, nbytes, bool(sharding.is_fully_replicated)
    if spec is not None:
        return shape, nbytes, _spec_is_replicated(spec)
    return shape, nbytes, None


def run_replicated_operand_rules(params, mesh_axes: Dict[str, int], *,
                                 specs=None, split_axes=None,
                                 rule_id: str = "shard-replicated-operand",
                                 report: Optional[Report] = None) -> Report:
    """Mesh-aware replicated-large-operand rule over training AND
    serving param trees (ISSUE 19 group 2): a ≥ 1 MiB, ≥ 2-D leaf fully
    replicated while a model-ish mesh axis is declared means every
    shard computes/stores it whole. Reads committed ``.sharding`` on
    placed arrays or the abstract ``specs`` tree; ``rule_id`` keeps the
    PR 15 ``serving-unsharded-matmul`` spelling as an alias (the serve
    preflight / existing tests)."""
    report = report if report is not None else Report()
    declared = {str(k): int(v) for k, v in (mesh_axes or {}).items()}
    if split_axes is None:
        split_axes = tuple(a for a in declared
                           if a not in _DATA_AXES and declared[a] > 1)
    sizes = [declared[a] for a in split_axes if declared.get(a, 1) > 1]
    if not sizes:
        return report
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    spec_leaves = None
    if specs is not None:
        from jax.sharding import PartitionSpec as P
        spec_leaves = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, P))
        if len(spec_leaves) != len(flat):
            spec_leaves = None  # shape mismatch: fall back to .sharding
    legacy = rule_id == "serving-unsharded-matmul"
    for n, (path, leaf) in enumerate(flat):
        if legacy and getattr(leaf, "sharding", None) is None:
            continue  # PR 15 semantics: placed trees only
        spec = spec_leaves[n] if spec_leaves is not None else None
        shape, nbytes, replicated = _leaf_layout(leaf, spec)
        if len(shape) < 2 or nbytes < SHARD_MIN_BYTES \
                or replicated is not True:
            continue
        where = jax.tree_util.keystr(path)
        splittable = sorted({a for a in split_axes
                             for d in shape if d % declared[a] == 0})
        if legacy:
            tp = max(sizes)
            report.add(Finding(
                rule=rule_id, family="serving", severity="error",
                message=f"{where}: {nbytes / 2**20:.1f} MiB weight "
                        f"{shape} is fully replicated under tp={tp} — "
                        "each chip runs this matmul whole",
                where=where,
                hint="shard dims the Megatron pairing can split "
                     "(d_model / heads divisible by K), or drop "
                     "--strategy tp for this model",
                detail={"bytes": nbytes, "shape": list(shape),
                        "tp": int(tp)}))
            continue
        mesh_str = ", ".join(f"{a}:{declared[a]}" for a in split_axes)
        if splittable:
            msg = (f"{where}: {nbytes / 2**20:.1f} MiB operand {shape} "
                   f"fully replicated though mesh axis(es) "
                   f"{splittable} could split a dim — every shard "
                   "computes it whole")
            hint = ("shard it over the model axis (megatron_specs "
                    "pairing / with_sharding_constraint), or shrink "
                    "the mesh")
        else:
            msg = (f"{where}: {nbytes / 2**20:.1f} MiB operand {shape} "
                   f"fully replicated and NO dim divides the declared "
                   f"axis(es) {{{mesh_str}}} — this degree does not "
                   "fit the model geometry")
            hint = ("pick a degree that divides d_model/heads, or "
                    "accept replication explicitly with --strategy dp")
        report.add(_shard_finding(
            rule_id, msg, where=where, hint=hint,
            detail={"bytes": nbytes, "shape": list(shape),
                    "mesh": {a: declared[a] for a in split_axes},
                    "splittable_axes": splittable}))
    return report


# ============================================ group 5: KV pool misfit
def run_kv_sharding_rules(kv_tree, tp_k: int, *, axis: str = "model",
                          page_tokens: Optional[int] = None,
                          report: Optional[Report] = None) -> Report:
    """KV-pool/cache sharding misfit under tp (ISSUE 19 group 5).
    ``kv_tree`` is the pools (paged; QuantPool nodes flatten to their
    q/s planes) or the dense cache — abstract ShapeDtypeStructs or
    placed arrays. Fires when a ≥ 1 MiB 4-D leaf's kv_heads dim
    (axis 1 of ``(slots|pages, kv_heads, tokens, head_dim)``) is not
    divisible by ``tp_k`` — the ``P(None,'model',None,None)`` head
    split the engines pin falls back to full replication — or when a
    placed leaf that COULD split was committed replicated anyway."""
    report = report if report is not None else Report()
    k = int(tp_k)
    if k <= 1:
        return report
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(kv_tree)
    for path, leaf in flat:
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = getattr(leaf, "dtype", None)
        if len(shape) != 4 or dtype is None:
            continue
        nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize
        if nbytes < SHARD_MIN_BYTES:
            continue
        where = jax.tree_util.keystr(path)
        kv_heads = int(shape[1])
        if kv_heads % k:
            report.add(_shard_finding(
                "kv-shard-misfit",
                f"{where}: KV leaf {shape} has kv_heads={kv_heads} not "
                f"divisible by tp={k} — the P(None,{axis!r},None,None) "
                f"head split falls back to replicating "
                f"{nbytes / 2**20:.1f} MiB of pages on every chip",
                where=where,
                hint="pick tp dividing kv_heads (GQA: raise kv_heads "
                     "or lower K), or serve this model dp-only",
                detail={"bytes": nbytes, "shape": list(shape),
                        "kv_heads": kv_heads, "tp": k,
                        "page_tokens": page_tokens}))
            continue
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None \
                and hasattr(sharding, "is_fully_replicated") \
                and sharding.is_fully_replicated:
            report.add(_shard_finding(
                "kv-shard-misfit",
                f"{where}: KV leaf {shape} could split kv_heads="
                f"{kv_heads} over tp={k} but was committed fully "
                f"replicated ({nbytes / 2**20:.1f} MiB per chip)",
                where=where,
                hint="commit the pools through ServingSharding."
                     "kv_shardings / PagedKvCache(sharding=...)",
                detail={"bytes": nbytes, "shape": list(shape),
                        "kv_heads": kv_heads, "tp": k}))
    return report


# ================================================= composed entry point
def run_sharding_rules(closed, *, mesh_axes: Optional[Dict[str, int]] = None,
                       strategy: Optional[str] = None, grad_comm=None,
                       param_specs=None, params=None,
                       context: str = "train",
                       report: Optional[Report] = None) -> Report:
    """All annotation-level shardlint rules over one traced sharded
    step (groups 1, 3, 4 — plus group 2 when ``params``/``param_specs``
    are given). ``mesh_axes`` is the declared mesh (axis -> size;
    defaults to every mesh observed in the annotations), ``strategy``
    the declared ``--strategy`` name, ``grad_comm`` the
    :class:`~bigdl_tpu.parallel.grad_comm.GradCommConfig` in effect,
    ``context`` ``"train"`` or ``"serving"`` (serving plans no explicit
    collectives)."""
    report = report if report is not None else Report()
    levels = sharded_levels(closed)
    declared = ({str(k): int(v) for k, v in mesh_axes.items()}
                if mesh_axes else observed_mesh_axes(levels))
    constraints = collect_constraints(levels)
    collectives = collect_collectives(levels)

    _rule_axis_membership(constraints, collectives, declared, report)
    _rule_extra_collectives(collectives, declared, strategy, context,
                            report)
    _rule_signature(levels, constraints, collectives, declared, strategy,
                    grad_comm, param_specs, report)
    _rule_wire_dtype(constraints, grad_comm, report)
    _rule_quant_remat(levels, report)
    _rule_reshard_churn(constraints, declared, report)
    if params is not None and declared:
        run_replicated_operand_rules(params, declared, specs=param_specs,
                                     report=report)
    return report
