"""tpulint — trace-time static analysis for TPU perf/correctness
anti-patterns (ISSUE 4 tentpole).

BigDL's operability came from catching config mistakes at submit time,
before a cluster burned hours (PAPER §BigDL). The TPU analogue: trace a
model's full train step with ``jax.make_jaxpr`` under **abstract**
inputs (no compilation, no device, seconds on CPU), walk every nested
pjit/custom_vjp/pallas_call sub-jaxpr, and evaluate a rule registry over
the jaxpr plus the kernel/block/layout metadata PRs 1–3 already record.
The same pass is the CI gate that keeps those PRs' wins from regressing.

Public surface:

* :func:`lint_fn` — lint any callable (traced with the given abstract
  args); jaxpr rules only.
* :func:`lint_perf_model` — lint a perf-zoo model end-to-end: builds the
  model (LMs get the flash kernel forced on so the TPU-projected trace
  is analyzed even off-chip), constructs the donated SGD train step the
  perf harness runs, traces it abstractly, and evaluates jaxpr + module
  rules. The ``bigdl-tpu lint`` CLI and the perf ``--lint`` pre-flight
  call this.
* :func:`preflight_optimizer` — lint a built
  :class:`~bigdl_tpu.optim.Optimizer` before ``optimize()`` (the
  training CLIs' ``--lint`` flag): module rules always; the real
  ``_build_step`` product is traced when the dataset exposes its batch
  geometry without consuming the shuffle stream.

Findings: :class:`~bigdl_tpu.analysis.report.Finding` /
:class:`~bigdl_tpu.analysis.report.Report`; the rule catalog with
severities lives in :data:`bigdl_tpu.analysis.rules.CATALOG`
(documented in PERF.md §12).
"""

from __future__ import annotations

from typing import Optional

from bigdl_tpu.analysis.report import Finding, Report, SEVERITIES
from bigdl_tpu.analysis.rules import (CATALOG, assert_blocks_tileable,
                                      check_block_padding,
                                      check_block_tiling, min_sublane,
                                      run_comm_rules, run_decode_rules,
                                      run_jaxpr_rules,
                                      run_memory_rules, run_module_rules,
                                      run_serving_tp_rules)
from bigdl_tpu.analysis.sharding_rules import (SHARD_CATALOG,
                                               run_kv_sharding_rules,
                                               run_replicated_operand_rules,
                                               run_sharding_rules)

__all__ = ["Finding", "Report", "SEVERITIES", "CATALOG", "SHARD_CATALOG",
           "check_block_tiling", "check_block_padding",
           "assert_blocks_tileable", "min_sublane",
           "run_jaxpr_rules", "run_module_rules", "run_comm_rules",
           "run_memory_rules", "run_decode_rules",
           "run_serving_tp_rules", "run_sharding_rules",
           "run_replicated_operand_rules", "run_kv_sharding_rules",
           "lint_fn", "trace_train_step", "trace_sharded_train_step",
           "lint_perf_model", "lint_config",
           "preflight_optimizer"]


def lint_fn(fn, *args, report: Optional[Report] = None, **kwargs) -> Report:
    """Trace ``fn(*args, **kwargs)`` abstractly (args may be arrays or
    ``jax.ShapeDtypeStruct``) and run every jaxpr rule. Pass an already-
    jitted ``fn`` to get donation analysis of its pjit boundary."""
    import jax

    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    return run_jaxpr_rules(closed, report)


def trace_train_step(model, in_shape, batch, *, dtype=None, is_lm=False,
                     vocab: int = 32000, donate=(0, 1, 2)):
    """ClosedJaxpr of the canonical SGD train step over ``model`` at
    ``batch`` x ``in_shape`` — the same step shape the perf harness
    compiles (donated (params, mod_state, opt_state), bf16 activations
    by default, fp32 loss). Everything abstract: params/opt-state come
    from ``jax.eval_shape``, inputs are ShapeDtypeStructs; nothing is
    allocated or executed."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu import nn
    from bigdl_tpu.optim import SGD

    dtype = jnp.bfloat16 if dtype is None else dtype
    crit = (nn.TimeDistributedCriterion(nn.ClassNLLCriterion()) if is_lm
            else nn.ClassNLLCriterion())
    opt = SGD(learning_rate=0.01, momentum=0.9)

    if is_lm:
        if dtype == jnp.bfloat16:
            model.compute_dtype = dtype  # cast lives after the embedding
        x = jax.ShapeDtypeStruct((batch, *in_shape), jnp.int32)
        y = jax.ShapeDtypeStruct((batch, *in_shape), jnp.int32)
    else:
        x = jax.ShapeDtypeStruct((batch, *in_shape), jnp.float32)
        y = jax.ShapeDtypeStruct((batch,), jnp.int32)

    key = jax.random.PRNGKey(0)
    params = jax.eval_shape(model.init, key)
    mod_state = model.init_state()
    opt_state = jax.eval_shape(opt.init, params)

    def train_step(params, mod_state, opt_state, x, y, rng):
        def loss_fn(p):
            xc = (x.astype(dtype)
                  if jnp.issubdtype(x.dtype, jnp.floating) else x)
            out, ms = model.apply(p, mod_state, xc, training=True, rng=rng)
            return crit(out.astype(jnp.float32), y), ms

        (loss, ms), grads = jax.value_and_grad(loss_fn,
                                               has_aux=True)(params)
        new_p, new_o = opt.update(grads, opt_state, params)
        return new_p, ms, new_o, loss

    step = (jax.jit(train_step, donate_argnums=donate) if donate
            else jax.jit(train_step))
    return jax.make_jaxpr(step)(params, mod_state, opt_state, x, y, key)


def trace_sharded_train_step(model, in_shape, batch, *, mesh_axes,
                             dtype=None, is_lm=False, grad_comm=None,
                             donate=(0, 1, 2)):
    """ClosedJaxpr of the SHARDED SGD train step over ``model`` on the
    declared ``mesh_axes`` (axis -> size), plus the metadata shardlint
    needs: ``(closed, {"param_specs", "mesh_axes", "params"})``.

    The mesh is a :class:`jax.sharding.AbstractMesh` — annotations only,
    zero real devices, no compile, so a 32-chip layout lints on a 1-CPU
    box (the ISSUE 19 contract). The layout mirrors what the real
    strategies build: Megatron param specs when a ``model`` axis > 1
    (:func:`~bigdl_tpu.parallel.tensor_parallel.megatron_specs`, with
    its divisibility fallbacks — so a mis-fitting tp degree shows up
    here exactly as it would on chips), replicated params otherwise,
    batch sharded over ``data`` (and ``seq`` when declared), and the
    compressed-bucket grad path when ``grad_comm`` is active."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import AbstractMesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu import nn
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.parallel.tensor_parallel import (megatron_specs,
                                                    replicated_specs)

    axes = {str(k): int(v) for k, v in dict(mesh_axes).items()}
    mesh = AbstractMesh(tuple(axes.items()))
    dtype = jnp.bfloat16 if dtype is None else dtype
    crit = (nn.TimeDistributedCriterion(nn.ClassNLLCriterion()) if is_lm
            else nn.ClassNLLCriterion())
    opt = SGD(learning_rate=0.01, momentum=0.9)

    key = jax.random.PRNGKey(0)
    params = jax.eval_shape(model.init, key)
    mod_state = model.init_state()
    opt_state = jax.eval_shape(opt.init, params)

    if axes.get("model", 1) > 1:
        specs = megatron_specs(model, params, "model", axes["model"])
    else:
        specs = replicated_specs(params)
    is_spec = lambda s: isinstance(s, P)
    p_sh = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs, is_leaf=is_spec)
    o_sh = jax.tree_util.tree_map(
        lambda _: NamedSharding(mesh, P()), opt_state)
    seq_axis = "seq" if (is_lm and axes.get("seq", 1) > 1) else None
    if is_lm:
        if dtype == jnp.bfloat16:
            model.compute_dtype = dtype
        x = jax.ShapeDtypeStruct((batch, *in_shape), jnp.int32)
        y = jax.ShapeDtypeStruct((batch, *in_shape), jnp.int32)
        x_sh = y_sh = NamedSharding(mesh, P("data", seq_axis))
    else:
        x = jax.ShapeDtypeStruct((batch, *in_shape), jnp.float32)
        y = jax.ShapeDtypeStruct((batch,), jnp.int32)
        x_sh = NamedSharding(mesh, P("data"))
        y_sh = NamedSharding(mesh, P("data"))

    def train_step(params, mod_state, opt_state, x, y, rng):
        def loss_fn(p):
            xc = (x.astype(dtype)
                  if jnp.issubdtype(x.dtype, jnp.floating) else x)
            out, ms = model.apply(p, mod_state, xc, training=True, rng=rng)
            return crit(out.astype(jnp.float32), y), ms

        (loss, ms), grads = jax.value_and_grad(loss_fn,
                                               has_aux=True)(params)
        if grad_comm is not None and getattr(grad_comm, "active", False):
            from bigdl_tpu.parallel.grad_comm import apply_grad_comm
            grads, _ = apply_grad_comm(grads, grad_comm, mesh)
        new_p, new_o = opt.update(grads, opt_state, params)
        return new_p, ms, new_o, loss

    step = jax.jit(train_step,
                   in_shardings=(p_sh, None, o_sh, x_sh, y_sh, None),
                   donate_argnums=donate or ())
    closed = jax.make_jaxpr(step)(params, mod_state, opt_state, x, y, key)
    return closed, {"param_specs": specs, "mesh_axes": axes,
                    "params": params}


def _bn_fallback_rule(model, closed, report: Report) -> None:
    """Model+jaxpr combo rule: fused BN was requested, eligible sites
    exist, but fewer forward kernels were traced than sites — some (or
    all) silently fell back to the jnp path (rows untileable at this
    batch)."""
    from bigdl_tpu.analysis.jaxpr_walk import (iter_levels,
                                               pallas_kernel_name)
    from bigdl_tpu.nn.norm import BatchNormalization

    sites = [m for m in model.modules()
             if isinstance(m, BatchNormalization) and m.fused
             and m.affine and m.axis_name is None and not m.stat_sample
             and int(m.n_output) % 128 == 0]
    if not sites:
        return
    fwd_names = {"_fba_fwd_kernel", "_stats_kernel"}
    traced = 0
    for lv in iter_levels(closed):
        for eqn in lv.jaxpr.eqns:
            if eqn.primitive.name == "pallas_call" \
                    and pallas_kernel_name(eqn) in fwd_names:
                traced += 1
    if traced < len(sites):
        report.add(Finding(
            rule="tile-bn-fallback", family="tiling",
            severity="warning",
            message=(f"fused BN requested on {len(sites)} eligible "
                     f"site(s) but only {traced} fused stats/apply "
                     "kernel(s) traced — the rest fell back to the jnp "
                     "path (rows % row-block != 0 at this batch)"),
            hint="--autotune measure can unlock smaller legal row "
                 "blocks; or pick a batch whose rows tile",
            detail={"eligible_sites": len(sites),
                    "traced_kernels": traced}))


def lint_perf_model(name: str, batch: int = 32, *, seq_len=None,
                    dtype=None, fused_bn=None, classes: int = 1000,
                    trace: bool = True, strategy=None,
                    grad_compress=None) -> Report:
    """Full lint of one perf-zoo model (see module docstring). LMs are
    built with ``attn_impl='flash'`` forced so the TPU-projected kernels
    appear in the CPU trace; ``trace=False`` skips the jaxpr pass
    (module rules only — used when only configuration is in question).
    ``strategy``/``grad_compress`` are the perf CLI's spec strings; when
    a multi-device strategy is requested the gradient-communication
    rules run over the abstract param tree (PERF.md §17)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.cli.common import apply_fused_bn
    from bigdl_tpu.cli.perf import build_model

    dtype = jnp.bfloat16 if dtype is None else dtype
    model, in_shape = build_model(name, class_num=classes,
                                  seq_len=seq_len, lm_attn_impl="flash")
    apply_fused_bn(model, fused_bn)
    is_lm = name.startswith("transformer_lm")
    seq = in_shape[0] if is_lm else None

    report = Report()
    dtname = jnp.dtype(dtype).name
    run_module_rules(model, report, seq=seq, dtype=dtname)
    if strategy is not None:
        from bigdl_tpu.cli.common import parse_strategy_spec

        strat_name, _ = parse_strategy_spec(strategy)
        params = jax.eval_shape(model.init, jax.random.PRNGKey(0))
        run_comm_rules(params, strat_name, grad_compress, report)
    if trace:
        closed = trace_train_step(model, in_shape, batch, dtype=dtype,
                                  is_lm=is_lm)
        run_jaxpr_rules(closed, report)
        _bn_fallback_rule(model, closed, report)
    # HBM working-set rule (ISSUE 12): abstract plan over the same
    # state pytrees the perf step would hold — argument-side categories
    # only (no compilation), so "plan exceeds HBM" fires pre-compile
    try:
        from bigdl_tpu.obs import memory
        from bigdl_tpu.optim import SGD

        params = jax.eval_shape(model.init, jax.random.PRNGKey(0))
        opt_state = jax.eval_shape(
            SGD(learning_rate=0.01, momentum=0.9).init, params)
        if is_lm:
            x = jax.ShapeDtypeStruct((batch, *in_shape), jnp.int32)
            y = jax.ShapeDtypeStruct((batch, *in_shape), jnp.int32)
        else:
            x = jax.ShapeDtypeStruct((batch, *in_shape), jnp.float32)
            y = jax.ShapeDtypeStruct((batch,), jnp.int32)
        plan = memory.build_plan(params=params, opt_state=opt_state,
                                 batch=(x, y), batch_size=batch,
                                 model_name=name)
        run_memory_rules(plan, report)
    except Exception as e:
        report.add(Finding(
            rule="lint-trace-error", family="meta", severity="info",
            message=f"memory rules skipped ({type(e).__name__}: {e})",
            hint="the jaxpr/module rules still ran"))
    return report


def lint_config(cfg) -> Report:
    """Lint everything one resolved run configuration would execute
    (ISSUE 19): the single-device pass (:func:`lint_perf_model`), the
    SHARDED train step when ``--strategy`` declares a mesh (shardlint
    rules over an :class:`~jax.sharding.AbstractMesh` trace — zero real
    devices), and the serving decode surface when ``--quantize`` /
    ``--speculate`` / ``--kvPageTokens`` ask for one. ``cfg`` is a
    :class:`bigdl_tpu.cli.common.ResolvedConfig` — the one object the
    lint CLI and every preflight hand over (the ResolvedConfig
    spine)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.cli.common import apply_fused_bn
    from bigdl_tpu.cli.perf import build_model

    dtype = jnp.float32 if cfg.dtype == "float32" else jnp.bfloat16
    strat_spec = None
    if cfg.strategy:
        strat_spec = (f"{cfg.strategy}:{cfg.strategy_k}"
                      if cfg.strategy_k else cfg.strategy)
    report = lint_perf_model(cfg.model, cfg.batch, seq_len=cfg.seq,
                             dtype=dtype, fused_bn=cfg.fused_bn,
                             classes=cfg.classes, trace=cfg.trace,
                             strategy=strat_spec,
                             grad_compress=cfg.grad_compress)
    mesh = cfg.mesh
    is_lm = cfg.model.startswith("transformer_lm")
    grad_comm = cfg.make_grad_comm()

    # ------------------------------------------- sharded training step
    if cfg.trace and mesh and cfg.strategy in ("dp", "tp", "sp"):
        model, in_shape = build_model(cfg.model, class_num=cfg.classes,
                                      seq_len=cfg.seq,
                                      lm_attn_impl="flash")
        apply_fused_bn(model, cfg.fused_bn)
        try:
            closed, meta = trace_sharded_train_step(
                model, in_shape, cfg.batch, mesh_axes=mesh, dtype=dtype,
                is_lm=is_lm, grad_comm=grad_comm)
        except Exception as e:
            report.add(Finding(
                rule="lint-trace-error", family="meta", severity="info",
                message=f"sharded step trace skipped "
                        f"({type(e).__name__}: {e})",
                hint="the single-device passes still ran"))
        else:
            run_sharding_rules(closed, mesh_axes=meta["mesh_axes"],
                               strategy=cfg.strategy,
                               grad_comm=grad_comm,
                               param_specs=meta["param_specs"],
                               params=meta["params"], context="train",
                               report=report)
    elif cfg.strategy in ("pp", "ep"):
        report.add(Finding(
            rule="lint-trace-error", family="meta", severity="info",
            message=f"--strategy {cfg.strategy}: the staged/expert step "
                    "composes inside the perf harness; shardlint traces "
                    "dp/tp/sp step graphs",
            hint="the config-level comm rules above still apply"))

    # ------------------------------------------- serving decode surface
    wants_serving = bool(cfg.quantize or cfg.speculate
                         or cfg.kv_page_tokens)
    if cfg.trace and wants_serving:
        if not is_lm:
            report.add(Finding(
                rule="lint-trace-error", family="meta", severity="info",
                message="--quantize/--speculate/--kvPageTokens describe "
                        "the LM serving surface; skipped for "
                        f"{cfg.model}",
                hint="lint a transformer_lm* model to cover decode"))
        else:
            tp_k = int(mesh.get("model", 1)) if cfg.strategy == "tp" \
                else 1
            try:
                from bigdl_tpu.serving.decode import \
                    abstract_decode_engine
                smodel, _ = build_model(cfg.model, class_num=cfg.classes,
                                        seq_len=cfg.seq,
                                        lm_attn_impl="flash")
                kvp = cfg.kv_page_tokens
                if cfg.quantize and "kv8" in cfg.quantize and not kvp:
                    # kv8 is a page-pool layout (mirrors serve's pick)
                    for cand in (128, 64, 32, 256):
                        if smodel.max_len % cand == 0:
                            kvp = cand
                            break
                eng = abstract_decode_engine(
                    smodel, slots=cfg.slots, kv_page_tokens=kvp,
                    speculate=cfg.speculate, tp=tp_k,
                    quantize=cfg.quantize)
                closed = eng.trace_step_jaxpr()
            except Exception as e:
                report.add(Finding(
                    rule="lint-trace-error", family="meta",
                    severity="info",
                    message=f"serving decode trace skipped "
                            f"({type(e).__name__}: {e})",
                    hint="the training-side passes still ran"))
            else:
                head_dim = getattr(
                    smodel.encoder._modules[0].mha, "head_dim",
                    smodel.d_model // 4)
                run_decode_rules(closed, page_tokens=kvp,
                                 max_len=eng.max_len, head_dim=head_dim,
                                 dtype=eng.cache_dtype, report=report)
                if tp_k > 1:
                    run_sharding_rules(closed,
                                       mesh_axes={"model": tp_k},
                                       strategy=None, context="serving",
                                       report=report)
                    run_kv_sharding_rules(
                        eng._kv.pools if eng.paged else eng._cache,
                        tp_k, page_tokens=kvp, report=report)
                    # replicated-operand over the serving layout the
                    # engine would commit (abstract: specs, not arrays)
                    raw = jax.eval_shape(smodel.init,
                                         jax.random.PRNGKey(0))
                    specs = eng._shard.param_specs(smodel, raw)
                    run_replicated_operand_rules(
                        raw, {"model": tp_k}, specs=specs,
                        report=report)
    return report


def preflight_optimizer(opt) -> Report:
    """Lint a built Optimizer before it trains (the training CLIs'
    ``--lint`` pre-flight). Module rules always run; the jaxpr pass runs
    when the step can be traced without side effects: single-device
    strategy and a dataset exposing ``features``/``labels``/
    ``batch_size`` (reading them, unlike pulling a batch, does not
    advance the shuffle RNG that step-equivalent resume depends on)."""
    import numpy as np

    report = Report()
    dtname = ("bfloat16" if getattr(opt, "compute_dtype", None) is not None
              else "float32")
    run_module_rules(opt.model, report, dtype=dtname)

    strat_name = None
    if opt.strategy is not None:
        try:
            import jax

            from bigdl_tpu.parallel import DataParallel, TensorParallel

            if isinstance(opt.strategy, TensorParallel):
                strat_name = "tp"
            elif isinstance(opt.strategy, DataParallel):
                strat_name = "dp"
            cfg = getattr(opt.strategy, "grad_comm", None)
            compress = cfg.compress if cfg is not None else None
            params = jax.eval_shape(opt.model.init, jax.random.PRNGKey(0))
            run_comm_rules(params, strat_name, compress, report)
        except Exception as e:
            report.add(Finding(
                rule="lint-trace-error", family="meta", severity="info",
                message=f"comm rules skipped ({type(e).__name__}: {e})",
                hint="module-level rules still ran"))

    ds = opt.dataset
    feats = getattr(ds, "features", None)
    labs = getattr(ds, "labels", None)
    bs = getattr(ds, "batch_size", None)
    if opt.strategy is not None:
        # shardlint (ISSUE 19): the SHARDED step this run would compile,
        # traced over an AbstractMesh clone of the strategy's real mesh —
        # megatron specs + the strategy's grad_comm annotations, no
        # compile, so the multichip preflight stays seconds on CPU
        if strat_name not in ("dp", "tp") or feats is None or not bs:
            return report
        try:
            smeta = opt.strategy.lint_spec_metadata()
            axes = smeta.get("mesh_axes") or {}
            if not axes:
                return report
            import jax.numpy as jnp
            dt = (jnp.bfloat16
                  if getattr(opt, "compute_dtype", None) is not None
                  else jnp.float32)
            closed, meta = trace_sharded_train_step(
                opt.model, tuple(feats.shape[1:]), int(bs),
                mesh_axes=axes, dtype=dt, is_lm=False,
                grad_comm=smeta.get("grad_comm"))
            run_sharding_rules(
                closed, mesh_axes=meta["mesh_axes"],
                strategy=smeta.get("strategy", strat_name),
                grad_comm=smeta.get("grad_comm"),
                param_specs=meta["param_specs"], params=meta["params"],
                context="train", report=report)
        except Exception as e:
            report.add(Finding(
                rule="lint-trace-error", family="meta", severity="info",
                message=f"sharded step trace skipped "
                        f"({type(e).__name__}: {e})",
                hint="module/comm rules still ran"))
        return report
    if feats is None or labs is None or not bs:
        return report
    try:
        import jax

        from bigdl_tpu.ops.conv2d import policy_snapshot, restore_policy

        x = jax.ShapeDtypeStruct((int(bs),) + tuple(feats.shape[1:]),
                                 np.asarray(feats).dtype)
        y = jax.ShapeDtypeStruct((int(bs),) + tuple(labs.shape[1:]),
                                 np.asarray(labs).dtype)
        snap = policy_snapshot()
        try:
            step, _ = opt._build_step()
            key = jax.random.PRNGKey(0)
            params = jax.eval_shape(opt.model.init, key)
            mod_state = opt.model.init_state()
            opt_state = jax.eval_shape(opt.optim_method.init, params)
            closed = jax.make_jaxpr(step)(params, mod_state, opt_state,
                                          x, y, key)
        finally:
            restore_policy(snap)
        run_jaxpr_rules(closed, report)
        _bn_fallback_rule(opt.model, closed, report)
    except Exception as e:  # surface, never block training on lint bugs
        report.add(Finding(
            rule="lint-trace-error", family="meta", severity="info",
            message=f"step trace skipped ({type(e).__name__}: {e})",
            hint="module-level rules still ran"))
    return report
