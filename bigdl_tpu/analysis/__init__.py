"""tpulint — trace-time static analysis for TPU perf/correctness
anti-patterns (ISSUE 4 tentpole).

BigDL's operability came from catching config mistakes at submit time,
before a cluster burned hours (PAPER §BigDL). The TPU analogue: trace a
model's full train step with ``jax.make_jaxpr`` under **abstract**
inputs (no compilation, no device, seconds on CPU), walk every nested
pjit/custom_vjp/pallas_call sub-jaxpr, and evaluate a rule registry over
the jaxpr plus the kernel/block/layout metadata PRs 1–3 already record.
The same pass is the CI gate that keeps those PRs' wins from regressing.

Public surface:

* :func:`lint_fn` — lint any callable (traced with the given abstract
  args); jaxpr rules only.
* :func:`lint_perf_model` — lint a perf-zoo model end-to-end: builds the
  model (LMs get the flash kernel forced on so the TPU-projected trace
  is analyzed even off-chip), constructs the donated SGD train step the
  perf harness runs, traces it abstractly, and evaluates jaxpr + module
  rules. The ``bigdl-tpu lint`` CLI and the perf ``--lint`` pre-flight
  call this.
* :func:`preflight_optimizer` — lint a built
  :class:`~bigdl_tpu.optim.Optimizer` before ``optimize()`` (the
  training CLIs' ``--lint`` flag): module rules always; the real
  ``_build_step`` product is traced when the dataset exposes its batch
  geometry without consuming the shuffle stream.

Findings: :class:`~bigdl_tpu.analysis.report.Finding` /
:class:`~bigdl_tpu.analysis.report.Report`; the rule catalog with
severities lives in :data:`bigdl_tpu.analysis.rules.CATALOG`
(documented in PERF.md §12).
"""

from __future__ import annotations

from typing import Optional

from bigdl_tpu.analysis.report import Finding, Report, SEVERITIES
from bigdl_tpu.analysis.rules import (CATALOG, assert_blocks_tileable,
                                      check_block_padding,
                                      check_block_tiling, min_sublane,
                                      run_comm_rules, run_decode_rules,
                                      run_jaxpr_rules,
                                      run_memory_rules, run_module_rules,
                                      run_serving_tp_rules)

__all__ = ["Finding", "Report", "SEVERITIES", "CATALOG",
           "check_block_tiling", "check_block_padding",
           "assert_blocks_tileable", "min_sublane",
           "run_jaxpr_rules", "run_module_rules", "run_comm_rules",
           "run_memory_rules", "run_decode_rules",
           "run_serving_tp_rules",
           "lint_fn", "trace_train_step", "lint_perf_model",
           "preflight_optimizer"]


def lint_fn(fn, *args, report: Optional[Report] = None, **kwargs) -> Report:
    """Trace ``fn(*args, **kwargs)`` abstractly (args may be arrays or
    ``jax.ShapeDtypeStruct``) and run every jaxpr rule. Pass an already-
    jitted ``fn`` to get donation analysis of its pjit boundary."""
    import jax

    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    return run_jaxpr_rules(closed, report)


def trace_train_step(model, in_shape, batch, *, dtype=None, is_lm=False,
                     vocab: int = 32000, donate=(0, 1, 2)):
    """ClosedJaxpr of the canonical SGD train step over ``model`` at
    ``batch`` x ``in_shape`` — the same step shape the perf harness
    compiles (donated (params, mod_state, opt_state), bf16 activations
    by default, fp32 loss). Everything abstract: params/opt-state come
    from ``jax.eval_shape``, inputs are ShapeDtypeStructs; nothing is
    allocated or executed."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu import nn
    from bigdl_tpu.optim import SGD

    dtype = jnp.bfloat16 if dtype is None else dtype
    crit = (nn.TimeDistributedCriterion(nn.ClassNLLCriterion()) if is_lm
            else nn.ClassNLLCriterion())
    opt = SGD(learning_rate=0.01, momentum=0.9)

    if is_lm:
        if dtype == jnp.bfloat16:
            model.compute_dtype = dtype  # cast lives after the embedding
        x = jax.ShapeDtypeStruct((batch, *in_shape), jnp.int32)
        y = jax.ShapeDtypeStruct((batch, *in_shape), jnp.int32)
    else:
        x = jax.ShapeDtypeStruct((batch, *in_shape), jnp.float32)
        y = jax.ShapeDtypeStruct((batch,), jnp.int32)

    key = jax.random.PRNGKey(0)
    params = jax.eval_shape(model.init, key)
    mod_state = model.init_state()
    opt_state = jax.eval_shape(opt.init, params)

    def train_step(params, mod_state, opt_state, x, y, rng):
        def loss_fn(p):
            xc = (x.astype(dtype)
                  if jnp.issubdtype(x.dtype, jnp.floating) else x)
            out, ms = model.apply(p, mod_state, xc, training=True, rng=rng)
            return crit(out.astype(jnp.float32), y), ms

        (loss, ms), grads = jax.value_and_grad(loss_fn,
                                               has_aux=True)(params)
        new_p, new_o = opt.update(grads, opt_state, params)
        return new_p, ms, new_o, loss

    step = (jax.jit(train_step, donate_argnums=donate) if donate
            else jax.jit(train_step))
    return jax.make_jaxpr(step)(params, mod_state, opt_state, x, y, key)


def _bn_fallback_rule(model, closed, report: Report) -> None:
    """Model+jaxpr combo rule: fused BN was requested, eligible sites
    exist, but fewer forward kernels were traced than sites — some (or
    all) silently fell back to the jnp path (rows untileable at this
    batch)."""
    from bigdl_tpu.analysis.jaxpr_walk import (iter_levels,
                                               pallas_kernel_name)
    from bigdl_tpu.nn.norm import BatchNormalization

    sites = [m for m in model.modules()
             if isinstance(m, BatchNormalization) and m.fused
             and m.affine and m.axis_name is None and not m.stat_sample
             and int(m.n_output) % 128 == 0]
    if not sites:
        return
    fwd_names = {"_fba_fwd_kernel", "_stats_kernel"}
    traced = 0
    for lv in iter_levels(closed):
        for eqn in lv.jaxpr.eqns:
            if eqn.primitive.name == "pallas_call" \
                    and pallas_kernel_name(eqn) in fwd_names:
                traced += 1
    if traced < len(sites):
        report.add(Finding(
            rule="tile-bn-fallback", family="tiling",
            severity="warning",
            message=(f"fused BN requested on {len(sites)} eligible "
                     f"site(s) but only {traced} fused stats/apply "
                     "kernel(s) traced — the rest fell back to the jnp "
                     "path (rows % row-block != 0 at this batch)"),
            hint="--autotune measure can unlock smaller legal row "
                 "blocks; or pick a batch whose rows tile",
            detail={"eligible_sites": len(sites),
                    "traced_kernels": traced}))


def lint_perf_model(name: str, batch: int = 32, *, seq_len=None,
                    dtype=None, fused_bn=None, classes: int = 1000,
                    trace: bool = True, strategy=None,
                    grad_compress=None) -> Report:
    """Full lint of one perf-zoo model (see module docstring). LMs are
    built with ``attn_impl='flash'`` forced so the TPU-projected kernels
    appear in the CPU trace; ``trace=False`` skips the jaxpr pass
    (module rules only — used when only configuration is in question).
    ``strategy``/``grad_compress`` are the perf CLI's spec strings; when
    a multi-device strategy is requested the gradient-communication
    rules run over the abstract param tree (PERF.md §17)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.cli.common import apply_fused_bn
    from bigdl_tpu.cli.perf import build_model

    dtype = jnp.bfloat16 if dtype is None else dtype
    model, in_shape = build_model(name, class_num=classes,
                                  seq_len=seq_len, lm_attn_impl="flash")
    apply_fused_bn(model, fused_bn)
    is_lm = name.startswith("transformer_lm")
    seq = in_shape[0] if is_lm else None

    report = Report()
    dtname = jnp.dtype(dtype).name
    run_module_rules(model, report, seq=seq, dtype=dtname)
    if strategy is not None:
        from bigdl_tpu.cli.common import parse_strategy_spec

        strat_name, _ = parse_strategy_spec(strategy)
        params = jax.eval_shape(model.init, jax.random.PRNGKey(0))
        run_comm_rules(params, strat_name, grad_compress, report)
    if trace:
        closed = trace_train_step(model, in_shape, batch, dtype=dtype,
                                  is_lm=is_lm)
        run_jaxpr_rules(closed, report)
        _bn_fallback_rule(model, closed, report)
    # HBM working-set rule (ISSUE 12): abstract plan over the same
    # state pytrees the perf step would hold — argument-side categories
    # only (no compilation), so "plan exceeds HBM" fires pre-compile
    try:
        from bigdl_tpu.obs import memory
        from bigdl_tpu.optim import SGD

        params = jax.eval_shape(model.init, jax.random.PRNGKey(0))
        opt_state = jax.eval_shape(
            SGD(learning_rate=0.01, momentum=0.9).init, params)
        if is_lm:
            x = jax.ShapeDtypeStruct((batch, *in_shape), jnp.int32)
            y = jax.ShapeDtypeStruct((batch, *in_shape), jnp.int32)
        else:
            x = jax.ShapeDtypeStruct((batch, *in_shape), jnp.float32)
            y = jax.ShapeDtypeStruct((batch,), jnp.int32)
        plan = memory.build_plan(params=params, opt_state=opt_state,
                                 batch=(x, y), batch_size=batch,
                                 model_name=name)
        run_memory_rules(plan, report)
    except Exception as e:
        report.add(Finding(
            rule="lint-trace-error", family="meta", severity="info",
            message=f"memory rules skipped ({type(e).__name__}: {e})",
            hint="the jaxpr/module rules still ran"))
    return report


def preflight_optimizer(opt) -> Report:
    """Lint a built Optimizer before it trains (the training CLIs'
    ``--lint`` pre-flight). Module rules always run; the jaxpr pass runs
    when the step can be traced without side effects: single-device
    strategy and a dataset exposing ``features``/``labels``/
    ``batch_size`` (reading them, unlike pulling a batch, does not
    advance the shuffle RNG that step-equivalent resume depends on)."""
    import numpy as np

    report = Report()
    dtname = ("bfloat16" if getattr(opt, "compute_dtype", None) is not None
              else "float32")
    run_module_rules(opt.model, report, dtype=dtname)

    if opt.strategy is not None:
        try:
            import jax

            from bigdl_tpu.parallel import DataParallel, TensorParallel

            if isinstance(opt.strategy, TensorParallel):
                strat_name = "tp"
            elif isinstance(opt.strategy, DataParallel):
                strat_name = "dp"
            else:
                strat_name = None
            cfg = getattr(opt.strategy, "grad_comm", None)
            compress = cfg.compress if cfg is not None else None
            params = jax.eval_shape(opt.model.init, jax.random.PRNGKey(0))
            run_comm_rules(params, strat_name, compress, report)
        except Exception as e:
            report.add(Finding(
                rule="lint-trace-error", family="meta", severity="info",
                message=f"comm rules skipped ({type(e).__name__}: {e})",
                hint="module-level rules still ran"))

    ds = opt.dataset
    feats = getattr(ds, "features", None)
    labs = getattr(ds, "labels", None)
    bs = getattr(ds, "batch_size", None)
    if opt.strategy is not None or feats is None or labs is None or not bs:
        return report
    try:
        import jax

        from bigdl_tpu.ops.conv2d import policy_snapshot, restore_policy

        x = jax.ShapeDtypeStruct((int(bs),) + tuple(feats.shape[1:]),
                                 np.asarray(feats).dtype)
        y = jax.ShapeDtypeStruct((int(bs),) + tuple(labs.shape[1:]),
                                 np.asarray(labs).dtype)
        snap = policy_snapshot()
        try:
            step, _ = opt._build_step()
            key = jax.random.PRNGKey(0)
            params = jax.eval_shape(opt.model.init, key)
            mod_state = opt.model.init_state()
            opt_state = jax.eval_shape(opt.optim_method.init, params)
            closed = jax.make_jaxpr(step)(params, mod_state, opt_state,
                                          x, y, key)
        finally:
            restore_policy(snap)
        run_jaxpr_rules(closed, report)
        _bn_fallback_rule(opt.model, closed, report)
    except Exception as e:  # surface, never block training on lint bugs
        report.add(Finding(
            rule="lint-trace-error", family="meta", severity="info",
            message=f"step trace skipped ({type(e).__name__}: {e})",
            hint="module-level rules still ran"))
    return report
