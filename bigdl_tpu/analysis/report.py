"""Finding/Report containers for the tpulint static-analysis pass.

A :class:`Finding` is one rule hit: rule id, family, severity, a
human message, *provenance* (``where`` — an eqn path inside the traced
jaxpr, or a module path inside the model tree), a fix hint, and a
free-form ``detail`` dict (counts, byte totals, example sites). A
:class:`Report` is the ordered collection the CLI renders (human table
via ``utils/table.format_table``, or JSON), summarizes into perf-JSON
provenance (``annotation()`` — stamped next to ``bn_fused``/``autotune``
in every perf line), and turns into an exit code (``--lint=strict`` =
nonzero on any error-severity finding).

The reference's analog is the Spark-side config validation that failed a
job at submit time instead of hours in (PAPER §BigDL operability); here
the "submit time" is a CPU-only trace, seconds instead of a chip run.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Iterable, List, Optional

__all__ = ["SEVERITIES", "Finding", "Report"]

# ordered most → least severe; strict mode fails on "error" only
SEVERITIES = ("error", "warning", "info")


@dataclass
class Finding:
    rule: str            # catalog id, e.g. "fusion-bn-unfused"
    family: str          # rule family: dtype|donation|tiling|fusion|layout|host-sync|meta
    severity: str        # one of SEVERITIES
    message: str         # one-line human statement of the problem
    where: str = ""      # eqn path / module path provenance
    hint: str = ""       # how to fix (flag spelling, API call)
    detail: dict = field(default_factory=dict)

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(f"severity must be one of {SEVERITIES}, "
                             f"got {self.severity!r}")

    def to_json(self) -> dict:
        out = {"rule": self.rule, "family": self.family,
               "severity": self.severity, "message": self.message}
        if self.where:
            out["where"] = self.where
        if self.hint:
            out["hint"] = self.hint
        if self.detail:
            out["detail"] = self.detail
        return out


class Report:
    """Ordered findings + the summaries every consumer needs."""

    def __init__(self, findings: Optional[Iterable[Finding]] = None):
        self.findings: List[Finding] = list(findings or [])

    def add(self, finding: Finding) -> "Report":
        self.findings.append(finding)
        return self

    def extend(self, findings: Iterable[Finding]) -> "Report":
        self.findings.extend(findings)
        return self

    def count(self, severity: str) -> int:
        return sum(1 for f in self.findings if f.severity == severity)

    @property
    def errors(self) -> int:
        return self.count("error")

    @property
    def warnings(self) -> int:
        return self.count("warning")

    def families(self) -> List[str]:
        """Distinct families with at least one finding, first-hit order."""
        seen: List[str] = []
        for f in self.findings:
            if f.family not in seen:
                seen.append(f.family)
        return seen

    def by_family(self, family: str) -> List[Finding]:
        return [f for f in self.findings if f.family == family]

    def by_rule(self, rule: str) -> List[Finding]:
        return [f for f in self.findings if f.rule == rule]

    def sorted(self) -> List[Finding]:
        """Severity-major (errors first), then family, stable within."""
        rank = {s: i for i, s in enumerate(SEVERITIES)}
        return sorted(self.findings,
                      key=lambda f: (rank[f.severity], f.family))

    # ------------------------------------------------------------ outputs
    def annotation(self) -> dict:
        """Compact provenance for perf JSON lines (the ``lint`` field,
        stamped like ``bn_fused``/``autotune`` decisions are)."""
        return {"errors": self.errors, "warnings": self.warnings,
                "infos": self.count("info"),
                "rules": sorted({f.rule for f in self.findings})}

    def to_json(self) -> dict:
        return {"summary": self.annotation(),
                "families": self.families(),
                "findings": [f.to_json() for f in self.sorted()]}

    def render(self) -> str:
        """Human table (severity-sorted) + one summary line."""
        from bigdl_tpu.utils.table import format_table

        if not self.findings:
            return "lint: no findings"
        rows = [[f.severity.upper(), f.rule, f.message,
                 f.where, f.hint] for f in self.sorted()]
        table = format_table(
            ["severity", "rule", "finding", "where", "fix hint"], rows)
        summary = (f"lint: {self.errors} error(s), {self.warnings} "
                   f"warning(s), {self.count('info')} info(s) across "
                   f"{len(self.families())} rule familie(s)")
        return f"{table}\n{summary}"

    def dump_json(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=2, sort_keys=False)
            f.write("\n")

    def exit_code(self, strict: bool = False) -> int:
        """0 unless strict and at least one error-severity finding."""
        return 2 if (strict and self.errors) else 0
