"""tpulint rule registry — TPU perf/correctness anti-patterns caught at
trace time on CPU, before a chip is ever touched (ISSUE 4 tentpole).

Two kinds of rules share one catalog:

* **jaxpr rules** (:func:`run_jaxpr_rules`) walk the traced ClosedJaxpr
  of a train/eval step — every nested pjit/custom_vjp/pallas_call level —
  and fire on equation-level evidence: bf16→f32 upcasts re-reading large
  activations, scalar captures that promote a bf16 path, un-donated step
  buffers (~2x HBM), Pallas blocks that violate the Mosaic minimum-tile
  rules or pad their arrays, per-kernel VMEM working sets near the
  budget, and host callbacks inside the step.
* **module rules** (:func:`run_module_rules`) walk the model tree with
  the kernel/eligibility metadata PRs 1–3 already expose
  (``ops/conv2d.resolve_site_layouts``, ``ops/bn_kernel`` tileability,
  ``ops/attention_kernel.flash_block_plan``) and fire on configuration:
  BN sites eligible for the fused apply block running unfused, GEMM-
  eligible 1x1 convs resolving to a spatial layout, channel/head dims
  off the 128-lane grid, ragged sequences that knock attention off the
  flash kernel.

Every finding carries rule id, family, severity, provenance and a fix
hint (:mod:`bigdl_tpu.analysis.report`). Severity policy: **error** =
measured-regression configs and compile-on-chip hazards (unfused
apply-eligible BN, illegal/padded Pallas tiles, ragged-seq kernel
fallback, host sync in the step) — ``--lint=strict`` refuses to launch
on these; **warning** = costs worth a look (missing donation, large
upcasts, VMEM pressure, GEMM opportunity); **info** = grid-fit notes.

The shared tile checkers (:func:`check_block_tiling`,
:func:`assert_blocks_tileable`) are also THE source of truth the kernel
tests assert through — previously each test file carried its own copy of
the (8,128)/(16,128) modulus asserts (ISSUE 4 satellite).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.analysis.jaxpr_walk import (aval_bytes, consumers_map,
                                           iter_levels, pallas_block_views,
                                           pallas_kernel_name,
                                           pallas_scratch_avals)
from bigdl_tpu.analysis.report import Finding, Report

__all__ = ["CATALOG", "run_jaxpr_rules", "run_module_rules",
           "run_comm_rules", "run_memory_rules", "run_decode_rules",
           "run_serving_tp_rules",
           "check_block_tiling", "check_block_padding",
           "assert_blocks_tileable", "min_sublane",
           "UPCAST_MIN_BYTES", "DONATE_MIN_BYTES", "VMEM_BUDGET_BYTES",
           "COMM_F32_MIN_BYTES", "COMM_MAX_COLLECTIVES",
           "HBM_WARN_FRAC", "SERVING_TP_MIN_BYTES"]

# rule id -> (family, severity, one-line catalog description)
CATALOG: Dict[str, Tuple[str, str, str]] = {
    "dtype-upcast": (
        "dtype", "warning",
        "large bf16→f32 convert feeding a leading-axis reduction or a "
        "matmul/conv — the activation crosses HBM again at 2x width "
        "(the unfused-BN stats pattern, PERF.md §2)"),
    "dtype-weak-scalar": (
        "dtype", "warning",
        "a captured f32 scalar promotes a large bf16 tensor to f32 — "
        "use a python scalar or cast the constant to bf16"),
    "donate-missing": (
        "donation", "warning",
        "jitted step keeps non-donated input buffers whose shape/dtype "
        "match outputs (params/opt-state round-trip) — ~2x HBM for the "
        "train state"),
    "donate-ok": (
        "donation", "info",
        "step donates its round-tripping buffers (the "
        "optim/optimizer.py + parallel/data_parallel.py contract)"),
    "tile-min": (
        "tiling", "error",
        "Pallas block violates the Mosaic minimum-tile rule "
        "((8,128) f32 / (16,128) bf16 / (32,128) int8, or block dim == "
        "array dim) — lowers in interpret mode, compile-fails on chip"),
    "tile-pad": (
        "tiling", "error",
        "Pallas block does not divide its array dim — Mosaic pads every "
        "block and the kernel burns the padding fraction (the s=768 "
        "q-block case, ADVICE r5 #2)"),
    "tile-ragged-attn": (
        "tiling", "error",
        "sequence not lane-tileable — attention silently leaves the "
        "flash kernel for the remat-scan/dense fallback"),
    "tile-bn-ineligible": (
        "tiling", "info",
        "BN site cannot take the single-read kernel (C % 128 != 0); the "
        "jnp path re-reads the activation per pass"),
    "vmem-budget": (
        "tiling", "warning",
        "per-program VMEM working set (double-buffered blocks + scratch) "
        "near the ~16 MiB budget — spills or compile failure on chip"),
    "tile-seq-clamp": (
        "tiling", "info",
        "sequence clamps the flash blocks below the 512 default (the "
        "s=768 fix: 256-blocks instead of padded 1024-blocks)"),
    "fusion-bn-unfused": (
        "fusion", "error",
        "BatchNormalization site eligible for the fused apply block "
        "(fused='apply', PERF.md §10) is running unfused/stats — the "
        "measured-regression config"),
    "tile-bn-fallback": (
        "tiling", "warning",
        "fused BN requested but sites fell back to the jnp path (rows "
        "not tileable at this batch) — the fusion silently isn't "
        "happening"),
    "fusion-conv-gemm": (
        "fusion", "warning",
        "GEMM-eligible 1x1/s1 conv resolves to a spatial layout — "
        "lax.dot_general lowering available (PERF.md §11)"),
    "fusion-attn-dense": (
        "fusion", "info",
        "attention runs the dense XLA path; the Pallas flash kernel is "
        "available (attn_impl='flash')"),
    "layout-c128": (
        "layout", "info",
        "feature dims off the 128-lane grid — MXU tiles are padded, "
        "waste estimated via utils/flops.conv_unit_flops"),
    "layout-headdim": (
        "layout", "info",
        "attention head_dim is not a multiple of 128 — the MXU "
        "contracts over it half-filled (hd128 A/B: +24% tok/s, "
        "PERF.md §8.2)"),
    "host-sync": (
        "host-sync", "error",
        "host callback inside the step — every dispatch round-trips "
        "through the host (tunneled-runtime cost: ~2.5-3.5 ms each)"),
    "comm-f32-allreduce": (
        "comm", "warning",
        "multi-device strategy reduces >=1 MiB gradient buckets in f32 "
        "with compression off — twice the wire bytes the 16-bit codec "
        "path (--gradCompress bf16) would move"),
    "comm-unbucketed": (
        "comm", "warning",
        "gradient reduction is per-leaf (>16 collectives in one step "
        "graph / unbucketed grad tree) — per-collective launch latency "
        "is paid per parameter instead of per dense bucket"),
    "hbm-oversubscribed": (
        "memory", "error",
        "the compiled step's working set (obs/memory.build_plan) "
        "exceeds the device HBM — the run will RESOURCE_EXHAUST on "
        "first dispatch; caught pre-compile on CPU"),
    "hbm-tight": (
        "memory", "warning",
        "the compiled step's working set is within 15% of the device "
        "HBM — fragmentation or a live-buffer spike will tip it over "
        "(obs/memory forecasts the max batch that still fits)"),
    "lint-trace-error": (
        "meta", "info",
        "the step could not be traced; only module-level rules ran"),
    "decode-sampling-sort": (
        "decode", "warning",
        "full-vocab sort inside the per-token decode step — top-k/top-p "
        "warping pays O(V log V) per slot per token; at large vocab the "
        "sampler dominates the step (serve only the sort-free program "
        "to greedy/temperature traffic, or filter on a partial "
        "threshold)"),
    "kv-page-misfit": (
        "decode", "warning",
        "KV page token size misfits the layout: off the 8-sublane grid "
        "every pool page pads its tile, and when neither the flash "
        "block_k nor the page divides the other, K blocks straddle "
        "page boundaries in the gathered view (kv_page_plan)"),
    "quant-dequant-upcast": (
        "dtype", "error",
        "a dequantized int8/fp8 weight is re-materialized as f32 "
        "feeding a matmul whose other operand was upcast from bf16 — "
        "the dequant epilogue defeats the 8-bit storage AND drags the "
        "activation to f32; dequantize into the activation dtype "
        "instead (quant._QView does)"),
    "serving-unsharded-matmul": (
        "serving", "error",
        "tp-strategy serving graph carries a >=1 MiB matmul weight with "
        "fully-replicated placement — every chip runs the full matmul "
        "and tp buys nothing for it (a megatron_specs divisibility gate "
        "fell back to replication); alias of the mesh-aware "
        "shard-replicated-operand rule (ISSUE 19), kept for stable "
        "serve --lint output"),
}

UPCAST_MIN_BYTES = 2 * 1024 * 1024    # ignore small/scalar converts
DONATE_MIN_BYTES = 1 * 1024 * 1024    # per-buffer floor for the HBM rule
VMEM_BUDGET_BYTES = 16 * 1024 * 1024  # ~16 MB/core (pallas_guide.md)
VMEM_WARN_FRAC = 0.8
COMM_F32_MIN_BYTES = 1 * 1024 * 1024  # grad wire worth compressing
COMM_MAX_COLLECTIVES = 16             # per-leaf-reduce smell threshold
HBM_WARN_FRAC = 0.85                  # plan/HBM ratio that earns hbm-tight
DECODE_SORT_MIN_LANES = 16384         # vocab size where the warp sort bites
SERVING_TP_MIN_BYTES = 1 * 1024 * 1024  # matmul weight worth sharding

_SUBLANE = {4: 8, 2: 16, 1: 32}


def min_sublane(*dtypes) -> int:
    """Mosaic's minimum sublane count across dtypes (8 for 4-byte, 16
    for bf16, 32 for int8) — shared with ops/bn_kernel's private copy."""
    need = 8
    for d in dtypes:
        need = max(need, _SUBLANE.get(np.dtype(d).itemsize, 8))
    return need


def _finding(rule: str, message: str, where: str = "", hint: str = "",
             detail: Optional[dict] = None,
             severity: Optional[str] = None) -> Finding:
    family, default_sev, _ = CATALOG[rule]
    return Finding(rule=rule, family=family,
                   severity=severity or default_sev, message=message,
                   where=where, hint=hint, detail=detail or {})


# ======================================================== shared checkers
def check_block_tiling(block_shape: Sequence, array_shape: Sequence,
                       dtype=np.float32) -> List[str]:
    """Problems (empty = legal) with ONE Pallas block against the Mosaic
    tiling rules: over the last two dims, the lane dim must be a multiple
    of 128 or equal the array dim, and the sublane dim a multiple of the
    dtype's minimum (8/16/32) or equal the array dim. The single source
    of truth the kernel tests assert through (previously copied per test
    file)."""
    probs: List[str] = []
    bs, ashape = tuple(block_shape), tuple(array_shape)
    if len(bs) < 1 or len(ashape) < 1:
        return probs
    pairs = list(zip(bs[-2:], ashape[-2:]))
    if not all(isinstance(b, (int, np.integer)) and
               isinstance(a, (int, np.integer)) for b, a in pairs):
        return probs  # squeezed/symbolic dims: nothing to check
    b_lane, a_lane = pairs[-1]
    if not (b_lane == a_lane or b_lane % 128 == 0):
        probs.append(f"lane dim {b_lane} not %128 and != array dim "
                     f"{a_lane}")
    if len(pairs) == 2:
        ms = min_sublane(dtype)
        b_sub, a_sub = pairs[0]
        if not (b_sub == a_sub or b_sub % ms == 0):
            probs.append(f"sublane dim {b_sub} not %{ms} "
                         f"(dtype {np.dtype(dtype).name}) and != array "
                         f"dim {a_sub}")
    return probs


def check_block_padding(block_shape: Sequence, array_shape: Sequence
                        ) -> float:
    """Padding-waste fraction (0.0 = none) a block induces over the last
    two dims: Mosaic rounds each dim up to a whole number of blocks."""
    real, padded = 1.0, 1.0
    for b, a in zip(tuple(block_shape)[-2:], tuple(array_shape)[-2:]):
        if not (isinstance(b, (int, np.integer)) and
                isinstance(a, (int, np.integer))) or b <= 0 or a <= 0:
            return 0.0
        real *= a
        padded *= -(-a // b) * b
    return 0.0 if padded <= real else 1.0 - real / padded


def assert_blocks_tileable(pairs: Iterable[Tuple[Sequence, Sequence]],
                           dtype=np.float32) -> None:
    """Raise AssertionError listing every (block, array) pair that fails
    :func:`check_block_tiling` — the spelling the kernel tests use."""
    bad = []
    for bs, ashape in pairs:
        probs = check_block_tiling(bs, ashape, dtype)
        if probs:
            bad.append((tuple(bs), tuple(ashape), probs))
    assert not bad, f"Mosaic-illegal blocks: {bad}"


# =========================================================== jaxpr rules
_REDUCE_PRIMS = ("reduce_sum", "reduce_max", "reduce_min", "reduce_prod")
_MATMUL_PRIMS = ("dot_general", "conv_general_dilated")
_BINARY_PRIMS = ("add", "sub", "mul", "div", "max", "min", "pow")
_CALLBACK_PRIMS = ("pure_callback", "io_callback", "debug_callback",
                   "infeed", "outfeed")


def _dtype_name(aval) -> str:
    d = getattr(aval, "dtype", None)
    return np.dtype(d).name if d is not None else ""


def _rule_dtype_upcast(levels, report: Report) -> None:
    hits = []
    total = 0
    for lv in levels:
        cmap = consumers_map(lv.jaxpr)
        for i, eqn in enumerate(lv.jaxpr.eqns):
            if eqn.primitive.name != "convert_element_type":
                continue
            if _dtype_name(eqn.invars[0].aval) != "bfloat16":
                continue
            if np.dtype(eqn.params.get("new_dtype")).name != "float32":
                continue
            out = eqn.outvars[0]
            b = aval_bytes(out.aval)
            if b < UPCAST_MIN_BYTES:
                continue
            cons = cmap.get(out, [])
            interesting = False
            for c in cons:
                if c.primitive.name in _MATMUL_PRIMS:
                    interesting = True
                elif c.primitive.name in _REDUCE_PRIMS:
                    nd = len(getattr(out.aval, "shape", ()))
                    axes = tuple(c.params.get("axes", ()))
                    # leading-axis reductions are the BN-stats pattern;
                    # a last-axis reduce is the (expected) fp32 softmax
                    if nd and (nd - 1) not in axes:
                        interesting = True
            if interesting:
                hits.append(lv.where(i, eqn))
                total += b
    if hits:
        report.add(_finding(
            "dtype-upcast",
            f"{len(hits)} bf16→f32 upcast(s) totalling "
            f"{total / 2**20:.0f} MiB feed leading-axis reductions or "
            "matmuls — the activation crosses HBM again at 2x width",
            where="; ".join(hits[:4]) + ("…" if len(hits) > 4 else ""),
            hint="fuse the consumer (e.g. --fusedBN apply keeps the "
                 "upcast inside one kernel) or keep the chain in bf16",
            detail={"count": len(hits), "bytes": total,
                    "sites": hits[:16]}))


def _rule_weak_scalar(levels, report: Report) -> None:
    """Type promotion inserts the upcast BEFORE the mixing op, so the
    pattern in the jaxpr is: convert(bf16→f32) whose consumer is a
    binary elementwise op against a STRONG f32 scalar (an np.float32
    constant captured from python; a plain python scalar stays weak and
    never forces the promotion)."""
    hits = []
    for lv in levels:
        cmap = consumers_map(lv.jaxpr)
        for i, eqn in enumerate(lv.jaxpr.eqns):
            if eqn.primitive.name != "convert_element_type":
                continue
            if _dtype_name(eqn.invars[0].aval) != "bfloat16":
                continue
            if np.dtype(eqn.params.get("new_dtype")).name != "float32":
                continue
            out = eqn.outvars[0]
            if aval_bytes(out.aval) < UPCAST_MIN_BYTES:
                continue
            for c in cmap.get(out, []):
                if c.primitive.name not in _BINARY_PRIMS \
                        or len(c.invars) != 2:
                    continue
                other = (c.invars[0] if c.invars[1] is out
                         else c.invars[1])
                oav = getattr(other, "aval", None)
                if getattr(oav, "shape", None) == () \
                        and _dtype_name(oav) == "float32":
                    hits.append(lv.where(i, eqn))
                    break
    if hits:
        report.add(_finding(
            "dtype-weak-scalar",
            f"{len(hits)} op(s) promote a large bf16 tensor to f32 via "
            "a captured f32 scalar",
            where="; ".join(hits[:4]) + ("…" if len(hits) > 4 else ""),
            hint="use a plain python scalar (weak-typed, stays bf16) or "
                 "cast the constant to the tensor dtype",
            detail={"count": len(hits), "sites": hits[:16]}))


def _rule_donation(closed, report: Report) -> None:
    """Top-level pjit eqns only: the traced step itself (nested jits
    don't round-trip the train state)."""
    jaxpr = getattr(closed, "jaxpr", closed)
    for i, eqn in enumerate(jaxpr.eqns):
        if eqn.primitive.name != "pjit":
            continue
        donated = eqn.params.get("donated_invars")
        if not donated:
            donated = (False,) * len(eqn.invars)
        out_counts: Dict[tuple, int] = {}
        for v in eqn.outvars:
            shape = getattr(v.aval, "shape", None)
            key = (shape, _dtype_name(v.aval))
            out_counts[key] = out_counts.get(key, 0) + 1
        missing = donated_bytes = 0
        n_missing = 0
        for v, d in zip(eqn.invars, donated):
            b = aval_bytes(getattr(v, "aval", None))
            if d:
                donated_bytes += b
                continue
            key = (getattr(v.aval, "shape", None), _dtype_name(v.aval))
            if b >= DONATE_MIN_BYTES and out_counts.get(key, 0) > 0:
                out_counts[key] -= 1
                missing += b
                n_missing += 1
        name = eqn.params.get("name") or "step"
        if missing:
            report.add(_finding(
                "donate-missing",
                f"pjit:{name} keeps {n_missing} non-donated buffer(s) "
                f"({missing / 2**20:.0f} MiB) whose shape/dtype "
                "round-trip to outputs — params/opt-state live twice "
                "in HBM",
                where=f"pjit:{name}#{i}",
                hint="jax.jit(step, donate_argnums=(0, 1, 2)) — the "
                     "optim/optimizer.py:394 / data_parallel.py:180 "
                     "entry points already do",
                detail={"bytes": missing, "buffers": n_missing}))
        elif donated_bytes:
            report.add(_finding(
                "donate-ok",
                f"pjit:{name} donates {donated_bytes / 2**20:.0f} MiB "
                "of round-tripping train state",
                where=f"pjit:{name}#{i}",
                detail={"bytes": donated_bytes}))


def _rule_pallas(levels, report: Report) -> None:
    for lv in levels:
        for i, eqn in enumerate(lv.jaxpr.eqns):
            if eqn.primitive.name != "pallas_call":
                continue
            where = lv.where(i, eqn)
            kname = pallas_kernel_name(eqn) or "pallas_call"
            views = pallas_block_views(eqn)
            tile_probs, pad_notes = [], []
            block_bytes = 0
            for bs, ashape, dtype, is_out in views:
                ints = [int(d) for d in bs
                        if isinstance(d, (int, np.integer))]
                block_bytes += int(np.prod(ints or [0])) * dtype.itemsize
                for p in check_block_tiling(bs, ashape, dtype):
                    tile_probs.append(f"block {tuple(bs)} on "
                                      f"{tuple(ashape)}: {p}")
                waste = check_block_padding(bs, ashape)
                if waste > 0.0:
                    pad_notes.append(
                        f"block {tuple(bs)} pads {tuple(ashape)} "
                        f"({waste * 100:.0f}% wasted)")
            if tile_probs:
                report.add(_finding(
                    "tile-min",
                    f"kernel {kname}: {len(tile_probs)} Mosaic-illegal "
                    f"block(s): {tile_probs[0]}",
                    where=where,
                    hint="use a (>=min-sublane, >=128) tile or make the "
                         "block dim equal the array dim",
                    detail={"problems": tile_probs}))
            if pad_notes:
                report.add(_finding(
                    "tile-pad",
                    f"kernel {kname}: {pad_notes[0]}",
                    where=where,
                    hint="clamp the block to a divisor of the array dim "
                         "(ops/attention_kernel._clamp_block is the "
                         "pattern) or pad the data once at the edge",
                    detail={"padded": pad_notes}))
            scratch = sum(aval_bytes(a) for a in pallas_scratch_avals(eqn))
            # streamed in/out blocks are double-buffered by Pallas;
            # scratch is single-instance
            working_set = 2 * block_bytes + scratch
            if working_set > VMEM_WARN_FRAC * VMEM_BUDGET_BYTES:
                report.add(_finding(
                    "vmem-budget",
                    f"kernel {kname}: ~{working_set / 2**20:.1f} MiB "
                    f"VMEM working set (budget ~"
                    f"{VMEM_BUDGET_BYTES / 2**20:.0f} MiB)",
                    where=where,
                    hint="shrink the block sizes (--autotune measure "
                         "searches the legal grid)",
                    detail={"bytes": working_set,
                            "block_bytes": block_bytes,
                            "scratch_bytes": scratch}))


# explicit cross-device reduction primitives (shard_map/pmap graphs —
# jit-SPMD traces carry none; the partitioner inserts those later, which
# is what run_comm_rules covers at the config level)
_COLLECTIVE_PRIMS = ("psum", "ppermute", "all_gather", "all_to_all",
                     "reduce_scatter", "psum_scatter", "pmax", "pmin")


def _rule_collectives(levels, report: Report) -> None:
    """Count explicit collective eqns in the step graph: more than
    COMM_MAX_COLLECTIVES means the reduction is per-leaf — the dense-
    bucket accumulation grad_comm does (and the reference's partitioned
    all-reduce did) amortizes that launch latency away."""
    hits = []
    for lv in levels:
        for i, eqn in enumerate(lv.jaxpr.eqns):
            if eqn.primitive.name in _COLLECTIVE_PRIMS:
                hits.append(lv.where(i, eqn))
    if len(hits) > COMM_MAX_COLLECTIVES:
        report.add(_finding(
            "comm-unbucketed",
            f"{len(hits)} collective op(s) in one step graph (threshold "
            f"{COMM_MAX_COLLECTIVES}) — per-leaf reduction pays launch "
            "latency per parameter",
            where="; ".join(hits[:4]) + ("…" if len(hits) > 4 else ""),
            hint="bucket the grads into dense size-bounded buffers "
                 "(parallel/grad_comm; --gradCompress enables it)",
            detail={"count": len(hits), "sites": hits[:16]}))


def _rule_host_sync(levels, report: Report) -> None:
    for lv in levels:
        for i, eqn in enumerate(lv.jaxpr.eqns):
            if eqn.primitive.name in _CALLBACK_PRIMS:
                report.add(_finding(
                    "host-sync",
                    f"{eqn.primitive.name} inside the step — the "
                    "dispatch stalls on a host round-trip every "
                    "iteration",
                    where=lv.where(i, eqn),
                    hint="move host I/O outside the jitted step (log "
                         "from returned scalars; debug prints only "
                         "under a debug flag)"))


# prims a dequant chain routes through between the convert and the
# matmul: the scale multiply/add, layout moves, and the converts
# themselves — anything else breaks the chain (it's no longer "the
# dequantized weight", it's a computed tensor)
_DEQUANT_PASSTHRU = ("convert_element_type", "mul", "add", "transpose",
                     "reshape", "broadcast_in_dim")
_QUANT_SRC_DTYPES = ("int8", "float8_e4m3fn", "float8_e5m2")


def _convert_sources(var, produced_by, max_depth: int = 8) -> set:
    """Source dtype names of every convert_element_type on ``var``'s
    producer chain, walking back through :data:`_DEQUANT_PASSTHRU`
    prims only (bounded depth — dequant epilogues are shallow)."""
    out: set = set()
    stack = [(var, 0)]
    seen: set = set()
    while stack:
        v, d = stack.pop()
        if d > max_depth or id(v) in seen:
            continue
        seen.add(id(v))
        eqn = produced_by.get(id(v))
        if eqn is None or eqn.primitive.name not in _DEQUANT_PASSTHRU:
            continue
        if eqn.primitive.name == "convert_element_type":
            out.add(_dtype_name(eqn.invars[0].aval))
        for iv in eqn.invars:
            if getattr(iv, "count", None) is not None:  # Var, not Literal
                stack.append((iv, d + 1))
    return out


def _rule_quant_dequant_upcast(levels, report: Report) -> None:
    """ISSUE 17: a dot_general where one operand traces back to an
    int8/fp8 -> wide convert (the dequant) AND the other to a bf16 ->
    f32 convert means the epilogue was folded in f32 — the matmul runs
    at 2x the activation width for no accuracy reason. The quant module
    dequantizes into the ACTIVATION dtype, which never hits this."""
    hits = []
    for lv in levels:
        produced_by = {}
        for eqn in lv.jaxpr.eqns:
            for ov in eqn.outvars:
                produced_by[id(ov)] = eqn
        for i, eqn in enumerate(lv.jaxpr.eqns):
            if eqn.primitive.name != "dot_general":
                continue
            if len(eqn.invars) < 2:
                continue
            srcs = [_convert_sources(v, produced_by)
                    for v in eqn.invars[:2]]
            for a, b in ((0, 1), (1, 0)):
                if (any(s in _QUANT_SRC_DTYPES for s in srcs[a])
                        and "bfloat16" in srcs[b]
                        and _dtype_name(eqn.invars[b].aval)
                        == "float32"):
                    hits.append(lv.where(i, eqn))
                    break
    if hits:
        report.add(_finding(
            "quant-dequant-upcast",
            f"{len(hits)} matmul(s) pair a f32-rematerialized "
            "dequantized weight with a bf16-upcast activation — the "
            "contraction runs at f32 width, defeating both the 8-bit "
            "storage and the bf16 compute dtype",
            where="; ".join(hits[:4]) + ("…" if len(hits) > 4 else ""),
            hint="dequantize into the activation dtype "
                 "(w.astype(x.dtype), the serving/quant.py epilogue) "
                 "or take the native int8 dot_general path",
            detail={"count": len(hits), "sites": hits[:16]}))


def _rule_decode_sort(levels, report: Report) -> None:
    for lv in levels:
        for i, eqn in enumerate(lv.jaxpr.eqns):
            if eqn.primitive.name != "sort":
                continue
            aval = eqn.invars[0].aval
            lanes = int(aval.shape[-1]) if getattr(aval, "shape", ()) \
                else 0
            if lanes >= DECODE_SORT_MIN_LANES:
                report.add(_finding(
                    "decode-sampling-sort",
                    f"sort over {lanes} lanes in the decode step "
                    "(top-k/top-p warp) — O(V log V) per slot per "
                    "token",
                    where=lv.where(i, eqn),
                    hint="route greedy/temperature-only traffic "
                         "through the sort-free step program (the "
                         "engine picks per round); consider a "
                         "threshold-filter sampler at this vocab",
                    detail={"lanes": lanes}))


def run_decode_rules(closed=None, *, page_tokens: Optional[int] = None,
                     max_len: Optional[int] = None,
                     head_dim: Optional[int] = None, dtype=None,
                     report: Optional[Report] = None) -> Report:
    """Decode-hot-path rules (ISSUE 14), run by the serve preflight
    before the first request: equation-level anti-patterns in the traced
    decode step (``DecodeEngine.trace_step_jaxpr()``) — host callbacks
    (error: a per-token host round-trip caps tokens/s at the tunnel
    latency) and full-vocab sampling sorts (warning) — plus the static
    page-layout fit against the flash block plan when paging is on."""
    report = report if report is not None else Report()
    if closed is not None:
        levels = list(iter_levels(closed))
        _rule_host_sync(levels, report)
        _rule_decode_sort(levels, report)
    if page_tokens and max_len and head_dim:
        from bigdl_tpu.ops.attention_kernel import kv_page_plan
        plan = kv_page_plan(page_tokens, max_len, head_dim,
                            dtype if dtype is not None else np.float32)
        problems = []
        if not plan["sublane_ok"]:
            problems.append(f"page_tokens {page_tokens} % "
                            f"{plan.get('sublane', 8)} != 0 "
                            "(padded sublanes on every pool page)")
        if not plan["block_aligned"]:
            problems.append(
                f"page_tokens {page_tokens} vs flash block_k "
                f"{plan['block_k']}: neither divides the other — K "
                "blocks straddle page boundaries")
        if problems:
            report.add(_finding(
                "kv-page-misfit", "; ".join(problems),
                where=f"kv_pages(page_tokens={page_tokens}, "
                      f"max_len={max_len})",
                hint="pick --kvPageTokens from the tuned ladder "
                     "(tuning.kv_page_tokens: 32/64/128/256, 8-aligned "
                     "and block-commensurate) or 'auto'",
                detail=plan))
    return report


def run_serving_tp_rules(params, n_shard: int,
                         report: Optional[Report] = None) -> Report:
    """Tensor-parallel serving placement rules (ISSUE 16), run by the
    serve preflight when ``--strategy tp:K`` (K > 1) is active, over the
    PLACED param tree (leaves are committed ``jax.Array``s carrying
    their sharding). Like :func:`run_comm_rules`, this reads placement
    rather than the jaxpr: jit-SPMD traces carry no sharding eqns, but
    the committed weights ARE the serving graph's matmul operands — a
    >=1 MiB weight matrix left fully replicated under tp means every
    chip runs that matmul whole (a ``megatron_specs`` divisibility gate
    fell back), which is exactly the perf bug worth refusing to serve.

    Since ISSUE 19 this is an alias of the mesh-aware
    :func:`bigdl_tpu.analysis.sharding_rules.run_replicated_operand_rules`
    (training + serving, any mesh), kept so the serve preflight output
    and its tests stay byte-stable."""
    from bigdl_tpu.analysis.sharding_rules import \
        run_replicated_operand_rules

    report = report if report is not None else Report()
    if n_shard <= 1:
        return report
    return run_replicated_operand_rules(
        params, {"model": int(n_shard)}, split_axes=("model",),
        rule_id="serving-unsharded-matmul", report=report)


def run_jaxpr_rules(closed, report: Optional[Report] = None) -> Report:
    """All equation-level rules over one traced ClosedJaxpr (the step,
    or any fn traced via :func:`bigdl_tpu.analysis.lint_fn`)."""
    report = report if report is not None else Report()
    levels = list(iter_levels(closed))
    _rule_donation(closed, report)
    _rule_dtype_upcast(levels, report)
    _rule_weak_scalar(levels, report)
    _rule_pallas(levels, report)
    _rule_host_sync(levels, report)
    _rule_collectives(levels, report)
    _rule_quant_dequant_upcast(levels, report)
    return report


# ============================================================ comm rules
def run_comm_rules(params, strategy: Optional[str],
                   grad_compress: Optional[str] = None,
                   report: Optional[Report] = None) -> Report:
    """Gradient-communication rules over one run CONFIGURATION (ISSUE
    10): jit-SPMD traces carry no collective eqns — the partitioner
    inserts the grad all-reduce after lint runs — so what f32 bytes
    would cross the wire is derived from the param tree + strategy +
    --gradCompress instead of from the jaxpr. ``params`` may be real or
    abstract (jax.eval_shape) leaves."""
    report = report if report is not None else Report()
    if strategy not in ("dp", "tp", "sp"):
        return report  # pp/ep own their comm structure; single-device
        # runs have no grad wire
    compress = grad_compress or "off"
    from bigdl_tpu.parallel.grad_comm import (DEFAULT_BUCKET_BYTES,
                                              build_bucket_plan)
    plan = build_bucket_plan(params, DEFAULT_BUCKET_BYTES)
    if compress == "off":
        big = [b for b in plan.buckets if b.nbytes >= COMM_F32_MIN_BYTES]
        if big:
            total = sum(b.nbytes for b in plan.buckets)
            report.add(_finding(
                "comm-f32-allreduce",
                f"--strategy {strategy} all-reduces "
                f"{total / 2**20:.1f} MiB of gradient in f32 "
                f"({len(big)} bucket(s) >= "
                f"{COMM_F32_MIN_BYTES / 2**20:.0f} MiB) with "
                "compression off",
                where=f"grad tree: {plan.n_leaves} leaves, "
                      f"{len(plan.buckets)} bucket(s)",
                hint="--gradCompress bf16 halves the wire bytes "
                     "(bf16+ec keeps optimizer math exactly f32)",
                detail={"bytes_f32": total,
                        "big_buckets": len(big),
                        "n_leaves": plan.n_leaves}))
        n_inexact = plan.n_leaves - len(plan.passthrough)
        if n_inexact > COMM_MAX_COLLECTIVES:
            report.add(_finding(
                "comm-unbucketed",
                f"{n_inexact} gradient leaves reduce without bucketing "
                f"(threshold {COMM_MAX_COLLECTIVES}) — per-leaf "
                "collectives pay launch latency per parameter",
                where=f"grad tree: {plan.n_leaves} leaves",
                hint="--gradCompress bf16 packs them into "
                     f"{len(plan.buckets)} dense bucket(s)",
                detail={"n_leaves": n_inexact,
                        "n_buckets": len(plan.buckets)}))
    return report


# ========================================================= memory rules
def run_memory_rules(plan: Optional[dict],
                     report: Optional[Report] = None) -> Report:
    """HBM working-set rules over one memory plan (ISSUE 12): ``plan``
    is an :func:`bigdl_tpu.obs.memory.build_plan` dict — built from
    abstract pytrees + ``compiled.memory_analysis()``, so it is exact on
    CPU before a chip is touched. Fires **error** when the plan's total
    exceeds the device HBM (the run would RESOURCE_EXHAUST on first
    dispatch) and **warning** above ``HBM_WARN_FRAC`` of capacity.
    ``plan=None`` (plan construction failed) adds nothing."""
    report = report if report is not None else Report()
    if not plan:
        return report
    total = int(plan.get("total_bytes") or 0)
    hbm = int(plan.get("hbm_bytes") or 0)
    if not total or not hbm:
        return report
    frac = total / hbm
    cats = plan.get("categories") or {}
    top = sorted(cats.items(), key=lambda kv: -kv[1])[:3]
    top_s = ", ".join(f"{k} {v / 2**20:.0f} MiB" for k, v in top)
    where = (f"{plan.get('model') or 'step'} b={plan.get('batch')} on "
             f"{plan.get('device') or 'device'}")
    if frac > 1.0:
        report.add(_finding(
            "hbm-oversubscribed",
            f"step working set {total / 2**30:.2f} GiB exceeds device "
            f"HBM {hbm / 2**30:.1f} GiB ({frac * 100:.0f}%) — top: "
            f"{top_s}",
            where=where,
            hint="shrink the batch (bigdl-tpu explain --mem predicts "
                 "the max that fits), drop --optim momentum state, or "
                 "shard the model (--strategy tp)",
            detail={"total_bytes": total, "hbm_bytes": hbm,
                    "frac": round(frac, 4),
                    "categories": dict(cats)}))
    elif frac > HBM_WARN_FRAC:
        report.add(_finding(
            "hbm-tight",
            f"step working set {total / 2**30:.2f} GiB is "
            f"{frac * 100:.0f}% of device HBM {hbm / 2**30:.1f} GiB "
            f"(threshold {HBM_WARN_FRAC * 100:.0f}%) — top: {top_s}",
            where=where,
            hint="headroom this thin ooms on fragmentation; "
                 "bigdl-tpu explain --mem forecasts the fit per batch",
            detail={"total_bytes": total, "hbm_bytes": hbm,
                    "frac": round(frac, 4)}))
    return report


# ========================================================== module rules
def _mod_label(m) -> str:
    n = getattr(m, "name", None)
    cls = type(m).__name__
    return f"{cls}({n})" if n and n != cls else cls


def _ceil128(n: int) -> int:
    return -(-int(n) // 128) * 128


def _rule_bn(model, report: Report) -> None:
    from bigdl_tpu.nn.norm import BatchNormalization

    unfused, ineligible = [], []
    for m in model.modules():
        if not isinstance(m, BatchNormalization):
            continue
        c = int(m.n_output)
        kernel_ok = (m.affine and m.axis_name is None
                     and not m.stat_sample and c % 128 == 0)
        if not kernel_ok:
            if c % 128:
                ineligible.append((f"{_mod_label(m)} C={c}", c))
            continue
        if m.fused != "apply":
            mode = m.fused or "off"
            unfused.append((f"{_mod_label(m)} C={c} fused={mode}", c))
    if unfused:
        report.add(_finding(
            "fusion-bn-unfused",
            f"{len(unfused)} BatchNormalization site(s) eligible for "
            "the fused apply block are running "
            f"{'/'.join(sorted({s.rsplit('=', 1)[-1] for s, _ in unfused}))}"
            " — the config PERF.md §10 measured as the regression",
            where="; ".join(s for s, _ in unfused[:4])
                  + ("…" if len(unfused) > 4 else ""),
            hint="--fusedBN apply (CLI) / set_bn_fused(model, 'apply')",
            detail={"count": len(unfused),
                    "channels": sorted({c for _, c in unfused})}))
    if ineligible:
        report.add(_finding(
            "tile-bn-ineligible",
            f"{len(ineligible)} BN site(s) with C % 128 != 0 "
            f"(C in {sorted({c for _, c in ineligible})}) cannot take "
            "the single-read kernel",
            where="; ".join(s for s, _ in ineligible[:4])
                  + ("…" if len(ineligible) > 4 else ""),
            hint="widen the channel plan to the 128-lane grid where the "
                 "architecture allows",
            detail={"count": len(ineligible)}))


def _conv_geom_args(m) -> tuple:
    """(kh, kw, stride, padding, dilation, groups, cin, cout) of one
    SpatialConvolution-family module."""
    dil = (int(getattr(m, "dilation_h", 1)), int(getattr(m, "dilation_w", 1)))
    return (int(m.kernel_h), int(m.kernel_w),
            (int(m.stride_h), int(m.stride_w)),
            ((int(m.pad_h), int(m.pad_h)), (int(m.pad_w), int(m.pad_w))),
            dil, int(m.n_group),
            int(m.n_input_plane), int(m.n_output_plane))


def _rule_conv_gemm(model, report: Report, dtype="bfloat16") -> None:
    from bigdl_tpu.nn.conv import SpatialConvolution
    from bigdl_tpu.ops.conv2d import gemm_eligible, resolve_site_layouts

    hits = []
    for m in model.modules():
        if not isinstance(m, SpatialConvolution):
            continue
        kh, kw, stride, pad, dil, groups, cin, cout = _conv_geom_args(m)
        if not gemm_eligible(kh, kw, stride, pad, dil, groups):
            continue
        lays = resolve_site_layouts(kh, kw, stride, pad, dil, groups,
                                    cin, cout, dtype)
        spatial = [p for p, l in lays.items() if l != "GEMM"]
        if spatial:
            hits.append((f"{_mod_label(m)} {cin}->{cout} "
                         f"passes={','.join(spatial)}", cin, cout))
    if hits:
        report.add(_finding(
            "fusion-conv-gemm",
            f"{len(hits)} GEMM-eligible 1x1/s1 conv site(s) resolve to "
            "a spatial layout — the dot_general lowering (~half of "
            "ResNet-50's FLOPs live in these sites) is not engaged",
            where="; ".join(s for s, _, _ in hits[:4])
                  + ("…" if len(hits) > 4 else ""),
            hint="--convLayout with GEMM per pass, a --convGeom decision "
                 "file, or --autotune measure on chip",
            detail={"count": len(hits)}))


def _rule_channels(model, report: Report) -> None:
    from bigdl_tpu.nn.conv import SpatialConvolution
    from bigdl_tpu.nn.linear import Linear
    from bigdl_tpu.utils.flops import conv_unit_flops

    hits = []
    for m in model.modules():
        if isinstance(m, SpatialConvolution):
            kh, kw, _, _, _, groups, cin, cout = _conv_geom_args(m)
        elif isinstance(m, Linear):
            kh = kw = groups = 1
            cin, cout = int(m.in_features), int(m.out_features)
        else:
            continue
        if cin % 128 == 0 and cout % 128 == 0:
            continue
        real = conv_unit_flops(1, 1, 1, cin, cout, kh, kw, groups)
        padded = conv_unit_flops(1, 1, 1, _ceil128(cin), _ceil128(cout),
                                 kh, kw, groups)
        waste = 1.0 - real / padded
        hits.append((waste, f"{_mod_label(m)} {cin}->{cout} "
                            f"(~{waste * 100:.0f}% padded MXU tiles)"))
    if hits:
        hits.sort(reverse=True)
        report.add(_finding(
            "layout-c128",
            f"{len(hits)} layer(s) with feature dims off the 128-lane "
            f"grid; worst: {hits[0][1]}",
            where="; ".join(s for _, s in hits[:4])
                  + ("…" if len(hits) > 4 else ""),
            hint="edge layers (stems, heads) are usually unavoidable; "
                 "interior channel plans should stay on multiples of 128",
            detail={"count": len(hits),
                    "worst_waste": round(hits[0][0], 3)}))


def _rule_attention(model, report: Report, seq: Optional[int],
                    dtype="bfloat16") -> None:
    try:
        from bigdl_tpu.nn.attention import MultiHeadAttention
    except Exception:
        return
    from bigdl_tpu.nn.attention import dot_product_attention
    from bigdl_tpu.ops.attention_kernel import flash_attention

    dense, ragged, clamped, headdims = [], [], [], {}
    for m in model.modules():
        if not isinstance(m, MultiHeadAttention):
            continue
        hd = int(m.head_dim)
        if hd % 128:
            headdims[hd] = headdims.get(hd, 0) + 1
        # the constructor resolves attn_impl into self.attn_fn
        fn = getattr(m, "attn_fn", None)
        if fn is None or fn is dot_product_attention:
            dense.append(_mod_label(m))
            continue
        if fn is not flash_attention or not seq:
            continue  # custom/blockwise impls: the user chose them
        from bigdl_tpu.ops.attention_kernel import flash_block_plan
        plan = flash_block_plan(seq, seq, hd, bool(m.causal), dtype)
        if not plan["kernel_ok"]:
            ragged.append((_mod_label(m), plan))
        elif plan["q_pad"] or plan["k_pad"]:
            waste = plan["q_pad"] / (seq + plan["q_pad"])
            report.add(_finding(
                "tile-pad",
                f"{_mod_label(m)}: flash q/k blocks "
                f"({plan['block_q']},{plan['block_k']}) pad seq {seq} "
                f"(~{waste * 100:.0f}% wasted rows)",
                where=_mod_label(m),
                hint="pick a seq the blocks divide, or explicit "
                     "block_q/block_k that divide it"))
        elif plan["clamped"]:
            clamped.append((_mod_label(m), plan))
    if dense:
        report.add(_finding(
            "fusion-attn-dense",
            f"{len(dense)} attention site(s) on the dense XLA path",
            where="; ".join(dense[:4]) + ("…" if len(dense) > 4 else ""),
            hint="attn_impl='flash' (the perf zoo enables it on TPU)",
            detail={"count": len(dense)}))
    if clamped:
        label, plan = clamped[0]
        report.add(_finding(
            "tile-seq-clamp",
            f"{len(clamped)} attention site(s): seq {seq} clamps flash "
            f"blocks to ({plan['block_q']},{plan['block_k']}) — fine, "
            "but a 512-divisible seq keeps the measured-best tiling",
            where=label,
            detail={"count": len(clamped), "block_q": plan["block_q"],
                    "block_k": plan["block_k"]}))
    if ragged:
        label, plan = ragged[0]
        report.add(_finding(
            "tile-ragged-attn",
            f"{len(ragged)} attention site(s): seq {seq} does not tile "
            f"(block_k={plan['block_k']}) — the flash kernel silently "
            "falls back to the remat-scan path",
            where="; ".join(l for l, _ in ragged[:4])
                  + ("…" if len(ragged) > 4 else ""),
            hint="pad/pack sequences to a multiple of 128 "
                 "(dataset.text.pack_sequences) or accept the fallback",
            detail={"seq": seq, "count": len(ragged),
                    **{k: plan[k] for k in ("block_q", "block_k")}}))
    if headdims:
        report.add(_finding(
            "layout-headdim",
            "attention head_dim in "
            f"{sorted(headdims)} half-fills the MXU's 128-wide tiles "
            "(hd128 A/B measured +24% tok/s, PERF.md §8.2)",
            where=f"{sum(headdims.values())} attention site(s)",
            hint="same d_model with fewer, 128-wide heads "
                 "(e.g. transformer_lm_1k_hd128)",
            detail={"head_dims": sorted(headdims)}))


def run_module_rules(model, report: Optional[Report] = None, *,
                     seq: Optional[int] = None,
                     dtype="bfloat16") -> Report:
    """All configuration-level rules over one model tree. ``seq`` (the
    traced sequence length, when known) enables the attention block-plan
    checks; ``dtype`` keys the conv-geometry resolution."""
    report = report if report is not None else Report()
    _rule_bn(model, report)
    _rule_conv_gemm(model, report, dtype=dtype)
    _rule_channels(model, report)
    _rule_attention(model, report, seq, dtype=dtype)
    return report


# shardlint (ISSUE 19) shares this catalog: merge its rule family in so
# the CLI's rule listing and report grouping see one registry
from bigdl_tpu.analysis.sharding_rules import \
    SHARD_CATALOG as _SHARD_CATALOG  # noqa: E402

CATALOG.update(_SHARD_CATALOG)
