"""Normalization layers (reference nn/{BatchNormalization,SpatialCrossMapLRN,...}.scala).

BatchNormalization carries running statistics in the module *state* pytree —
the functional replacement for the reference's mutable runningMean/runningVar
buffers (nn/BatchNormalization.scala, 625 LoC). Its per-channel Engine
threading (:151,220,435,523) is XLA's fusion problem now.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.core.module import Module, SimpleModule

__all__ = [
    "BatchNormalization",
    "set_bn_stat_sample",
    "set_bn_fused",
    "bn_fused_mode",
    "unfuse_bn_for_spmd",
    "SpatialBatchNormalization",
    "SpatialCrossMapLRN",
    "SpatialSubtractiveNormalization",
    "SpatialDivisiveNormalization",
    "SpatialContrastiveNormalization",
    "Normalize",
]


def _canon_fused(fused) -> "bool | str":
    """Normalize the ``fused`` knob: False/None/"off" → False (jnp path),
    True/"stats" → "stats" (single-read stats kernel), "apply" → "apply"
    (the full fused block)."""
    if fused in (False, None, "off"):
        return False
    if fused in (True, "stats"):
        return "stats"
    if fused == "apply":
        return "apply"
    raise ValueError(f"fused must be one of False/'off'/True/'stats'/"
                     f"'apply', got {fused!r}")


class BatchNormalization(Module):
    """Batch normalization over the feature (last) axis
    (reference nn/BatchNormalization.scala; defaults eps=1e-5, momentum=0.1,
    affine=true match the reference's constructor).

    State = {running_mean, running_var}; training mode updates them with the
    reference's EMA rule ``r = (1-m)*r + m*batch_stat`` and normalizes by the
    *batch* statistics; eval mode normalizes by the running statistics.

    Distributed note: under the jit-SPMD :class:`~bigdl_tpu.parallel
    .DataParallel` strategy, leave ``axis_name=None`` — the batch mean/var
    reductions there are *global* ops over the sharded batch, so XLA already
    computes exact global-batch statistics (sync-BN for free; the reference's
    per-executor clones used local stats). ``axis_name`` exists for
    shard_map/pmap execution, where reductions are per-shard and must be
    pmean'd across the named axis.
    """

    reduce_axes: tuple = (0,)

    def __init__(self, n_output: int, eps: float = 1e-5, momentum: float = 0.1,
                 affine: bool = True, axis_name: Optional[str] = None,
                 gamma_init: float = 1.0, stat_sample: Optional[int] = None,
                 fused=False, name: Optional[str] = None):
        super().__init__(name)
        self.n_output = n_output
        self.eps, self.momentum, self.affine = eps, momentum, affine
        self.axis_name = axis_name
        self.gamma_init = gamma_init
        # fused routes training-mode BN through the Pallas kernels
        # (ops/bn_kernel.py). Modes: False/"off" = jnp (XLA fuses);
        # True/"stats" = single-read stats kernel, apply/dx in jnp (the
        # round-4 lever, measured NEGATIVE on chip — PERF.md §8.2);
        # "apply" = the FULL fused block (ISSUE 2): stats+apply(+absorbed
        # ReLU, see ``fuse_relu``) one kernel forward, reductions+dx one
        # kernel backward. Single-device jit only: under SPMD-sharded
        # batches a pallas_call does not auto-partition (use axis_name +
        # shard_map for sync-BN instead), and it composes with neither
        # axis_name nor stat_sample.
        self.fused = _canon_fused(fused)
        # set by nn.structural.absorb_bn_relu when this BN swallowed the
        # ReLU that followed it in a Sequential chain; EVERY code path
        # (fused or jnp, train or eval) then applies the ReLU here, so
        # semantics survive mode flips and the SPMD unfuse fallback
        self.fuse_relu = False
        # stat_sample=k: training statistics from the first k batch rows
        # only. The stats pass re-reads every activation from HBM (the
        # dominant BN cost on TPU — PERF.md §2); a subset cuts that read
        # by batch/k while the normalize stays exact. Statistically this
        # is the reference's per-executor local-stats BN (each clone
        # normalized by a batch fraction). Throughput lever — leave None
        # for exact full-batch stats.
        self.stat_sample = stat_sample

    def init(self, rng):
        if not self.affine:
            return {}
        del rng
        # reference init: weight=1, bias=0 (BatchNormalization.reset);
        # gamma_init=0 gives the zero-init-residual recipe for ResNet
        return {"weight": jnp.full((self.n_output,), self.gamma_init,
                                   jnp.float32),
                "bias": jnp.zeros((self.n_output,), jnp.float32)}

    def init_state(self):
        return {"running_mean": jnp.zeros((self.n_output,), jnp.float32),
                "running_var": jnp.ones((self.n_output,), jnp.float32)}

    def apply(self, params, state, x, *, training=False, rng=None):
        axes = tuple(range(x.ndim - 1))  # all but features
        if (training and self.fused and self.affine
                and self.axis_name is None and not self.stat_sample):
            if self.fused == "apply":
                from bigdl_tpu.ops.bn_kernel import fused_bn_apply_train

                y, mean, var = fused_bn_apply_train(
                    x, params["weight"], params["bias"], self.eps,
                    bool(self.fuse_relu))
            else:
                from bigdl_tpu.ops.bn_kernel import fused_bn_train

                y, mean, var = fused_bn_train(x, params["weight"],
                                              params["bias"], self.eps)
                if self.fuse_relu:
                    y = jnp.maximum(y, jnp.zeros((), y.dtype))
            m = self.momentum
            n = x.size // x.shape[-1]
            unbiased = var * n / max(1, n - 1)
            new_state = {
                "running_mean": (1 - m) * state["running_mean"] + m * mean,
                "running_var": (1 - m) * state["running_var"] + m * unbiased,
            }
            return y, new_state
        xf = x.astype(jnp.float32)
        if training:
            k = self.stat_sample
            xs = xf if (not k or k >= xf.shape[0]) else xf[:k]
            mean = jnp.mean(xs, axis=axes)
            mean_sq = jnp.mean(jnp.square(xs), axis=axes)
            if self.axis_name is not None:
                # cross-replica moments (not per-shard variances!) — sync-BN
                mean = lax.pmean(mean, self.axis_name)
                mean_sq = lax.pmean(mean_sq, self.axis_name)
            var = mean_sq - jnp.square(mean)
            m = self.momentum
            n = xs.size // xs.shape[-1]
            if self.axis_name is not None:
                n = n * lax.psum(1, self.axis_name)  # global sample count
            unbiased = var * n / jnp.maximum(1, n - 1)
            new_state = {
                "running_mean": (1 - m) * state["running_mean"] + m * mean,
                "running_var": (1 - m) * state["running_var"] + m * unbiased,
            }
        else:
            mean, var = state["running_mean"], state["running_var"]
            new_state = state
        inv = lax.rsqrt(var + self.eps)
        if self.affine:
            scale = inv * params["weight"]
            shift = params["bias"] - mean * scale
        else:
            scale = inv
            shift = -mean * scale
        y = xf * scale + shift
        if self.fuse_relu:  # absorbed ReLU: applies on EVERY path
            y = jnp.maximum(y, 0.0)
        return y.astype(x.dtype), new_state


def set_bn_stat_sample(module, k: Optional[int]):
    """Set ``stat_sample`` on every BatchNormalization in a module tree
    (post-construction — saves threading the knob through every model
    builder). Returns the module."""
    for m in module.modules():
        if isinstance(m, BatchNormalization):
            m.stat_sample = k
    return module


def set_bn_fused(module, fused=True):
    """Route every BatchNormalization through a Pallas BN path
    (ops/bn_kernel.py; single-device jit — see the ``fused`` constructor
    note). ``fused``: True/"stats" = the single-read stats kernel,
    "apply" = the FULL fused block (stats+apply+absorbed-ReLU forward,
    reductions+dx backward — ISSUE 2), False/"off" = back to jnp.
    "apply" additionally rewrites Sequential chains so a ReLU directly
    following a BN is absorbed into the kernel epilogue
    (:func:`~bigdl_tpu.nn.structural.absorb_bn_relu`); the rewrite is
    sticky — flipping back to "stats"/off keeps semantics because the BN
    applies the absorbed ReLU on every path. Returns the module."""
    mode = _canon_fused(fused)
    for m in module.modules():
        if isinstance(m, BatchNormalization):
            m.fused = mode
    if mode == "apply":
        from bigdl_tpu.nn.structural import absorb_bn_relu
        absorb_bn_relu(module)
    return module


def bn_fused_mode(module) -> str:
    """The model's effective BN fusion mode for result-JSON provenance:
    "apply" if any BatchNormalization runs the full fused block, else
    "stats" if any runs the stats kernel, else "off" (also for models
    with no BN at all)."""
    modes = {m.fused for m in module.modules()
             if isinstance(m, BatchNormalization)}
    if "apply" in modes:
        return "apply"
    if "stats" in modes:
        return "stats"
    return "off"


def unfuse_bn_for_spmd(module, n_devices: int) -> int:
    """Disable ``fused`` (Pallas) BN stats before compiling a step over a
    multi-device mesh: ``pallas_call`` carries no GSPMD partitioning rule,
    so a batch-sharded activation would be replicated onto every device
    (memory/perf cliff) or fail to lower — defeating the kernel's purpose.
    Called by the Optimizer's distributed compile path; returns the number
    of modules switched back to the jnp path. Covers both "stats" and
    "apply" modes; an absorbed ReLU (``fuse_relu``) keeps applying on the
    jnp path, so the fallback is semantics-preserving."""
    count = 0
    if n_devices > 1:
        for m in module.modules():
            if isinstance(m, BatchNormalization) and m.fused:
                m.fused = False
                count += 1
    return count


class SpatialBatchNormalization(BatchNormalization):
    """BN over NHWC with per-channel stats (reference
    nn/SpatialBatchNormalization.scala) — identical reduction (all axes but
    channels), kept as a distinct class for model-zoo parity."""


class SpatialCrossMapLRN(SimpleModule):
    """Local response normalization across channels
    (reference nn/SpatialCrossMapLRN.scala, 221 LoC):
    ``y = x / (k + alpha/size * sum_{local window} x^2)^beta``.

    Implemented as a channel-axis reduce_window — one fused XLA op chain
    (memory-bound; XLA's fusion already keeps it at bandwidth, so no
    custom kernel is warranted)."""

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75,
                 k: float = 1.0, name: Optional[str] = None):
        super().__init__(name)
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k

    def _forward(self, params, x, *, training, rng):
        sq = jnp.square(x)
        half = (self.size - 1) // 2
        sums = lax.reduce_window(
            sq, 0.0, lax.add,
            (1, 1, 1, self.size), (1, 1, 1, 1),
            ((0, 0), (0, 0), (0, 0), (half, self.size - 1 - half)))
        denom = jnp.power(self.k + (self.alpha / self.size) * sums, self.beta)
        return x / denom


def _gaussian_kernel2d(size: int, dtype=jnp.float32):
    """Normalized 2-D gaussian window, sigma = 0.25*size, matching Torch's
    image.gaussian default the reference layers use."""
    sigma = 0.25 * size
    r = jnp.arange(size, dtype=dtype) - (size - 1) / 2.0
    g = jnp.exp(-0.5 * jnp.square(r / sigma))
    k = jnp.outer(g, g)
    return k / jnp.sum(k)


class SpatialSubtractiveNormalization(SimpleModule):
    """Subtract a weighted local mean per channel
    (reference nn/SpatialSubtractiveNormalization.scala, 196 LoC)."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.n_input_plane = n_input_plane
        self.kernel = kernel if kernel is not None else _gaussian_kernel2d(9)

    def _local_mean(self, x):
        k = jnp.asarray(self.kernel, x.dtype)
        k = k / jnp.sum(k)
        kh, kw = k.shape
        # depthwise conv: same kernel per channel
        w = jnp.tile(k[:, :, None, None], (1, 1, 1, self.n_input_plane))
        mean = lax.conv_general_dilated(
            x, w, (1, 1),
            padding=((kh // 2, (kh - 1) // 2), (kw // 2, (kw - 1) // 2)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=self.n_input_plane)
        # edge correction: divide by the actual kernel mass inside the image
        ones = jnp.ones_like(x[:1, :, :, :1])
        mass = lax.conv_general_dilated(
            ones, k[:, :, None, None], (1, 1),
            padding=((kh // 2, (kh - 1) // 2), (kw // 2, (kw - 1) // 2)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return mean / jnp.maximum(mass, 1e-8)

    def _forward(self, params, x, *, training, rng):
        return x - self._local_mean(x)


class SpatialDivisiveNormalization(SimpleModule):
    """Divide by local standard deviation
    (reference nn/SpatialDivisiveNormalization.scala, 211 LoC)."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 threshold: float = 1e-4, name: Optional[str] = None):
        super().__init__(name)
        self.sub = SpatialSubtractiveNormalization(n_input_plane, kernel)
        self.threshold = threshold

    def _forward(self, params, x, *, training, rng):
        local_var = self.sub._local_mean(jnp.square(x))
        local_std = jnp.sqrt(jnp.maximum(local_var, 0.0))
        # reference thresholds by max(mean(std), threshold) per sample
        mean_std = jnp.mean(local_std, axis=(1, 2, 3), keepdims=True)
        denom = jnp.maximum(local_std, jnp.maximum(mean_std, self.threshold))
        return x / denom


class SpatialContrastiveNormalization(SimpleModule):
    """Subtractive then divisive normalization
    (reference nn/SpatialContrastiveNormalization.scala)."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 threshold: float = 1e-4, name: Optional[str] = None):
        super().__init__(name)
        self.sub = SpatialSubtractiveNormalization(n_input_plane, kernel)
        self.div = SpatialDivisiveNormalization(n_input_plane, kernel, threshold)

    def _forward(self, params, x, *, training, rng):
        y = self.sub._forward({}, x, training=training, rng=rng)
        return self.div._forward({}, y, training=training, rng=rng)


class Normalize(SimpleModule):
    """Lp-normalize rows to unit norm (reference nn/Normalize.scala)."""

    def __init__(self, p: float = 2.0, eps: float = 1e-10,
                 name: Optional[str] = None):
        super().__init__(name)
        self.p, self.eps = p, eps

    def _forward(self, params, x, *, training, rng):
        if self.p == float("inf"):
            n = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
        else:
            n = jnp.power(jnp.sum(jnp.power(jnp.abs(x), self.p), axis=-1,
                                  keepdims=True), 1.0 / self.p)
        return x / jnp.maximum(n, self.eps)
