"""Convolution layers (reference nn/Spatial*Convolution*.scala).

TPU-native design: NHWC activations, HWIO kernels, a single
``lax.conv_general_dilated`` per layer. The reference's im2col + gemm
pipeline (nn/SpatialConvolution.scala:403-430 via NNPrimitive.im2colFloat)
and its per-sample Engine threading (:175,233,296) do not exist here — XLA
lowers the conv directly onto the MXU with its own tiling, which is the whole
point of the redesign. Grouped conv maps to ``feature_group_count``; the
``_1x1`` aliasing fast path (:66-71) is an XLA fusion, not our code.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.core.module import SimpleModule, uniform_fan_in, xavier_uniform

__all__ = [
    "SpatialConvolution",
    "SpatialShareConvolution",
    "SpatialFullConvolution",
    "SpatialDilatedConvolution",
    "SpatialConvolutionMap",
    "TemporalConvolution",
]

DIMSPEC = ("NHWC", "HWIO", "NHWC")


class SpatialConvolution(SimpleModule):
    """2-D convolution (reference nn/SpatialConvolution.scala, 574 LoC).

    Args mirror the reference: (n_input_plane, n_output_plane, kernel_w,
    kernel_h, stride_w, stride_h, pad_w, pad_h, n_group). Weight shape is
    HWIO ``(kh, kw, nin/groups, nout)`` instead of the reference's
    ``[group][nOut/g][nIn/g][kH][kW]`` (:48-49) — same degrees of freedom,
    laid out for the MXU.

    Default init matches the reference reset(): U(+-1/sqrt(kW*kH*nIn)) for
    "default", Xavier over fan_in/fan_out for "xavier"
    (nn/SpatialConvolution.scala:88-103).
    """

    def __init__(
        self,
        n_input_plane: int,
        n_output_plane: int,
        kernel_w: int,
        kernel_h: int,
        stride_w: int = 1,
        stride_h: int = 1,
        pad_w: int = 0,
        pad_h: int = 0,
        n_group: int = 1,
        with_bias: bool = True,
        init: str = "default",
        param_dtype=jnp.float32,
        name: Optional[str] = None,
    ):
        super().__init__(name)
        assert n_input_plane % n_group == 0 and n_output_plane % n_group == 0
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel_w, self.kernel_h = kernel_w, kernel_h
        self.stride_w, self.stride_h = stride_w, stride_h
        self.pad_w, self.pad_h = pad_w, pad_h
        self.n_group = n_group
        self.with_bias = with_bias
        self.init_method = init
        self.param_dtype = param_dtype

    def _kernel_shape(self):
        return (self.kernel_h, self.kernel_w,
                self.n_input_plane // self.n_group, self.n_output_plane)

    def init(self, rng):
        k_w, k_b = jax.random.split(rng)
        fan_in = self.kernel_w * self.kernel_h * (self.n_input_plane // self.n_group)
        fan_out = self.kernel_w * self.kernel_h * (self.n_output_plane // self.n_group)
        shape = self._kernel_shape()
        if self.init_method == "xavier":
            w = xavier_uniform(k_w, shape, fan_in, fan_out, self.param_dtype)
        else:
            w = uniform_fan_in(k_w, shape, fan_in, self.param_dtype)
        p = {"weight": w}
        if self.with_bias:
            p["bias"] = uniform_fan_in(k_b, (self.n_output_plane,), fan_in,
                                       self.param_dtype)
        return p

    def _forward(self, params, x, *, training, rng):
        w = params["weight"].astype(x.dtype)
        from bigdl_tpu.ops import conv2d as _c2d

        if _c2d.policy_active():
            # a layout decision can apply (probe/per-geometry/autotune):
            # route through the per-pass-layout custom vjp (ops/conv2d.py)
            # so each of fwd/dgrad/wgrad compiles under its winning
            # layout — NHWC, NCHW, or dot_general (GEMM) for 1x1/s1 sites
            y = _c2d.conv2d(
                x, w, (self.stride_h, self.stride_w),
                ((self.pad_h, self.pad_h), (self.pad_w, self.pad_w)),
                (1, 1), self.n_group)
        else:
            y = lax.conv_general_dilated(
                x, w,
                window_strides=(self.stride_h, self.stride_w),
                padding=((self.pad_h, self.pad_h), (self.pad_w, self.pad_w)),
                dimension_numbers=DIMSPEC,
                feature_group_count=self.n_group,
            )
        if self.with_bias:
            y = y + params["bias"].astype(y.dtype)
        return y


class SpatialShareConvolution(SpatialConvolution):
    """(reference nn/SpatialShareConvolution.scala, 400 LoC) — there it exists
    only to share im2col buffers across layers for memory ("optnet"). Under
    XLA, buffer reuse is the compiler's memory planner's job, so this is
    exactly SpatialConvolution; the class exists for model-zoo API parity
    (models/resnet/ResNet.scala:50 uses it)."""


class SpatialFullConvolution(SimpleModule):
    """Transposed convolution / deconvolution
    (reference nn/SpatialFullConvolution.scala, 637 LoC). Implemented as
    ``lax.conv_transpose`` with explicit padding + adj (output-padding)."""

    def __init__(
        self,
        n_input_plane: int,
        n_output_plane: int,
        kernel_w: int,
        kernel_h: int,
        stride_w: int = 1,
        stride_h: int = 1,
        pad_w: int = 0,
        pad_h: int = 0,
        adj_w: int = 0,
        adj_h: int = 0,
        n_group: int = 1,
        with_bias: bool = True,
        param_dtype=jnp.float32,
        init: str = "default",
        name: Optional[str] = None,
    ):
        super().__init__(name)
        self.n_input_plane, self.n_output_plane = n_input_plane, n_output_plane
        self.kernel_w, self.kernel_h = kernel_w, kernel_h
        self.stride_w, self.stride_h = stride_w, stride_h
        self.pad_w, self.pad_h = pad_w, pad_h
        self.adj_w, self.adj_h = adj_w, adj_h
        self.n_group = n_group
        self.with_bias = with_bias
        self.param_dtype = param_dtype
        if init not in ("default", "bilinear", "bilinear_upsample"):
            raise ValueError(f"init {init!r} not in "
                             "('default','bilinear','bilinear_upsample')")
        self.init_method = init

    def init(self, rng):
        k_w, k_b = jax.random.split(rng)
        fan_in = self.kernel_w * self.kernel_h * (self.n_output_plane // self.n_group)
        shape = (self.kernel_h, self.kernel_w,
                 self.n_input_plane // self.n_group, self.n_output_plane)
        if self.init_method.startswith("bilinear"):
            # "bilinear": BilinearFiller parity (reference
            # SpatialFullConvolution.scala:121-135) — EVERY (in,out)
            # channel pair gets the separable triangle kernel, bias zeroed.
            # "bilinear_upsample": the Caffe/FCN diagonal variant — only
            # matching channels filled, so the deconv starts as exact
            # bilinear upsampling (what segmentation heads actually want;
            # identical to "bilinear" when n_in == n_out == 1).
            # generated in float32 end-to-end (no float64 intermediate
            # that a final cast then hides) so init is dtype-consistent
            # with every other layer and tpulint's dtype rules never
            # have to special-case our own defaults (ISSUE 4 satellite);
            # the single jnp.asarray below is the only conversion
            f_h = (self.kernel_h + 1) // 2
            c_h = np.float32((2 * f_h - 1 - f_h % 2) / (2.0 * f_h))
            wh = 1 - np.abs(np.arange(self.kernel_h, dtype=np.float32)
                            / f_h - c_h)
            f_w = (self.kernel_w + 1) // 2
            c_w = np.float32((2 * f_w - 1 - f_w % 2) / (2.0 * f_w))
            ww = 1 - np.abs(np.arange(self.kernel_w, dtype=np.float32)
                            / f_w - c_w)
            tri = wh[:, None] * ww[None, :]
            cin = self.n_input_plane // self.n_group
            if self.init_method == "bilinear":
                w = np.broadcast_to(tri[:, :, None, None], shape).copy()
            else:
                w = np.zeros(shape, np.float32)
                for i in range(min(cin, self.n_output_plane)):
                    w[:, :, i, i] = tri
            p = {"weight": jnp.asarray(w, self.param_dtype)}
        else:
            p = {"weight": uniform_fan_in(k_w, shape, fan_in,
                                          self.param_dtype)}
        if self.with_bias:
            p["bias"] = (jnp.zeros((self.n_output_plane,), self.param_dtype)
                         if self.init_method.startswith("bilinear") else
                         uniform_fan_in(k_b, (self.n_output_plane,), fan_in,
                                        self.param_dtype))
        return p

    def _forward(self, params, x, *, training, rng):
        w = params["weight"].astype(x.dtype)
        # Gradient-of-conv formulation: dilate the input by stride, then run a
        # VALID conv with the spatially-flipped kernel and adjusted padding —
        # the exact transpose of SpatialConvolution's forward, which is what
        # the reference computes via col2im.
        kh, kw = self.kernel_h, self.kernel_w
        pad_h_lo = kh - 1 - self.pad_h
        pad_w_lo = kw - 1 - self.pad_w
        y = lax.conv_general_dilated(
            x,
            jnp.flip(w, (0, 1)),
            window_strides=(1, 1),
            padding=((pad_h_lo, pad_h_lo + self.adj_h),
                     (pad_w_lo, pad_w_lo + self.adj_w)),
            lhs_dilation=(self.stride_h, self.stride_w),
            dimension_numbers=DIMSPEC,
            feature_group_count=self.n_group,
        )
        if self.with_bias:
            y = y + params["bias"].astype(y.dtype)
        return y


class SpatialDilatedConvolution(SpatialConvolution):
    """Atrous convolution (reference nn/SpatialDilatedConvolution.scala,
    555 LoC) — rhs_dilation on the same single XLA conv."""

    def __init__(self, n_input_plane, n_output_plane, kernel_w, kernel_h,
                 stride_w=1, stride_h=1, pad_w=0, pad_h=0,
                 dilation_w=1, dilation_h=1, with_bias=True,
                 param_dtype=jnp.float32, name=None):
        super().__init__(n_input_plane, n_output_plane, kernel_w, kernel_h,
                         stride_w, stride_h, pad_w, pad_h, 1, with_bias,
                         "default", param_dtype, name)
        self.dilation_w, self.dilation_h = dilation_w, dilation_h

    def _forward(self, params, x, *, training, rng):
        w = params["weight"].astype(x.dtype)
        from bigdl_tpu.ops import conv2d as _c2d

        if _c2d.policy_active():
            y = _c2d.conv2d(
                x, w, (self.stride_h, self.stride_w),
                ((self.pad_h, self.pad_h), (self.pad_w, self.pad_w)),
                (self.dilation_h, self.dilation_w), 1)
        else:
            y = lax.conv_general_dilated(
                x, w,
                window_strides=(self.stride_h, self.stride_w),
                padding=((self.pad_h, self.pad_h), (self.pad_w, self.pad_w)),
                rhs_dilation=(self.dilation_h, self.dilation_w),
                dimension_numbers=DIMSPEC,
            )
        if self.with_bias:
            y = y + params["bias"].astype(y.dtype)
        return y


class SpatialConvolutionMap(SimpleModule):
    """Convolution with an explicit input->output connection table
    (reference nn/SpatialConvolutionMap.scala, 355 LoC, Torch-style).

    ``conn_table`` is an (nPairs, 2) int array of (in_plane, out_plane)
    0-based pairs. Implemented as a full conv with a fixed binary mask on the
    kernel — sparse connectivity as masked-dense is the MXU-friendly form.
    """

    def __init__(self, conn_table, kernel_w: int, kernel_h: int,
                 stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0,
                 n_input_plane: Optional[int] = None,
                 n_output_plane: Optional[int] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        ct = np.asarray(conn_table, np.int32)
        assert ct.ndim == 2 and ct.shape[1] == 2
        self.conn_table = ct
        # explicit plane counts matter when the table leaves the highest
        # plane unconnected (legal in torch's nn.tables.random)
        self.n_input_plane = (int(ct[:, 0].max()) + 1
                              if n_input_plane is None else n_input_plane)
        self.n_output_plane = (int(ct[:, 1].max()) + 1
                               if n_output_plane is None else n_output_plane)
        assert ct[:, 0].max() < self.n_input_plane
        assert ct[:, 1].max() < self.n_output_plane
        self.kernel_w, self.kernel_h = kernel_w, kernel_h
        self.stride_w, self.stride_h = stride_w, stride_h
        self.pad_w, self.pad_h = pad_w, pad_h
        mask = np.zeros((1, 1, self.n_input_plane, self.n_output_plane), np.float32)
        mask[0, 0, ct[:, 0], ct[:, 1]] = 1.0
        self._mask = jnp.asarray(mask)

    @staticmethod
    def full(n_in: int, n_out: int):
        """Full connection table (reference SpatialConvolutionMap.full)."""
        return np.stack(np.meshgrid(np.arange(n_in), np.arange(n_out),
                                    indexing="ij"), -1).reshape(-1, 2)

    @staticmethod
    def one_to_one(n: int):
        """Depthwise table (reference SpatialConvolutionMap.oneToOne)."""
        i = np.arange(n)
        return np.stack([i, i], -1)

    def init(self, rng):
        k_w, k_b = jax.random.split(rng)
        # fan-in per output = (#inputs feeding it) * kW * kH, as in the
        # reference's reset; use average connectivity for the shared stdv.
        fan_in = self.kernel_w * self.kernel_h * max(
            1, len(self.conn_table) // self.n_output_plane)
        w = uniform_fan_in(
            k_w, (self.kernel_h, self.kernel_w, self.n_input_plane,
                  self.n_output_plane), fan_in)
        return {"weight": w,
                "bias": uniform_fan_in(k_b, (self.n_output_plane,), fan_in)}

    def _forward(self, params, x, *, training, rng):
        w = (params["weight"] * self._mask).astype(x.dtype)
        y = lax.conv_general_dilated(
            x, w,
            window_strides=(self.stride_h, self.stride_w),
            padding=((self.pad_h, self.pad_h), (self.pad_w, self.pad_w)),
            dimension_numbers=DIMSPEC,
        )
        return y + params["bias"].astype(y.dtype)


class TemporalConvolution(SimpleModule):
    """1-D convolution over (B, T, C) sequences — the layer the reference's
    text-classification example emulates by reshaping into SpatialConvolution
    (example/textclassification/TextClassifier.scala); here it is native."""

    def __init__(self, input_frame_size: int, output_frame_size: int,
                 kernel_w: int, stride_w: int = 1, pad_w: int = 0,
                 with_bias: bool = True, name: Optional[str] = None):
        super().__init__(name)
        self.input_frame_size = input_frame_size
        self.output_frame_size = output_frame_size
        self.kernel_w, self.stride_w, self.pad_w = kernel_w, stride_w, pad_w
        self.with_bias = with_bias

    def init(self, rng):
        k_w, k_b = jax.random.split(rng)
        fan_in = self.kernel_w * self.input_frame_size
        p = {"weight": uniform_fan_in(
            k_w, (self.kernel_w, self.input_frame_size, self.output_frame_size),
            fan_in)}
        if self.with_bias:
            p["bias"] = uniform_fan_in(k_b, (self.output_frame_size,), fan_in)
        return p

    def _forward(self, params, x, *, training, rng):
        w = params["weight"].astype(x.dtype)
        y = lax.conv_general_dilated(
            x, w,
            window_strides=(self.stride_w,),
            padding=((self.pad_w, self.pad_w),),
            dimension_numbers=("NWC", "WIO", "NWC"),
        )
        if self.with_bias:
            y = y + params["bias"].astype(y.dtype)
        return y
