"""Structural, shape, and table layers (reference nn/{Concat,Reshape,...}.scala).

"Tables" (the reference's nested Activity, nn/abstractnn/Activity.scala) are
plain Python tuples/lists here — JAX pytrees, so they nest through jit/grad
for free.

Dimension arguments are 0-based (the reference is 1-based Lua convention);
negative axes follow numpy rules. Batch is axis 0.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from bigdl_tpu.core.module import (
    Container,
    Module,
    SimpleModule,
    ElementwiseModule,
    Sequential,
    Identity,
    EMPTY_STATE,
    _child_rng,
)

__all__ = [
    "absorb_bn_relu",
    "Concat", "ConcatTable", "ParallelTable", "MapTable", "NarrowTable",
    "FlattenTable", "JoinTable", "MixtureTable", "CriterionTable", "Bottle",
    "Reshape", "View", "Transpose", "Squeeze", "Unsqueeze", "Select",
    "SelectTable", "Narrow", "Index", "MaskedSelect", "MaskedFill",
    "Replicate", "Padding", "SpatialZeroPadding", "Copy", "Contiguous",
    "Echo", "Max", "Min", "Mean", "Sum", "Dropout",
    "CAddTable", "CSubTable", "CMulTable", "CDivTable", "CMaxTable",
    "CMinTable",
]


def absorb_bn_relu(module: Module) -> int:
    """Graph rewrite for the fused BN block (ISSUE 2): in every
    :class:`Sequential` under ``module``, a ReLU directly following a
    BatchNormalization is absorbed into the BN (``bn.fuse_relu = True``,
    applied inside the Pallas epilogue on the fused path and as a jnp max
    on every other path) and replaced by :class:`Identity`.

    The swap is checkpoint-compatible: ReLU and Identity both own empty
    params (``{}``) and state (``()``), so child indices and pytree
    structure are unchanged. Only Sequential chains are rewritten —
    siblings in Concat/ConcatTable consume the same INPUT, not each
    other's output, so adjacency there is not data flow. Returns the
    number of ReLUs absorbed; idempotent (an absorbed ReLU is already an
    Identity on the second pass)."""
    from bigdl_tpu.nn.activation import ReLU
    from bigdl_tpu.nn.norm import BatchNormalization

    count = 0
    for m in module.modules():
        if not isinstance(m, Sequential):
            continue
        mods = m._modules
        for i in range(len(mods) - 1):
            if (isinstance(mods[i], BatchNormalization)
                    and type(mods[i + 1]) is ReLU):
                mods[i].fuse_relu = True
                mods[i + 1] = Identity(name=f"{mods[i + 1].name}(absorbed)")
                count += 1
    return count


# --------------------------------------------------------------------------
# Containers beyond Sequential
# --------------------------------------------------------------------------

class Concat(Container):
    """Run children on the same input, concatenate outputs along ``axis``
    (reference nn/Concat.scala, 297 LoC — its Engine.model.invoke branch
    threading is XLA's problem now). Default axis: features (last), the NHWC
    analog of the reference's channel dim."""

    def __init__(self, *modules: Module, axis: int = -1, name=None):
        super().__init__(*modules, name=name)
        self.axis = axis

    def apply(self, params, state, x, *, training=False, rng=None):
        outs, new_state = [], {}
        for i, m in enumerate(self._modules):
            k = str(i)
            y, s = m.apply(params[k], state[k], x,
                           training=training, rng=_child_rng(rng, i))
            outs.append(y)
            new_state[k] = s
        return jnp.concatenate(outs, axis=self.axis), new_state


class ConcatTable(Container):
    """Run children on the same input, output the table of results
    (reference nn/ConcatTable.scala)."""

    def apply(self, params, state, x, *, training=False, rng=None):
        outs, new_state = [], {}
        for i, m in enumerate(self._modules):
            k = str(i)
            y, s = m.apply(params[k], state[k], x,
                           training=training, rng=_child_rng(rng, i))
            outs.append(y)
            new_state[k] = s
        return tuple(outs), new_state


class ParallelTable(Container):
    """i-th child consumes i-th table element (reference nn/ParallelTable.scala)."""

    def apply(self, params, state, x, *, training=False, rng=None):
        outs, new_state = [], {}
        for i, m in enumerate(self._modules):
            k = str(i)
            y, s = m.apply(params[k], state[k], x[i],
                           training=training, rng=_child_rng(rng, i))
            outs.append(y)
            new_state[k] = s
        return tuple(outs), new_state


class MapTable(Container):
    """One shared child applied to every table element (reference
    nn/MapTable.scala — there the child is *cloned with shared weights*;
    functionally that is exactly "same params, many inputs")."""

    def __init__(self, module: Module, name=None):
        super().__init__(module, name=name)

    def apply(self, params, state, x, *, training=False, rng=None):
        m = self._modules[0]
        outs = []
        s = state["0"]
        for i, xi in enumerate(x):
            y, s = m.apply(params["0"], s, xi,
                           training=training, rng=_child_rng(rng, i))
            outs.append(y)
        return tuple(outs), {"0": s}


class NarrowTable(SimpleModule):
    """Select a length-``length`` slice of the input table starting at
    ``offset`` (reference nn/NarrowTable.scala). 0-based."""

    def __init__(self, offset: int, length: int = 1, name=None):
        super().__init__(name)
        self.offset, self.length = offset, length

    def _forward(self, params, x, *, training, rng):
        return tuple(x[self.offset:self.offset + self.length])


class FlattenTable(SimpleModule):
    """Flatten nested tables into one flat table (reference nn/FlattenTable.scala)."""

    def _forward(self, params, x, *, training, rng):
        out = []

        def rec(t):
            if isinstance(t, (tuple, list)):
                for e in t:
                    rec(e)
            else:
                out.append(t)

        rec(x)
        return tuple(out)


class JoinTable(SimpleModule):
    """Concatenate table elements along ``axis`` (reference nn/JoinTable.scala)."""

    def __init__(self, axis: int = -1, name=None):
        super().__init__(name)
        self.axis = axis

    def _forward(self, params, x, *, training, rng):
        return jnp.concatenate(list(x), axis=self.axis)


class MixtureTable(SimpleModule):
    """Mixture-of-experts gate (reference nn/MixtureTable.scala, 220 LoC):
    input = (gates (B,E), experts) where experts is a table of E tensors
    (B, ...) or one stacked tensor (B, E, ...); output = sum_e g_e * x_e."""

    def _forward(self, params, x, *, training, rng):
        gates, experts = x
        if isinstance(experts, (tuple, list)):
            experts = jnp.stack(list(experts), axis=1)  # (B, E, ...)
        g = gates.reshape(gates.shape + (1,) * (experts.ndim - gates.ndim))
        return jnp.sum(g * experts, axis=1)


class CriterionTable(SimpleModule):
    """Wrap a criterion as a module over a table (input, target)
    (reference nn/CriterionTable.scala)."""

    def __init__(self, criterion, name=None):
        super().__init__(name)
        self.criterion = criterion

    def _forward(self, params, x, *, training, rng):
        inp, tgt = x
        return self.criterion.forward(inp, tgt)


class Bottle(Container):
    """Collapse leading dims, apply child, restore (reference nn/Bottle.scala).
    ``n_input_dims`` counts non-batch dims the child expects."""

    def __init__(self, module: Module, n_input_dims: int = 2, name=None):
        super().__init__(module, name=name)
        self.n_input_dims = n_input_dims

    def apply(self, params, state, x, *, training=False, rng=None):
        lead = x.shape[: x.ndim - self.n_input_dims + 1]
        flat = x.reshape((-1,) + x.shape[x.ndim - self.n_input_dims + 1:])
        y, s = self._modules[0].apply(params["0"], state["0"], flat,
                                      training=training, rng=rng)
        y = y.reshape(lead + y.shape[1:])
        return y, {"0": s}


# --------------------------------------------------------------------------
# Shape ops
# --------------------------------------------------------------------------

class Reshape(SimpleModule):
    """Reshape non-batch dims to ``size`` (reference nn/Reshape.scala;
    batch_mode=None auto behavior simplified to: axis 0 is always batch)."""

    def __init__(self, size: Sequence[int], name=None):
        super().__init__(name)
        self.size = tuple(size)

    def _forward(self, params, x, *, training, rng):
        # pin batch sharding across the dim-collapse so GSPMD doesn't pick
        # a spatial layout for the backward's cotangent (parallel/hints.py)
        from bigdl_tpu.parallel.hints import constrain_batch

        return constrain_batch(
            constrain_batch(x).reshape((x.shape[0],) + self.size))


class View(Reshape):
    """Alias of Reshape (reference nn/View.scala; no storage aliasing to
    preserve — XLA decides layout)."""


class Transpose(SimpleModule):
    """Swap listed axis pairs in order (reference nn/Transpose.scala)."""

    def __init__(self, *pairs: tuple[int, int], name=None):
        super().__init__(name)
        self.pairs = pairs

    def _forward(self, params, x, *, training, rng):
        for a, b in self.pairs:
            x = jnp.swapaxes(x, a, b)
        return x


class Squeeze(SimpleModule):
    """(reference nn/Squeeze.scala)"""

    def __init__(self, axis: Optional[int] = None, name=None):
        super().__init__(name)
        self.axis = axis

    def _forward(self, params, x, *, training, rng):
        return jnp.squeeze(x, axis=self.axis)


class Unsqueeze(SimpleModule):
    """(reference nn/Unsqueeze.scala)"""

    def __init__(self, axis: int, name=None):
        super().__init__(name)
        self.axis = axis

    def _forward(self, params, x, *, training, rng):
        return jnp.expand_dims(x, self.axis)


class Select(SimpleModule):
    """Select index along an axis, removing it (reference nn/Select.scala)."""

    def __init__(self, axis: int, index: int, name=None):
        super().__init__(name)
        self.axis, self.index = axis, index

    def _forward(self, params, x, *, training, rng):
        return jnp.take(x, self.index, axis=self.axis)


class SelectTable(SimpleModule):
    """Select one element of a table (reference nn/SelectTable.scala)."""

    def __init__(self, index: int, name=None):
        super().__init__(name)
        self.index = index

    def _forward(self, params, x, *, training, rng):
        return x[self.index]


class Narrow(SimpleModule):
    """Static slice along an axis (reference nn/Narrow.scala / Tensor.narrow,
    tensor/Tensor.scala:420)."""

    def __init__(self, axis: int, offset: int, length: int, name=None):
        super().__init__(name)
        self.axis, self.offset, self.length = axis, offset, length

    def _forward(self, params, x, *, training, rng):
        return jax.lax.slice_in_dim(x, self.offset, self.offset + self.length,
                                    axis=self.axis)


class Index(SimpleModule):
    """Gather rows by an index tensor: input table (src, idx)
    (reference nn/Index.scala)."""

    def __init__(self, axis: int = 0, name=None):
        super().__init__(name)
        self.axis = axis

    def _forward(self, params, x, *, training, rng):
        src, idx = x
        return jnp.take(src, idx.astype(jnp.int32), axis=self.axis)


class MaskedSelect(SimpleModule):
    """Select elements where mask is true, input table (src, mask)
    (reference nn/MaskedSelect.scala).

    Dynamic output shape is incompatible with XLA tracing; outside jit this
    returns the 1-D masked values (reference semantics). Inside jit, prefer
    :class:`MaskedFill` or a fixed-size gather."""

    def _forward(self, params, x, *, training, rng):
        src, mask = x
        return src[mask.astype(bool)]


class MaskedFill(SimpleModule):
    """Jit-friendly companion of MaskedSelect: fill masked-out entries with a
    constant (the pattern the reference implements as maskedFill,
    tensor/TensorMath.scala:618-636)."""

    def __init__(self, value: float = 0.0, name=None):
        super().__init__(name)
        self.value = value

    def _forward(self, params, x, *, training, rng):
        src, mask = x
        return jnp.where(mask.astype(bool), src,
                         jnp.asarray(self.value, src.dtype))


class Replicate(SimpleModule):
    """Insert a new broadcast axis of size n (reference nn/Replicate.scala)."""

    def __init__(self, n_features: int, axis: int = 0, name=None):
        super().__init__(name)
        self.n_features, self.axis = n_features, axis

    def _forward(self, params, x, *, training, rng):
        return jnp.repeat(jnp.expand_dims(x, self.axis), self.n_features,
                          axis=self.axis)


class Padding(SimpleModule):
    """Pad ``pad`` entries (negative = before, positive = after) along an axis
    with ``value`` (reference nn/Padding.scala)."""

    def __init__(self, axis: int, pad: int, value: float = 0.0, name=None):
        super().__init__(name)
        self.axis, self.pad, self.value = axis, pad, value

    def _forward(self, params, x, *, training, rng):
        widths = [(0, 0)] * x.ndim
        ax = self.axis % x.ndim
        widths[ax] = (-self.pad, 0) if self.pad < 0 else (0, self.pad)
        return jnp.pad(x, widths, constant_values=self.value)


class SpatialZeroPadding(SimpleModule):
    """Zero-pad H/W of NHWC input (reference nn/SpatialZeroPadding.scala)."""

    def __init__(self, pad_left: int, pad_right: int, pad_top: int,
                 pad_bottom: int, name=None):
        super().__init__(name)
        self.pads = (pad_left, pad_right, pad_top, pad_bottom)

    def _forward(self, params, x, *, training, rng):
        l, r, t, b = self.pads
        return jnp.pad(x, [(0, 0), (t, b), (l, r), (0, 0)])


class Copy(ElementwiseModule):
    """Identity-with-copy (reference nn/Copy.scala) — functionally identity;
    XLA owns buffers, so there is nothing to copy."""

    def _fn(self, x):
        return x


class Contiguous(Copy):
    """(reference nn/Contiguous.scala) — meaningless under XLA layouts; identity."""


class Echo(SimpleModule):
    """Debug print per forward (reference nn/Echo.scala prints every
    updateOutput). Shape/dtype are static so they print at trace time;
    ``jax.debug.print`` fires on every EXECUTION too — including under
    jit — matching the reference's per-forward behavior."""

    def _forward(self, params, x, *, training, rng):
        print(f"[Echo:{self.name}] shape={tuple(x.shape)} dtype={x.dtype}")
        jax.debug.print("[Echo:{n}] max={m:.4g} mean={a:.4g}",
                        n=self.name or "?", m=jnp.max(x),
                        a=jnp.mean(x))
        return x


class _Reduce(SimpleModule):
    _op = None

    def __init__(self, axis: int = 1, keepdims: bool = False, name=None):
        super().__init__(name)
        self.axis, self.keepdims = axis, keepdims

    def _forward(self, params, x, *, training, rng):
        return self._op(x, axis=self.axis, keepdims=self.keepdims)


class Max(_Reduce):
    """(reference nn/Max.scala)"""
    _op = staticmethod(jnp.max)


class Min(_Reduce):
    """(reference nn/Min.scala)"""
    _op = staticmethod(jnp.min)


class Mean(_Reduce):
    """(reference nn/Mean.scala)"""
    _op = staticmethod(jnp.mean)


class Sum(_Reduce):
    """(reference nn/Sum.scala)"""
    _op = staticmethod(jnp.sum)


class Dropout(SimpleModule):
    """Inverted dropout (reference nn/Dropout.scala — scales by 1/(1-p) at
    train time, identity at eval; its Engine-threaded noise fill is just one
    fused random op here)."""

    def __init__(self, p: float = 0.5, name=None):
        super().__init__(name)
        assert 0.0 <= p < 1.0
        self.p = p

    def _forward(self, params, x, *, training, rng):
        if not training or self.p == 0.0:
            return x
        if rng is None:
            raise ValueError("Dropout needs an rng in training mode")
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, jnp.zeros_like(x))


# --------------------------------------------------------------------------
# Componentwise table ops (reference nn/C{Add,Sub,Mul,Div,Max,Min}Table.scala)
# --------------------------------------------------------------------------

class _CTable(SimpleModule):
    _op = None

    def _forward(self, params, x, *, training, rng):
        out = x[0]
        for t in x[1:]:
            out = self._op(out, t)
        return out


class CAddTable(_CTable):
    _op = staticmethod(jnp.add)


class CSubTable(_CTable):
    _op = staticmethod(jnp.subtract)


class CMulTable(_CTable):
    _op = staticmethod(jnp.multiply)


class CDivTable(_CTable):
    _op = staticmethod(jnp.divide)


class CMaxTable(_CTable):
    _op = staticmethod(jnp.maximum)


class CMinTable(_CTable):
    _op = staticmethod(jnp.minimum)
